"""ICI roofline model: predicted DP scaling efficiency, 1 → 32 v5e chips.

The north star (BASELINE.json) is ≥90% scaling efficiency at 32 chips.
Real 1→32 hardware is unavailable in this rig, so this model predicts it
from measured inputs instead of asserting it:

1. **Per-step collective bytes — measured from the program.** The DP
   train step is SPMD-compiled over a simulated 8-device mesh and every
   ``all-reduce`` instruction in the optimized HLO is parsed for its
   shape: gradient all-reduce (the f32 parameter gradients), the sync-BN
   batch-stat reductions that run inside the forward/backward, and the
   scalar metric reductions. This is exactly what XLA will emit on a
   real slice — not a hand estimate of "params × 4 bytes".
2. **Per-chip step time — measured on the chip.** The round-3 on-chip
   sweep (BASELINE.md, TPU v5 lite): the table below, refreshable from a
   ``BENCH_local*.json`` with ``platform: "tpu"`` when the tunnel is up.
3. **ICI bandwidth — published.** TPU v5e exposes 1600 Gbit/s of ICI
   per chip over 4 links (public v5e spec). A bidirectional ring
   all-reduce occupies one link pair each way → 100 GB/s effective is
   the primary assumption; 50 (single link, worst case) and 200
   (all-links, multi-ring torus collectives) bound it.

Ring all-reduce cost: each chip moves ``2·(N-1)/N · bytes`` at the
effective bandwidth. Efficiency bounds per N:

- no overlap (pessimistic):  t = t_compute + t_comm
- full overlap (XLA overlaps the gradient all-reduce with remaining
  backward compute; optimistic): t = max(t_compute, t_comm)

All 32 chips sit inside one v5e pod (ICI reaches 256 chips), so no DCN
hop enters the model. Writes SCALING_MODEL.json and prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import re

# Round-3 on-chip measurements (BASELINE.md "Where the ceiling is";
# committed at b9e8bc7): per-chip images/sec by per-chip batch, bf16
# NHWC ResNet-50 train step on TPU v5 lite behind the axon tunnel.
MEASURED_ON_CHIP = {
    "device": "TPU v5 lite",
    "source": "BASELINE.md round-3 sweep (bench.py)",
    "images_per_sec_by_batch": {212: 2334.0, 256: 2410.0, 384: 2429.0,
                                512: 2354.0},
}

# Public v5e ICI spec: 4 links × 400 Gbit/s = 1600 Gbit/s per chip.
ICI_EFFECTIVE_GBPS = {
    "single_link_worst": 50.0e9,
    "ring_link_pair_primary": 100.0e9,
    "all_links_best": 200.0e9,
}

DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
               "f64": 8, "pred": 1, "s8": 1, "u8": 1}


def measure_allreduce_bytes(n_devices: int = 8, batch_per_device: int = 2,
                            image: int = 224, num_classes: int = 1000):
    """Compile the DP train step SPMD and sum all-reduce bytes from HLO."""
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from dss_ml_at_scale_tpu.utils.benchlib import (
        build_resnet_task,
        dp_sharded_step,
    )

    task = build_resnet_task(num_classes=num_classes, on_accel=True)
    step, state, batch = dp_sharded_step(
        task, n_devices, batch_per_device, image, num_classes=num_classes,
        donate=False,  # lowering only; donation would just warn
    )
    hlo = step.lower(state, batch).compile().as_text()

    # Instruction lines look like either
    #   %x = f32[25583592]{0} all-reduce(...)
    # or (XLA groups several reductions into one collective)
    #   %x = (f32[64]{0}, f32[64]{0}) all-reduce(...)
    # — sum every array in the result shape, which is what the collective
    # moves per chip. Async pairs are counted at `all-reduce-done` (whose
    # shape is just the result); the matching `-start` carries an
    # (operands, results) tuple that would double-count.
    shape_pat = re.compile(r"(\w+)\[([\d,]*)\]")
    total = 0
    breakdown: dict[str, int] = {}
    for line in hlo.splitlines():
        if " all-reduce(" in line:
            op = line.find(" all-reduce(")
        elif " all-reduce-done(" in line:
            op = line.find(" all-reduce-done(")
        else:
            continue
        eq = line.find("= ")
        if eq < 0 or op < eq:
            continue
        for dtype, dims in shape_pat.findall(line[eq:op]):
            if dtype not in DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes = n * DTYPE_BYTES[dtype]
            total += nbytes
            key = f"{dtype}[{dims}]"
            breakdown[key] = breakdown.get(key, 0) + nbytes
    if total < 4 * 25_000_000:
        # ResNet-50 DP must all-reduce >= its ~25.6M f32 gradients; less
        # means the HLO text stopped matching (renamed ops, a
        # reduce-scatter decomposition, changed formatting) and a silent
        # zero would fabricate a perfect-efficiency prediction.
        raise RuntimeError(
            f"parsed only {total} all-reduce bytes from HLO — parser no "
            "longer matches this XLA version's collective text"
        )
    top = dict(sorted(breakdown.items(), key=lambda kv: -kv[1])[:6])
    return total, top


def predict(allreduce_bytes: int) -> dict:
    chips = [1, 2, 4, 8, 16, 32]
    out: dict = {}
    for batch, ips in MEASURED_ON_CHIP["images_per_sec_by_batch"].items():
        t_compute = batch / ips  # seconds/step on one chip
        rows = {}
        for name, bw in ICI_EFFECTIVE_GBPS.items():
            per_n = {}
            for n in chips:
                t_comm = 2.0 * (n - 1) / n * allreduce_bytes / bw
                eff_no = t_compute / (t_compute + t_comm)
                eff_full = t_compute / max(t_compute, t_comm)
                per_n[str(n)] = {
                    "t_comm_ms": round(t_comm * 1e3, 3),
                    "eff_no_overlap": round(eff_no, 4),
                    "eff_full_overlap": round(eff_full, 4),
                }
            rows[name] = per_n
        out[str(batch)] = {
            "t_compute_ms": round(t_compute * 1e3, 2),
            "by_bandwidth": rows,
        }
    return out


def refresh_measured(bench_json: str) -> None:
    """Replace the embedded step-time table with a real on-chip sweep
    (a bench.py artifact with platform == "tpu")."""
    with open(bench_json, encoding="utf-8") as f:
        bench = json.load(f)
    if bench.get("platform") != "tpu":
        raise SystemExit(
            f"{bench_json} has platform={bench.get('platform')!r}, not "
            "'tpu' — refusing to model ICI scaling from non-chip (or "
            "unattributed) step times"
        )
    table = {
        int(p["batch"]): float(p["images_per_sec"])
        for p in bench.get("sweep", [])
        if "images_per_sec" in p
    }
    if not table:
        raise SystemExit(f"{bench_json} carries no usable sweep points")
    MEASURED_ON_CHIP["images_per_sec_by_batch"] = table
    MEASURED_ON_CHIP["device"] = bench.get("device", "tpu")
    MEASURED_ON_CHIP["source"] = bench_json


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--bench-json", default=None,
        help="refresh the measured step-time table from a bench.py "
        "artifact (platform must be tpu)",
    )
    args = ap.parse_args()
    if args.bench_json:
        refresh_measured(args.bench_json)
    allreduce_bytes, top = measure_allreduce_bytes()
    predictions = predict(allreduce_bytes)
    # Headline at the measured sweet-spot batch (max per-chip throughput),
    # so a refreshed sweep with a different batch grid still works.
    table = MEASURED_ON_CHIP["images_per_sec_by_batch"]
    best_batch = max(table, key=table.get)
    primary = (
        predictions[str(best_batch)]["by_bandwidth"]
        ["ring_link_pair_primary"]["32"]
    )
    result = {
        "metric": "resnet50_dp_predicted_scaling_efficiency_32chip",
        "value": primary["eff_no_overlap"],
        "unit": f"fraction (pessimistic no-overlap bound, batch "
        f"{best_batch}/chip, 100 GB/s effective ICI)",
        "full_overlap_value": primary["eff_full_overlap"],
        "allreduce_bytes_per_step": allreduce_bytes,
        "allreduce_top_shapes_bytes": top,
        "measured_inputs": MEASURED_ON_CHIP,
        "ici_assumptions_bytes_per_sec": ICI_EFFECTIVE_GBPS,
        "topology_note": "32 chips sit inside one v5e ICI pod (<=256), "
        "no DCN hop modeled; ring all-reduce moves 2(N-1)/N x bytes/chip",
        "predictions": predictions,
        "north_star": {"target": 0.90, "met_by_prediction":
                       primary["eff_no_overlap"] >= 0.90},
    }
    with open("SCALING_MODEL.json", "w", encoding="utf-8") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: result[k] for k in
                      ("metric", "value", "full_overlap_value",
                       "allreduce_bytes_per_step", "north_star")}))


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# One-shot on-chip artifact refresh for when the accelerator tunnel is up:
#   ./run_tpu_artifacts.sh [out_suffix]
# Runs the headline bench (probe-gated, watchdogged), the accuracy
# proof, and the scaling roofline refresh on the real chip, writing
# BENCH_local{suffix}.json, ACCURACY_r04.json, and SCALING_MODEL.json.
# Safe to run against a dead tunnel: the bench degrades with a
# diagnosis in ~25 min instead of hanging.
set -u
cd "$(dirname "$0")"
SUFFIX="${1:-}"

# Both analysis tiers BEFORE the claim: a program that fails the AST
# lint or whose lowered IR breaks a contract (donation dropped, surprise
# all-gather, program-baseline drift) must never spend scarce chip time.
# Pinned to cpu so the preflight itself cannot touch (or hang on) the
# tunnel; `dsst audit` multiplexes 8 virtual devices for the abstract
# mesh on its own.
echo "== preflight: dsst lint && dsst audit (cpu, abstract mesh) =="
if ! JAX_PLATFORMS=cpu timeout 600 python -m dss_ml_at_scale_tpu.config.cli lint; then
  echo "preflight FAILED: dsst lint dirty - refusing to spend the TPU claim"
  exit 1
fi
if ! JAX_PLATFORMS=cpu timeout 900 python -m dss_ml_at_scale_tpu.config.cli audit; then
  echo "preflight FAILED: dsst audit dirty - refusing to spend the TPU claim"
  exit 1
fi
# Third tier: run the threaded subsystems under lock/thread
# instrumentation — a lock-order inversion or guarded-by violation in
# the feeder/serving/journal path must not ride a chip claim either.
if ! JAX_PLATFORMS=cpu timeout 600 python -m dss_ml_at_scale_tpu.config.cli sanitize; then
  echo "preflight FAILED: dsst sanitize dirty - refusing to spend the TPU claim"
  exit 1
fi
# Fourth tier: the tier-1 bench scenarios against the committed
# BENCH_BASELINE.json — a host-side performance regression (decode,
# reader, scheduler, recorder overhead, LM continuous-batching
# throughput) measured BEFORE the claim is a finding on CPU time, not
# a mystery in the on-chip numbers. 2100s exceeds the sum of tier-1
# per-scenario child timeouts (~1920s with the group_fit grid launch
# and the lm_serving stream), so a
# hung scenario dies to ITS watchdog (per-scenario finding + salvage)
# rather than this blanket kill.
# NOTE: baselines are environment-fingerprinted; on a host with no
# committed entry gated metrics report no-baseline and PASS — run
# `dsst bench --update-baseline --reason '...'` there once (or add
# --require-baseline to hard-fail ungated hosts).
if ! JAX_PLATFORMS=cpu timeout 2100 python -m dss_ml_at_scale_tpu.config.cli bench --tier tier1; then
  echo "preflight FAILED: dsst bench tier1 regressed - refusing to spend the TPU claim"
  exit 1
fi
# Live-SLO gate (fifth tier's judging half): rerun the serving scenario
# with a JSON artifact and judge the stub server's embedded /slo
# snapshot. Baseline-free: the objectives are code
# (telemetry/slo.py default_objectives), so there is nothing to pin.
# --strict on purpose: the bench's ~5s of load is shorter than the 10s
# pending->firing debounce, so "firing" is unreachable here — a burning
# objective shows as "pending" in the snapshot, and that is the state
# this gate must refuse on.
if ! JAX_PLATFORMS=cpu timeout 600 python -m dss_ml_at_scale_tpu.config.cli bench --scenarios serving --json > /tmp/dsst_bench_serving_slo.json; then
  echo "preflight FAILED: serving bench for slo check - refusing to spend the TPU claim"
  exit 1
fi
if ! JAX_PLATFORMS=cpu timeout 120 python -m dss_ml_at_scale_tpu.config.cli slo check --strict --report /tmp/dsst_bench_serving_slo.json; then
  echo "preflight FAILED: dsst slo check found a burning objective - refusing to spend the TPU claim"
  exit 1
fi
# Fleet gate (the SLO plane at fleet scope): spawn TWO stub serving
# replicas, drive propagated-trace traffic at each, then judge the
# MERGED fleet view through `dsst slo check --fleet` — the aggregator
# scrape, sketch federation, and fleet judgment all smoke-tested over
# real processes before any multi-replica claim ships. A third stub
# replica runs the LM tier: a propagated-trace streamed generation
# through the continuous-batching engine, then `dsst slo check
# --strict` on its armed TTFT/inter-token objectives.
if ! JAX_PLATFORMS=cpu timeout 300 python scripts/check_fleet_smoke.py; then
  echo "preflight FAILED: fleet smoke (slo check --fleet + LM stream gate) - refusing to spend the TPU claim"
  exit 1
fi

echo "== probe =="
timeout 150 python - <<'EOF'
import jax
d = jax.devices()[0]
print(f"platform={d.platform} device={d.device_kind}")
EOF
PROBE_RC=$?
if [ $PROBE_RC -ne 0 ]; then
  echo "tunnel unreachable (rc=$PROBE_RC); bench will record the failure"
fi

echo "== bench =="
timeout 3600 python bench.py > "BENCH_local${SUFFIX}.json" 2> "bench_stderr.log"
echo "bench rc=$? -> BENCH_local${SUFFIX}.json"
tail -c 600 "BENCH_local${SUFFIX}.json" || true
echo

if [ $PROBE_RC -eq 0 ]; then
  echo "== accuracy proof on chip =="
  timeout 1800 python bench_accuracy.py --out ACCURACY_r04.json
  echo "accuracy rc=$?"

  echo "== scaling roofline from the fresh on-chip sweep =="
  timeout 900 python scaling_model.py --bench-json "BENCH_local${SUFFIX}.json"
  echo "scaling model rc=$?"

  echo "== 2-device DeviceTrials smoke (skips on 1-device hosts) =="
  timeout 600 python smoke_two_device_trials.py
  echo "2dev smoke rc=$?"
fi

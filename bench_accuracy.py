"""Accuracy-convergence proof: the trainer trains, not just steps.

The reference's deliverable is a classifier trained to a monitored
``val_acc`` (``deep_learning/2.distributed-data-loading-petastorm.py:
190-208,408-415``). Every fast test in this repo only asserts "loss went
down"; this opt-in run (NOT part of ``bench.py``'s driver contract)
drives the full stack — generated JPEG Delta table → sharded streaming
decode → DP trainer with eval cadence, best-checkpoint tracking, and the
tracking store — until validation accuracy crosses 90% on a 10-class
dataset, and writes the accuracy curve to ``ACCURACY_r{N}.json``.

The dataset is synthetic but honest work for the model: each class is a
distinct spatial-frequency/orientation grating whose phase, amplitude,
and noise vary per image, so the classifier must learn structure (a
linear probe on mean color fails; ~10% accuracy at init).

Run from the repo root:  python bench_accuracy.py [--out ACCURACY.json]
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path


def make_dataset(path: Path, n_train: int, n_val: int, classes: int = 10,
                 size: int = 64, seed: int = 0, label_noise: float = 0.0):
    # The grating generator lives in the framework proper
    # (datagen/images.py; also `dsst datagen images`) — this harness just
    # cuts a train/val pair from it. Label noise applies to BOTH splits:
    # the val ceiling (1-p)+p/classes is then exact and pinnable.
    from dss_ml_at_scale_tpu.datagen.images import write_image_delta

    write_image_delta(path / "train", n_train, classes=classes, size=size,
                      seed=seed, label_noise=label_noise)
    write_image_delta(path / "val", n_val, classes=classes, size=size,
                      seed=seed + 1, label_noise=label_noise)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="ACCURACY.json")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--n-train", type=int, default=4096)
    ap.add_argument("--n-val", type=int, default=512)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--target", type=float, default=0.90)
    ap.add_argument(
        "--label-noise", type=float, default=0.2,
        help="stored-label corruption rate on BOTH splits; caps val_acc "
        "at exactly (1-p)+p/classes, so the run passes only if the final "
        "accuracy lands in a pinned band around that ceiling — a "
        "BN/optimizer/data regression moves it out, where the clean "
        "task's saturating 1.0 would hide it. 0 restores the clean "
        "reach-the-target mode",
    )
    ap.add_argument(
        "--cpu", action="store_true",
        help="force the CPU backend (accuracy is hardware-independent; "
        "use when the accelerator is unavailable)",
    )
    ap.add_argument(
        "--pallas-fused", action="store_true",
        help="train the Pallas prologue-fused bottleneck program "
        "(ops/fused_matmul.py) instead of the HLO fused basic-block "
        "model — the convergence proof for the second byte lever "
        "(single-chip; interpret-mode kernels on CPU)",
    )
    args = ap.parse_args()

    import tempfile

    import optax

    import jax

    from bench import _enable_compile_cache

    _enable_compile_cache(jax)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dss_ml_at_scale_tpu.data import DeltaTable, batch_loader
    from dss_ml_at_scale_tpu.data.transform import imagenet_transform_spec
    from dss_ml_at_scale_tpu.models.resnet import (
        BottleneckBlock,
        ResNet,
        ResNetBlock,
    )
    from dss_ml_at_scale_tpu.parallel import ClassifierTask, Trainer, TrainerConfig
    from dss_ml_at_scale_tpu.runtime import make_mesh
    from dss_ml_at_scale_tpu.tracking import RunStore

    t_start = time.time()
    workdir = Path(args.workdir) if args.workdir else Path(tempfile.mkdtemp())
    workdir.mkdir(parents=True, exist_ok=True)
    print(f"dataset: {args.n_train}+{args.n_val} JPEGs, "
          f"{args.classes} classes, label noise {args.label_noise} "
          f"-> {workdir}", flush=True)
    make_dataset(workdir, args.n_train, args.n_val, classes=args.classes,
                 label_noise=args.label_noise)

    spec = imagenet_transform_spec(crop=64)
    if args.pallas_fused and len(jax.devices()) > 1 and (
            jax.devices()[0].platform != "cpu"):
        # Same refusal as the dsst-train CLI: compiled pallas_call has
        # no GSPMD partitioning rule; a multi-chip mesh would
        # compile-error or replicate the batch and corrupt the artifact.
        print(json.dumps({
            "failed": True,
            "note": "--pallas-fused is single-chip; run without it or "
                    "on one device",
        }))
        return 1
    model = ResNet(
        stage_sizes=[1, 1],
        # --pallas-fused: bottleneck blocks + the Pallas prologue-fused
        # program (single-chip), so the accuracy band also guards the
        # second byte lever's training path end to end.
        block_cls=BottleneckBlock if args.pallas_fused else ResNetBlock,
        num_filters=16,
        num_classes=args.classes,
        # The production default: the accuracy band then also guards the
        # fused custom-VJP training path end to end.
        fused_bn="pallas" if args.pallas_fused else True,
    )
    task = ClassifierTask(model=model, tx=optax.adam(1e-3))
    store = RunStore(str(workdir / "runs"), "accuracy_proof", run_name="train")
    train_table = DeltaTable(workdir / "train")
    val_table = DeltaTable(workdir / "val")

    trainer = Trainer(
        TrainerConfig(
            max_epochs=args.epochs,
            total_train_rows=train_table.num_records(),
            limit_val_batches=args.n_val // args.batch_size,
            checkpoint_dir=str(workdir / "ckpt"),
            log_every_steps=20,
        ),
        mesh=make_mesh(),
        tracker=store,
    )

    def val_factory():
        return batch_loader(
            val_table, batch_size=args.batch_size, num_epochs=1,
            transform_spec=spec, shuffle_row_groups=False,
        ).__enter__()

    def build_artifact(history, *, complete: bool, best_ckpt=None) -> dict:
        curve = [
            {
                "epoch": h["epoch"],
                "train_loss": round(h.get("train_loss", float("nan")), 4),
                "val_acc": round(h.get("val_acc", float("nan")), 4),
                "images_per_sec": round(h.get("images_per_sec", 0.0), 1),
            }
            for h in history
        ]
        final_acc = curve[-1]["val_acc"] if curve else 0.0
        best_acc = max((c["val_acc"] for c in curve), default=0.0)
        out = {
            "device": jax.devices()[0].device_kind,
            "model_variant": ("pallas-fused bottleneck"
                             if args.pallas_fused
                             else "HLO-fused basic block"),
            "classes": args.classes,
            "n_train": args.n_train,
            "n_val": args.n_val,
            "epochs_run": len(curve),
            "complete": complete,
            "curve": curve,
            "final_val_acc": final_acc,
            "best_val_acc": best_acc,
            "best_checkpoint": best_ckpt,
            "wall_seconds": round(time.time() - t_start, 1),
        }
        if args.label_noise > 0:
            # The discriminating regime: best achievable val_acc is
            # exactly the noise ceiling. Passing requires landing IN the
            # band — too low is a training regression, above the ceiling
            # + sampling slack means the eval itself is broken (e.g.
            # leaking labels).
            ceiling = (
                (1.0 - args.label_noise) + args.label_noise / args.classes
            )
            # 512-sample binomial std at the ceiling is ~0.017; 0.05 of
            # upward slack is ~3 sigma, 0.10 down tolerates a slow epoch.
            band = [round(ceiling - 0.10, 4),
                    round(min(1.0, ceiling + 0.05), 4)]
            out.update(
                label_noise=args.label_noise,
                acc_ceiling=round(ceiling, 4),
                pinned_band=band,
                reached_target=bool(band[0] <= best_acc <= band[1]),
            )
        else:
            out.update(target=args.target,
                       reached_target=best_acc >= args.target)
        return out

    def write_artifact(out: dict) -> None:
        # Atomic (tmp + rename): a watchdog kill mid-write must leave
        # the previous complete artifact, not a truncated JSON.
        tmp = Path(args.out + ".tmp")
        tmp.write_text(json.dumps(out, indent=1))
        tmp.replace(args.out)

    history: list[dict] = []

    def on_epoch(summary: dict) -> None:
        # Checkpoint the artifact after EVERY epoch (complete=false): a
        # watchdog kill or tunnel stall mid-run still leaves the curve
        # measured so far on disk instead of nothing.
        history.append(summary)
        write_artifact(build_artifact(history, complete=False))

    with batch_loader(
        workdir / "train",
        batch_size=args.batch_size,
        num_epochs=None,
        workers_count=2,
        results_queue_size=8,
        transform_spec=spec,
    ) as reader:
        result = trainer.fit(task, reader, val_data_factory=val_factory,
                             epoch_callback=on_epoch)
    store.finish()

    out = build_artifact(result.history, complete=True,
                         best_ckpt=result.best_checkpoint_path)
    write_artifact(out)
    print(json.dumps({k: v for k, v in out.items() if k != "curve"}))
    for c in out["curve"]:
        print(f"  epoch {c['epoch']}: val_acc {c['val_acc']}", flush=True)
    return 0 if out["reached_target"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

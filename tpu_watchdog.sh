#!/usr/bin/env bash
# Probe the accelerator tunnel; on the first successful claim run the
# full on-chip artifact chain, then exit.  Order: bench (headline, now
# checkpoint/resume-hardened) -> accuracy -> scaling refresh -> 2-device
# smoke -> staged diag last (its bulk transfers are the likeliest to
# stall, and a stall then costs nothing downstream).
cd "$(dirname "$0")"
# No new probes/chains after this UTC hour:minute — the round driver
# runs its own one-shot bench at round end, and a watchdog chain firing
# then would contend for the single device lease.
# Round 5 started ~15:40 UTC Jul 31 with a ~12 h budget; leave the last
# ~45 min uncontended for the driver's round-end bench.
DEADLINE="${DSST_WATCHDOG_DEADLINE:-02:45}"
# Arm the deadline as an ABSOLUTE UTC epoch, computed once at start: the
# next occurrence of $DEADLINE (today if still ahead, else tomorrow).
# The old day-rollover heuristic compared wall-clock strings and only
# armed after the UTC day changed relative to script start — so a
# watchdog *re*started just after midnight deferred a 02:45 deadline by
# ~24h of device-lease contention (ADVICE r5).
DEADLINE_EPOCH="$(date -u -d "today $DEADLINE" +%s)"
if [ "$DEADLINE_EPOCH" -le "$(date -u +%s)" ]; then
  DEADLINE_EPOCH="$(date -u -d "tomorrow $DEADLINE" +%s)"
fi
echo "$(date -u +%H:%M:%S) deadline armed: $DEADLINE utc (epoch $DEADLINE_EPOCH)" >> tpu_watchdog.log
# Preflight both analysis tiers BEFORE entering the probe loop: if the
# AST lint or the IR audit is dirty, the chain must not fire at all — a
# claim spent compiling a program whose train step lost its donation or
# grew a surprise all-gather is a claim wasted. Pinned to cpu so the
# preflight can never touch (or hang on) the tunnel; the audit
# multiplexes its own 8-device abstract mesh.
if ! JAX_PLATFORMS=cpu timeout 600 python -m dss_ml_at_scale_tpu.config.cli \
    lint >> tpu_watchdog.log 2>&1; then
  echo "$(date -u +%H:%M:%S) preflight FAILED: dsst lint dirty - watchdog refusing to arm" >> tpu_watchdog.log
  exit 1
fi
if ! JAX_PLATFORMS=cpu timeout 900 python -m dss_ml_at_scale_tpu.config.cli \
    audit >> tpu_watchdog.log 2>&1; then
  echo "$(date -u +%H:%M:%S) preflight FAILED: dsst audit dirty - watchdog refusing to arm" >> tpu_watchdog.log
  exit 1
fi
if ! JAX_PLATFORMS=cpu timeout 600 python -m dss_ml_at_scale_tpu.config.cli \
    sanitize >> tpu_watchdog.log 2>&1; then
  echo "$(date -u +%H:%M:%S) preflight FAILED: dsst sanitize dirty - watchdog refusing to arm" >> tpu_watchdog.log
  exit 1
fi
# 2100s: must exceed the SUM of tier-1 per-scenario child timeouts
# (~1920s worst case with the group_fit grid launch and the
# lm_serving stream) so a hung
# scenario dies to ITS watchdog with a
# per-scenario finding/salvage note, not to this blanket kill.
if ! JAX_PLATFORMS=cpu timeout 2100 python -m dss_ml_at_scale_tpu.config.cli \
    bench --tier tier1 >> tpu_watchdog.log 2>&1; then
  echo "$(date -u +%H:%M:%S) preflight FAILED: dsst bench tier1 regressed - watchdog refusing to arm" >> tpu_watchdog.log
  exit 1
fi
# Live-SLO gate: rerun the serving scenario with a JSON artifact and
# judge its embedded /slo snapshot (the stub server's burn-rate state).
# --strict: the bench's ~5s of load cannot outlast the 10s
# pending->firing debounce, so a burning objective appears as
# "pending" — the state this gate refuses on.
if ! JAX_PLATFORMS=cpu timeout 600 python -m dss_ml_at_scale_tpu.config.cli \
    bench --scenarios serving --json > /tmp/dsst_watchdog_serving_slo.json \
    2>> tpu_watchdog.log; then
  echo "$(date -u +%H:%M:%S) preflight FAILED: serving bench for slo check - watchdog refusing to arm" >> tpu_watchdog.log
  exit 1
fi
if ! JAX_PLATFORMS=cpu timeout 120 python -m dss_ml_at_scale_tpu.config.cli \
    slo check --strict --report /tmp/dsst_watchdog_serving_slo.json \
    >> tpu_watchdog.log 2>&1; then
  echo "$(date -u +%H:%M:%S) preflight FAILED: dsst slo check found a burning objective - watchdog refusing to arm" >> tpu_watchdog.log
  exit 1
fi
# Fleet gate: 2 stub serving replicas, propagated-trace traffic, then
# `dsst slo check --fleet` over the merged view (scrape + sketch
# federation + fleet judgment smoke-tested over real processes); plus
# one stub LM replica streaming a propagated-trace generation through
# the continuous-batching engine, judged with `dsst slo check --strict`
# on its armed TTFT/inter-token objectives.
if ! JAX_PLATFORMS=cpu timeout 300 python scripts/check_fleet_smoke.py \
    >> tpu_watchdog.log 2>&1; then
  echo "$(date -u +%H:%M:%S) preflight FAILED: fleet smoke (slo check --fleet + LM stream gate) - watchdog refusing to arm" >> tpu_watchdog.log
  exit 1
fi
echo "$(date -u +%H:%M:%S) preflight clean: lint + audit + sanitize + bench + slo + fleet" >> tpu_watchdog.log
N=0
while true; do
  if [ "$(date -u +%s)" -ge "$DEADLINE_EPOCH" ]; then
    echo "$(date -u +%H:%M:%S) deadline $DEADLINE reached - watchdog exiting" >> tpu_watchdog.log
    break
  fi
  N=$((N + 1))
  # Quick probes catch a healthy tunnel; every 4th probe is patient
  # (30 min): the one observed definitive resolution of a half-up claim
  # took ~25 min (a 20-min probe hung to its kill), and killing a claim
  # mid-flight leaves a stale lease that poisons the next one.
  PT=150; [ $((N % 4)) -eq 0 ] && PT=1800
  echo "$(date -u +%H:%M:%S) probe #$N (timeout ${PT}s)" >> tpu_watchdog.log
  timeout $PT python - >> tpu_watchdog.log 2>&1 <<'PY'
import jax
d = jax.devices()[0]
assert d.platform != "cpu"
import jax.numpy as jnp
jnp.zeros((8, 8)).sum().block_until_ready()
print("CLAIM OK", d.platform, d.device_kind, flush=True)
PY
  if [ $? -eq 0 ]; then
    echo "$(date -u +%H:%M:%S) tunnel up -> doctor + bench" >> tpu_watchdog.log
    sleep 10
    # Crash-only revival FIRST: a watchdog restart usually means the VM
    # (or the tunnel) died under a run. The doctor sweeps the run store,
    # marks dead-PID runs INTERRUPTED, and re-executes each interrupted
    # run's recorded command with --resume-auto — so a recovered TPU VM
    # re-enters training from the newest intact checkpoint instead of
    # idling until a human notices. Bounded so a pathological resume
    # cannot eat the bench window.
    timeout 3600 python -m dss_ml_at_scale_tpu.config.cli \
      runs doctor --resume >> tpu_watchdog.log 2>&1
    echo "$(date -u +%H:%M:%S) runs doctor --resume rc=$?" >> tpu_watchdog.log
    DSST_BENCH_TIMEOUT=2400 DSST_BENCH_GROUP_TIMEOUT=1500 DSST_BENCH_LM_TIMEOUT=1200 \
      DSST_BENCH_VIT=1 \
      timeout 14400 python bench.py > BENCH_onchip_r5.json 2> bench_onchip_stderr.log
    echo "$(date -u +%H:%M:%S) bench rc=$?" >> tpu_watchdog.log
    timeout 2400 python bench_accuracy.py --label-noise 0 --out ACCURACY_onchip_r5.json >> tpu_watchdog.log 2>&1
    echo "$(date -u +%H:%M:%S) accuracy rc=$?" >> tpu_watchdog.log
    timeout 1800 python bench_accuracy.py --label-noise 0 --pallas-fused --out ACCURACY_pallas_onchip_r5.json >> tpu_watchdog.log 2>&1
    echo "$(date -u +%H:%M:%S) pallas accuracy rc=$?" >> tpu_watchdog.log
    timeout 900 python scaling_model.py --bench-json BENCH_onchip_r5.json >> tpu_watchdog.log 2>&1
    echo "$(date -u +%H:%M:%S) scaling rc=$?" >> tpu_watchdog.log
    timeout 600 python smoke_two_device_trials.py >> tpu_watchdog.log 2>&1
    echo "$(date -u +%H:%M:%S) 2dev smoke rc=$?" >> tpu_watchdog.log
    timeout 1800 python tpu_diag.py > tpu_diag_live.log 2>&1
    echo "$(date -u +%H:%M:%S) diag rc=$? - chain complete" >> tpu_watchdog.log
    break
  fi
  sleep 700
done

"""Staged tunnel diagnostic: find WHERE on-chip bench time goes.

The round-4 live bench attempts compiled the batch-212 train_step in
~3 min (23.5 MB executable cached at 03:51:08) and then produced
nothing for the remaining 12 min of watchdog budget.  Hypothesis: the
host->device transfer of the 127 MB float32 synthetic batch
(`jax.device_put(host_batch)` in bench._bench_compute_at) is orders of
magnitude slower through today's tunnel than the round-3 tunnel.

This script prints a timestamped line after EVERY stage, flushing, so
a watchdog kill still leaves a complete record of the last stage that
finished.  Stages: import, claim, tiny dispatch, host->device transfer
at 1/8/32/128 MB, device->host fetch at 1/8 MB, on-device batch
generation (the zero-transfer alternative), ResNet-50 init (device),
train_step compile (should hit the persistent cache), first execution,
10 timed steps.

Usage:  timeout 1800 python tpu_diag.py [--skip-transfers]
"""

from __future__ import annotations

import sys
import time

T0 = time.perf_counter()


def mark(msg: str) -> None:
    print(f"[{time.perf_counter() - T0:8.1f}s] {msg}", flush=True)


def main() -> None:
    skip_transfers = "--skip-transfers" in sys.argv

    mark("importing jax")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _enable_compile_cache

    _enable_compile_cache(jax)
    mark("jax imported")

    devs = jax.devices()
    mark(f"devices claimed: {devs}")

    x = jnp.ones((8, 128), jnp.float32)
    (x @ x.T).block_until_ready()
    mark("tiny dispatch ok")

    if not skip_transfers:
        for mb in (1, 8, 32, 128):
            host = np.random.default_rng(0).normal(
                size=(mb * 1024 * 1024 // 4,)
            ).astype(np.float32)
            t = time.perf_counter()
            dev = jax.device_put(host)
            dev.block_until_ready()
            dt = time.perf_counter() - t
            mark(f"h2d {mb:4d} MB: {dt:7.2f}s  ({mb / dt:8.2f} MB/s)")
            if dt > 120:
                mark("h2d too slow; skipping larger sizes")
                break
        for mb in (1, 8):
            dev = jnp.zeros((mb * 1024 * 1024 // 4,), jnp.float32) + 1.0
            dev.block_until_ready()
            t = time.perf_counter()
            _ = np.asarray(dev)
            dt = time.perf_counter() - t
            mark(f"d2h {mb:4d} MB: {dt:7.2f}s  ({mb / dt:8.2f} MB/s)")

    # On-device batch generation: the zero-transfer path.
    batch, image = 212, 224

    @jax.jit
    def make_batch(key):
        ki, kl = jax.random.split(key)
        return {
            "image": jax.random.normal(
                ki, (batch, image, image, 3), jnp.float32
            ),
            "label": jax.random.randint(kl, (batch,), 0, 1000, jnp.int32),
        }

    t = time.perf_counter()
    device_batch = make_batch(jax.random.key(0))
    jax.block_until_ready(device_batch)
    mark(f"on-device batch gen (compile+run): {time.perf_counter() - t:.2f}s")

    from dss_ml_at_scale_tpu.utils.benchlib import build_resnet_task

    task = build_resnet_task(num_classes=1000, on_accel=True)
    mark("task built")

    t = time.perf_counter()
    state = task.init_state(jax.random.key(0), device_batch)
    jax.block_until_ready(state.params)
    mark(f"init_state: {time.perf_counter() - t:.2f}s")

    t = time.perf_counter()
    compiled = jax.jit(task.train_step, donate_argnums=0).lower(
        state, device_batch
    ).compile()
    mark(f"train_step compile: {time.perf_counter() - t:.2f}s")

    t = time.perf_counter()
    state, metrics = compiled(state, device_batch)
    loss = float(metrics["train_loss"])
    mark(f"first step (exec+fetch): {time.perf_counter() - t:.2f}s "
         f"loss={loss:.3f}")

    t = time.perf_counter()
    steps = 10
    for _ in range(steps):
        state, metrics = compiled(state, device_batch)
    float(metrics["train_loss"])
    dt = time.perf_counter() - t
    mark(f"{steps} steps: {dt:.2f}s -> {batch * steps / dt:.1f} img/s")


if __name__ == "__main__":
    main()

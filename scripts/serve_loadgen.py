#!/usr/bin/env python
"""Closed-loop load generator for the serving scheduler (CLI shim).

The implementation moved to ``dss_ml_at_scale_tpu.bench.loadgen`` so
the bench harness can register serving load as a scenario (``dsst
bench --scenarios serving`` — the ``BENCH_serving.json`` producer);
this shim keeps the historical entry point and flags:

    python scripts/serve_loadgen.py --selftest --threads 16 \
        --duration 3 --out BENCH_serving.json
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from dss_ml_at_scale_tpu.bench.loadgen import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())

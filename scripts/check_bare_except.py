#!/usr/bin/env python
"""Lint: no swallowed errors in the library or scripts.

Swallowed exceptions are how robustness bugs hide: a retry loop that
"works" because the failure it should surface is eaten two frames down
is worse than no retry at all. Two patterns are banned:

- bare ``except:`` — catches ``KeyboardInterrupt``/``SystemExit`` too,
  which no library code here should ever intend;
- silent broad handlers — ``except Exception:`` / ``except
  BaseException:`` (alone or in a tuple) whose entire body is ``pass``
  (or a docstring + ``pass``); catching broadly is sometimes right, but
  then the handler must DO something: log, count, re-wrap, or fall back.

The allowlist maps a file to the number of audited, comment-justified
silent handlers it may keep; adding a new one anywhere else (or a new
one in an allowlisted file) fails tier-1 via
``tests/test_no_bare_except.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
SCAN_DIRS = ("dss_ml_at_scale_tpu", "scripts")

# path (relative to repo root) -> max audited silent broad handlers.
# Every entry must carry an in-source comment justifying the swallow.
ALLOWED_SILENT = {
    # DeviceMonitor sampler thread: a flaky backend must not kill it.
    "dss_ml_at_scale_tpu/telemetry/device.py": 1,
    # Reader generator finalizer at interpreter shutdown: nothing raised
    # there is actionable.
    "dss_ml_at_scale_tpu/data/reader.py": 1,
}

_BROAD = {"Exception", "BaseException"}


def _is_broad(expr: ast.expr | None) -> bool:
    if expr is None:
        return True  # bare except
    if isinstance(expr, ast.Name):
        return expr.id in _BROAD
    if isinstance(expr, ast.Tuple):
        return any(_is_broad(e) for e in expr.elts)
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    body = handler.body
    if body and isinstance(body[0], ast.Expr) and isinstance(
        getattr(body[0], "value", None), ast.Constant
    ):
        body = body[1:]  # skip a docstring-style leading constant
    return all(isinstance(stmt, ast.Pass) for stmt in body)


def find_violations(root: Path = ROOT) -> list[str]:
    violations: list[str] = []
    for scan in SCAN_DIRS:
        for path in sorted((root / scan).rglob("*.py")):
            rel = path.relative_to(root).as_posix()
            tree = ast.parse(path.read_text(encoding="utf-8"),
                             filename=str(path))
            silent_broad = 0
            for node in ast.walk(tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if node.type is None:
                    violations.append(
                        f"{rel}:{node.lineno}: bare `except:` — name the "
                        "exceptions (or Exception) you actually mean"
                    )
                elif _is_broad(node.type) and _is_silent(node):
                    silent_broad += 1
                    if silent_broad > ALLOWED_SILENT.get(rel, 0):
                        violations.append(
                            f"{rel}:{node.lineno}: silent broad except "
                            "(body is just `pass`) — log, count, or "
                            "narrow it; swallowed errors hide robustness "
                            "bugs"
                        )
    return violations


def main() -> int:
    violations = find_violations()
    for line in violations:
        sys.stderr.write(line + "\n")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Thin shim — this lint moved into the analysis subsystem.

The rule now lives at
:mod:`dss_ml_at_scale_tpu.analysis.checkers.bare_except` (rule name
``bare-except``) and runs with the whole suite via ``dsst lint`` and
``tests/test_lint.py``. The old file→count allowlist became in-source
``# dsst: ignore[bare-except] reason`` suppressions at the audited
sites. This shim keeps the old entry point alive for external
references.
"""

from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))


def find_violations(root: Path = ROOT) -> list[str]:
    from dss_ml_at_scale_tpu.analysis import run_lint

    root = Path(root)
    res = run_lint(
        ["bare-except"],
        roots=[
            ("package", root / "dss_ml_at_scale_tpu"),
            ("scripts", root / "scripts"),
        ],
    )
    return [f.text() for f in res.findings]


def main() -> int:
    violations = find_violations()
    for line in violations:
        sys.stderr.write(line + "\n")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

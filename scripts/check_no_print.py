#!/usr/bin/env python
"""Thin shim — this lint moved into the analysis subsystem.

The rule now lives at
:mod:`dss_ml_at_scale_tpu.analysis.checkers.no_print` (rule name
``no-print``) and runs with the whole suite via ``dsst lint`` and
``tests/test_lint.py``. This shim keeps the old entry point (and
``find_violations()`` signature) alive for external references.
"""

from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

PACKAGE = ROOT / "dss_ml_at_scale_tpu"


def find_violations(package: Path = PACKAGE) -> list[str]:
    from dss_ml_at_scale_tpu.analysis import run_lint

    res = run_lint(
        ["no-print"], roots=[("package", Path(package))]
    )
    return [f.text() for f in res.findings]


def main() -> int:
    violations = find_violations()
    for line in violations:
        sys.stderr.write(line + "\n")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

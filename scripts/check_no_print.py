#!/usr/bin/env python
"""Lint: no bare ``print(`` inside the library.

Every user-facing line must flow through an accountable channel —
telemetry (metered), tracking (archived), or ``logging`` (filterable).
A bare ``print`` in library code bypasses all three and corrupts
machine-parseable CLI stdout. The CLI surface (``config/``: cli,
commands, pipeline — whose *job* is stdout) is the one exemption.

AST-based so strings, comments, and ``pprint``-style names never false
positive; ``file=sys.stderr`` prints in library code are violations too
(use logging). Runs in tier-1 via ``tests/test_no_print.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PACKAGE = Path(__file__).resolve().parents[1] / "dss_ml_at_scale_tpu"

# The CLI surface: stdout is its contract.
ALLOWED_FIRST_PARTS = {"config"}


def find_violations(package: Path = PACKAGE) -> list[str]:
    violations: list[str] = []
    for path in sorted(package.rglob("*.py")):
        rel = path.relative_to(package)
        if rel.parts[0] in ALLOWED_FIRST_PARTS:
            continue
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                violations.append(
                    f"{rel}:{node.lineno}: bare print() — route through "
                    "telemetry/tracking/logging"
                )
    return violations


def main() -> int:
    violations = find_violations()
    for line in violations:
        sys.stderr.write(line + "\n")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Two-replica fleet observability smoke (CI preflight).

Spawns TWO stub-scorer serving subprocesses (the same
``bench.loadgen.spawn_stub_server`` path the serving bench uses),
drives a little real traffic with propagated trace headers at each,
then judges the FLEET through the real CLI:

    dsst slo check --fleet 127.0.0.1:P1 127.0.0.1:P2

Exit 0 means the whole plane held together end to end: both replicas
served ``/telemetry``, the aggregator merged their registries and SLO
windows inside its timeout budget, and no fleet-level objective is
burning. Any crash, straggler-blocked scrape, or merged burn fails the
preflight — exactly the multi-replica claim the TPU artifact pipeline
wants gated before it publishes serving numbers.

A third replica exercises the LM tier: one stub ``serve-lm`` process
(``bench.loadgen.spawn_stub_lm_server``), streamed generations with a
propagated trace header per request, then two judgments — ``dsst slo
check --strict`` against the replica alone (TTFT and inter-token
objectives armed and not even pending), and the LM replica MERGED into
the ``--fleet`` view with the two image replicas, so the LM windowed
sketches federate through the same wire forms before any LM serving
claim ships.
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))


def main() -> int:
    from dss_ml_at_scale_tpu.bench.loadgen import (
        run_lm_load,
        run_load,
        spawn_stub_lm_server,
        spawn_stub_server,
    )
    from dss_ml_at_scale_tpu.config.cli import main as dsst_main
    from dss_ml_at_scale_tpu.telemetry import federation

    procs = []
    try:
        endpoints = []
        for _ in range(2):
            proc, port = spawn_stub_server(score_ms=1.0,
                                           batch_window_ms=1.0)
            procs.append(proc)
            endpoints.append(f"127.0.0.1:{port}")
            report = run_load("127.0.0.1", port, b"0", threads=2,
                              duration_s=1.0)
            if report["requests"] == 0:
                print(f"fleet smoke: no requests served by {port}",
                      file=sys.stderr)
                return 1
            if report["trace_propagated"] != report["requests"]:
                print(
                    "fleet smoke: trace propagation broken "
                    f"({report['trace_propagated']}/{report['requests']} "
                    "echoed the injected trace id)",
                    file=sys.stderr,
                )
                return 1

        # -- LM tier: one streaming replica joins the fleet -----------
        proc, lm_port = spawn_stub_lm_server(
            step_ms=2.0, deadline_ms=2000.0, inter_token_budget_ms=250.0,
        )
        procs.append(proc)
        report = run_lm_load("127.0.0.1", lm_port, prompt=[1, 2, 3],
                             max_new_tokens=8, streams=4, duration_s=1.0)
        if report["requests"] == 0:
            print(f"fleet smoke: no generations served by {lm_port}",
                  file=sys.stderr)
            return 1
        if report["trace_propagated"] != report["requests"]:
            print(
                "fleet smoke: LM trace propagation broken "
                f"({report['trace_propagated']}/{report['requests']} "
                "done-lines echoed the injected trace id)",
                file=sys.stderr,
            )
            return 1
        # Strict solo gate first: TTFT/inter-token armed and not even
        # pending on the replica that actually decoded.
        rc = dsst_main([
            "slo", "check", "--strict",
            "--url", f"http://127.0.0.1:{lm_port}",
        ])
        if rc != 0:
            print(f"fleet smoke: LM slo check --strict exited {rc}",
                  file=sys.stderr)
            return 1
        endpoints.append(f"127.0.0.1:{lm_port}")

        with tempfile.TemporaryDirectory() as td:
            journal = Path(td) / "fleet.jsonl"
            rc = dsst_main([
                "slo", "check",
                "--fleet", *endpoints,
                "--fleet-journal", str(journal),
            ])
            if rc != 0:
                print(f"fleet smoke: slo check --fleet exited {rc}",
                      file=sys.stderr)
                return 1
            cycles = federation.read_fleet_journal(journal)
            if not cycles or cycles[-1]["up"] != 3:
                print(f"fleet smoke: journal shows {cycles!r}",
                      file=sys.stderr)
                return 1
        print("fleet smoke: 2 image replicas + 1 LM replica scraped, "
              "merged, and judged OK; LM streams propagated traces and "
              "passed the strict SLO gate")
        return 0
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            proc.wait(15)


if __name__ == "__main__":
    sys.exit(main())

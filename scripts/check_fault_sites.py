#!/usr/bin/env python
"""Lint: fault-injection sites cannot drift from their registry.

Every ``maybe_fail("...")`` / ``fault_fires("...")`` call site in the
library is part of the chaos-testing surface operators arm with
``--fault-plan`` — so every site name used in the package must be
declared (with a description) in ``resilience.faults.KNOWN_SITES``, and
every declared site must still have a call site. Otherwise injection
sites silently drift from the docs and the CLI help (which is generated
from the same dict), and a chaos plan arms nothing.

Rules (AST-based, so comments/strings never false-positive):

- a site argument must be a string literal, or an f-string whose
  *leading literal prefix* (e.g. ``f"rpc.send.{method}"`` → ``rpc.send``)
  matches a registered site — dynamic suffixes are how per-method RPC
  sites work;
- a bare variable argument is allowed only inside a function that is
  itself a registered marker (``maybe_fail``/``fault_fires`` wrappers
  forwarding their parameter, e.g. ``runtime.rpc._maybe_fail``);
- every ``KNOWN_SITES`` key must be used by at least one call site and
  carry a non-empty description.

Runs in tier-1 via ``tests/test_fault_sites.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PACKAGE = Path(__file__).resolve().parents[1] / "dss_ml_at_scale_tpu"

# Call names that mark an injection site. Wrapper functions carrying one
# of these names may forward a variable site argument.
MARKERS = {"maybe_fail", "fault_fires", "_maybe_fail", "check", "fires"}


def _known_sites() -> dict:
    # Import the live registry — the lint must test what ships, not a
    # copy that could itself drift.
    sys.path.insert(0, str(PACKAGE.parent))
    try:
        from dss_ml_at_scale_tpu.resilience.faults import KNOWN_SITES
    finally:
        sys.path.pop(0)
    return KNOWN_SITES


def _call_name(node: ast.Call) -> str | None:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _site_literal(arg: ast.expr) -> tuple[str | None, bool]:
    """``(site, is_prefix)`` from the argument node, or ``(None, False)``
    when it is not a (partially) literal string."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr):
        prefix = ""
        for part in arg.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        return (prefix.rstrip(".") or None), True
    return None, False


def _registered(site: str, is_prefix: bool, known: dict) -> bool:
    for key in known:
        if site == key or site.startswith(key + "."):
            return True
        if is_prefix and key.startswith(site + "."):
            return True
    return False


def find_violations(package: Path = PACKAGE,
                    known: dict | None = None) -> list[str]:
    known = _known_sites() if known is None else known
    violations: list[str] = []
    used: list[tuple[str, bool]] = []
    for path in sorted(package.rglob("*.py")):
        rel = path.relative_to(package)
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        # Map each call to its innermost enclosing function name, so
        # forwarding wrappers can be recognized.
        parents: dict[ast.AST, str | None] = {}

        def assign_parents(node, fn=None):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = node.name
            for child in ast.iter_child_nodes(node):
                parents[child] = fn
                assign_parents(child, fn)

        assign_parents(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name not in ("maybe_fail", "fault_fires", "_maybe_fail"):
                continue
            if not node.args:
                continue
            site, is_prefix = _site_literal(node.args[0])
            if site is None:
                if (
                    isinstance(node.args[0], ast.Name)
                    and parents.get(node) in MARKERS
                ):
                    continue  # a wrapper forwarding its site parameter
                violations.append(
                    f"{rel}:{node.lineno}: {name}() with a non-literal "
                    "site — use a string literal (or f-string with a "
                    "registered prefix) so the site registry can see it"
                )
                continue
            used.append((site, is_prefix))
            if not _registered(site, is_prefix, known):
                violations.append(
                    f"{rel}:{node.lineno}: site {site!r} is not registered "
                    "in resilience.faults.KNOWN_SITES — declare and "
                    "document it there"
                )
    for key, doc in known.items():
        if not (isinstance(doc, str) and doc.strip()):
            violations.append(
                f"KNOWN_SITES[{key!r}] has no description — document "
                "what arming it simulates"
            )
        if not any(
            site == key or site.startswith(key + ".")
            or (is_prefix and key.startswith(site + "."))
            for site, is_prefix in used
        ):
            violations.append(
                f"KNOWN_SITES[{key!r}] has no call site left in the "
                "package — remove the entry or restore the site"
            )
    return violations


def main() -> int:
    violations = find_violations()
    for line in violations:
        sys.stderr.write(line + "\n")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md); the operative target is
the driver-defined north star — ResNet-50 images/sec/chip vs an
8×A100-class DDP baseline. ``vs_baseline`` is measured throughput divided
by A100_IMG_PER_SEC (a public ~A100 ResNet-50 mixed-precision per-GPU
figure), so 1.0 == per-chip parity with the reference-class hardware.

Runs on whatever jax.devices() provides: the real TPU chip under the
driver, or (fallback) CPU where the number is meaningless but the
harness still exercises end to end.
"""

from __future__ import annotations

import json
import time

A100_IMG_PER_SEC = 2500.0  # ResNet-50 train, mixed precision, per A100


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dss_ml_at_scale_tpu.models import ResNet50
    from dss_ml_at_scale_tpu.parallel import ClassifierTask

    on_accel = jax.devices()[0].platform != "cpu"
    # Reference per-rank batch is 212 (deep_learning/2...py:342); bf16
    # ResNet-50 at 212×224×224 fits a v5e chip.
    batch = 212 if on_accel else 8
    image = 224 if on_accel else 64
    steps = 10 if on_accel else 2

    model = ResNet50(num_classes=1000) if on_accel else ResNet50(
        num_classes=1000, num_filters=16, dtype=jnp.float32
    )
    task = ClassifierTask(model=model, tx=optax.adam(1e-5))

    rng = np.random.default_rng(0)
    host_batch = {
        "image": rng.normal(size=(batch, image, image, 3)).astype(np.float32),
        "label": rng.integers(0, 1000, batch).astype(np.int32),
    }
    state = task.init_state(jax.random.key(0), host_batch)
    device_batch = jax.device_put(host_batch)
    train_step = jax.jit(task.train_step, donate_argnums=0)

    # Warmup: compile + 2 steady steps.
    for _ in range(3):
        state, metrics = train_step(state, device_batch)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, device_batch)
    # Force full materialization: fetch a scalar that depends on the last
    # step (block_until_ready alone has proven unreliable through remote
    # device tunnels).
    float(metrics["train_loss"])
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(ips, 2),
                "unit": f"images/sec (batch {batch}, {jax.devices()[0].device_kind})",
                "vs_baseline": round(ips / A100_IMG_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()

"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

``value`` is compute-path images/sec/chip on synthetic device-resident
batches. The ``pipeline`` sub-object holds the number the reference's
track A is actually about (``deep_learning/2.distributed-data-loading-
petastorm.py:246-259,338``): end-to-end images/sec when the same train
step is fed by the real input pipeline — a Delta table of JPEGs streamed
through the sharded Parquet reader, the native decode pool, and
host→device prefetch — plus the input-stall fraction
(1 − e2e/compute; 0.0 means the chip never waits on input).

The reference publishes no numbers (BASELINE.md); the operative target is
the driver-defined north star — ResNet-50 images/sec/chip vs an
8×A100-class DDP baseline. ``vs_baseline`` is measured throughput divided
by A100_IMG_PER_SEC (a public ~A100 ResNet-50 mixed-precision per-GPU
figure), so 1.0 == per-chip parity with the reference-class hardware.

Harness discipline: this process NEVER exits non-zero and always prints
exactly one JSON line. The accelerator backend lives behind a remote
tunnel that has been observed to both *fail* transiently and *hang
indefinitely* in ``jax.devices()`` — so the measurement runs in a
watchdog subprocess with a hard timeout, retried once, then falls back
to a forced-CPU subprocess with the failure recorded in ``note`` — a
meaningless number with a diagnosis beats a crash or a stall.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

A100_IMG_PER_SEC = 2500.0  # ResNet-50 train, mixed precision, per A100

_CHILD_ENV = "DSST_BENCH_CHILD"
_FORCE_CPU_ENV = "DSST_BENCH_FORCE_CPU"
_TIMEOUT_ENV = "DSST_BENCH_TIMEOUT"  # seconds per child attempt


# ---------------------------------------------------------------------------
# Parent: watchdog around a child process that does the real work
# ---------------------------------------------------------------------------

def parent_main() -> None:
    timeout = float(os.environ.get(_TIMEOUT_ENV, "480"))
    notes: list[str] = []

    def run_child(force_cpu: bool, t: float):
        env = dict(os.environ, **{_CHILD_ENV: "1"})
        if force_cpu:
            env[_FORCE_CPU_ENV] = "1"
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, timeout=t, capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            return None, f"timed out after {t:.0f}s (backend hang?)"
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(line)
                if isinstance(parsed, dict) and "metric" in parsed:
                    if parsed.get("failed"):
                        # The child completed but measured nothing (e.g. a
                        # transient backend error it caught): report it as a
                        # failure so the retry / CPU fallback still runs.
                        note = str(parsed.get("note", ""))[-300:]
                        return None, f"child failed: {note}"
                    return parsed, None
            except json.JSONDecodeError:
                continue
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        return None, f"rc={proc.returncode}, no JSON line; tail: {' | '.join(tail)}"

    for attempt in (1, 2):
        result, err = run_child(force_cpu=False, t=timeout)
        if result is not None:
            _emit(result, notes)
            return
        notes.append(f"accelerator attempt {attempt}: {err}")
        if attempt == 1:
            time.sleep(5.0)  # transient-failure cooldown between attempts

    result, err = run_child(force_cpu=True, t=min(timeout, 300.0))
    if result is not None:
        notes.append("fell back to cpu — number is a harness check only")
        _emit(result, notes)
        return
    notes.append(f"cpu fallback: {err}")
    _emit(
        {
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec",
            "vs_baseline": 0.0,
        },
        notes,
    )


def _emit(result: dict, notes: list[str]) -> None:
    if notes:
        prior = result.get("note")
        result["note"] = "; ".join(([prior] if prior else []) + notes)
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# Child: the actual measurement
# ---------------------------------------------------------------------------

def _chw(batch):
    """Benchmark batches in CHW to match the reader's field contract, so
    the compute phase and the pipeline phase share one compiled step."""
    import numpy as np

    return {
        "image": np.ascontiguousarray(np.transpose(batch["image"], (0, 3, 1, 2))),
        "label": batch["label"],
    }


def _bench_compute(jax, task, batch_size: int, image: int, steps: int):
    """Compute-only images/sec: synthetic batch already resident in HBM."""
    from dss_ml_at_scale_tpu.utils.benchlib import (
        synthetic_image_batch,
        timed_train_steps,
    )

    host_batch = _chw(synthetic_image_batch(batch_size, image, num_classes=1000))
    state = task.init_state(jax.random.key(0), host_batch)
    device_batch = jax.device_put(host_batch)
    train_step = jax.jit(task.train_step, donate_argnums=0)
    _, dt = timed_train_steps(train_step, state, device_batch, steps)
    return train_step, batch_size * steps / dt


def _write_jpeg_table(path, *, n_images: int, source_size: int, seed: int = 0):
    """Synthetic JPEG Delta table shaped like the reference's ImageNet
    ingest (binary ``content`` + int ``label_index``, R1/`1.data-preparation.py`)."""
    import io

    import numpy as np
    import pyarrow as pa
    from PIL import Image

    from dss_ml_at_scale_tpu.data import write_delta

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 1000, n_images)
    jpegs = []
    # Blocky low-frequency content: realistic JPEG entropy (pure noise
    # inflates decode cost; flat color deflates it).
    for _ in range(n_images):
        blocks = rng.uniform(0, 255, (8, 8, 3))
        img = np.kron(blocks, np.ones((source_size // 8, source_size // 8, 1)))
        buf = io.BytesIO()
        Image.fromarray(img.astype(np.uint8)).save(buf, format="JPEG", quality=85)
        jpegs.append(buf.getvalue())
    table = pa.table(
        {
            "content": pa.array(jpegs, type=pa.binary()),
            "label_index": pa.array(labels.astype(np.int64)),
        }
    )
    write_delta(table, path, max_rows_per_file=max(16, n_images // 16))
    return path


def _bench_pipeline(jax, train_step, task, *, batch_size: int, image: int,
                    source_size: int, steps: int, workers: int, tmpdir: str):
    """End-to-end images/sec: Delta table → sharded reader → decode pool →
    prefetch → the SAME compiled train step as the compute phase."""
    from pathlib import Path

    from dss_ml_at_scale_tpu.data import batch_loader
    from dss_ml_at_scale_tpu.data.prefetch import prefetch_to_devices
    from dss_ml_at_scale_tpu.data.transform import imagenet_transform_spec
    from dss_ml_at_scale_tpu.utils.benchlib import synthetic_image_batch

    n_images = max(4 * batch_size, 256)
    table_path = _write_jpeg_table(
        Path(tmpdir) / "bench_imagenet",
        n_images=n_images,
        source_size=source_size,
    )
    spec = imagenet_transform_spec(resize=image + image // 8, crop=image)
    state = task.init_state(
        jax.random.key(0),
        _chw(synthetic_image_batch(batch_size, image, num_classes=1000)),
    )
    with batch_loader(
        table_path,
        batch_size=batch_size,
        num_epochs=None,  # infinite stream; the step count draws the window
        workers_count=workers,
        results_queue_size=8,
        transform_spec=spec,
    ) as reader:
        batches = prefetch_to_devices(iter(reader), depth=2)
        for _ in range(2):  # warmup: fill prefetch + first dispatch
            state, metrics = train_step(state, next(batches))
        float(metrics["train_loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = train_step(state, next(batches))
        float(metrics["train_loss"])
        dt = time.perf_counter() - t0
    return batch_size * steps / dt


def child_main() -> None:
    result = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": 0.0,
    }
    try:
        import jax

        if os.environ.get(_FORCE_CPU_ENV):
            # Env-var JAX_PLATFORMS is overridden by the accelerator plugin
            # in this image; the in-process config update is what sticks.
            jax.config.update("jax_platforms", "cpu")

        platform = jax.devices()[0].platform
        on_accel = platform != "cpu"
        result["platform"] = platform
        result["device"] = jax.devices()[0].device_kind

        from dss_ml_at_scale_tpu.utils.benchlib import build_resnet_task

        # Reference per-rank batch is 212 (deep_learning/2...py:342); bf16
        # ResNet-50 at 212×224×224 fits a v5e chip.
        batch = 212 if on_accel else 8
        image = 224 if on_accel else 64
        steps = 10 if on_accel else 2

        task = build_resnet_task(num_classes=1000, on_accel=on_accel)
        train_step, ips = _bench_compute(jax, task, batch, image, steps)
        result.update(
            value=round(ips, 2),
            unit=f"images/sec (batch {batch}, {jax.devices()[0].device_kind})",
            vs_baseline=round(ips / A100_IMG_PER_SEC, 4),
        )

        # -- end-to-end input pipeline (the track-A thesis) -----------------
        import tempfile

        try:
            workers = min(8, os.cpu_count() or 2)
            with tempfile.TemporaryDirectory() as tmpdir:
                e2e_ips = _bench_pipeline(
                    jax, train_step, task,
                    batch_size=batch, image=image,
                    source_size=image + image // 4,
                    steps=steps, workers=workers, tmpdir=tmpdir,
                )
            result["pipeline"] = {
                "e2e_images_per_sec": round(e2e_ips, 2),
                "input_stall_fraction": round(max(0.0, 1.0 - e2e_ips / ips), 4)
                if ips > 0 else None,
                "step_time_ratio_vs_synthetic": round(ips / e2e_ips, 4)
                if e2e_ips > 0 else None,
                "reader_workers": workers,
                "host_cores": os.cpu_count(),
            }
        except Exception:
            result["pipeline"] = {"error": traceback.format_exc(limit=5)}
    except Exception:
        note = traceback.format_exc(limit=5)
        result["note"] = (result.get("note", "") + " | " + note).strip(" |")
        result["failed"] = True  # tells the parent to retry / fall back
    print(json.dumps(result))


if __name__ == "__main__":
    if os.environ.get(_CHILD_ENV):
        child_main()
    else:
        parent_main()
    sys.exit(0)

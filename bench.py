"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md); the operative target is
the driver-defined north star — ResNet-50 images/sec/chip vs an
8×A100-class DDP baseline. ``vs_baseline`` is measured throughput divided
by A100_IMG_PER_SEC (a public ~A100 ResNet-50 mixed-precision per-GPU
figure), so 1.0 == per-chip parity with the reference-class hardware.

Runs on whatever jax.devices() provides: the real TPU chip under the
driver, or (fallback) CPU where the number is meaningless but the
harness still exercises end to end.
"""

from __future__ import annotations

import json

A100_IMG_PER_SEC = 2500.0  # ResNet-50 train, mixed precision, per A100


def main() -> None:
    import jax

    from dss_ml_at_scale_tpu.utils.benchlib import (
        build_resnet_task,
        synthetic_image_batch,
        timed_train_steps,
    )

    on_accel = jax.devices()[0].platform != "cpu"
    # Reference per-rank batch is 212 (deep_learning/2...py:342); bf16
    # ResNet-50 at 212×224×224 fits a v5e chip.
    batch = 212 if on_accel else 8
    image = 224 if on_accel else 64
    steps = 10 if on_accel else 2

    task = build_resnet_task(num_classes=1000, on_accel=on_accel)
    host_batch = synthetic_image_batch(batch, image, num_classes=1000)
    state = task.init_state(jax.random.key(0), host_batch)
    device_batch = jax.device_put(host_batch)
    train_step = jax.jit(task.train_step, donate_argnums=0)

    _, dt = timed_train_steps(train_step, state, device_batch, steps)
    ips = batch * steps / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(ips, 2),
                "unit": f"images/sec (batch {batch}, {jax.devices()[0].device_kind})",
                "vs_baseline": round(ips / A100_IMG_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()

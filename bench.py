"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

``value`` is compute-path images/sec/chip on synthetic device-resident
NHWC batches, at the best per-chip batch size from a sweep (the
reference's 212 per rank, ``deep_learning/2...py:342``, plus larger TPU
candidates). Alongside it:

- ``sweep``: images/sec, MFU (model-flops util, XLA-counted flops over
  peak bf16), and HBM-bandwidth utilization per batch size — the
  roofline coordinates that explain the ceiling (ResNet-50 at these
  rates is HBM-bound on v5e, not MXU-bound).
- ``profile``: top-3 HLO categories by device time from a
  ``jax.profiler`` trace of the compiled step (SURVEY.md §5.1).
- ``pipeline``: the numbers the reference's track A is actually about
  (``2...py:246-259,338``): decode backend actually used, decode-only
  throughput (native batch call, no reader), reader-only throughput
  (decode pool + sharding, no training), end-to-end throughput feeding
  the SAME compiled step, the input-stall fraction, and the
  cores-per-chip feeding formula
  ``feeding_cores_per_chip = compute_ips / decode_ips_per_core`` — the
  TPU analogue of the reference's reader memory model (``:338``).
- ``group``: group-parallel SARIMAX at reference scale (G=1000 SKUs,
  ``group_apply/02...py:516-528``) — SKUs/sec through the sharded
  vmapped tuner vs a measured sequential host estimate (run in its own
  watchdog child; see ``_group_child``).
- ``lm``: long-context evidence — flash-attention transformer LM train
  step at seq 2048, tokens/sec + MFU (own watchdog child).

The reference publishes no numbers (BASELINE.md); the operative target is
the driver-defined north star — ResNet-50 images/sec/chip vs an
8×A100-class DDP baseline. ``vs_baseline`` is measured throughput divided
by A100_IMG_PER_SEC (a public ~A100 ResNet-50 mixed-precision per-GPU
figure), so 1.0 == per-chip parity with the reference-class hardware.

Harness discipline: this process NEVER exits non-zero and always prints
exactly one JSON line. The accelerator backend lives behind a remote
tunnel that has been observed to both *fail* transiently and *hang
indefinitely* in ``jax.devices()`` — so a cheap probe child (claim the
device, run one tiny dispatch; 240s watchdog via
``DSST_BENCH_PROBE_TIMEOUT``) gates the expensive
attempts: if the probe can't reach the accelerator twice, every
measurement goes straight to the forced-CPU fallback with the failure
recorded in ``note``. Each measurement itself runs in a watchdog
subprocess with a hard timeout, retried once — a meaningless number
with a diagnosis beats a crash or a stall.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

A100_IMG_PER_SEC = 2500.0  # ResNet-50 train, mixed precision, per A100

# Public peak figures for utilization reporting (per chip).
PEAK_BF16_FLOPS = {"TPU v5 lite": 197e12, "TPU v4": 275e12}
PEAK_HBM_BYTES = {"TPU v5 lite": 819e9, "TPU v4": 1228e9}

_CHILD_ENV = "DSST_BENCH_CHILD"
_MODE_ENV = "DSST_BENCH_MODE"  # "train" (default) | "group" | "lm" | "probe"
_FORCE_CPU_ENV = "DSST_BENCH_FORCE_CPU"
_TIMEOUT_ENV = "DSST_BENCH_TIMEOUT"  # seconds per child attempt
_GROUP_TIMEOUT_ENV = "DSST_BENCH_GROUP_TIMEOUT"
_LM_TIMEOUT_ENV = "DSST_BENCH_LM_TIMEOUT"
_VIT_TIMEOUT_ENV = "DSST_BENCH_VIT_TIMEOUT"
_PROBE_TIMEOUT_ENV = "DSST_BENCH_PROBE_TIMEOUT"
_PARTIAL_ENV = "DSST_BENCH_PARTIAL"  # child progress file (resume + salvage)


def _save_partial(result: dict) -> None:
    """Checkpoint child progress so a watchdog kill loses nothing.

    Published durably after every completed stage via the package's
    crash-only primitive (fsync'd tmp → atomic rename → dir fsync — the
    same ``resilience.durability`` publish every other salvage point
    uses; this file hand-rolled a weaker rename before the bench/
    framework subsumed partial salvage); the parent salvages it when an
    attempt times out, and the next attempt resumes from it (observed
    need: a degraded tunnel where each stage is minutes, so two 900 s
    attempts that each restart from zero never finish)."""
    path = os.environ.get(_PARTIAL_ENV)
    if not path:
        return
    try:
        from dss_ml_at_scale_tpu.resilience.durability import (
            durable_write_json,
        )

        durable_write_json(path, result, kind="bench")
    except OSError:
        pass


def _load_partial() -> dict | None:
    path = os.environ.get(_PARTIAL_ENV)
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _salvage(path: str, key: str):
    """Parent-side reader for a watchdog-killed accelerator child's
    checkpoint: any on-accelerator record with a real measurement under
    ``key`` beats the CPU fallback."""
    try:
        with open(path) as f:
            partial = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if partial.get("platform", "cpu") == "cpu":
        return None
    # `is not None` (not truthiness): a legitimately-zero measurement is
    # still a salvageable on-accelerator record.
    return partial if partial.get(key) is not None else None


# ---------------------------------------------------------------------------
# Parent: watchdog around child processes that do the real work
# ---------------------------------------------------------------------------

def _run_child(mode: str, force_cpu: bool, t: float,
               partial_path: str | None = None):
    env = dict(os.environ, **{_CHILD_ENV: "1", _MODE_ENV: mode})
    if force_cpu:
        env[_FORCE_CPU_ENV] = "1"
    if partial_path:
        env[_PARTIAL_ENV] = partial_path
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=t, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return None, f"timed out after {t:.0f}s (backend hang?)"
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and ("metric" in parsed or mode != "train"):
                if parsed.get("failed"):
                    # The child completed but measured nothing (e.g. a
                    # transient backend error it caught): report it as a
                    # failure so the retry / CPU fallback still runs.
                    note = str(parsed.get("note", ""))[-300:]
                    return None, f"child failed: {note}"
                return parsed, None
        except json.JSONDecodeError:
            continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    return None, f"rc={proc.returncode}, no JSON line; tail: {' | '.join(tail)}"


def _probe_accelerator(notes: list[str]) -> bool:
    """Cheap device-claim probe before committing to long measurement
    attempts: a hung tunnel otherwise burns 2 × timeout before the CPU
    fallback runs (observed: ``jax.devices()`` blocking indefinitely).
    One retry after a lease-recovery pause; worst case 2×240s + 120s
    sleep = 10 min, instead of ~35 for the full attempt ladder.
    """
    # 240s per claim attempt: generous against a slow-but-live tunnel
    # (first init has been observed at 20-40s; minutes means hung), with
    # the same 120s stale-lease recovery pause the train path uses.
    pt = float(os.environ.get(_PROBE_TIMEOUT_ENV, "240"))
    for attempt in (1, 2):
        probe, err = _run_child("probe", force_cpu=False, t=pt)
        platform = probe.get("platform") if probe is not None else None
        if platform == "cpu":
            # The only definitive negative: the default backend IS cpu —
            # no accelerator on this host; retrying cannot change it.
            # Anything else (timeout, crash, failed=True, missing
            # platform) may be a transient tunnel flake and gets a retry.
            notes.append("accelerator probe: platform 'cpu'")
            return False
        if platform is not None:
            return True
        notes.append(
            f"accelerator probe {attempt}: {err or 'no platform in probe'}"
        )
        if attempt == 1:
            # Timeout/crash may be a transient tunnel flake — retry after
            # the observed stale-lease recovery time.
            time.sleep(min(120.0, pt / 2))
    return False


def parent_main() -> None:
    timeout = float(os.environ.get(_TIMEOUT_ENV, "900"))
    notes: list[str] = []

    accelerator_up = _probe_accelerator(notes)

    import tempfile

    scratch = tempfile.mkdtemp(prefix="dsst_bench_")
    train_partial = os.path.join(scratch, "train.json")
    result = None
    train_timed_out = False
    if accelerator_up:
        time.sleep(10.0)  # let the probe's device lease clear
        for attempt in (1, 2):
            result, err = _run_child("train", force_cpu=False, t=timeout,
                                     partial_path=train_partial)
            if result is not None:
                break
            notes.append(f"accelerator attempt {attempt}: {err}")
            train_timed_out = train_timed_out or "timed out" in err
            if attempt == 1:
                # A child killed mid-claim leaves a stale device lease
                # behind the tunnel; observed recovery takes minutes.
                time.sleep(120.0 if "timed out" in err else 5.0)
        if result is None:
            result = _salvage(train_partial, "value")
            if result is not None:
                notes.append(
                    "train attempts watchdog-killed; salvaged on-chip "
                    "partial results (sections may be incomplete)"
                )

    if result is None:
        result, err = _run_child("train", force_cpu=True, t=min(timeout, 300.0))
        if result is not None:
            notes.append("fell back to cpu — number is a harness check only")
        else:
            notes.append(f"cpu fallback: {err}")
            result = {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": 0.0,
                "unit": "images/sec",
                "vs_baseline": 0.0,
            }
    result.setdefault("metric", "resnet50_train_images_per_sec_per_chip")

    # Group-parallel bench rides its own child + timeout so a slow panel
    # compile can never starve the headline measurement.
    gt = float(os.environ.get(_GROUP_TIMEOUT_ENV, "900"))
    group_partial = os.path.join(scratch, "group.json")
    group = gerr = None
    if accelerator_up:
        if train_timed_out:
            # Only a killed TRAIN child leaves a fresh stale lease; a
            # probe timeout followed by clean train runs already cleared.
            time.sleep(120.0)
        group, gerr = _run_child("group", force_cpu=False, t=gt,
                                 partial_path=group_partial)
        if group is None:
            group = _salvage(group_partial, "skus_per_sec")
            if group is not None:
                group["note"] = (
                    f"{gerr}; salvaged on-chip partial (sequential "
                    "estimate may be missing)"
                )
    if group is None:
        # Accelerator down or the sharded panel failed on it: a scaled-down
        # CPU measurement (smaller G) keeps the group block present and
        # diagnosable rather than absent.
        had_g = "DSST_BENCH_GROUP_G" in os.environ
        os.environ.setdefault("DSST_BENCH_GROUP_G", "32")
        os.environ["DSST_BENCH_GROUP_FAST"] = "1"
        group, cpu_err = _run_child("group", force_cpu=True, t=min(gt, 600.0))
        os.environ.pop("DSST_BENCH_GROUP_FAST", None)
        if not had_g:
            os.environ.pop("DSST_BENCH_GROUP_G", None)
        accel_reason = gerr if gerr else "accelerator probe failed (see note)"
        if group is not None:
            g_note = "cpu liveness fallback" + (
                " at reduced G" if not had_g else ""
            ) + " — numbers not chip-representative"
            group["note"] = (f"{gerr}; " if gerr else "") + g_note
        else:
            group = {"error": f"accelerator: {accel_reason}; cpu: {cpu_err}"}
    result["group"] = group

    def _accel_block(mode, t, salvage_key, prev_err):
        """The attempt → salvage → CPU-fallback → error ladder shared by
        the lm and vit blocks. ``prev_err`` from the preceding block:
        its watchdog kill leaves a stale device lease (see the
        train→group seam), so wait out the observed recovery first."""
        partial = os.path.join(scratch, f"{mode}.json")
        res = err = None
        if accelerator_up:
            if prev_err is not None and "timed out" in str(prev_err):
                time.sleep(120.0)
            res, err = _run_child(mode, force_cpu=False, t=t,
                                  partial_path=partial)
            if res is None:
                res = _salvage(partial, salvage_key)
                if res is not None:
                    res["note"] = f"{err}; salvaged on-chip partial"
        if res is None:
            res, cpu_err = _run_child(mode, force_cpu=True, t=min(t, 300.0))
            if res is not None:
                res["note"] = (
                    (f"{err}; " if err else "")
                    + "cpu liveness fallback — numbers not "
                    "chip-representative"
                )
            else:
                res = {"error": f"accelerator: {err or 'probe failed'}; "
                                f"cpu: {cpu_err}"}
        return res, err

    # Long-context LM block: flash-attention transformer tokens/sec.
    # Same child/watchdog discipline; CPU fallback shrinks the model to a
    # liveness check.
    lm, lerr = _accel_block(
        "lm", float(os.environ.get(_LM_TIMEOUT_ENV, "600")),
        "tokens_per_sec", prev_err=gerr,
    )
    result["lm"] = lm

    # Opt-in ViT-S/16 block (our artifact chain sets DSST_BENCH_VIT=1;
    # the driver's lean run skips it).
    if os.environ.get("DSST_BENCH_VIT"):
        vit, _verr = _accel_block(
            "vit", float(os.environ.get(_VIT_TIMEOUT_ENV, "900")),
            "images_per_sec", prev_err=lerr,
        )
        result["vit"] = vit

    import shutil

    shutil.rmtree(scratch, ignore_errors=True)
    _emit(result, notes)


def _emit(result: dict, notes: list[str]) -> None:
    if notes:
        prior = result.get("note")
        result["note"] = "; ".join(([prior] if prior else []) + notes)
    print(json.dumps(result))



def _peak_device_memory(jax):
    """Peak bytes in use on device 0, where the backend reports it
    (TPU/GPU plugins do; the CPU backend returns None)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            return int(stats.get("peak_bytes_in_use", 0)) or None
    except Exception:
        pass
    return None


def _enable_compile_cache(jax) -> None:
    """Persistent XLA compilation cache shared across bench runs.

    First TPU compile through the tunnel is slow (~20-40s per program,
    observed worse); caching it in-repo means retries, the group child,
    and future rounds replay it from disk instead of spending watchdog
    budget recompiling.
    """
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimization, never a failure


# ---------------------------------------------------------------------------
# Train child: compute sweep + profile + input pipeline
# ---------------------------------------------------------------------------

def _xla_cost(compiled) -> dict:
    """Best-effort XLA cost analysis: {flops_per_step, bytes_per_step}."""
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        return {
            "flops_per_step": float(ca.get("flops", 0.0)),
            "bytes_per_step": float(ca.get("bytes accessed", 0.0)),
        }
    except Exception:
        return {}  # cost analysis is best-effort; throughput still measures


def _bench_compute_at(jax, task, batch_size: int, image: int, steps: int):
    """One sweep point: images/sec + XLA-counted flops/bytes per step.

    Compiles ONCE ahead-of-time and reuses the executable for both the
    cost analysis and the timed steps — the jit-cache path would compile
    a second time, and compiles through this tunnel cost 30-60 s each.
    """
    from dss_ml_at_scale_tpu.utils.benchlib import (
        synthetic_image_batch_device,
        timed_train_steps,
    )

    device_batch = synthetic_image_batch_device(
        batch_size, image, num_classes=1000
    )
    state = task.init_state(jax.random.key(0), device_batch)
    compiled = jax.jit(task.train_step, donate_argnums=0).lower(
        state, device_batch
    ).compile()
    cost = _xla_cost(compiled)
    _, dt = timed_train_steps(compiled, state, device_batch, steps)
    return compiled, batch_size * steps / dt, cost


def _profile_top_categories(jax, train_step, task, batch_size: int, image: int,
                            tmpdir: str, top_k: int = 3):
    """Top HLO categories by device time from a short profiler trace."""
    import collections
    import glob
    import gzip

    from dss_ml_at_scale_tpu.utils.benchlib import (
        synthetic_image_batch_device,
    )

    device_batch = synthetic_image_batch_device(
        batch_size, image, num_classes=1000
    )
    state = task.init_state(jax.random.key(0), device_batch)
    state, m = train_step(state, device_batch)
    jax.block_until_ready(m["train_loss"])
    trace_dir = os.path.join(tmpdir, "trace")
    jax.profiler.start_trace(trace_dir)
    for _ in range(3):
        state, m = train_step(state, device_batch)
    jax.block_until_ready(m["train_loss"])
    jax.profiler.stop_trace()

    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    if not files:
        return None
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    device_pids = {
        e["pid"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "TPU" in e.get("args", {}).get("name", "")
    }
    by_cat = collections.Counter()
    total = 0.0
    for e in events:
        # Op-level events carry hlo_category; step/jit aggregates don't.
        cat = e.get("args", {}).get("hlo_category")
        if e.get("ph") == "X" and e.get("pid") in device_pids and cat:
            by_cat[cat] += e.get("dur", 0.0)
            total += e.get("dur", 0.0)
    if total == 0:
        return None
    return [
        {"category": cat, "device_time_share": round(d / total, 4)}
        for cat, d in by_cat.most_common(top_k)
    ]


def _write_jpeg_table(path, *, n_images: int, source_size: int, seed: int = 0):
    """Synthetic JPEG Delta table shaped like the reference's ImageNet
    ingest (binary ``content`` + int ``label_index``, R1/`1.data-preparation.py`)."""
    import io

    import numpy as np
    import pyarrow as pa
    from PIL import Image

    from dss_ml_at_scale_tpu.data import write_delta

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 1000, n_images)
    jpegs = []
    # Blocky low-frequency content: realistic JPEG entropy (pure noise
    # inflates decode cost; flat color deflates it).
    for _ in range(n_images):
        blocks = rng.uniform(0, 255, (8, 8, 3))
        img = np.kron(blocks, np.ones((source_size // 8, source_size // 8, 1)))
        buf = io.BytesIO()
        Image.fromarray(img.astype(np.uint8)).save(buf, format="JPEG", quality=85)
        jpegs.append(buf.getvalue())
    table = pa.table(
        {
            "content": pa.array(jpegs, type=pa.binary()),
            "label_index": pa.array(labels.astype(np.int64)),
        }
    )
    write_delta(table, path, max_rows_per_file=max(16, n_images // 16))
    return jpegs


def _bench_pipeline(jax, task, compute_ips: float, *,
                    batch_size: int, image: int, source_size: int, steps: int,
                    workers: int, tmpdir: str):
    """Per-stage input-pipeline measurement.

    Stages, each isolating one seam (VERDICT r2 asked for exactly this
    decomposition so environment and engineering stop being conflated):

    1. decode-only: the transform called directly on raw JPEG bytes — no
       reader, no device;
    2. reader-only: Delta table → sharded reader → decode pool → host
       batches — no device;
    3. e2e: the same stream prefetched to device feeding a train step
       specialized to the pipeline's uint8 batches. The stall fraction
       is computed against a compute-only run of THAT executable on a
       device-resident uint8 batch — same program both sides, so
       normalize-in-step cost can never masquerade as input stall.
    """
    from pathlib import Path

    from dss_ml_at_scale_tpu.data import batch_loader
    from dss_ml_at_scale_tpu.data.prefetch import DeviceFeeder
    from dss_ml_at_scale_tpu.data.transform import imagenet_transform_spec
    from dss_ml_at_scale_tpu.utils.benchlib import synthetic_image_batch

    n_images = max(2 * batch_size, 512)
    table_path = Path(tmpdir) / "bench_imagenet"
    jpegs = _write_jpeg_table(
        table_path, n_images=n_images, source_size=source_size
    )
    # uint8 transfer mode: raw quantized bytes through queue + transfer
    # (4x less than float32), normalized inside the jitted step — the
    # tightest pipeline configuration, which is what the on-chip
    # stall-fraction target is measured against.
    spec = imagenet_transform_spec(
        resize=image + image // 8, crop=image, output_dtype="uint8"
    )
    host_cores = os.cpu_count() or 1

    out = {
        "decode_backend": spec.backend,
        "image_layout": spec.layout,
        "transfer_dtype": "uint8",
        "reader_workers": workers,
        "host_cores": host_cores,
    }

    # -- stage 1: decode-only ------------------------------------------------
    probe = {"content": jpegs[: min(len(jpegs), 256)],
             "label_index": [0] * min(len(jpegs), 256)}
    spec(dict(probe))  # warm the decode path (thread pool, caches)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        spec(dict(probe))
    decode_dt = (time.perf_counter() - t0) / reps
    decode_ips = len(probe["content"]) / decode_dt
    decode_ips_per_core = decode_ips / host_cores
    out["decode_images_per_sec"] = round(decode_ips, 2)
    out["decode_images_per_sec_per_core"] = round(decode_ips_per_core, 2)
    # The cores-per-chip feeding formula (TPU analogue of the reference's
    # reader memory model, 2...py:338): how many host cores keep one chip
    # of this model fed.
    if decode_ips_per_core > 0 and compute_ips > 0:
        out["feeding_cores_per_chip"] = round(
            compute_ips / decode_ips_per_core, 2
        )

    # -- stage 2: reader-only ------------------------------------------------
    n_reader_batches = max(4, min(steps, n_images // batch_size))
    with batch_loader(
        table_path,
        batch_size=batch_size,
        num_epochs=None,
        workers_count=workers,
        results_queue_size=8,
        transform_spec=spec,
    ) as reader:
        it = iter(reader)
        next(it)  # warm: open files, fill pool
        t0 = time.perf_counter()
        for _ in range(n_reader_batches):
            next(it)
        reader_dt = time.perf_counter() - t0
    out["reader_images_per_sec"] = round(
        batch_size * n_reader_batches / reader_dt, 2
    )

    # -- stage 3: end-to-end -------------------------------------------------
    import numpy as np

    # The stall fraction is a RATIO of two timed loops; at the sweep's
    # step counts (2 on the CPU fallback, 10 on accel) per-step jitter
    # dominates it. Floor the window — both sides of the ratio use the
    # SAME count, so the comparison stays program-identical.
    e2e_steps = max(steps, 16)
    state = task.init_state(
        jax.random.key(0),
        synthetic_image_batch(batch_size, image, num_classes=1000),
    )
    # The compute-phase executable is AOT-specialized to float32
    # synthetic batches; the pipeline feeds uint8 (normalize-in-step), so
    # e2e gets its own jit — and its OWN compute-only reference on a
    # device-resident uint8 batch, so the stall fraction compares the
    # same program against itself and normalize-in-step cost can never
    # read as input stall.
    e2e_step = jax.jit(task.train_step, donate_argnums=0)
    rng = np.random.default_rng(0)
    u8_batch = jax.device_put({
        "image": rng.integers(0, 256, (batch_size, image, image, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, 1000, batch_size).astype(np.int32),
    })
    for _ in range(2):  # warmup incl. the uint8-specialized compile
        state, metrics = e2e_step(state, u8_batch)
    float(metrics["train_loss"])
    t0 = time.perf_counter()
    for _ in range(e2e_steps):
        state, metrics = e2e_step(state, u8_batch)
    float(metrics["train_loss"])
    u8_compute_ips = batch_size * e2e_steps / (time.perf_counter() - t0)
    out["compute_images_per_sec_uint8_step"] = round(u8_compute_ips, 2)

    feeder_depth = 3
    with batch_loader(
        table_path,
        batch_size=batch_size,
        num_epochs=None,  # infinite stream; the step count draws the window
        workers_count=workers,
        results_queue_size=8,
        transform_spec=spec,
    ) as reader:
        # The production input path: a background feeder thread stages +
        # device_puts batches into a bounded queue, so host-side input
        # work overlaps step dispatch instead of serializing with it.
        # Occupancy at each consumer read is the overlap evidence: near
        # depth = input keeps ahead of compute; pinned at 0 with stall
        # accruing = input-bound.
        feeder = DeviceFeeder(iter(reader), depth=feeder_depth, name="e2e")
        try:
            for _ in range(2):  # warmup: fill the feeder + first dispatch
                batch, _ = next(feeder)
                state, metrics = e2e_step(state, batch)
            float(metrics["train_loss"])
            occ = []
            reader_occ = []
            stall = 0.0
            t0 = time.perf_counter()
            for _ in range(e2e_steps):
                s0 = time.perf_counter()
                batch, _ = next(feeder)
                stall += time.perf_counter() - s0
                occ.append(feeder.occupancy)
                reader_occ.append(reader.queue_occupancy)
                state, metrics = e2e_step(state, batch)
            float(metrics["train_loss"])
            dt = time.perf_counter() - t0
        finally:
            feeder.close()
    e2e_ips = batch_size * e2e_steps / dt
    out["e2e_images_per_sec"] = round(e2e_ips, 2)
    out["feeder_depth"] = feeder_depth
    out["feeder_occupancy_mean"] = round(sum(occ) / len(occ), 2)
    out["feeder_occupancy_min"] = min(occ)
    out["feeder_stall_fraction"] = round(stall / dt, 4) if dt > 0 else 0.0
    # Reader-side occupancy locates a stall when one appears: feeder at
    # 0 with the reader queue full = transfer-bound; both at 0 =
    # decode-bound.
    out["reader_queue_occupancy_mean"] = round(
        sum(reader_occ) / len(reader_occ), 2
    )
    if u8_compute_ips > 0:
        out["input_stall_fraction"] = round(
            max(0.0, 1.0 - e2e_ips / u8_compute_ips), 4
        )
    # Accounting: e2e should track min(reader capacity, compute). If it
    # doesn't, the gap is feeder/transfer overhead — record the bound
    # so the artifact is self-explaining.
    out["e2e_bound"] = round(
        min(out["reader_images_per_sec"], u8_compute_ips), 2
    )

    # -- stage 4: flight-recorder overhead -----------------------------------
    # The SAME traced loop twice — recorder disabled (span begin/end
    # events go to the in-memory rings only) vs enabled (write-through
    # JSONL tail, the always-on configuration every tracked run gets) —
    # so the tail's cost is measured against an identical program. The
    # loop carries the production tracing shape: the feeder's per-batch
    # step trace adopted around a train_step span, ~6 recorder events
    # per step across both threads. Budget: overhead < 1% of mean step
    # time.
    from dss_ml_at_scale_tpu import telemetry
    from dss_ml_at_scale_tpu.telemetry import flightrec, tracecontext

    rec_steps = max(e2e_steps, 32)
    tail_path = Path(tmpdir) / "bench_flightrec.jsonl"

    def _traced_loop(st, tail):
        if tail is not None:
            flightrec.enable(tail)
        try:
            with batch_loader(
                table_path,
                batch_size=batch_size,
                num_epochs=None,
                workers_count=workers,
                results_queue_size=8,
                transform_spec=spec,
            ) as reader:
                feeder = DeviceFeeder(
                    iter(reader), depth=feeder_depth, name="e2e"
                )
                try:
                    for _ in range(2):  # warmup: fill feeder, prime tail
                        b, _ = next(feeder)
                        with feeder.last_handoff.activate(), \
                                telemetry.span("train_step"):
                            st, m = e2e_step(st, b)
                    float(m["train_loss"])
                    t0 = time.perf_counter()
                    for _ in range(rec_steps):
                        b, _ = next(feeder)
                        with feeder.last_handoff.activate(), \
                                telemetry.span("train_step"):
                            st, m = e2e_step(st, b)
                    float(m["train_loss"])
                    dt = time.perf_counter() - t0
                finally:
                    feeder.close()
        finally:
            if tail is not None:
                flightrec.disable(tail)
        return st, dt / rec_steps

    state, base_step_s = _traced_loop(state, None)
    state, rec_step_s = _traced_loop(state, tail_path)
    overhead = (rec_step_s - base_step_s) / base_step_s \
        if base_step_s > 0 else 0.0
    out["recorder_off_step_ms"] = round(base_step_s * 1e3, 4)
    out["recorder_on_step_ms"] = round(rec_step_s * 1e3, 4)
    # Jitter can read as negative on a quiet loop; the artifact reports
    # the signed measurement (a large |negative| is as suspicious as a
    # large positive — both mean the window was too noisy).
    out["recorder_overhead_fraction"] = round(overhead, 4)
    out["recorder_overhead_ok"] = bool(overhead < 0.01)
    try:
        out["recorder_tail_bytes"] = tail_path.stat().st_size
        out["recorder_events"] = sum(
            1 for line in tail_path.read_text().splitlines() if line
        )
    except OSError:
        pass

    # -- stage 5: thread-sanitizer overhead -----------------------------------
    # The SAME traced loop as stage 4 (recorder off on both sides), once
    # disarmed — plain threading objects, the production configuration —
    # and once inside a `dsst sanitize` scope, where every lock the
    # feeder/telemetry path creates is interposed and every
    # _guarded_by_lock attribute access is checked. Disarmed is
    # zero-cost BY CONSTRUCTION (nothing is patched; stage 4 already
    # measured this loop), so the artifact's job is the armed cost: the
    # price of running a soak or CI pass with DSST_SANITIZE=1.
    from dss_ml_at_scale_tpu.analysis.sanitize import (
        build_result,
        sanitize_scope,
    )

    state, san_off_step_s = _traced_loop(state, None)
    with sanitize_scope() as san_scope:
        # The feeder (and its locks) are created INSIDE the armed scope
        # — instrumentation covers objects constructed while armed.
        state, san_on_step_s = _traced_loop(state, None)
    san_res = build_result(san_scope, ["bench"], full_run=False)
    san_overhead = (san_on_step_s - san_off_step_s) / san_off_step_s \
        if san_off_step_s > 0 else 0.0
    out["sanitizer_off_step_ms"] = round(san_off_step_s * 1e3, 4)
    out["sanitizer_on_step_ms"] = round(san_on_step_s * 1e3, 4)
    # Signed, like the recorder fraction: a large |negative| means the
    # window was too noisy to trust, which is itself worth seeing.
    out["sanitizer_overhead_fraction"] = round(san_overhead, 4)
    out["sanitizer_locks_instrumented"] = san_res.stats["locks"]
    out["sanitizer_order_edges"] = san_res.stats["edges"]
    out["sanitizer_findings"] = len(san_res.findings)
    return out


def _append_note(result: dict, msg: str) -> None:
    result["note"] = (result.get("note", "") + " | " + msg).strip(" |")


def child_train() -> None:
    # "value" is deliberately ABSENT until the first real measurement:
    # the parent's _salvage treats a present value (even 0.0) as a
    # measurement, so a pre-measurement checkpoint (e.g. the tunnel
    # block) must not carry a placeholder.
    result = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "unit": "images/sec",
    }
    try:
        import jax

        _enable_compile_cache(jax)
        if os.environ.get(_FORCE_CPU_ENV):
            # Env-var JAX_PLATFORMS is overridden by the accelerator plugin
            # in this image; the in-process config update is what sticks.
            jax.config.update("jax_platforms", "cpu")

        platform = jax.devices()[0].platform
        on_accel = platform != "cpu"
        device_kind = jax.devices()[0].device_kind
        result["platform"] = platform
        result["device"] = device_kind

        # Resume from a prior watchdog-killed attempt on the SAME
        # platform: completed sweep points / sections are not redone.
        partial = _load_partial()
        if partial and partial.get("platform") == platform:
            # ("note" deliberately not copied: a stale truncation note
            # would mislabel a resumed sweep that then completed.)
            for k in ("sweep", "unfused", "unfused_headline", "pallas",
                      "pallas_headline", "profile", "pipeline",
                      "peak_device_memory_bytes_sweep", "value",
                      "unit", "vs_baseline", "tunnel"):
                v = partial.get(k)
                if v is None:
                    continue
                if isinstance(v, dict) and set(v) == {"error"}:
                    # A section that only recorded a failure is NOT done:
                    # the retry attempt exists to replace it.
                    continue
                result[k] = v

        # In-band tunnel health: one small h2d transfer, timed.  Small
        # enough to finish even through a degraded tunnel; big enough to
        # expose bulk-transfer collapse (healthy round-3 tunnel moved
        # the old 127 MB batch in seconds).
        if on_accel and "tunnel" not in result:
            import numpy as np

            host_mb = np.ones((1024 * 1024 // 4,), np.float32)  # 1 MB
            t0 = time.perf_counter()
            jax.device_put(host_mb).block_until_ready()
            result["tunnel"] = {
                "h2d_mb_per_s_1mb": round(1.0 / (time.perf_counter() - t0), 2)
            }
            _save_partial(result)

        from dss_ml_at_scale_tpu.utils.benchlib import build_resnet_task

        # HEADLINE-FIRST ordering: with the tunnel's observed pattern of
        # brief live windows, the first ~2 minutes of chip time must
        # produce the one number that matters.  The expected-winning
        # batch (384; override via DSST_BENCH_HEADLINE_BATCH) is
        # measured FIRST and checkpointed, the fused/unfused pair runs
        # immediately after it (see the in-loop pair block), and only
        # then do the remaining candidates run — the reference's 212
        # per-rank batch (deep_learning/2...py:342) plus larger
        # TPU-shaped points; 768 probes the HBM ceiling (an OOM there is
        # caught as a sweep point, not a failure).
        try:
            headline_bs = int(
                os.environ.get("DSST_BENCH_HEADLINE_BATCH", "384")
            )
        except ValueError:
            # A typo'd tuning knob must not zero the headline (the env
            # var reaches every child, so raising here would fail the
            # accelerator attempts AND the CPU fallback identically).
            headline_bs = 384
            _append_note(
                result, "bad DSST_BENCH_HEADLINE_BATCH ignored; using 384"
            )
        batches = (
            [headline_bs] + [b for b in (212, 256, 384, 512, 768)
                             if b != headline_bs]
            if on_accel else [8]
        )
        image = 224 if on_accel else 64
        steps = 10 if on_accel else 2
        peak_flops = PEAK_BF16_FLOPS.get(device_kind)
        peak_bw = PEAK_HBM_BYTES.get(device_kind)

        task = build_resnet_task(num_classes=1000, on_accel=on_accel)
        # Only SUCCESSFUL points count as done: a batch that errored on
        # a transient flake last attempt is dropped here and re-measured.
        sweep = [p for p in result.get("sweep", [])
                 if "images_per_sec" in p]
        done_batches = {p.get("batch") for p in sweep}
        best = None  # (ips, batch, train_step_or_None)
        for p in sweep:  # every entry is a successful point (filter above)
            if best is None or p["images_per_sec"] > best[0]:
                best = (p["images_per_sec"], p["batch"], None)
        t_start = time.perf_counter()
        pair_cache = None  # (batch, step, task, ips) from the in-loop pair
        for bs in batches:
            if bs in done_batches:
                continue
            if sweep and time.perf_counter() - t_start > 300:
                _append_note(result, "sweep truncated by time budget")
                break
            try:
                train_step, ips, cost = _bench_compute_at(
                    jax, task, bs, image, steps
                )
            except Exception as e:
                # One failed point (OOM at a large batch, a tunnel flake)
                # must not discard the points already measured — without
                # this the headline would fall through to the CPU fallback.
                sweep.append({"batch": bs, "error": f"{type(e).__name__}: {e}"[:200]})
                result["sweep"] = sweep
                _save_partial(result)
                continue
            point = {"batch": bs, "images_per_sec": round(ips, 2)}
            steps_per_sec = ips / bs
            if cost.get("flops_per_step") and peak_flops:
                point["mfu"] = round(
                    cost["flops_per_step"] * steps_per_sec / peak_flops, 4
                )
            if cost.get("bytes_per_step") and peak_bw:
                point["hbm_bw_util"] = round(
                    cost["bytes_per_step"] * steps_per_sec / peak_bw, 4
                )
            sweep.append(point)
            if best is None or ips > best[0]:
                best = (ips, bs, train_step)
            # Checkpoint after EVERY point: best-so-far is the headline
            # a watchdog kill salvages.
            result["sweep"] = sweep
            result.update(
                value=round(best[0], 2),
                unit=f"images/sec (batch {best[1]}, {device_kind})",
                vs_baseline=round(best[0] / A100_IMG_PER_SEC, 4),
            )
            _save_partial(result)
            # Fused/unfused pair IMMEDIATELY after the first successful
            # point (normally the headline batch): the measured byte-cut
            # ratio must exist within minutes of a live window, not only
            # if the whole sweep survives it.
            if on_accel and "unfused" not in result:
                try:
                    pair_task = build_resnet_task(
                        num_classes=1000, on_accel=on_accel, fused_bn=False
                    )
                    _pair_step, pair_ips, _ = _bench_compute_at(
                        jax, pair_task, bs, image, steps
                    )
                    result["unfused"] = {
                        "batch": bs,
                        "images_per_sec": round(pair_ips, 2),
                        "fused_speedup": round(ips / pair_ips, 4),
                    }
                    # Deliberately NOT caching the unfused executable:
                    # holding it through the remaining (larger) sweep
                    # points could shift the intentional HBM-ceiling
                    # probe at batch 768.  The rare swap path below
                    # rebuilds it via the compile cache instead.
                    del _pair_step, pair_task
                except Exception as e:
                    result["unfused"] = {
                        "error": f"{type(e).__name__}: {e}"[:200]
                    }
                _save_partial(result)
            # Second lever immediately after the first: the Pallas
            # prologue-fused model (ops/fused_matmul.py) at the same
            # batch.  Measured before the rest of the sweep for the
            # same reason the pair is; swap insurance stays post-sweep.
            if (on_accel and "pallas" not in result
                    and not os.environ.get("DSST_BENCH_NO_PALLAS")):
                try:
                    pl_task = build_resnet_task(
                        num_classes=1000, on_accel=on_accel,
                        fused_bn="pallas",
                    )
                    _pl_step, pl_ips, _ = _bench_compute_at(
                        jax, pl_task, bs, image, steps
                    )
                    result["pallas"] = {
                        "batch": bs,
                        "images_per_sec": round(pl_ips, 2),
                        "speedup_vs_fused": round(pl_ips / ips, 4),
                    }
                    del _pl_step, pl_task  # same HBM discipline as pair
                except Exception as e:
                    result["pallas"] = {
                        "error": f"{type(e).__name__}: {e}"[:200]
                    }
                _save_partial(result)
        if best is None:
            raise RuntimeError(f"every sweep point failed: {sweep}")
        # A prior (killed) attempt may already have swapped the headline
        # to the unfused or pallas program — its sweep point carries bn=.
        unfused_headline = any(p.get("bn") == "unfused" for p in sweep)
        pallas_headline = any(p.get("bn") == "pallas" for p in sweep)
        ips, best_batch, train_step = best
        # The FUSED program's rate at the winning batch, captured BEFORE
        # any headline swap: speedup_vs_fused must always divide by the
        # fused throughput (ADVICE round 5 — after an unfused swap, `ips`
        # holds the unfused rate and would silently inflate/deflate the
        # pallas ratio). On a resumed attempt whose earlier run already
        # swapped, the sweep point preserves the fused rate under
        # images_per_sec_fused.
        fused_best_ips = ips
        for p in sweep:
            if p.get("batch") == best_batch and "images_per_sec_fused" in p:
                fused_best_ips = p["images_per_sec_fused"]
        result["sweep"] = sweep
        bn_tag = (", unfused BN)" if unfused_headline
                  else ", pallas-fused)" if pallas_headline else ")")
        result.update(
            value=round(ips, 2),
            unit=f"images/sec (batch {best_batch}, {device_kind}{bn_tag}",
            vs_baseline=round(ips / A100_IMG_PER_SEC, 4),
        )
        if train_step is None and not (unfused_headline or pallas_headline):
            # Resumed past the winning point: rebuild its executable
            # (persistent compile cache makes this cheap) for the
            # profile / pipeline sections below.
            train_step, _ips_re, _ = _bench_compute_at(
                jax, task, best_batch, image, steps
            )

        import tempfile

        # Peak across the WHOLE sweep — including any failed/OOM'd batch
        # attempts AND the in-loop fused/unfused pair at the headline
        # batch — hence the explicit _sweep suffix; it is the process's
        # HBM high-water mark for everything tried so far, not a
        # fused-model-only bound (the headline-first pair run made a
        # pure-fused bound impossible to capture; the honest label
        # changed with it).
        if "peak_device_memory_bytes_sweep" not in result:
            peak = _peak_device_memory(jax)
            if peak is not None:
                result["peak_device_memory_bytes_sweep"] = peak
        _save_partial(result)

        # A resumed attempt whose earlier run already swapped the
        # headline to the unfused/pallas program must rebuild THAT
        # executable for the profile / pipeline sections.
        if on_accel and (unfused_headline or pallas_headline):
            swapped_task = build_resnet_task(
                num_classes=1000, on_accel=on_accel,
                fused_bn=False if unfused_headline else "pallas",
            )
            train_step, _ips_re, _ = _bench_compute_at(
                jax, swapped_task, best_batch, image, steps
            )
            task = swapped_task

        # The sweep runs the fused-BN model (the default); the unfused
        # comparison documents the fused-VJP byte cut as a measured
        # on-chip speedup, not just a cost-analysis claim.  The pair
        # normally already ran in-loop at the headline batch; it is
        # (re)measured here only if missing, or if a DIFFERENT batch won
        # the sweep — so the swap-insurance below always compares fused
        # vs unfused at the winning batch.
        if on_accel:
            pair = result.get("unfused")
            pair_ok = isinstance(pair, dict) and "images_per_sec" in pair
            if pair_ok and pair.get("batch") != best_batch:
                # Keep the early (headline-batch) pair as evidence; the
                # winning-batch pair replaces it as the canonical one.
                result["unfused_headline"] = pair
                pair_ok = False
            if not pair_ok:
                try:
                    unfused_task = build_resnet_task(
                        num_classes=1000, on_accel=on_accel, fused_bn=False
                    )
                    unfused_step, unfused_ips, _ = _bench_compute_at(
                        jax, unfused_task, best_batch, image, steps
                    )
                    result["unfused"] = {
                        "batch": best_batch,
                        "images_per_sec": round(unfused_ips, 2),
                        "fused_speedup": round(ips / unfused_ips, 4),
                    }
                    pair_cache = (best_batch, unfused_step, unfused_task,
                                  unfused_ips)
                    pair_ok = True
                except Exception as e:
                    result["unfused"] = {
                        "error": f"{type(e).__name__}: {e}"[:200]
                    }
                _save_partial(result)
            if pair_ok:
                unfused_ips = result["unfused"]["images_per_sec"]
                if unfused_ips > ips:
                    # Insurance for the driver's one shot: if the fused
                    # path ever regresses on real hardware, the headline
                    # must be the best the framework can do, with the
                    # regression recorded rather than reported as the
                    # result. The downstream profile/pipeline sections
                    # follow the swap so every block of the artifact
                    # describes the SAME (headline) program.
                    if pair_cache is not None and pair_cache[0] == best_batch:
                        _, unfused_step, unfused_task, _ = pair_cache
                    else:
                        # Resumed attempt: rebuild the unfused executable
                        # (persistent compile cache makes this cheap).
                        unfused_task = build_resnet_task(
                            num_classes=1000, on_accel=on_accel,
                            fused_bn=False
                        )
                        unfused_step, _ips_re, _ = _bench_compute_at(
                            jax, unfused_task, best_batch, image, steps
                        )
                    train_step, task, ips = unfused_step, unfused_task, unfused_ips
                    for point in sweep:
                        # The sweep feeds scaling_model.py's step-time
                        # table; the winning point must carry the
                        # headline (unfused) rate, with the fused one
                        # preserved under an explicit key.
                        if point.get("batch") == best_batch and "images_per_sec" in point:
                            point["images_per_sec_fused"] = point["images_per_sec"]
                            point["images_per_sec"] = round(unfused_ips, 2)
                            point["bn"] = "unfused"
                    result.update(
                        value=round(unfused_ips, 2),
                        unit=f"images/sec (batch {best_batch}, "
                        f"{device_kind}, unfused BN)",
                        vs_baseline=round(unfused_ips / A100_IMG_PER_SEC, 4),
                    )
                    _append_note(
                        result,
                        "fused-BN path measured slower than unfused at the "
                        "winning batch; headline, profile, and pipeline all "
                        "use the unfused program",
                    )
                    _save_partial(result)

        # Second-lever swap: if the Pallas prologue-fused program is the
        # fastest at the winning batch, it becomes the headline (and the
        # profile/pipeline program).  Re-measured at best_batch if the
        # in-loop point ran at a different one.
        if on_accel and not os.environ.get("DSST_BENCH_NO_PALLAS"):
            pall = result.get("pallas")
            pall_ok = isinstance(pall, dict) and "images_per_sec" in pall
            if pall_ok and pall.get("batch") != best_batch:
                result["pallas_headline"] = pall
                pall_ok = False
            if not pall_ok and not (isinstance(pall, dict)
                                    and "error" in pall):
                try:
                    pl_task = build_resnet_task(
                        num_classes=1000, on_accel=on_accel,
                        fused_bn="pallas",
                    )
                    _pl_step, pl_ips, _ = _bench_compute_at(
                        jax, pl_task, best_batch, image, steps
                    )
                    result["pallas"] = {
                        "batch": best_batch,
                        "images_per_sec": round(pl_ips, 2),
                        # Against the fused rate captured pre-swap: `ips`
                        # may already hold the unfused headline here.
                        "speedup_vs_fused": round(pl_ips / fused_best_ips, 4),
                    }
                    del _pl_step, pl_task
                    pall_ok = True
                except Exception as e:
                    result["pallas"] = {
                        "error": f"{type(e).__name__}: {e}"[:200]
                    }
                _save_partial(result)
            if pall_ok:
                pl_ips = result["pallas"]["images_per_sec"]
                if pl_ips > ips:
                    pl_task = build_resnet_task(
                        num_classes=1000, on_accel=on_accel,
                        fused_bn="pallas",
                    )
                    pl_step, _ips_re, _ = _bench_compute_at(
                        jax, pl_task, best_batch, image, steps
                    )
                    train_step, task, ips = pl_step, pl_task, pl_ips
                    for point in sweep:
                        if (point.get("batch") == best_batch
                                and "images_per_sec" in point):
                            point.setdefault(
                                "images_per_sec_fused",
                                point["images_per_sec"],
                            )
                            point["images_per_sec"] = round(pl_ips, 2)
                            point["bn"] = "pallas"
                    result.update(
                        value=round(pl_ips, 2),
                        unit=f"images/sec (batch {best_batch}, "
                        f"{device_kind}, pallas-fused)",
                        vs_baseline=round(pl_ips / A100_IMG_PER_SEC, 4),
                    )
                    _append_note(
                        result,
                        "pallas prologue-fused program fastest at the "
                        "winning batch; headline, profile, and pipeline "
                        "all use it",
                    )
                    _save_partial(result)

        with tempfile.TemporaryDirectory() as tmpdir:
            # -- profiler: top device-time categories -----------------------
            if "profile" not in result:
                try:
                    top = _profile_top_categories(
                        jax, train_step, task, best_batch, image, tmpdir
                    )
                    # Empty success still marks the section done, or a
                    # resumed attempt repeats the trace run for nothing.
                    result["profile"] = {"top_hlo_categories": top or []}
                except Exception:
                    result["profile"] = {"error": traceback.format_exc(limit=3)}
                _save_partial(result)

            # -- end-to-end input pipeline (the track-A thesis) --------------
            if "pipeline" not in result:
                try:
                    workers = min(8, os.cpu_count() or 2)
                    result["pipeline"] = _bench_pipeline(
                        jax, task, ips,
                        batch_size=best_batch, image=image,
                        source_size=image + image // 4,
                        steps=steps, workers=workers, tmpdir=tmpdir,
                    )
                except Exception:
                    result["pipeline"] = {"error": traceback.format_exc(limit=5)}
                _save_partial(result)
    except Exception:
        _append_note(result, traceback.format_exc(limit=5))
        result["failed"] = True  # tells the parent to retry / fall back
    result.setdefault("value", 0.0)
    result.setdefault("vs_baseline", 0.0)
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# Group child: per-SKU SARIMAX tuning at reference scale (G=1000)
# ---------------------------------------------------------------------------

def child_group() -> None:
    """SKUs/sec for the sharded vmapped fit-tune-score panel at G=1000.

    The reference tutorial runs 50 groups as 50 Spark tasks and its prose
    claims thousands (``group_apply/02...py:516-528``); this measures the
    claim: 1000 synthetic SKUs × 157 weeks through
    ``tune_and_forecast_panel`` (max_evals=10), against a sequential
    host-path estimate measured on a 4-SKU sample.
    """
    result: dict = {"n_groups": 0, "failed": False}
    try:
        import numpy as np
        import pandas as pd

        import jax

        _enable_compile_cache(jax)
        if os.environ.get(_FORCE_CPU_ENV):
            jax.config.update("jax_platforms", "cpu")

        result["platform"] = jax.devices()[0].platform
        result["device"] = jax.devices()[0].device_kind

        from dss_ml_at_scale_tpu.ops import SarimaxConfig
        from dss_ml_at_scale_tpu.runtime import make_mesh
        from dss_ml_at_scale_tpu.workloads.forecasting import (
            EXO_FIELDS,
            add_exo_variables,
            tune_and_forecast_panel,
        )

        # Synthetic panel at reference scale: G SKUs × 157 weekly points.
        # (G overridable for harness smoke tests on CPU; FAST shrinks the
        # whole problem so the forced-CPU diagnostic path finishes on a
        # 1-core host — its numbers are a liveness check, not a result.)
        fast = bool(os.environ.get("DSST_BENCH_GROUP_FAST"))
        G = int(os.environ.get("DSST_BENCH_GROUP_G", "1000"))
        weeks = 40 if fast else 157
        max_evals = 2 if fast else 10
        rng = np.random.default_rng(0)
        dates = pd.date_range("2020-01-06", periods=weeks, freq="W-MON")
        rows = []
        for g in range(G):
            level = rng.uniform(20, 80)
            noise = rng.normal(0, 3.0, weeks)
            demand = np.maximum(
                level + np.cumsum(rng.normal(0, 1.0, weeks)) * 0.5 + noise, 0.0
            )
            rows.append(
                pd.DataFrame(
                    {
                        "Product": f"P{g % 5}",
                        "SKU": f"P{g % 5}_{g:04d}",
                        "Date": dates,
                        "Demand": demand,
                    }
                )
            )
        panel = add_exo_variables(pd.concat(rows, ignore_index=True))
        cfg = SarimaxConfig(k_exog=len(EXO_FIELDS), max_iter=40 if fast else 200)
        if fast:
            # Liveness-check geometry: small orders keep the padded
            # state dim (and the CPU compile) tiny.
            import dataclasses

            cfg = dataclasses.replace(cfg, max_p=1, max_d=1, max_q=1)

        print(f"group bench: panel built ({G} SKUs)", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        out = tune_and_forecast_panel(
            panel, max_evals=max_evals, forecast_horizon=20 if fast else 40, rstate=123,
            mesh=make_mesh(), cfg=cfg,
        )
        wall = time.perf_counter() - t0
        print(f"group bench: panel tuned in {wall:.0f}s", file=sys.stderr, flush=True)
        groups_done = out.groupby(["Product", "SKU"]).ngroups
        result.update(
            n_groups=int(groups_done),
            weeks=weeks,
            max_evals=max_evals,
            wall_seconds=round(wall, 1),
            skus_per_sec=round(groups_done / wall, 2),
        )
        peak = _peak_device_memory(jax)
        if peak is not None:
            result["peak_device_memory_bytes"] = peak
        _save_partial(result)

        # Sequential estimate: the applyInPandas-style host path (same
        # kernels, one group per launch, ``group_apply`` inline executor)
        # measured on a small sample and extrapolated to G — what the
        # workload costs WITHOUT the batched vmapped restructuring.
        # Skipped in fast mode: the comparison is the accelerator story,
        # and per-group host fits dominate the 1-core fallback budget.
        if fast:
            print(json.dumps(result))
            return
        from dss_ml_at_scale_tpu.parallel.group_apply import group_apply
        from dss_ml_at_scale_tpu.workloads.forecasting import (
            build_tune_and_score_model,
        )

        sample_skus = sorted(panel["SKU"].unique())[:4]
        sample = panel[panel["SKU"].isin(sample_skus)]
        t0 = time.perf_counter()
        group_apply(
            sample, ["Product", "SKU"],
            lambda g: build_tune_and_score_model(g, max_evals=max_evals, cfg=cfg),
            executor="inline",
        )
        seq_wall = time.perf_counter() - t0
        est_total = seq_wall / len(sample_skus) * G
        result["sequential_sample_skus"] = len(sample_skus)
        result["sequential_est_seconds_for_g"] = round(est_total, 1)
        result["speedup_vs_sequential_est"] = round(est_total / wall, 2)
        # The reference's actual execution shape: 50 groups as Spark
        # tasks over 2 single-core workers (``group_apply/02...py:
        # 516-528``; cluster config in the tutorial).  Modeled with the
        # measured per-SKU host-path cost — i.e. granting the reference
        # our kernels — against this panel's wall-clock for the SAME
        # 50-SKU slice.  The one-XLA-launch-vs-many-tasks thesis,
        # quantified.
        per_sku_seq = seq_wall / len(sample_skus)
        result["reference_shape_model"] = {
            "shape": "50 groups / 2 workers (applyInPandas-style)",
            "modeled_seconds": round(per_sku_seq * 50 / 2, 1),
            "panel_seconds_for_50": round(wall * 50 / groups_done, 1),
            "speedup": round(
                (per_sku_seq * 50 / 2) / (wall * 50 / groups_done), 2
            ),
        }
    except Exception:
        result["failed"] = True
        result["note"] = traceback.format_exc(limit=5)
    print(json.dumps(result))


def child_lm() -> None:
    """Long-context LM block: flash-attention transformer tokens/sec.

    The framework claims long-context as first-class (ring/flash
    attention, SURVEY.md §5.7); this records the single-chip evidence: a
    causal transformer LM train step at seq 2048 with the Pallas flash
    kernel, tokens/sec + XLA-counted MFU. Off-accelerator it shrinks to
    a liveness check on the reference attention (the flash kernel would
    run in Pallas interpret mode — correctness-only speed).
    """
    result: dict = {"failed": False}
    try:
        import numpy as np

        import jax
        import jax.numpy as jnp
        import optax

        _enable_compile_cache(jax)
        if os.environ.get(_FORCE_CPU_ENV):
            jax.config.update("jax_platforms", "cpu")

        device_kind = jax.devices()[0].device_kind
        on_accel = jax.devices()[0].platform != "cpu"
        result["platform"] = jax.devices()[0].platform
        result["device"] = device_kind

        from dss_ml_at_scale_tpu.models import TransformerLM, next_token_loss
        from dss_ml_at_scale_tpu.utils.benchlib import timed_train_steps

        if on_accel:
            cfg = dict(vocab_size=8192, dim=1024, num_heads=8, num_layers=4,
                       max_seq=2048, attention="flash", dtype=jnp.bfloat16)
            batch, steps = 8, 10
        else:
            cfg = dict(vocab_size=128, dim=64, num_heads=4, num_layers=1,
                       max_seq=256, attention="reference", dtype=jnp.float32)
            batch, steps = 2, 2
        seq = cfg["max_seq"]
        result.update(
            seq_len=seq, batch=batch, dim=cfg["dim"],
            num_layers=cfg["num_layers"], attention=cfg["attention"],
        )

        model = TransformerLM(**cfg)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(
                0, cfg["vocab_size"], (batch, seq)
            ),
            jnp.int32,
        )
        params = model.init(jax.random.key(0), tokens)
        tx = optax.adam(3e-4)
        opt = tx.init(params)

        def train_step(state, tokens):
            params, opt = state
            loss, grads = jax.value_and_grad(
                lambda p: next_token_loss(model.apply(p, tokens), tokens)
            )(params)
            updates, opt = tx.update(grads, opt)
            return (optax.apply_updates(params, updates), opt), {
                "train_loss": loss
            }

        compiled = jax.jit(train_step, donate_argnums=0).lower(
            (params, opt), tokens
        ).compile()
        flops_per_step = _xla_cost(compiled).get("flops_per_step", 0.0)
        peak = PEAK_BF16_FLOPS.get(device_kind)

        def _record(tps: float, note: str | None = None) -> None:
            result["tokens_per_sec"] = round(tps, 1)
            if flops_per_step and peak:
                result["mfu"] = round(
                    flops_per_step * (tps / (batch * seq)) / peak, 4
                )
            if note:
                result["window"] = note

        # Coarse window first, checkpointed — so a watchdog kill during
        # the full window still salvages a real on-chip rate.
        state2, dt = timed_train_steps(compiled, (params, opt), tokens, 2)
        _record(batch * seq * 2 / dt, "coarse (2 steps)")
        _save_partial(result)
        _, dt = timed_train_steps(compiled, state2, tokens, steps, warmup=0)
        _record(batch * seq * steps / dt)
        result.pop("window", None)
        _save_partial(result)
    except Exception:
        result["failed"] = True
        result["note"] = traceback.format_exc(limit=5)
    print(json.dumps(result))


def child_vit() -> None:
    """Opt-in second-family block (DSST_BENCH_VIT=1): ViT-S/16 train
    step images/sec + MFU at one batch.

    ViT is the architecture the MXU likes best — pure matmuls, no
    BatchNorm byte traffic — so its on-chip rate next to ResNet-50's
    quantifies how much of the headline gap is the model, not the
    framework. Same watchdog/partial discipline as the other children.
    """
    result: dict = {"failed": False}
    try:
        import jax
        import optax

        _enable_compile_cache(jax)
        if os.environ.get(_FORCE_CPU_ENV):
            jax.config.update("jax_platforms", "cpu")

        device_kind = jax.devices()[0].device_kind
        on_accel = jax.devices()[0].platform != "cpu"
        result["platform"] = jax.devices()[0].platform
        result["device"] = device_kind

        from dss_ml_at_scale_tpu.models import ViT, vit_s16
        from dss_ml_at_scale_tpu.parallel import ClassifierTask
        from dss_ml_at_scale_tpu.utils.benchlib import (
            synthetic_image_batch_device,
            timed_train_steps,
        )

        import jax.numpy as jnp

        if on_accel:
            model, batch_size, image, steps = vit_s16(1000), 256, 224, 10
        else:
            model = ViT(num_classes=10, patch=8, dim=32, depth=2,
                        num_heads=2, dtype=jnp.float32)
            batch_size, image, steps = 8, 32, 2
        result.update(model="vit_s16" if on_accel else "vit_micro",
                      batch=batch_size, image=image)

        task = ClassifierTask(model=model, tx=optax.adam(1e-4))
        device_batch = synthetic_image_batch_device(
            batch_size, image, num_classes=model.num_classes
        )
        state = task.init_state(jax.random.key(0), device_batch)
        compiled = jax.jit(task.train_step, donate_argnums=0).lower(
            state, device_batch
        ).compile()
        flops_per_step = _xla_cost(compiled).get("flops_per_step", 0.0)
        peak = PEAK_BF16_FLOPS.get(device_kind)

        def _record(ips: float, note: str | None = None) -> None:
            result["images_per_sec"] = round(ips, 2)
            if flops_per_step and peak:
                result["mfu"] = round(
                    flops_per_step * (ips / batch_size) / peak, 4
                )
            if note:
                result["window"] = note

        # Coarse window first, checkpointed — a watchdog kill during the
        # full window still salvages a real on-chip rate (same
        # discipline as child_lm).
        state2, dt = timed_train_steps(compiled, state, device_batch, 2)
        _record(batch_size * 2 / dt, "coarse (2 steps)")
        _save_partial(result)
        _, dt = timed_train_steps(compiled, state2, device_batch, steps,
                                  warmup=0)
        _record(batch_size * steps / dt)
        result.pop("window", None)
        _save_partial(result)
    except Exception:
        result["failed"] = True
        result["note"] = traceback.format_exc(limit=5)
    print(json.dumps(result))


def child_probe() -> None:
    """Claim the default backend and report it — nothing else. The parent
    uses this (under a short watchdog) to decide whether the accelerator
    tunnel is alive before spending long measurement attempts on it."""
    result: dict = {}
    try:
        import jax

        _enable_compile_cache(jax)
        if os.environ.get(_FORCE_CPU_ENV):
            # The parent never forces CPU on a probe (its whole job is to
            # reach the accelerator); this is the test harness's handle
            # for exercising the child's JSON contract hermetically.
            jax.config.update("jax_platforms", "cpu")
        dev = jax.devices()[0]
        # One tiny dispatch proves the device executes, not just enumerates.
        import jax.numpy as jnp

        jnp.zeros((8, 8)).sum().block_until_ready()
        result.update(platform=dev.platform, device=dev.device_kind,
                      n=jax.device_count())
    except Exception:
        result.update(failed=True, note=traceback.format_exc(limit=3))
    print(json.dumps(result))


if __name__ == "__main__":
    if os.environ.get(_CHILD_ENV):
        mode = os.environ.get(_MODE_ENV)
        if mode == "group":
            child_group()
        elif mode == "lm":
            child_lm()
        elif mode == "vit":
            child_vit()
        elif mode == "probe":
            child_probe()
        else:
            child_train()
    else:
        parent_main()
    sys.exit(0)

"""Cost-analysis byte/flop comparison: fused-BN vs flax-BN train step,
plus the second image family's roofline coordinates (vit_comparison:
ViT-S/16 vs fused ResNet-50 flops/bytes per image).

Compiles the full ResNet-50 training step both ways and records XLA's
own cost analysis (bytes accessed, flops) — the committed, auditable
form of the fused-VJP byte-cut claim in BASELINE.md. Runs on the CPU
backend (the numbers are lowering-level, not chip measurements; the
on-chip img/s delta is measured separately by bench.py's fused-vs-
unfused pair when an accelerator is reachable — this artifact records
the structural ratio, which is platform-portable because it comes from
the saved-residual structure of the program, not the backend schedule).

    python bench_bytes.py [--batches 8 32] [--out BYTES_MODEL.json]
"""

from __future__ import annotations

import argparse
import json


def _cost_analysis(step, *args) -> dict:
    """Lower+compile ``step`` and extract XLA's cost analysis."""
    ca = step.lower(*args).compile().cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    return {
        "bytes_accessed": int(ca["bytes accessed"]),
        "flops": int(ca["flops"]),
    }


def measure(fused: bool, batch: int, num_classes: int = 1000):
    import jax
    import jax.numpy as jnp

    from dss_ml_at_scale_tpu.models.resnet import ResNet50

    model = ResNet50(
        num_classes=num_classes, fused_bn=fused, dtype=jnp.bfloat16
    )
    x = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
    y = jnp.zeros((batch,), jnp.int32)
    shapes = jax.eval_shape(lambda: model.init(jax.random.key(0), x))
    variables = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )

    def loss_fn(params, bs, x, y):
        logits, upd = model.apply(
            {"params": params, "batch_stats": bs}, x,
            train=True, mutable=["batch_stats"],
        )
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        l = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))
        return l, upd["batch_stats"]

    step = jax.jit(jax.grad(loss_fn, has_aux=True))
    return _cost_analysis(
        step, variables["params"], variables["batch_stats"], x, y
    )


def measure_vit(batch: int, num_classes: int = 1000):
    """Same cost analysis for the ViT-S/16 train step (models/vit.py)."""
    import jax
    import jax.numpy as jnp

    from dss_ml_at_scale_tpu.models.vit import vit_s16

    model = vit_s16(num_classes)
    x = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
    y = jnp.zeros((batch,), jnp.int32)
    shapes = jax.eval_shape(lambda: model.init(jax.random.key(0), x))
    variables = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )

    def loss_fn(params, x, y):
        logits = model.apply({"params": params}, x, train=True)
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

    step = jax.jit(jax.grad(loss_fn))
    return _cost_analysis(step, variables["params"], x, y)


def pallas_structural(image: int = 224) -> dict:
    """Structural HBM-trip model for the SECOND lever: the Pallas
    BN-apply + 1x1-conv prologue fusion (ops/fused_matmul.py).

    CPU cost analysis cannot price this one (interpret-mode Pallas
    lowers to per-grid-step HLO, and the CPU backend cannot compile the
    real kernels), so the committed number is the backend-independent
    activation-trip count at the fused site — the same saved-residual
    arithmetic that underlies the fused-BN row, counted explicitly:

    Per bottleneck block, at the middle-BN -> conv3 site (S spatial
    positions, w mid-channels, 2-byte activations), versus the
    HLO-fused baseline:

      forward:  baseline  y2 r2 (stats+apply), a2 w1, a2 r1 (conv3)
                fused     y2 r2 (stats+prologue)      -> saves 2 trips
      backward: baseline  da2 w1 r2, a2 r1 (dW), y2 r2, dy2 w1 = 7
                fused     gt  w1 r1, y2 r3 (da/finish/dW), dy2 w1 = 6
                                                      -> saves 1 trip
      net: 3 * S * w * 2 bytes per image.

    The block-output BN site is NOT fusable the same way: the residual
    shortcut gives that activation a second consumer, so materialize-
    once-read-twice is already optimal there (counted; not a TODO).

    The decisive number is bench.py's on-chip ``pallas`` point; this
    row records why the cut exists and how large it should be.
    """
    stage_sizes = [3, 4, 6, 3]
    saved = 0
    spatial = image // 4  # after stem conv s2 + maxpool s2
    for i, blocks in enumerate(stage_sizes):
        if i > 0:
            spatial //= 2
        w = 64 * 2 ** i
        saved += blocks * 3 * (spatial * spatial) * w * 2
    return {
        "method": "structural HBM activation-trip count (see docstring)",
        "site": "middle-BN apply fused into conv3 (1x1) as Pallas "
                "prologue",
        "saved_bytes_per_image": saved,
        "saved_mb_per_image": round(saved / 2**20, 2),
        "note": "decisive measurement = bench.py 'pallas' point on chip",
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, nargs="+", default=[8, 32])
    ap.add_argument("--out", default="BYTES_MODEL.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    rows = []
    for batch in args.batches:
        plain = measure(False, batch)
        fused = measure(True, batch)
        rows.append(
            {
                "batch": batch,
                "unfused": plain,
                "fused": fused,
                "bytes_ratio": round(
                    fused["bytes_accessed"] / plain["bytes_accessed"], 4
                ),
                "flops_ratio": round(fused["flops"] / plain["flops"], 4),
            }
        )

    # Second-family roofline coordinates: ViT-S/16 vs fused ResNet-50
    # flops/bytes per image. (Measured outcome at batch 32: arithmetic
    # intensities are comparable — 15.3 vs 17.6 flops/byte, ViT's f32
    # attention softmax costs bytes — and ViT-S/16 spends ~1.2x MORE
    # flops per image (30.1 vs 24.3 GF, 2-flops-per-MAC convention);
    # the on-chip img/s pair in bench.py's vit block is the ground
    # truth for throughput.)
    vb = args.batches[-1]
    vit = measure_vit(vb)
    r50 = rows[-1]["fused"]
    vit_cmp = {
        "batch": vb,
        "vit_s16": vit,
        "resnet50_fused": r50,
        "flops_per_image": {
            "vit_s16": round(vit["flops"] / vb),
            "resnet50": round(r50["flops"] / vb),
        },
        "bytes_per_image": {
            "vit_s16": round(vit["bytes_accessed"] / vb),
            "resnet50": round(r50["bytes_accessed"] / vb),
        },
        "arithmetic_intensity_flops_per_byte": {
            "vit_s16": round(vit["flops"] / vit["bytes_accessed"], 2),
            "resnet50": round(r50["flops"] / r50["bytes_accessed"], 2),
        },
    }

    result = {
        "metric": "resnet50_train_step_bytes_fused_vs_unfused",
        "platform": "cpu-lowering (XLA cost analysis; structural ratio)",
        "model": "ResNet50 bf16 NHWC, 1000 classes, grad-of-loss train step",
        "rows": rows,
        "headline_bytes_ratio": rows[-1]["bytes_ratio"],
        "pallas_lever": pallas_structural(),
        "vit_comparison": vit_cmp,
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: result[k] for k in ("metric", "headline_bytes_ratio")}
                     | {"rows": [(r["batch"], r["bytes_ratio"]) for r in rows]}))


if __name__ == "__main__":
    main()

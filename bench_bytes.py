"""Cost-analysis byte/flop comparison: fused-BN vs flax-BN train step,
plus the second image family's roofline coordinates (vit_comparison:
ViT-S/16 vs fused ResNet-50 flops/bytes per image).

Compiles the full ResNet-50 training step both ways and records XLA's
own cost analysis (bytes accessed, flops) — the committed, auditable
form of the fused-VJP byte-cut claim in BASELINE.md. Runs on the CPU
backend (the numbers are lowering-level, not chip measurements; the
on-chip img/s delta is measured separately by bench.py's fused-vs-
unfused pair when an accelerator is reachable — this artifact records
the structural ratio, which is platform-portable because it comes from
the saved-residual structure of the program, not the backend schedule).

    python bench_bytes.py [--batches 8 32] [--out BYTES_MODEL.json]
"""

from __future__ import annotations

import argparse
import json


def _cost_analysis(step, *args) -> dict:
    """Lower+compile ``step`` and extract XLA's cost analysis."""
    ca = step.lower(*args).compile().cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    return {
        "bytes_accessed": int(ca["bytes accessed"]),
        "flops": int(ca["flops"]),
    }


def measure(fused: bool, batch: int, num_classes: int = 1000):
    import jax
    import jax.numpy as jnp

    from dss_ml_at_scale_tpu.models.resnet import ResNet50

    model = ResNet50(
        num_classes=num_classes, fused_bn=fused, dtype=jnp.bfloat16
    )
    x = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
    y = jnp.zeros((batch,), jnp.int32)
    shapes = jax.eval_shape(lambda: model.init(jax.random.key(0), x))
    variables = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )

    def loss_fn(params, bs, x, y):
        logits, upd = model.apply(
            {"params": params, "batch_stats": bs}, x,
            train=True, mutable=["batch_stats"],
        )
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        l = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))
        return l, upd["batch_stats"]

    step = jax.jit(jax.grad(loss_fn, has_aux=True))
    return _cost_analysis(
        step, variables["params"], variables["batch_stats"], x, y
    )


def measure_vit(batch: int, num_classes: int = 1000):
    """Same cost analysis for the ViT-S/16 train step (models/vit.py)."""
    import jax
    import jax.numpy as jnp

    from dss_ml_at_scale_tpu.models.vit import vit_s16

    model = vit_s16(num_classes)
    x = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
    y = jnp.zeros((batch,), jnp.int32)
    shapes = jax.eval_shape(lambda: model.init(jax.random.key(0), x))
    variables = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )

    def loss_fn(params, x, y):
        logits = model.apply({"params": params}, x, train=True)
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

    step = jax.jit(jax.grad(loss_fn))
    return _cost_analysis(step, variables["params"], x, y)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, nargs="+", default=[8, 32])
    ap.add_argument("--out", default="BYTES_MODEL.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    rows = []
    for batch in args.batches:
        plain = measure(False, batch)
        fused = measure(True, batch)
        rows.append(
            {
                "batch": batch,
                "unfused": plain,
                "fused": fused,
                "bytes_ratio": round(
                    fused["bytes_accessed"] / plain["bytes_accessed"], 4
                ),
                "flops_ratio": round(fused["flops"] / plain["flops"], 4),
            }
        )

    # Second-family roofline coordinates: ViT-S/16 vs fused ResNet-50
    # flops/bytes per image. (Measured outcome at batch 32: arithmetic
    # intensities are comparable — 15.3 vs 17.6 flops/byte, ViT's f32
    # attention softmax costs bytes — and ViT-S/16 spends ~1.2x MORE
    # flops per image (30.1 vs 24.3 GF, 2-flops-per-MAC convention);
    # the on-chip img/s pair in bench.py's vit block is the ground
    # truth for throughput.)
    vb = args.batches[-1]
    vit = measure_vit(vb)
    r50 = rows[-1]["fused"]
    vit_cmp = {
        "batch": vb,
        "vit_s16": vit,
        "resnet50_fused": r50,
        "flops_per_image": {
            "vit_s16": round(vit["flops"] / vb),
            "resnet50": round(r50["flops"] / vb),
        },
        "bytes_per_image": {
            "vit_s16": round(vit["bytes_accessed"] / vb),
            "resnet50": round(r50["bytes_accessed"] / vb),
        },
        "arithmetic_intensity_flops_per_byte": {
            "vit_s16": round(vit["flops"] / vit["bytes_accessed"], 2),
            "resnet50": round(r50["flops"] / r50["bytes_accessed"], 2),
        },
    }

    result = {
        "metric": "resnet50_train_step_bytes_fused_vs_unfused",
        "platform": "cpu-lowering (XLA cost analysis; structural ratio)",
        "model": "ResNet50 bf16 NHWC, 1000 classes, grad-of-loss train step",
        "rows": rows,
        "headline_bytes_ratio": rows[-1]["bytes_ratio"],
        "vit_comparison": vit_cmp,
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: result[k] for k in ("metric", "headline_bytes_ratio")}
                     | {"rows": [(r["batch"], r["bytes_ratio"]) for r in rows]}))


if __name__ == "__main__":
    main()

"""dss_ml_at_scale_tpu — a TPU-native scale-out ML framework.

A ground-up JAX/XLA/pjit re-design of the capability surface of the
``sebrahimi1988/dss-ml-at-scale`` Databricks tutorial stack (Spark +
Petastorm + PyTorch Lightning DDP + Hyperopt SparkTrials + applyInPandas),
re-architected for TPU hardware:

- ``runtime``   — device-mesh topology, multi-host init, CPU-simulated slices
- ``data``      — sharded Arrow/Parquet streaming loader + Delta-log reader
                  (replaces Petastorm ``make_batch_reader`` + deltalake-rs)
- ``models``    — Flax model zoo (ResNet-50 flagship) + psum-reduced metrics
- ``ops``       — JAX numerical kernels: Kalman/SARIMAX, Holt-Winters, ARMA,
                  vmappable Nelder-Mead (replaces statsmodels in the
                  group-apply track)
- ``parallel``  — data-parallel Trainer, distributed HPO trials executor,
                  group-apply engine (replaces TorchDistributor/DDP,
                  SparkTrials, groupBy().applyInPandas())
- ``hpo``       — TPE + search spaces + fmin (hyperopt-compatible surface)
- ``tracking``  — run/param/metric store (replaces the MLflow wiring)
- ``config``    — dataclass configs + CLI (replaces dbutils.widgets / RUNME)
- ``datagen``   — synthetic demand / BoM / sized-regression generators
- ``ingest``    — image-dataset → Parquet ingestion tooling

Reference capability map: see SURVEY.md §2 at the repo root.
"""

__version__ = "0.1.0"

"""The ``hp.*`` space-construction namespace (hyperopt-compatible names)."""

from __future__ import annotations

from .space import Param


def uniform(label: str, low: float, high: float) -> Param:
    return Param(label, "uniform", (low, high))


def loguniform(label: str, low: float, high: float) -> Param:
    """NOTE: bounds are the *value* bounds, not exponents (unlike hyperopt,
    which takes log-bounds; value bounds read better and convert trivially)."""
    if low <= 0:
        raise ValueError(f"loguniform({label!r}) needs low > 0, got {low}")
    return Param(label, "loguniform", (low, high))


def normal(label: str, mu: float, sigma: float) -> Param:
    return Param(label, "normal", (mu, sigma))


def lognormal(label: str, mu: float, sigma: float) -> Param:
    """exp(Normal(mu, sigma)) — the reference's SVC-C prior
    (``hyperopt/1. hyperopt.py:72``)."""
    return Param(label, "lognormal", (mu, sigma))


def quniform(label: str, low: float, high: float, q: float) -> Param:
    return Param(label, "quniform", (low, high, q))


def qloguniform(label: str, low: float, high: float, q: float) -> Param:
    if low <= 0:
        raise ValueError(f"qloguniform({label!r}) needs low > 0, got {low}")
    return Param(label, "qloguniform", (low, high, q))


def choice(label: str, options) -> Param:
    return Param(label, "choice", (tuple(options),))


def randint(label: str, upper: int) -> Param:
    """Uniform integer in [0, upper) — modeled as a choice over range so
    every value is equally likely (quniform-with-rounding would halve the
    endpoint probabilities)."""
    return Param(label, "choice", (tuple(range(upper)),))


class scope:
    """``scope.int(hp.quniform(...))`` — integer cast marker
    (``group_apply/02...py:254-257``)."""

    @staticmethod
    def int(param: Param) -> Param:
        return Param(param.label, param.kind, param.args, to_int=True)

"""Tree-structured Parzen Estimator suggestion algorithm.

The reference's search algorithm is ``tpe.suggest``
(``hyperopt/1. hyperopt.py:84,94-98``). This is an independent
NumPy implementation of the TPE idea (Bergstra et al. 2011): split
completed trials into "good" (best γ-quantile) and "bad", model each
group's density per parameter with a Parzen (Gaussian-mixture) estimator
in latent space, draw candidates from the good model and keep the one
maximizing good(x)/bad(x) — the expected-improvement surrogate.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from .space import Param, iter_params


@dataclasses.dataclass
class TPE:
    n_startup_trials: int = 10
    gamma: float = 0.25
    n_candidates: int = 24
    prior_weight: float = 1.0

    def suggest(self, space, history, rng: np.random.Generator) -> dict:
        """Propose the next point.

        ``history``: sequence of ``(point_dict, loss)`` for completed
        trials (failed trials excluded by the caller). Non-finite losses
        are additionally dropped here: a single NaN would poison the
        argsort that splits good/bad (NaN compares false with
        everything, so the quantile split becomes arbitrary) and an Inf
        would skew the split point — a diverged trial must not steer
        the surrogate, whatever store produced the history.
        """
        params = iter_params(space)
        history = [
            (point, loss)
            for point, loss in history
            if loss is not None and math.isfinite(loss)
        ]
        if len(history) < self.n_startup_trials:
            return {p.label: p.sample(rng) for p in params}

        losses = np.array([loss for _, loss in history], float)
        n_good = max(1, int(math.ceil(self.gamma * len(losses))))
        good_idx = np.argsort(losses)[:n_good]
        good_mask = np.zeros(len(losses), bool)
        good_mask[good_idx] = True

        out = {}
        for p in params:
            obs = np.array(
                [p.to_latent(point[p.label]) for point, _ in history], float
            )
            good, bad = obs[good_mask], obs[~good_mask]
            if p.kind == "choice":
                out[p.label] = self._suggest_categorical(p, good, bad, rng)
            else:
                out[p.label] = p.from_latent(
                    self._suggest_numeric(p, good, bad, rng)
                )
        return out

    # -- numeric params: Parzen estimator in latent space ----------------

    def _suggest_numeric(
        self, p: Param, good: np.ndarray, bad: np.ndarray, rng
    ) -> float:
        lo, hi = p.latent_bounds
        prior_mu, prior_sigma = self._prior(p)

        good_mix = self._mixture(good, prior_mu, prior_sigma)
        bad_mix = self._mixture(bad, prior_mu, prior_sigma)
        cands = self._sample_mixture(good_mix, lo, hi, rng)
        score_good = self._log_pdf_mixture(cands, good_mix)
        score_bad = self._log_pdf_mixture(cands, bad_mix)
        return float(cands[np.argmax(score_good - score_bad)])

    def _prior(self, p: Param) -> tuple[float, float]:
        lo, hi = p.latent_bounds
        if math.isfinite(lo) and math.isfinite(hi):
            return (lo + hi) / 2.0, (hi - lo)
        # normal/lognormal: latent prior is the declared Gaussian
        return float(p.args[0]), float(p.args[1])

    def _bandwidths(self, mus: np.ndarray, prior_sigma: float) -> np.ndarray:
        """Adaptive per-component widths: distance to neighbouring points,
        floored to keep the mixture from collapsing."""
        if len(mus) == 1:
            return np.array([prior_sigma])
        order = np.argsort(mus)
        sorted_mus = mus[order]
        gaps = np.diff(sorted_mus)
        left = np.concatenate([[gaps[0]], gaps])
        right = np.concatenate([gaps, [gaps[-1]]])
        widths_sorted = np.maximum(left, right)
        floor = prior_sigma / max(10.0, len(mus))
        widths_sorted = np.clip(widths_sorted, floor, prior_sigma)
        widths = np.empty_like(widths_sorted)
        widths[order] = widths_sorted
        return widths

    def _mixture(self, mus, prior_mu, prior_sigma):
        """Observations + prior as one Parzen mixture (mus, sigmas, weights)."""
        bw = self._bandwidths(mus, prior_sigma) if len(mus) else np.empty(0)
        mus_all = np.concatenate([mus, [prior_mu]])
        sigmas_all = np.concatenate([bw, [prior_sigma]])
        weights = np.concatenate([np.ones(len(mus)), [self.prior_weight]])
        return mus_all, sigmas_all, weights / weights.sum()

    def _sample_mixture(self, mix, lo, hi, rng):
        mus_all, sigmas_all, weights = mix
        comp = rng.choice(len(mus_all), size=self.n_candidates, p=weights)
        z = rng.normal(mus_all[comp], sigmas_all[comp])
        return np.clip(z, lo, hi)

    def _log_pdf_mixture(self, x, mix):
        mus_all, sigmas_all, weights = mix
        x = x[:, None]
        log_comp = (
            -0.5 * ((x - mus_all[None, :]) / sigmas_all[None, :]) ** 2
            - np.log(sigmas_all[None, :] * math.sqrt(2 * math.pi))
            + np.log(weights[None, :])
        )
        m = log_comp.max(axis=1, keepdims=True)
        return (m + np.log(np.exp(log_comp - m).sum(axis=1, keepdims=True))).ravel()

    # -- categorical params: smoothed frequency ratio ---------------------

    def _suggest_categorical(self, p: Param, good, bad, rng) -> int:
        n = p.n_choices
        good_counts = np.bincount(good.astype(int), minlength=n) + self.prior_weight
        bad_counts = np.bincount(bad.astype(int), minlength=n) + self.prior_weight
        p_good = good_counts / good_counts.sum()
        p_bad = bad_counts / bad_counts.sum()
        # Sample candidates from the good distribution, keep best ratio.
        cands = rng.choice(n, size=min(self.n_candidates, 4 * n), p=p_good)
        return int(cands[np.argmax(p_good[cands] / p_bad[cands])])


_DEFAULT = TPE()


def tpe_suggest(space, history, rng) -> dict:
    """Default-config TPE (the ``tpe.suggest`` equivalent)."""
    return _DEFAULT.suggest(space, history, rng)


def random_suggest(space, history, rng) -> dict:
    """Pure random search (hyperopt's ``rand.suggest``)."""
    from .space import sample_space

    return sample_space(space, rng)

"""Search-space primitives.

Mirrors the hyperopt ``hp.*`` surface the reference uses:
``hp.lognormal('C', 0, 1.0)`` (``hyperopt/1. hyperopt.py:72``),
``hp.uniform('alpha', 0.0, 10.0)`` (``hyperopt/2...py:48``), and
``scope.int(hp.quniform('p', 0, 4, 1))`` for SARIMAX orders
(``group_apply/02...py:254-257``).

A space is any pytree of dict/list/tuple whose leaves may be
:class:`Param` nodes. Points are flat ``{label: value}`` dicts (same shape
hyperopt returns from ``fmin``); ``space_eval`` substitutes a point back
into the space structure.

Each param defines a bijection to an unconstrained "latent" space where
TPE models densities: uniform→identity, loguniform→log, etc.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np


@dataclasses.dataclass(frozen=True)
class Param:
    label: str
    kind: str  # uniform | loguniform | normal | lognormal | quniform | qloguniform | choice
    args: tuple
    to_int: bool = False

    # -- prior sampling ---------------------------------------------------

    def sample(self, rng: np.random.Generator):
        return self.from_latent(self.sample_latent(rng))

    def sample_latent(self, rng: np.random.Generator) -> float:
        k, a = self.kind, self.args
        if k in ("uniform", "quniform"):
            return float(rng.uniform(a[0], a[1]))
        if k in ("loguniform", "qloguniform"):
            return float(rng.uniform(math.log(a[0]), math.log(a[1])))
        if k == "normal":
            return float(rng.normal(a[0], a[1]))
        if k == "lognormal":
            return float(rng.normal(a[0], a[1]))  # latent is log-value
        if k == "choice":
            return int(rng.integers(len(a[0])))
        raise ValueError(f"unknown param kind {k}")

    # -- latent <-> value -------------------------------------------------

    def from_latent(self, z: float):
        k, a = self.kind, self.args
        if k == "uniform":
            v = float(np.clip(z, a[0], a[1]))
        elif k == "loguniform":
            v = float(np.exp(np.clip(z, math.log(a[0]), math.log(a[1]))))
        elif k == "normal":
            v = float(z)
        elif k == "lognormal":
            v = float(np.exp(z))
        elif k == "quniform":
            v = float(np.clip(round(z / a[2]) * a[2], a[0], a[1]))
        elif k == "qloguniform":
            v = float(np.clip(round(math.exp(z) / a[2]) * a[2], a[0], a[1]))
        elif k == "choice":
            v = int(np.clip(int(round(z)), 0, len(a[0]) - 1))
        else:
            raise ValueError(f"unknown param kind {k}")
        if self.to_int and k != "choice":
            return int(v)
        return v

    def to_latent(self, v) -> float:
        k, a = self.kind, self.args
        if k in ("uniform", "quniform", "normal"):
            return float(v)
        if k in ("loguniform", "qloguniform", "lognormal"):
            return math.log(max(float(v), 1e-300))
        if k == "choice":
            return float(v)
        raise ValueError(f"unknown param kind {k}")

    @property
    def latent_bounds(self) -> tuple[float, float]:
        k, a = self.kind, self.args
        if k in ("uniform", "quniform"):
            return float(a[0]), float(a[1])
        if k in ("loguniform", "qloguniform"):
            return math.log(a[0]), math.log(a[1])
        return -math.inf, math.inf

    @property
    def n_choices(self) -> int | None:
        return len(self.args[0]) if self.kind == "choice" else None

    def resolve(self, index_or_value):
        """Final user-facing value (choice params map index → option)."""
        if self.kind == "choice":
            return self.args[0][int(index_or_value)]
        return index_or_value


# -- traversal ---------------------------------------------------------------


def iter_params(space) -> list[Param]:
    out: dict[str, Param] = {}

    def walk(node):
        if isinstance(node, Param):
            if node.label in out and out[node.label] != node:
                raise ValueError(f"duplicate param label {node.label!r}")
            out[node.label] = node
        elif isinstance(node, dict):
            for v in node.values():
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)

    walk(space)
    return list(out.values())


def sample_space(space, rng: np.random.Generator) -> dict[str, Any]:
    """Sample a point (``{label: value}``) from the prior."""
    return {p.label: p.sample(rng) for p in iter_params(space)}


def space_eval(space, point: dict[str, Any]):
    """Substitute a point into the space structure (hyperopt's space_eval)."""

    def walk(node):
        if isinstance(node, Param):
            return node.resolve(point[node.label])
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(space)

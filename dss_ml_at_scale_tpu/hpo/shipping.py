"""Data-shipping strategies for distributed HPO objectives.

The reference dedicates a whole notebook to this
(``hyperopt/2. hyperopt on diff sizes of data.py``): how training data
reaches distributed trial workers at three size regimes —

1. **≤ ~10 MB: closure capture** (``:69-77``). In this framework trials
   run in-process threads, so closures ship by reference for free; this
   module adds nothing.
2. **~100 MB: broadcast** (``sc.broadcast`` / ``.value``, ``:90-101``).
   Spark needs an explicit broadcast to avoid re-pickling per task; here
   :class:`Broadcast` is a once-per-host handle that multi-host trial
   executors materialize exactly once per process. Cross-host usage:
   define a *module-level* ``Broadcast(factory=...)`` next to a
   module-level objective (see
   ``hpo/objectives.py:REGRESSION_BROADCAST``/``lasso_broadcast``) and
   pass the objective by reference to :class:`~dss_ml_at_scale_tpu.
   parallel.trials.HostTrials` — each worker process imports the module
   and builds the value on its first trial; every later trial on that
   worker shares it. The factory, not the data, is what ships.
3. **≥ ~1 GB: shared filesystem** (npz save/load helpers, ``:114-152``).
   :func:`save_shared` / :func:`load_shared` reproduce the
   ``save_to_dbfs``/``load`` pattern against any mounted path (NFS/GCS
   fuse), with per-process caching so N trials on a host read once.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path

import numpy as np


class Broadcast:
    """Host-level shared handle for medium-sized objects.

    ``Broadcast(factory)`` defers materialization; ``.value`` builds once
    per process (thread-safe) and every trial on the host shares it —
    the moral equivalent of ``sc.broadcast(x).value`` without a JVM.
    """

    def __init__(self, value=None, factory=None):
        if (value is None) == (factory is None):
            raise ValueError("pass exactly one of value / factory")
        self._value = value
        self._factory = factory
        self._lock = threading.Lock()

    @property
    def value(self):
        if self._value is None:
            with self._lock:
                if self._value is None:
                    self._value = self._factory()
        return self._value

    def unpersist(self) -> None:
        """Release the materialized value. Only factory-backed handles can
        rebuild later; a value-backed handle cannot, so refuse rather than
        silently keep (or lose) the data."""
        if self._factory is None:
            raise ValueError(
                "cannot unpersist a value-backed Broadcast (it could never "
                "be rebuilt); construct with factory= to make it releasable"
            )
        with self._lock:
            self._value = None


def broadcast(value) -> Broadcast:
    return Broadcast(value=value)


# -- shared-filesystem regime -------------------------------------------------

_cache: dict[str, dict[str, np.ndarray]] = {}
_cache_lock = threading.Lock()
_key_locks: dict[str, threading.Lock] = {}


def save_shared(path: str | os.PathLike, **arrays: np.ndarray) -> str:
    """Write arrays to a shared location (the ``save_to_dbfs`` analogue)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    np.savez(path, **arrays)
    out = str(path) if str(path).endswith(".npz") else str(path) + ".npz"
    return out


def load_shared(path: str | os.PathLike, cache: bool = True) -> dict[str, np.ndarray]:
    """Load arrays saved by :func:`save_shared`; cached once per process so
    concurrent trials don't re-read gigabytes from the shared FS."""
    key = str(path)
    if not cache:
        with np.load(key) as npz:
            return {name: npz[name] for name in npz.files}
    # Per-key lock held across the read: when N trial threads race on first
    # access, exactly one pays the (multi-GB) I/O and all N share one dict —
    # the whole point of this regime. The global lock only guards the maps.
    with _cache_lock:
        key_lock = _key_locks.setdefault(key, threading.Lock())
    with key_lock:
        with _cache_lock:
            if key in _cache:
                return _cache[key]
        with np.load(key) as npz:
            data = {name: npz[name] for name in npz.files}
        with _cache_lock:
            _cache[key] = data
        return data


def clear_shared_cache() -> None:
    with _cache_lock:
        _cache.clear()
        _key_locks.clear()

"""Module-level demo objectives for distributed HPO.

Remote trial workers resolve objectives by ``module:qualname`` reference
(:func:`dss_ml_at_scale_tpu.parallel.trials.objective_ref`), so sweep
demos and tests need importable functions — the analogue of the
reference's notebook-global ``objective`` that SparkTrials pickles to
executors (``hyperopt/1. hyperopt.py:54-62``).
"""

from __future__ import annotations

from .shipping import Broadcast


def quadratic(args) -> float:
    """Smooth 1-D bowl with minimum at x = 3."""
    return (args["x"] - 3.0) ** 2


def paced_quadratic(args) -> float:
    """Quadratic with a small per-trial sleep (``args['delay']``).

    Chaos tests need a sweep that stays in flight long enough for
    mid-sweep events — a worker dying and coming back, a heartbeat
    re-admission — to land while trials are still being proposed.
    """
    import time

    time.sleep(float(args.get("delay", 0.05)))
    return quadratic(args)


def brittle_quadratic(args) -> float:
    """Quadratic that raises on half its domain — failure-isolation probe."""
    if args["x"] < 0:
        raise RuntimeError(f"objective blew up at x={args['x']}")
    return (args["x"] - 3.0) ** 2


def group_pid_summary(group):
    """Per-group demo fn for ``group_apply(executor="process")``.

    Deliberately GIL-bound (pure-Python loop, a stand-in for a
    statsmodels-style fit) and reports the worker ``pid`` so tests can
    assert the group genuinely ran out-of-process.
    """
    import os

    import pandas as pd

    acc = 0.0
    for i in range(50_000):
        acc += (i % 7) * 0.5
    return pd.DataFrame(
        {
            "SKU": [group["SKU"].iloc[0]],
            "mean": [float(group["Demand"].mean())],
            "pid": [os.getpid()],
        }
    )


def brittle_group_head(group):
    """Group fn that raises for one SKU — per-group failure-isolation probe."""
    if group["SKU"].iloc[0] == "SKU2":
        raise RuntimeError("group blew up")
    return group.head(1)[["SKU"]]


# -- broadcast regime (~100 MB: hyperopt/2...py:90-101) ----------------------
#
# The module-level handle is the cross-host shipping mechanism: workers
# import this module, so referencing the objective by name gives every
# worker process its own lazy Broadcast that materializes exactly once
# there, no matter how many trials land on it (sc.broadcast semantics
# without a JVM). The build counter lets tests prove the once-per-process
# claim from outside.

_BROADCAST_BUILDS = 0


def _regression_broadcast_factory():
    global _BROADCAST_BUILDS
    _BROADCAST_BUILDS += 1
    import os

    from ..datagen.regression import gen_data

    # Default is a sized-down stand-in so the fast suite stays fast; the
    # slow suite sets DSST_BROADCAST_BYTES to run the regime at its real
    # ~100 MB size (reference ``hyperopt/2...py:90-101``).  Deterministic
    # either way, so every worker materializes the same dataset.
    return gen_data(int(os.environ.get("DSST_BROADCAST_BYTES", 1_000_000)))


REGRESSION_BROADCAST = Broadcast(factory=_regression_broadcast_factory)


def lasso_broadcast(args) -> dict:
    """Lasso fit against a per-process-broadcast dataset.

    Result carries the worker pid and the process's factory-build count
    so a sweep can verify one materialization per worker process.
    """
    import os

    from ..datagen.regression import train_and_eval

    result = train_and_eval(REGRESSION_BROADCAST.value, args["alpha"])
    result["pid"] = os.getpid()
    result["broadcast_builds"] = _BROADCAST_BUILDS
    return result


def lasso_shared(args) -> dict:
    """Lasso fit against a shared-FS dataset (the ≥1 GB shipping regime).

    ``args['data_path']`` names an npz written by
    :func:`dss_ml_at_scale_tpu.hpo.shipping.save_shared`; per-process
    caching in ``load_shared`` means N trials on a host read it once.
    """
    from ..datagen.regression import train_and_eval
    from .shipping import load_shared

    arrays = load_shared(args["data_path"])
    data = (
        arrays["X_train"], arrays["X_test"], arrays["y_train"], arrays["y_test"]
    )
    return train_and_eval(data, args["alpha"])

"""``fmin`` driver loop + ``Trials`` stores.

Reference surface: ``fmin(objective, space, algo=tpe.suggest,
max_evals=N, trials=..., rstate=np.random.default_rng(seed))`` returning
the best point dict (``hyperopt/1. hyperopt.py:94-103``,
``group_apply/02...py:461-469``). Objectives return either a bare loss or
``{'loss': x, 'status': STATUS_OK, ...}``.

SparkTrials semantics preserved: a raising objective marks its trial
``fail`` and the sweep continues (per-trial failure isolation,
SURVEY.md §5.3); distributed execution is a ``Trials`` subclass
(:class:`dss_ml_at_scale_tpu.parallel.trials.DeviceTrials`) that overlaps
up to ``parallelism`` evaluations, exactly how SparkTrials rides Spark.
"""

from __future__ import annotations

import time
import traceback
from typing import Any, Callable, Mapping

import numpy as np

from .space import space_eval
from .tpe import tpe_suggest

STATUS_OK = "ok"
STATUS_FAIL = "fail"


class Trials:
    """Sequential trial store + executor (hyperopt's plain ``Trials``)."""

    def __init__(self):
        self.trials: list[dict] = []

    # -- store ------------------------------------------------------------

    @property
    def results(self) -> list[dict]:
        return [t["result"] for t in self.trials]

    @property
    def losses(self) -> list[float | None]:
        return [t["result"].get("loss") for t in self.trials]

    @property
    def best_trial(self) -> dict:
        # Finiteness guard on top of the status filter: NaN poisons
        # min() comparisons (every comparison is False, so whichever
        # trial happens to sit first "wins"), and results recorded by
        # stores that bypass call_with_protocol must not crown a
        # diverged trial.
        ok = [
            t for t in self.trials
            if t["result"].get("status") == STATUS_OK
            and _finite_loss(t["result"].get("loss"))
        ]
        if not ok:
            raise ValueError("no successful trials")
        return min(ok, key=lambda t: t["result"]["loss"])

    def argmin(self) -> dict:
        return dict(self.best_trial["point"])

    def _history(self) -> list[tuple[dict, float]]:
        # Same guard: a non-finite loss must not feed the TPE surrogate
        # (tpe.suggest filters too — defense in depth across stores).
        return [
            (t["point"], t["result"]["loss"])
            for t in self.trials
            if t["result"].get("status") == STATUS_OK
            and _finite_loss(t["result"].get("loss"))
        ]

    def _record(self, tid, point, result, t0) -> None:
        self.trials.append(
            {
                "tid": tid,
                "point": point,
                "result": result,
                "book_time": t0,
                "duration": time.time() - t0,
            }
        )

    # -- execution (overridden by distributed stores) ---------------------

    def run(self, objective, space, algo, max_evals, rng, tracker=None) -> None:
        for tid in range(len(self.trials), max_evals):
            point = algo(space, self._history(), rng)
            t0 = time.time()
            result = _call_objective(objective, space, point)
            self._record(tid, point, result, t0)
            if tracker is not None:
                _log_trial(tracker, tid, point, result)


def _finite_loss(loss) -> bool:
    try:
        return loss is not None and np.isfinite(loss)
    except TypeError:
        return False


def _call_objective(objective, space, point) -> dict:
    return call_with_protocol(objective, space_eval(space, point))


def call_with_protocol(objective, args) -> dict:
    """Invoke ``objective(args)`` under the trial-result protocol.

    Protocol violations (missing/non-numeric loss) fail the TRIAL, not the
    sweep — same isolation as an objective that raises. Shared by local
    executors (post ``space_eval``) and remote trial workers (which
    receive already-evaluated args over the wire).
    """
    try:
        # Objective-side fault site: an injected fault here exercises the
        # permanent-fail path (objective failures are deterministic and
        # must NOT be transport-retried — contrast site rpc.send).
        from ..resilience.faults import maybe_fail

        maybe_fail("trial.evaluate")
        out = objective(args)
        if isinstance(out, Mapping):
            result = dict(out)
            result.setdefault("status", STATUS_OK)
            if result["status"] == STATUS_OK:
                result["loss"] = float(result["loss"])
        else:
            result = {"loss": float(out), "status": STATUS_OK}
        # A diverged objective (NaN/inf loss) must not win argmin — NaN
        # poisons min() comparisons — nor feed the TPE surrogate.
        if result["status"] == STATUS_OK and not np.isfinite(result["loss"]):
            return {"status": STATUS_FAIL, "error": f"non-finite loss {result['loss']}"}
        return result
    except Exception:
        return {"status": STATUS_FAIL, "error": traceback.format_exc()}


def _log_trial(tracker, tid, point, result) -> None:
    metrics = {"trial": float(tid)}
    if result.get("loss") is not None:
        metrics["loss"] = result["loss"]
    tracker.log_metrics(metrics, step=tid)
    tracker.log_params({f"trial_{tid}": point})
    # Intent-log the completed trial (RunStore journals durably): a
    # killed sweep resumes from exactly the journaled trials
    # (`dsst hpo --resume-auto`), re-running only what never committed.
    journal = getattr(tracker, "journal_event", None)
    if journal is not None:
        journal(
            "trial", tid=int(tid), point=dict(point),
            loss=result.get("loss"), status=result.get("status"),
        )


def fmin(
    fn: Callable[[Any], Any],
    space,
    algo=tpe_suggest,
    max_evals: int = 100,
    trials: Trials | None = None,
    rstate: np.random.Generator | int | None = None,
    tracker=None,
    return_argmin: bool = True,
):
    """Minimize ``fn`` over ``space``. Returns the best point dict.

    ``fn`` may be a ``module:qualname`` string only when ``trials`` is an
    executor that ships objectives by reference (``HostTrials``); local
    executors need the callable itself.
    """
    trials = trials if trials is not None else Trials()
    if isinstance(fn, str) and not getattr(trials, "accepts_objective_ref", False):
        raise TypeError(
            f"objective given as string ref {fn!r}, but {type(trials).__name__} "
            "evaluates locally and needs the callable (string refs are for "
            "remote executors like HostTrials)"
        )
    rng = (
        rstate
        if isinstance(rstate, np.random.Generator)
        else np.random.default_rng(rstate)
    )
    trials.run(fn, space, algo, max_evals, rng, tracker=tracker)
    if return_argmin:
        return trials.argmin()
    return trials

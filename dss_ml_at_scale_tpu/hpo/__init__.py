"""Hyperparameter optimization: search spaces, TPE, fmin, trials.

Hyperopt-compatible capability surface (the reference drives hyperopt in
all three tracks: ``hyperopt/1. hyperopt.py``, ``hyperopt/2...py``, and
nested inside the per-SKU UDF in
``group_apply/02_Fine_Grained_Demand_Forecasting.py:435-469``):
``fmin`` + ``tpe.suggest`` + ``hp.*`` spaces + ``Trials`` +
``STATUS_OK`` protocol, with seeded ``rstate``. Distributed execution
(the SparkTrials replacement) lives in
:mod:`dss_ml_at_scale_tpu.parallel.trials` and plugs in as ``trials=``.
"""

from . import hp  # noqa: F401
from .fmin import (  # noqa: F401
    STATUS_FAIL,
    STATUS_OK,
    Trials,
    fmin,
)
from .space import sample_space, space_eval  # noqa: F401
from .tpe import TPE, random_suggest, tpe_suggest  # noqa: F401

"""Achieved-FLOPs/s gauges priced by the audit-pinned cost budgets.

``dsst audit`` already commits a FLOPs budget for every production
entrypoint (``AUDIT_BASELINE.json``, ``programs[name].flops`` — the
XLA-counted cost of the exact compiled program). Multiplying that pin
by a *measured* steps/sec gives an achieved-FLOPs/s figure — and,
divided by the device's public peak, an MFU-style utilization — with
**no new tracing**: the steps/sec comes from measurements the runtime
already makes (a bench scenario's timed repetitions, or the flight
recorder's ``train_step`` spans).

The gauges land on the process-default registry, so any process that
serves ``GET /metrics`` (``dsst serve``) exposes them after publishing.

Honesty contract: the pin prices ONE program. Publish only for
steps/sec measured on the same entrypoint the pin names — the bench
scenarios that opt in (``Scenario.entrypoint``) run the audited
program itself via its registry builder, so the budget and the
measurement describe identical XLA.
"""

from __future__ import annotations

import json
from pathlib import Path

# Public peak bf16 figures per chip (bench.py's roofline table, shared
# here so utilization and the headline sweep price peak identically).
PEAK_BF16_FLOPS = {"TPU v5 lite": 197e12, "TPU v4": 275e12}


def pinned_flops(entrypoint: str,
                 baseline_path: Path | None = None) -> float | None:
    """The audit-committed FLOPs budget of ``entrypoint``, or None when
    the entrypoint is unpinned (or the budget was recorded cost-less)."""
    from ..analysis.audit.core import DEFAULT_AUDIT_BASELINE

    path = DEFAULT_AUDIT_BASELINE if baseline_path is None else baseline_path
    try:
        data = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError):
        return None
    prog = data.get("programs", {}).get(entrypoint)
    if not isinstance(prog, dict):
        return None
    flops = prog.get("flops")
    return float(flops) if flops else None


def publish_achieved(entrypoint: str, steps_per_sec: float, *,
                     device_kind: str | None = None,
                     baseline_path: Path | None = None) -> dict | None:
    """Set the achieved-FLOPs/s (and, when the device's peak is known,
    utilization) gauges for ``entrypoint``; returns the published block
    or None when the entrypoint has no pinned budget."""
    from .. import telemetry

    flops = pinned_flops(entrypoint, baseline_path)
    if flops is None or steps_per_sec <= 0:
        return None
    achieved = flops * steps_per_sec
    telemetry.gauge(
        "entrypoint_achieved_flops_per_sec",
        "measured steps/sec times the audit-pinned FLOPs budget",
        labels=("entrypoint",),
    ).labels(entrypoint=entrypoint).set(achieved)
    block = {
        "entrypoint": entrypoint,
        "steps_per_sec": round(steps_per_sec, 4),
        "flops_per_step": flops,
        "achieved_flops_per_sec": achieved,
        "utilization": None,
    }
    peak = PEAK_BF16_FLOPS.get(device_kind or "")
    if peak:
        util = achieved / peak
        telemetry.gauge(
            "entrypoint_flops_utilization",
            "achieved FLOPs/s over the device's public peak (MFU-style)",
            labels=("entrypoint",),
        ).labels(entrypoint=entrypoint).set(util)
        block["utilization"] = util
    return block


def publish_from_trace(tail_path, entrypoint: str, *,
                       span_name: str = "train_step",
                       device_kind: str | None = None,
                       baseline_path: Path | None = None) -> dict | None:
    """Price an existing flight-recorder tail: ``span_name`` arrival
    rate → steps/sec → :func:`publish_achieved`. No new tracing — the
    recorder was already on.

    Steps/sec is spans over the WALL window (first open to last close),
    not ``1/mean(duration)``: inter-step gaps (data wait — exactly what
    a stalled run has) must depress achieved FLOPs/s, or the
    utilization gauge would read *inflated* on the runs it exists to
    diagnose. A single span has no window and falls back to its own
    duration.
    """
    from ..telemetry import flightrec

    complete, _opens = flightrec.reconstruct(
        flightrec.read_events(tail_path)
    )
    spans = sorted(
        (e for e in complete
         if e.get("name") == span_name and e.get("dur", 0.0) > 0),
        key=lambda e: e.get("ts", 0.0),
    )
    if not spans:
        return None
    window = (spans[-1].get("ts", 0.0) + spans[-1].get("dur", 0.0)
              - spans[0].get("ts", 0.0))
    if window <= 0:
        window = spans[0]["dur"]
    return publish_achieved(
        entrypoint, len(spans) / window, device_kind=device_kind,
        baseline_path=baseline_path,
    )

"""``dsst bench profile``: host spans + device trace on ONE timeline.

``jax.profiler`` answers "what did the device do" (XLA ops, per-core
lanes); the flight recorder answers "what did the runtime do" (feeder
handoffs, step dispatch, with cross-thread flow arrows). Debugging an
input stall or a dispatch gap needs both on the SAME timeline — so this
module runs one scenario under both recorders and merges the results
into a single Perfetto ``trace_event`` file:

- the flight-recorder tail renders through
  :func:`~dss_ml_at_scale_tpu.telemetry.spans.to_perfetto` — lanes
  named after runtime threads, ``ph s/f`` flow arrows intact;
- the ``jax.profiler`` trace's events ride along with their pids
  offset into a dedicated range (no collision with host pids) and
  their clock aligned to wall time when the profiler emitted
  trace-relative timestamps.

When the profiled scenario declares an audited ``entrypoint``, its
``train_step`` spans are also priced into the achieved-FLOPs/s gauges
(:mod:`.mfu`) — the profile run doubles as a utilization reading.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
import time
from pathlib import Path

# Profiler pids land here so device lanes can never collide with host
# process pids in the merged file.
PROFILER_PID_OFFSET = 1 << 20

# Timestamps above this are epoch-anchored microseconds (~year 2001+);
# below, the profiler wrote trace-relative time and needs aligning.
_EPOCH_US_FLOOR = 1e12


def _load_profiler_events(trace_dir: str) -> list[dict]:
    files = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    events: list[dict] = []
    for f in sorted(files):
        try:
            with gzip.open(f, "rt") as fh:
                trace = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        evs = trace.get("traceEvents", [])
        if isinstance(evs, list):
            events.extend(e for e in evs if isinstance(e, dict))
    return events


def _merge_profiler_events(events: list[dict], wall_start_us: float,
                           min_dur_us: float) -> tuple[list[dict], int]:
    """Offset pids into the profiler range, align the clock, and floor
    event durations. The CPU/TPU runtimes emit hundreds of thousands of
    sub-microsecond TraceMes per second of wall time — a merged file
    keeping them all is ~100MB and chokes the viewer, so complete
    events shorter than ``min_dur_us`` are dropped and COUNTED (the
    report and the CLI both surface the number: a silent cap would
    read as full coverage). Metadata rows always survive."""
    xs = [e.get("ts") for e in events
          if e.get("ph") in ("X", "B", "E") and e.get("ts") is not None]
    shift = 0.0
    if xs and min(xs) < _EPOCH_US_FLOOR:
        shift = wall_start_us - min(xs)
    out = []
    dropped = 0
    for e in events:
        if (e.get("ph") == "X" and min_dur_us > 0
                and float(e.get("dur", 0.0) or 0.0) < min_dur_us):
            dropped += 1
            continue
        e2 = dict(e)
        try:
            # pid-less events (clock-sync markers) still get a pid so
            # every profiler event lands in the offset lane range.
            e2["pid"] = int(e2.get("pid", 0)) + PROFILER_PID_OFFSET
        except (TypeError, ValueError):
            e2["pid"] = PROFILER_PID_OFFSET
        if shift and e2.get("ts") is not None:
            try:
                e2["ts"] = float(e2["ts"]) + shift
            except (TypeError, ValueError):
                pass
        if e2.get("ph") == "M" and e2.get("name") == "process_name":
            args = dict(e2.get("args", {}))
            args["name"] = f"jax: {args.get('name', '?')}"
            e2["args"] = args
        out.append(e2)
    return out, dropped


def profile_scenario(name: str, out_path: str | os.PathLike, *,
                     repetitions: int = 1,
                     min_profiler_dur_us: float = 5.0) -> dict:
    """Run ``name`` once in-process under the flight recorder AND a
    ``jax.profiler`` trace; write ONE merged Perfetto file. Returns
    ``{"out", "spans", "flows", "profiler_events",
    "profiler_events_dropped", "mfu"}``. ``min_profiler_dur_us=0``
    keeps every profiler event."""
    import jax

    from ..telemetry import flightrec
    from ..telemetry.spans import load_span_jsonl, to_perfetto
    from . import mfu
    from .core import get_scenario, measure_scenario

    sc = get_scenario(name)
    out_path = Path(out_path)
    with tempfile.TemporaryDirectory(prefix="dsst_bench_prof_") as tmpdir:
        tail = os.path.join(tmpdir, "flightrec.jsonl")
        trace_dir = os.path.join(tmpdir, "jax_trace")
        flightrec.enable(tail)
        wall_start = time.time()
        jax.profiler.start_trace(trace_dir)
        try:
            # warmup=0: a profile wants the trace, not a gated number —
            # tracing the warmup repetition would double the (already
            # enormous) profiler event volume for no fidelity.
            measure_scenario(sc, repetitions=repetitions, warmup=0, env={})
        finally:
            jax.profiler.stop_trace()
            flightrec.disable(tail)

        spans = load_span_jsonl(tail)
        merged = to_perfetto(spans)
        flows = sum(
            1 for e in merged["traceEvents"] if e.get("ph") in ("s", "f")
        )
        profiler_events, dropped = _merge_profiler_events(
            _load_profiler_events(trace_dir), wall_start * 1e6,
            min_profiler_dur_us,
        )
        merged["traceEvents"].extend(profiler_events)

        block = None
        if sc.entrypoint:
            # device_kind makes the utilization-vs-peak half of the
            # gauge reachable on accelerators — the run_bench path
            # passes the same fingerprint field.
            block = mfu.publish_from_trace(
                tail, sc.entrypoint,
                device_kind=jax.devices()[0].device_kind,
            )

    out_path.parent.mkdir(parents=True, exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    return {
        "out": str(out_path),
        "spans": len(spans),
        "flows": flows,
        "profiler_events": len(profiler_events),
        "profiler_events_dropped": dropped,
        "mfu": block,
    }

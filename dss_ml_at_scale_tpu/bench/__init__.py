"""Performance-observability subsystem: ``dsst bench``.

The fourth analysis tier: a scenario registry with noise-aware
measurements (median/MAD over isolated-child repetitions), a committed
environment-fingerprinted ``BENCH_BASELINE.json`` with the same
add/expire/reopen semantics as LINT/AUDIT/SANITIZE, achieved-FLOPs/s
gauges priced against the audit-pinned cost budgets, and a profile
mode that merges the flight-recorder host spans with a
``jax.profiler`` device trace into one Perfetto timeline.
"""

from .core import (
    BENCH_SCHEMA_VERSION,
    DEFAULT_BENCH_BASELINE,
    BenchResult,
    BenchUsageError,
    Metric,
    Scenario,
    environment_fingerprint,
    fingerprint_key,
    get_scenario,
    load_bench_baseline,
    measure_scenario,
    register_scenario,
    resolve_selection,
    run_bench,
    scenario_catalog,
    scenario_names,
    write_bench_baseline,
)

__all__ = [
    "BENCH_SCHEMA_VERSION",
    "BenchResult",
    "BenchUsageError",
    "DEFAULT_BENCH_BASELINE",
    "Metric",
    "Scenario",
    "environment_fingerprint",
    "fingerprint_key",
    "get_scenario",
    "load_bench_baseline",
    "measure_scenario",
    "register_scenario",
    "resolve_selection",
    "run_bench",
    "scenario_catalog",
    "scenario_names",
    "write_bench_baseline",
]

"""Performance-observability framework: scenario registry, baseline, runner.

The repo guards correctness three ways (``dsst lint`` / ``dsst audit`` /
``dsst sanitize``: committed content-addressed baselines, expire
semantics, exit 0/1/2) but performance — the paper's actual thesis —
had no gate: measurement lived in one monolithic ``bench.py`` with no
committed numbers and no regression verdict. This module is the fourth
tier, built on the same idioms:

- **Scenario registry** (:class:`Scenario`, mirroring the audit
  entrypoint registry): each scenario declares its measure function, a
  metric schema with direction (higher/lower-is-better) and per-metric
  noise floors, repetitions/warmup, and a tier (``tier1`` fast CI /
  ``slow`` / ``tpu`` only-on-accelerator). The ``bench-registry`` lint
  rule reconciles declarations against
  ``telemetry.catalog.KNOWN_BENCH_METRICS`` in both directions.
- **Noise-aware measurement** (:mod:`.stats`): warmup discard, N
  repetitions, median + MAD, and a verdict whose tolerance derives from
  the measured dispersion.
- **Committed baseline** (``BENCH_BASELINE.json``): summaries keyed by
  an *environment fingerprint* (platform, device kind+count, jax
  version, host cores) — numbers from a different environment never
  gate. ``--update-baseline --reason`` records entries; a baselined
  scenario that left the registry (or a metric that left its schema)
  is *stale* and FAILS the run, exactly like the other three tiers.
- **Child isolation + durable salvage**: each scenario runs in its own
  subprocess (a hung backend or an OOM kills one scenario, not the
  harness) and checkpoints per-repetition partials through
  :func:`~dss_ml_at_scale_tpu.resilience.durability.durable_write_json`
  — the framework owns what ``bench.py`` hand-rolled as
  ``_save_partial``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Any, Callable, Mapping, Sequence

from . import stats

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BENCH_BASELINE = REPO_ROOT / "BENCH_BASELINE.json"
BENCH_SCHEMA_VERSION = 1

TIERS = ("tier1", "slow", "tpu")

# The audit mesh flag: scenarios that execute audited entrypoints need
# the same >=8-device view ``dsst audit`` multiplexes on CPU hosts.
MESH_FLAG = "--xla_force_host_platform_device_count=8"


class BenchUsageError(Exception):
    """Bad invocation (unknown scenario/tier, missing --reason): exit 2."""


@dataclasses.dataclass(frozen=True)
class Metric:
    """One declared output series of a scenario.

    ``direction`` declares which way is better; ``gate=False`` records
    the metric in artifacts/baselines without ever judging it (signed
    overhead fractions, occupancy gauges — diagnostics, not SLOs);
    ``floor`` is the minimum relative tolerance the verdict allows
    (dispersion can widen the band, never narrow it below this).
    """

    name: str
    unit: str
    direction: str = "higher"
    gate: bool = True
    floor: float = stats.DEFAULT_REL_FLOOR

    def __post_init__(self):
        if self.direction not in ("higher", "lower"):
            raise ValueError(
                f"metric {self.name!r}: direction must be 'higher' or "
                f"'lower', got {self.direction!r}"
            )


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One registered measurement.

    ``setup()`` builds state once per process (compiles, spawns stub
    servers); ``measure(ctx)`` performs ONE repetition and returns
    ``{metric_name: value}`` (plus an optional ``"_extra"`` dict of
    non-gated detail carried into the report verbatim);
    ``teardown(ctx)`` releases what setup built. The framework owns the
    warmup/repetition loop and the per-repetition durable partial.
    ``needs_mesh`` requests the 8-device audit-mesh view in the child.
    ``entrypoint``/``steps_metric`` opt the scenario into the
    achieved-FLOPs/s gauges: the named metric is steps/sec of the named
    audited entrypoint, priced against its audit-pinned cost budget
    (:mod:`.mfu`).
    """

    name: str
    description: str
    tier: str
    metrics: tuple[Metric, ...]
    measure: Callable[[Any], dict]
    setup: Callable[[], Any] | None = None
    teardown: Callable[[Any], None] | None = None
    repetitions: int = 5
    warmup: int = 1
    timeout_s: float = 240.0
    needs_mesh: bool = False
    entrypoint: str | None = None
    steps_metric: str | None = None

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(
                f"scenario {self.name!r}: tier must be one of {TIERS}, "
                f"got {self.tier!r}"
            )
        if self.steps_metric and self.steps_metric not in {
            m.name for m in self.metrics
        }:
            raise ValueError(
                f"scenario {self.name!r}: steps_metric "
                f"{self.steps_metric!r} is not in the metric schema"
            )

    def metric(self, name: str) -> Metric:
        for m in self.metrics:
            if m.name == name:
                return m
        raise KeyError(name)


_SCENARIOS: dict[str, Scenario] = {}


def register_scenario(sc: Scenario) -> Scenario:
    if sc.name in _SCENARIOS:
        raise ValueError(f"duplicate scenario name {sc.name!r}")
    _SCENARIOS[sc.name] = sc
    return sc


def _load_scenarios() -> None:
    # Import for side effect: the module registers its Scenario objects.
    from . import scenarios  # noqa: F401


def scenario_names() -> list[str]:
    _load_scenarios()
    return sorted(_SCENARIOS)


def scenario_catalog() -> list[tuple[str, str, str]]:
    """(name, tier, description) for --list-scenarios and the README."""
    _load_scenarios()
    return [
        (n, _SCENARIOS[n].tier, _SCENARIOS[n].description)
        for n in sorted(_SCENARIOS)
    ]


def get_scenario(name: str) -> Scenario:
    _load_scenarios()
    try:
        return _SCENARIOS[name]
    except KeyError:
        raise BenchUsageError(
            f"unknown scenario {name!r}; known: "
            f"{', '.join(sorted(_SCENARIOS))}"
        ) from None


# -- environment fingerprint --------------------------------------------------


def environment_fingerprint() -> dict:
    """The identity a baseline entry is keyed by: numbers measured on a
    different platform/device-count/jax build must never gate this run."""
    import jax

    dev = jax.devices()[0]
    return {
        "platform": dev.platform,
        "device": dev.device_kind,
        "device_count": jax.device_count(),
        "jax": jax.__version__,
        "cpus": os.cpu_count() or 1,
        "python": f"{sys.version_info.major}.{sys.version_info.minor}",
    }


def fingerprint_key(env: Mapping[str, Any]) -> str:
    parts = (
        str(env.get("platform", "?")),
        str(env.get("device", "?")).replace(" ", "_"),
        f"{env.get('device_count', '?')}dev",
        f"jax{env.get('jax', '?')}",
        f"py{env.get('python', '?')}",
        f"{env.get('cpus', '?')}cpu",
    )
    return ":".join(parts)


# -- baseline -----------------------------------------------------------------


def load_bench_baseline(path: Path) -> dict:
    """``{"entries": {fp_key: {"env": .., "scenarios": {..}}}}``."""
    if not path.exists():
        return {"entries": {}}
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except json.JSONDecodeError as e:
        raise BenchUsageError(f"baseline {path} is not valid JSON: {e}")
    if not isinstance(data, dict) or not isinstance(
        data.get("entries", {}), dict
    ):
        raise BenchUsageError(
            f"baseline {path}: top level and 'entries' must be objects"
        )
    return {"entries": data.get("entries", {})}


def write_bench_baseline(path: Path, result: "BenchResult", old: dict,
                         new_reason: str | None) -> int:
    """Rewrite the current fingerprint's entries to this run's
    summaries. Other fingerprints' entries are preserved verbatim
    (another box's truth); under the current fingerprint, scenarios
    outside this run's selection keep their entries (a subset update
    must not wipe what it never re-measured) and stale entries —
    scenarios that left the registry, metrics that left their schema —
    don't survive. New scenario entries need ``new_reason``."""
    _load_scenarios()
    broken = sorted({
        f["scenario"] for f in result.findings
        if f["kind"] in ("error", "timeout", "no-samples")
    })
    if broken:
        raise BenchUsageError(
            "refusing --update-baseline: scenario(s) "
            f"{', '.join(broken)} measured nothing this run — their "
            "entries would be dropped or pinned on garbage; fix first"
        )
    # A salvaged record (watchdog-killed child, partial repetitions) is
    # fine to REPORT but must never become the committed truth: a
    # median-of-one from a wedged host would silently weaken the gate
    # for every future run.
    salvaged = sorted(
        n for n, r in result.results.items() if r.get("salvaged")
    )
    if salvaged:
        raise BenchUsageError(
            "refusing --update-baseline: scenario(s) "
            f"{', '.join(salvaged)} were salvaged from a killed child — "
            "a degraded run's partial medians must not be pinned; rerun "
            "on a healthy host"
        )
    entries: dict = {k: v for k, v in old.get("entries", {}).items()}
    fp = entries.setdefault(
        result.fingerprint_key, {"env": result.env, "scenarios": {}}
    )
    fp["env"] = result.env
    scen_map = fp.setdefault("scenarios", {})
    # Expire stale ballast under this fingerprint.
    for name in list(scen_map):
        sc = _SCENARIOS.get(name)
        if sc is None:
            del scen_map[name]
            continue
        declared = {m.name for m in sc.metrics}
        mets = scen_map[name].get("metrics", {})
        scen_map[name]["metrics"] = {
            k: v for k, v in mets.items() if k in declared
        }
    added = 0
    for name, res in sorted(result.results.items()):
        summaries = res.get("metrics", {})
        if not summaries:
            continue
        prev = scen_map.get(name, {})
        if str(prev.get("reason", "")).strip():
            reason = prev["reason"]
        else:
            if not (new_reason and new_reason.strip()):
                raise BenchUsageError(
                    f"new baseline entry for scenario {name!r} needs "
                    "--reason TEXT (what run produced these numbers?)"
                )
            reason = new_reason.strip()
            added += 1
        scen_map[name] = {
            "reason": reason,
            "tier": res.get("tier"),
            "recorded": time.strftime("%Y-%m-%d", time.gmtime()),
            "metrics": {
                m: {
                    "median": s["summary"]["median"],
                    "mad": s["summary"]["mad"],
                    "n": s["summary"]["n"],
                    "unit": s.get("unit"),
                    "direction": s.get("direction"),
                }
                for m, s in sorted(summaries.items())
            },
        }
    payload = {
        "_comment": (
            "dsst bench baseline: per-environment-fingerprint robust "
            "summaries (median/MAD/n) of every registered scenario's "
            "metrics. Regenerate with `dsst bench --update-baseline "
            "--reason '...'`; a committed scenario that left the "
            "registry (or a metric that left its schema) goes stale "
            "and FAILS the bench until re-baselined. Entries under a "
            "different fingerprint never gate this host."
        ),
        "version": BENCH_SCHEMA_VERSION,
        "entries": entries,
    }
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n",
                    encoding="utf-8")
    return added


# -- measurement (runs inside the isolated child, or inline) ------------------


def measure_scenario(sc: Scenario, *, repetitions: int | None = None,
                     warmup: int | None = None,
                     partial_path: str | os.PathLike | None = None,
                     env: Mapping[str, Any] | None = None) -> dict:
    """The framework-owned repetition loop for ONE scenario.

    Runs ``setup``, ``warmup + repetitions`` calls of ``measure``,
    discards the warmup, and — after every kept repetition — durably
    checkpoints the partial record so a watchdog kill salvages every
    completed repetition (the bench.py lesson, now behind the
    framework). Returns ``{"scenario", "env", "samples", "extra",
    "completed"}``.
    """
    from ..resilience.durability import durable_write_json

    reps = sc.repetitions if repetitions is None else repetitions
    if reps < 1:
        raise BenchUsageError("repetitions must be >= 1")
    n_warm = sc.warmup if warmup is None else warmup
    declared = {m.name for m in sc.metrics}
    record: dict = {
        "scenario": sc.name,
        "env": dict(env) if env is not None else environment_fingerprint(),
        "samples": {m.name: [] for m in sc.metrics},
        "extra": {},
        "completed": 0,
    }
    ctx = sc.setup() if sc.setup is not None else None
    try:
        raw: list[dict] = []
        for _ in range(n_warm + reps):
            out = dict(sc.measure(ctx))
            extra = out.pop("_extra", None)
            unknown = sorted(set(out) - declared)
            if unknown:
                raise BenchUsageError(
                    f"scenario {sc.name!r} emitted undeclared metric(s) "
                    f"{', '.join(unknown)} — declare them in the schema "
                    "(and telemetry.catalog.KNOWN_BENCH_METRICS)"
                )
            raw.append(out)
            kept = stats.discard_warmup(raw, n_warm)
            if not kept:
                continue  # still inside the warmup window
            if isinstance(extra, dict):
                record["extra"].update(extra)
            record["samples"] = {
                name: [float(r[name]) for r in kept if name in r]
                for name in declared
            }
            record["completed"] = len(kept)
            if partial_path is not None:
                durable_write_json(partial_path, record, kind="bench")
    finally:
        if sc.teardown is not None:
            sc.teardown(ctx)
    return record


# -- the runner ---------------------------------------------------------------


@dataclasses.dataclass
class BenchResult:
    scenarios: list[str]                  # selected
    env: dict
    fingerprint_key: str
    results: dict[str, dict]              # name -> per-scenario report
    findings: list[dict]                  # regression/stale/error/...
    mfu: dict[str, dict]                  # entrypoint -> utilization block

    @property
    def ok(self) -> bool:
        return not self.findings

    @property
    def exit_code(self) -> int:
        return 0 if self.ok else 1

    def render_text(self) -> str:
        lines = []
        for name in self.scenarios:
            res = self.results.get(name)
            if res is None:
                continue
            note = f"  [{res['note']}]" if res.get("note") else ""
            lines.append(f"{name} ({res.get('tier')}){note}")
            for m, s in sorted(res.get("metrics", {}).items()):
                summ = s["summary"]
                v = s.get("verdict", "?")
                extra = ""
                if "rel_change" in s:
                    extra = (f"  {s['rel_change']:+.1%} vs baseline "
                             f"(tol ±{s['tolerance']:.1%})")
                lines.append(
                    f"  {m:<36} {summ['median']:>12.4g} {s.get('unit', ''):<12}"
                    f" ±{summ['mad']:.3g} (n={summ['n']})  {v}{extra}"
                )
        for ent, block in sorted(self.mfu.items()):
            util = block.get("utilization")
            lines.append(
                f"mfu {ent}: {block['achieved_flops_per_sec']:.4g} FLOP/s "
                f"achieved (pinned {block['flops_per_step']:.4g}/step)"
                + (f", {util:.2%} of peak" if util is not None else "")
            )
        for f in self.findings:
            lines.append(
                f"FINDING [{f['kind']}] {f['scenario']}"
                + (f".{f['metric']}" if f.get("metric") else "")
                + f": {f['message']}"
            )
        lines.append(
            f"{len(self.findings)} finding(s) over "
            f"{len(self.results)} scenario(s) "
            f"[fingerprint {self.fingerprint_key}]"
        )
        return "\n".join(lines)

    def render_json(self) -> str:
        return json.dumps({
            "version": BENCH_SCHEMA_VERSION,
            "fingerprint": {"key": self.fingerprint_key, **self.env},
            "scenarios": self.scenarios,
            "results": self.results,
            "mfu": self.mfu,
            "findings": self.findings,
            "counts": {
                "scenarios": len(self.results),
                "regressions": sum(
                    1 for f in self.findings if f["kind"] == "regression"
                ),
                "stale": sum(
                    1 for f in self.findings if f["kind"] == "stale"
                ),
                "errors": sum(
                    1 for f in self.findings
                    if f["kind"] in ("error", "timeout", "no-samples",
                                     "no-baseline")
                ),
            },
            "ok": self.ok,
        }, indent=2)


def resolve_selection(scenarios: Sequence[str] | None,
                      tier: str | None) -> list[str]:
    """Explicit names win; else a tier filter; else everything but the
    accelerator-only tier (the same default an operator box can run)."""
    _load_scenarios()
    if scenarios:
        unknown = sorted(set(scenarios) - set(_SCENARIOS))
        if unknown:
            raise BenchUsageError(
                f"unknown scenario(s) {', '.join(unknown)}; known: "
                f"{', '.join(sorted(_SCENARIOS))}"
            )
        return list(scenarios)
    if tier is not None:
        if tier not in TIERS:
            raise BenchUsageError(
                f"unknown tier {tier!r}; known: {', '.join(TIERS)}"
            )
        names = [n for n, sc in sorted(_SCENARIOS.items())
                 if sc.tier == tier]
        if not names:
            raise BenchUsageError(f"no scenarios registered in tier {tier!r}")
        return names
    return [n for n, sc in sorted(_SCENARIOS.items()) if sc.tier != "tpu"]


def _child_cmd(sc: Scenario, repetitions: int | None,
               partial: str) -> list[str]:
    cmd = [sys.executable, "-m", "dss_ml_at_scale_tpu.bench",
           "--scenario", sc.name, "--partial", partial]
    if repetitions is not None:
        cmd += ["--repetitions", str(repetitions)]
    return cmd


def _run_child(sc: Scenario, repetitions: int | None,
               scratch: Path) -> tuple[dict | None, str | None]:
    """(record, note) — ``record`` is None only when nothing at all was
    measured (the note then carries the diagnosis)."""
    partial = scratch / f"{sc.name}.partial.json"
    env = dict(os.environ)
    if sc.needs_mesh and MESH_FLAG not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + MESH_FLAG).strip()
    try:
        proc = subprocess.run(
            _child_cmd(sc, repetitions, str(partial)),
            env=env, cwd=str(REPO_ROOT), timeout=sc.timeout_s,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        rec = _salvage_partial(partial)
        if rec is not None:
            rec["salvaged"] = True
            return rec, (f"timed out after {sc.timeout_s:.0f}s; salvaged "
                         f"{rec.get('completed', 0)} completed repetition(s)")
        return None, f"timed out after {sc.timeout_s:.0f}s, no partial"
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(parsed, dict) or "scenario" not in parsed:
            continue
        if parsed.get("failed"):
            return None, f"child failed: {str(parsed.get('error', ''))[-400:]}"
        return parsed, None
    rec = _salvage_partial(partial)
    if rec is not None:
        rec["salvaged"] = True
        return rec, (f"child died (rc={proc.returncode}); salvaged "
                     f"{rec.get('completed', 0)} completed repetition(s)")
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    return None, (f"rc={proc.returncode}, no JSON line, no partial; "
                  f"tail: {' | '.join(tail)}")


def _salvage_partial(partial: Path) -> dict | None:
    try:
        rec = json.loads(partial.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError):
        return None
    return rec if rec.get("completed", 0) >= 1 else None


def run_bench(
    scenarios: Sequence[str] | None = None,
    *,
    tier: str | None = None,
    repetitions: int | None = None,
    baseline_path: Path | None = None,
    isolation: bool = True,
    require_baseline: bool = False,
) -> BenchResult:
    """Run the selection; the single entry point the CLI and tier-1
    share. ``isolation=False`` measures inline (tests, ``bench
    profile``) — everything else is identical, including the verdicts.
    ``require_baseline`` turns a gated metric with no committed entry
    under the current fingerprint into a failing finding — the strict
    preflight mode for hosts that must never run ungated.
    """
    from .. import telemetry
    from . import mfu

    if repetitions is not None and repetitions < 1:
        raise BenchUsageError("repetitions must be >= 1")
    names = resolve_selection(scenarios, tier)
    env = environment_fingerprint()
    fp_key = fingerprint_key(env)
    bl_path = (
        DEFAULT_BENCH_BASELINE if baseline_path is None else baseline_path
    )
    baseline = load_bench_baseline(bl_path)
    fp_entry = baseline["entries"].get(fp_key, {})
    bl_scenarios = fp_entry.get("scenarios", {})

    results: dict[str, dict] = {}
    findings: list[dict] = []
    mfu_blocks: dict[str, dict] = {}
    scratch = Path(tempfile.mkdtemp(prefix="dsst_bench_"))
    try:
        for name in names:
            sc = _SCENARIOS[name]
            if isolation:
                record, note = _run_child(sc, repetitions, scratch)
            else:
                try:
                    record, note = measure_scenario(
                        sc, repetitions=repetitions, env=env,
                        partial_path=scratch / f"{name}.partial.json",
                    ), None
                except Exception as e:  # noqa: BLE001 - reported as finding
                    # Includes BenchUsageError from inside a scenario
                    # (an undeclared emitted metric): in child mode that
                    # surfaces as an error finding, and the in-process
                    # mode's verdicts must stay identical — only
                    # pre-run selection/flag errors are exit-2 usage.
                    record, note = None, f"{type(e).__name__}: {e}"
            if record is None:
                findings.append({
                    "kind": "timeout" if "timed out" in (note or "")
                    else "error",
                    "scenario": name, "message": note or "measured nothing",
                })
                continue
            res = _judge_scenario(sc, record, bl_scenarios.get(name),
                                  findings)
            if note:
                res["note"] = note
            if record.get("salvaged"):
                res["salvaged"] = True
            results[name] = res
            if sc.entrypoint and sc.steps_metric:
                summ = res["metrics"].get(sc.steps_metric, {}).get("summary")
                if summ and summ["n"]:
                    block = mfu.publish_achieved(
                        sc.entrypoint, summ["median"],
                        device_kind=env.get("device"),
                    )
                    if block is not None:
                        mfu_blocks[sc.entrypoint] = block
    finally:
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)

    findings.extend(_stale_findings(fp_entry))
    if require_baseline:
        for name, res in sorted(results.items()):
            sc = _SCENARIOS[name]
            for mname, m in sorted(res.get("metrics", {}).items()):
                if sc.metric(mname).gate and m.get("verdict") == \
                        "no-baseline":
                    findings.append({
                        "kind": "no-baseline", "scenario": name,
                        "metric": mname,
                        "message": "gated metric has no committed "
                        f"baseline under {fp_key} — record one "
                        "(dsst bench --update-baseline --reason) "
                        "before gating this host",
                    })
    telemetry.counter(
        "bench_scenarios_total", "scenarios measured by dsst bench"
    ).inc(len(results))
    telemetry.counter(
        "bench_regressions_total",
        "regression verdicts reported by dsst bench",
    ).inc(sum(1 for f in findings if f["kind"] == "regression"))
    return BenchResult(
        scenarios=names,
        env=env,
        fingerprint_key=fp_key,
        results=results,
        findings=findings,
        mfu=mfu_blocks,
    )


def _judge_scenario(sc: Scenario, record: dict, bl_entry: dict | None,
                    findings: list[dict]) -> dict:
    res: dict = {
        "tier": sc.tier,
        "completed": record.get("completed", 0),
        "metrics": {},
    }
    if record.get("extra"):
        res["extra"] = record["extra"]
    bl_metrics = (bl_entry or {}).get("metrics", {})
    for m in sc.metrics:
        samples = record.get("samples", {}).get(m.name, [])
        if not samples:
            findings.append({
                "kind": "no-samples", "scenario": sc.name, "metric": m.name,
                "message": "declared metric produced no samples — the "
                "measure function and the schema disagree",
            })
            continue
        summ = stats.summarize(samples)
        bl = bl_metrics.get(m.name)
        bl_summary = (
            stats.Summary(median=float(bl["median"]),
                          mad=float(bl.get("mad", 0.0)),
                          n=int(bl.get("n", 0)))
            if isinstance(bl, dict) else None
        )
        verdict = stats.classify(
            m.direction, summ, bl_summary, gate=m.gate, floor=m.floor,
        )
        entry = {
            "unit": m.unit,
            "direction": m.direction,
            "summary": summ.to_json(),
            **verdict,
        }
        if bl_summary is not None:
            entry["baseline_median"] = bl_summary.median
        res["metrics"][m.name] = entry
        if verdict["verdict"] == "regression":
            findings.append({
                "kind": "regression", "scenario": sc.name, "metric": m.name,
                "message": (
                    f"{summ.median:.6g} {m.unit} vs baseline "
                    f"{bl_summary.median:.6g} "
                    f"({verdict['rel_change']:+.1%}, tolerance "
                    f"±{verdict['tolerance']:.1%}, "
                    f"{m.direction}-is-better)"
                ),
            })
    return res


def _stale_findings(fp_entry: dict) -> list[dict]:
    """Baseline ballast under the CURRENT fingerprint: a scenario that
    left the registry, or a committed metric that left its scenario's
    schema. Registry membership is static knowledge, so staleness is
    judged on every run regardless of the selection — exactly the
    expire semantics of the other three tiers."""
    out: list[dict] = []
    for name, entry in sorted(fp_entry.get("scenarios", {}).items()):
        sc = _SCENARIOS.get(name)
        if sc is None:
            out.append({
                "kind": "stale", "scenario": name,
                "message": "baselined scenario is no longer registered — "
                "remove the entry (dsst bench --update-baseline)",
            })
            continue
        declared = {m.name for m in sc.metrics}
        for mname in sorted(entry.get("metrics", {})):
            if mname not in declared:
                out.append({
                    "kind": "stale", "scenario": name, "metric": mname,
                    "message": "baselined metric left the scenario's "
                    "schema — re-baseline to shed it",
                })
    return out

"""Closed-loop load generator for the serving scheduler (library form).

Ported from ``scripts/serve_loadgen.py`` (which remains as a thin CLI
shim) so the bench harness can register serving load as a *scenario*:
``threads`` clients each run a closed loop (send one single-image
POST /predict, wait, repeat) for ``duration`` seconds — offered load
scales with measured latency, so numbers compare run to run. Reports
p50/p99 latency, throughput, status mix, and the server's own
batch-fill / time-in-queue telemetry as a before/after ``/metrics``
delta (a shared server doesn't pollute the numbers).

Two targets: any running ``dsst serve`` (``--url`` + ``--image``), or
``--selftest`` — a stub-scorer server in a SUBPROCESS loaded over real
sockets. The stub path measures the SCHEDULER (admission, decode pool,
cross-request batching, HTTP keep-alive), which is exactly what CI can
pin; the subprocess split matters because an in-process server would
share the client threads' GIL and inflate tail latency with scheduling
artifacts. ``BENCH_serving.json`` is produced through the bench
harness's ``serving`` scenario on top of this module.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import statistics
import sys
import threading
import time
from pathlib import Path

from ..telemetry.tracecontext import Handoff
from ..telemetry.windows import quantile


def _wait_ready(host: str, port: int, timeout_s: float = 30.0) -> None:
    """Poll /healthz until the server answers, with bounded backoff.

    A freshly spawned server (the --selftest subprocess, or a real
    ``dsst serve`` still compiling its scorer) announces its port before
    the accept loop is warm; connection-refused during that window must
    not fail the whole run. Raises the last error once the budget is
    spent — a server that never comes up is still a loud failure.
    """
    deadline = time.monotonic() + timeout_s
    delay = 0.05
    while True:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=5)
            try:
                conn.request("GET", "/healthz")
                conn.getresponse().read()
            finally:
                conn.close()
            return
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2, 1.0)


def _scrape(host: str, port: int) -> dict:
    """Histogram/counter samples from /metrics (Prometheus text)."""
    conn = http.client.HTTPConnection(host, port, timeout=10)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    text = resp.read().decode()
    conn.close()
    out: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, _, value = line.rpartition(" ")
        if "{" in name:  # labeled series aren't needed here
            continue
        try:
            out[name.strip()] = float(value)
        except ValueError:
            continue
    return out


def _hist_delta(before: dict, after: dict, name: str) -> dict:
    count = after.get(f"{name}_count", 0.0) - before.get(f"{name}_count", 0.0)
    total = after.get(f"{name}_sum", 0.0) - before.get(f"{name}_sum", 0.0)
    return {
        "count": int(count),
        "mean": (total / count) if count else None,
    }


# dsst: ignore[lock-discipline] cross-thread channels are the Barrier/Event; latencies/statuses are written by the client thread alone and read only after join()
class _Client(threading.Thread):
    """One closed-loop client over ONE keep-alive connection."""

    def __init__(self, host: str, port: int, body: bytes,
                 barrier: threading.Barrier, stop: threading.Event):
        super().__init__(daemon=True)
        self.host, self.port, self.body = host, port, body
        self.barrier, self.stop = barrier, stop
        self.latencies: list[float] = []
        self.statuses: dict[int, int] = {}
        self.errors = 0
        self.propagated = 0

    def run(self) -> None:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        self.barrier.wait()
        while not self.stop.is_set():
            # The client mints the request's identity and injects it —
            # the cross-process half of the Handoff contract. A server
            # that adopts it echoes the SAME trace id back, so the
            # propagated count below verifies end-to-end adoption.
            handoff = Handoff.root("request")
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/predict", body=self.body,
                             headers={"Content-Type": "image/jpeg",
                                      "X-DSST-Trace": handoff.to_header()})
                resp = conn.getresponse()
                resp.read()
                status = resp.status
                echoed = resp.getheader("X-DSST-Trace")
            except Exception:
                self.errors += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=30
                )
                continue
            self.latencies.append(time.perf_counter() - t0)
            self.statuses[status] = self.statuses.get(status, 0) + 1
            if echoed == handoff.ctx.trace_id:
                self.propagated += 1
        conn.close()


def run_load(host: str, port: int, body: bytes, *, threads: int,
             duration_s: float) -> dict:
    before = _scrape(host, port)
    barrier = threading.Barrier(threads + 1)
    stop = threading.Event()
    clients = [_Client(host, port, body, barrier, stop)
               for _ in range(threads)]
    for c in clients:
        c.start()
    barrier.wait()  # all connections up before the clock starts
    t0 = time.perf_counter()
    time.sleep(duration_s)
    stop.set()
    for c in clients:
        c.join(10)
    wall = time.perf_counter() - t0
    after = _scrape(host, port)

    latencies = sorted(x for c in clients for x in c.latencies)
    statuses: dict[str, int] = {}
    for c in clients:
        for code, n in c.statuses.items():
            statuses[str(code)] = statuses.get(str(code), 0) + n
    ok = statuses.get("200", 0)

    def pct(p: float):
        # THE shared quantile definition (telemetry.windows.quantile):
        # the offline p50/p99 here and the live windowed sketch on
        # /metrics compute the same statistic — they can only differ by
        # the sketch's bounded bucket error, never by definition drift.
        if not latencies:
            return None
        return quantile(latencies, p)

    return {
        "threads": threads,
        "duration_s": round(wall, 3),
        "requests": len(latencies),
        "throughput_rps": round(len(latencies) / wall, 2),
        "ok_rps": round(ok / wall, 2),
        "statuses": statuses,
        "transport_errors": sum(c.errors for c in clients),
        # Requests whose injected trace id came back in X-DSST-Trace —
        # equal to `requests` against a propagation-aware server.
        "trace_propagated": sum(c.propagated for c in clients),
        "latency_s": {
            "p50": pct(0.50),
            "p90": pct(0.90),
            "p99": pct(0.99),
            "mean": statistics.fmean(latencies) if latencies else None,
        },
        "server": {
            "batch_fill": _hist_delta(before, after, "serving_batch_fill"),
            "time_in_queue_s": _hist_delta(
                before, after, "serving_time_in_queue_seconds"
            ),
            "rejected_429": after.get("serving_admission_rejected_total", 0.0)
            - before.get("serving_admission_rejected_total", 0.0),
            "deadline_503": after.get("serving_deadline_expired_total", 0.0)
            - before.get("serving_deadline_expired_total", 0.0),
        },
    }


# dsst: ignore[lock-discipline] cross-thread channels are the Barrier/Event; per-stream samples are written by the client thread alone and read only after join()
class _LMClient(threading.Thread):
    """One closed-loop token-stream client over ONE keep-alive
    connection: POST /generate, read the chunked ndjson token-by-token
    (TTFT = first line, inter-token = gap between lines), repeat."""

    def __init__(self, host: str, port: int, body: bytes,
                 barrier: threading.Barrier, stop: threading.Event):
        super().__init__(daemon=True)
        self.host, self.port, self.body = host, port, body
        self.barrier, self.stop = barrier, stop
        self.requests = 0
        self.tokens = 0
        self.ttfts: list[float] = []
        self.gaps: list[float] = []
        self.statuses: dict[int, int] = {}
        self.errors = 0
        self.propagated = 0

    def run(self) -> None:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=60)
        self.barrier.wait()
        while not self.stop.is_set():
            handoff = Handoff.root("request")
            t0 = time.perf_counter()
            try:
                conn.request(
                    "POST", "/generate", body=self.body,
                    headers={"Content-Type": "application/json",
                             "X-DSST-Trace": handoff.to_header()},
                )
                resp = conn.getresponse()
                status = resp.status
                echoed = resp.getheader("X-DSST-Trace")
                if status != 200:
                    resp.read()
                    self.statuses[status] = self.statuses.get(status, 0) + 1
                    continue
                # http.client decodes the chunked framing transparently;
                # readline() therefore yields exactly one ndjson record
                # per flushed server chunk — the timing boundary the
                # TTFT/inter-token samples need.
                last = None
                done = False
                for line in iter(resp.readline, b""):
                    now = time.perf_counter()
                    row = json.loads(line)
                    if "done" in row:
                        done = True
                        break
                    if last is None:
                        self.ttfts.append(now - t0)
                    else:
                        self.gaps.append(now - last)
                    last = now
                    self.tokens += 1
                resp.read()  # settle the connection for keep-alive
                if not done:
                    self.errors += 1
                    raise OSError("stream ended without a done record")
            except Exception:
                self.errors += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=60
                )
                continue
            self.requests += 1
            self.statuses[status] = self.statuses.get(status, 0) + 1
            if echoed == handoff.ctx.trace_id:
                self.propagated += 1
        conn.close()


def run_lm_load(host: str, port: int, *, prompt, max_new_tokens: int,
                streams: int, duration_s: float) -> dict:
    """Closed-loop streamed-generation load: ``streams`` concurrent
    clients for ``duration_s``. The headline is tokens/sec; TTFT and
    inter-token percentiles go through THE shared quantile helper
    (``telemetry.windows.quantile``), so the offline numbers and the
    live ``ttft_p99``/``inter_token_p99`` SLO windows can only differ
    by sketch error, never by definition drift."""
    body = json.dumps({
        "tokens": list(prompt),
        "max_new_tokens": int(max_new_tokens),
    }).encode()
    barrier = threading.Barrier(streams + 1)
    stop = threading.Event()
    clients = [_LMClient(host, port, body, barrier, stop)
               for _ in range(streams)]
    for c in clients:
        c.start()
    barrier.wait()
    t0 = time.perf_counter()
    time.sleep(duration_s)
    stop.set()
    for c in clients:
        c.join(30)
    wall = time.perf_counter() - t0

    ttfts = sorted(x for c in clients for x in c.ttfts)
    gaps = sorted(x for c in clients for x in c.gaps)
    tokens = sum(c.tokens for c in clients)
    requests = sum(c.requests for c in clients)
    statuses: dict[str, int] = {}
    for c in clients:
        for code, n in c.statuses.items():
            statuses[str(code)] = statuses.get(str(code), 0) + n

    def pct(samples, p):
        return quantile(samples, p) if samples else None

    return {
        "streams": streams,
        "duration_s": round(wall, 3),
        "requests": requests,
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 2),
        "statuses": statuses,
        "transport_errors": sum(c.errors for c in clients),
        "trace_propagated": sum(c.propagated for c in clients),
        "ttft_s": {
            "p50": pct(ttfts, 0.50),
            "p99": pct(ttfts, 0.99),
            "mean": statistics.fmean(ttfts) if ttfts else None,
        },
        "inter_token_s": {
            "p50": pct(gaps, 0.50),
            "p99": pct(gaps, 0.99),
            "mean": statistics.fmean(gaps) if gaps else None,
        },
    }


class _StubScorer:
    """Predictor-shaped stub with a simulated per-batch score cost."""

    meta = {"model": "loadgen-stub"}
    step = 0
    crop = 8

    def __init__(self, micro_batch: int, score_ms: float):
        import numpy as np

        self._np = np
        self.micro_batch = micro_batch
        self.score_s = score_ms / 1000.0

    def decode(self, jpegs):
        return self._np.zeros((len(jpegs), 1), self._np.float32)

    def score(self, images):
        if self.score_s:
            time.sleep(self.score_s)
        return [{"pred_index": 0, "pred_prob": 1.0} for _ in images]


def spawn_stub_server(*, micro_batch: int = 8, score_ms: float = 5.0,
                      batch_window_ms: float = 5.0, queue_depth: int = 64,
                      deadline_ms: float = 0.0, access_log=None,
                      flightrec=None):
    """Spawn the stub-scorer server subprocess; returns ``(proc, port)``
    with ``/healthz`` already answering. Callers terminate ``proc``.

    ``access_log``/``flightrec`` (paths) arm the stub's structured
    request log and flight-recorder tail — what the fleet tests use to
    compare merged sketches against per-replica journaled ground truth
    and to merge per-replica recorder files into one timeline."""
    import subprocess

    argv = [sys.executable, "-m", "dss_ml_at_scale_tpu.bench.loadgen",
            "--stub-serve",
            "--micro-batch", str(micro_batch),
            "--score-ms", str(score_ms),
            "--batch-window-ms", str(batch_window_ms),
            "--queue-depth", str(queue_depth),
            "--deadline-ms", str(deadline_ms)]
    if access_log is not None:
        argv += ["--access-log", str(access_log)]
    if flightrec is not None:
        argv += ["--flightrec", str(flightrec)]
    # stdin is the parent-death channel: if the spawning process is
    # SIGKILLed (a bench watchdog kill can't run teardown), the kernel
    # closes the pipe and the stub's watcher thread sees EOF — no
    # orphaned server accumulating on the host per killed child.
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE, stdin=subprocess.PIPE, text=True,
    )
    try:
        boot = json.loads(proc.stdout.readline())
        port = boot["port"]
        _wait_ready("127.0.0.1", port)
    except BaseException:
        proc.terminate()
        raise
    return proc, port


def spawn_stub_lm_server(*, slots: int = 8, max_len: int = 96,
                         prefill_buckets: str = "8,16",
                         step_ms: float = 3.0, queue_depth: int = 32,
                         deadline_ms: float = 0.0,
                         inter_token_budget_ms: float = 0.0,
                         access_log=None):
    """Spawn the stub-decoder LM streaming server subprocess; returns
    ``(proc, port)`` with ``/healthz`` already answering. Same
    subprocess split and parent-death stdin channel as
    :func:`spawn_stub_server` — the stub decoder's per-STEP cost is
    independent of active slots, so this measures the ENGINE
    (admission, continuous batching, streaming, retirement)."""
    import subprocess

    argv = [sys.executable, "-m", "dss_ml_at_scale_tpu.bench.loadgen",
            "--stub-serve-lm",
            "--slots", str(slots),
            "--max-len", str(max_len),
            "--prefill-buckets", str(prefill_buckets),
            "--step-ms", str(step_ms),
            "--queue-depth", str(queue_depth),
            "--deadline-ms", str(deadline_ms),
            "--inter-token-budget-ms", str(inter_token_budget_ms)]
    if access_log is not None:
        argv += ["--access-log", str(access_log)]
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE, stdin=subprocess.PIPE, text=True,
    )
    try:
        boot = json.loads(proc.stdout.readline())
        port = boot["port"]
        _wait_ready("127.0.0.1", port)
    except BaseException:
        proc.terminate()
        raise
    return proc, port


def _stub_serve_lm(args) -> int:
    """The --stub-serve-lm server half: stub decoder + real engine +
    real streaming front end; announce the port, serve until SIGTERM,
    drain on the way out."""
    import signal

    from ..serving.lm import LMConfig, LMEngine, StubLMDecoder
    from ..workloads.serving import serve_lm_in_thread

    buckets = tuple(
        int(b) for b in str(args.prefill_buckets).split(",") if b
    )
    cfg = LMConfig(
        slots=args.slots, max_len=args.max_len, prefill_buckets=buckets,
        queue_depth=args.queue_depth, deadline_ms=args.deadline_ms,
        inter_token_budget_ms=args.inter_token_budget_ms,
    )
    engine = LMEngine(
        StubLMDecoder(step_ms=args.step_ms, slots=args.slots,
                      max_len=args.max_len, buckets=buckets),
        cfg,
    ).start()
    handle = serve_lm_in_thread(engine, access_log=args.access_log or None)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())

    def _watch_parent() -> None:
        try:
            sys.stdin.buffer.read()
        except (OSError, ValueError):
            pass
        stop.set()

    threading.Thread(target=_watch_parent, daemon=True,
                     name="loadgen-parent-watch").start()
    # dsst: ignore[no-print] subprocess port-announce protocol line on stdout
    print(json.dumps({"port": handle.port}), flush=True)
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        handle.close()
    return 0


def _stub_serve(args) -> int:
    """The --stub-serve server half: announce the port, serve until
    SIGTERM, drain on the way out."""
    import signal

    from ..serving import SchedulerConfig
    from ..telemetry import flightrec
    from ..workloads.serving import serve_in_thread

    if args.flightrec:
        # Arm the flight-recorder tail BEFORE the server threads start,
        # so every serving span of this replica reaches the file.
        flightrec.enable(args.flightrec)
    handle = serve_in_thread(
        _StubScorer(args.micro_batch, args.score_ms),
        config=SchedulerConfig(
            queue_depth=args.queue_depth,
            batch_window_ms=args.batch_window_ms,
            deadline_ms=args.deadline_ms,
        ),
        access_log=args.access_log or None,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())

    def _watch_parent() -> None:
        # EOF on stdin = the spawning parent is gone (it held the write
        # end; even SIGKILL closes it). A tty stdin just blocks forever.
        try:
            sys.stdin.buffer.read()
        except (OSError, ValueError):
            pass
        stop.set()

    threading.Thread(target=_watch_parent, daemon=True,
                     name="loadgen-parent-watch").start()
    # dsst: ignore[no-print] subprocess port-announce protocol line on stdout
    print(json.dumps({"port": handle.port}), flush=True)
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        handle.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    target = ap.add_mutually_exclusive_group(required=True)
    target.add_argument("--url", help="running server, e.g. http://127.0.0.1:8008")
    target.add_argument(
        "--selftest", action="store_true",
        help="subprocess stub server (scheduler smoke bench; no checkpoint)",
    )
    # Internal: the server half of --selftest (announces its port as a
    # JSON line, serves until SIGTERM).
    target.add_argument("--stub-serve", action="store_true",
                        help=argparse.SUPPRESS)
    # Internal: the LM-engine flavor (stub decoder + real continuous-
    # batching engine + chunked /generate streaming).
    target.add_argument("--stub-serve-lm", action="store_true",
                        help=argparse.SUPPRESS)
    ap.add_argument("--image", default=None,
                    help="JPEG file to POST (required with --url)")
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--micro-batch", type=int, default=8,
                    help="(selftest) compiled-batch size the stub simulates")
    ap.add_argument("--score-ms", type=float, default=5.0,
                    help="(selftest) simulated per-batch score cost")
    ap.add_argument("--batch-window-ms", type=float, default=5.0)
    ap.add_argument("--queue-depth", type=int, default=64)
    ap.add_argument("--deadline-ms", type=float, default=0.0)
    ap.add_argument("--access-log", default=None,
                    help="(stub-serve) structured request log path")
    ap.add_argument("--flightrec", default=None,
                    help="(stub-serve) flight-recorder tail path")
    ap.add_argument("--slots", type=int, default=8,
                    help="(stub-serve-lm) KV arena slots")
    ap.add_argument("--max-len", type=int, default=96,
                    help="(stub-serve-lm) per-slot KV capacity")
    ap.add_argument("--prefill-buckets", default="8,16",
                    help="(stub-serve-lm) comma-separated bucket lengths")
    ap.add_argument("--step-ms", type=float, default=3.0,
                    help="(stub-serve-lm) simulated per-STEP decode cost")
    ap.add_argument("--inter-token-budget-ms", type=float, default=0.0,
                    help="(stub-serve-lm) arms the inter_token_p99 SLO")
    ap.add_argument("--out", default=None, help="write the report JSON here")
    args = ap.parse_args(argv)

    if args.stub_serve:
        return _stub_serve(args)
    if args.stub_serve_lm:
        return _stub_serve_lm(args)

    proc = None
    if args.selftest:
        proc, port = spawn_stub_server(
            micro_batch=args.micro_batch, score_ms=args.score_ms,
            batch_window_ms=args.batch_window_ms,
            queue_depth=args.queue_depth, deadline_ms=args.deadline_ms,
        )
        host, body = "127.0.0.1", b"0"
    else:
        if not args.image:
            ap.error("--url needs --image (a real JPEG the server can decode)")
        url = args.url.removeprefix("http://")
        host, _, port_s = url.partition(":")
        port = int(port_s.rstrip("/") or 8008)
        body = Path(args.image).read_bytes()

    try:
        _wait_ready(host, port)
        report = {
            "bench": "serve_loadgen",
            "mode": "selftest" if args.selftest else "url",
            # Tail latencies are host-sensitive: on a small shared box
            # the p99 reflects scheduler noise, not the serving stack.
            "host_cpus": os.cpu_count(),
            "config": {
                "micro_batch": args.micro_batch if args.selftest else None,
                "score_ms": args.score_ms if args.selftest else None,
                "batch_window_ms": args.batch_window_ms,
                "queue_depth": args.queue_depth,
                "deadline_ms": args.deadline_ms,
            },
            **run_load(host, port, body, threads=args.threads,
                       duration_s=args.duration),
        }
    finally:
        if proc is not None:
            proc.terminate()
            proc.wait(15)

    text = json.dumps(report, indent=1)
    # dsst: ignore[no-print] the loadgen CLI's report contract: one JSON document on stdout
    print(text)
    if args.out:
        Path(args.out).write_text(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Noise-aware statistics for the bench harness: median/MAD + verdicts.

A throughput sample on a shared host is a draw from a noisy
distribution, not a number — so the regression gate never compares two
single runs. Every scenario repetition contributes one sample, warmup
repetitions are discarded (cold caches, first-touch page faults, lazy
imports), the summary is **median + MAD** (both robust to the one
stalled repetition a busy box produces), and the regression tolerance
is *derived from the measured dispersion* of both sides rather than
hardcoded: a scenario that measures steadily is held to a tight band,
a jittery one gets the band its own noise demands — never less than
the metric's declared floor, so a quiet run cannot ratchet the gate
into flakiness.

The verdict vocabulary (:func:`classify`):

- ``regression`` — the current median is outside the noise band on the
  *bad* side of the metric's declared direction; fails the run.
- ``improvement`` — outside the band on the good side; reported (and a
  hint to re-baseline) but never a failure.
- ``within-noise`` — inside the band.
- ``no-baseline`` — nothing committed for this metric under the current
  environment fingerprint yet.
- ``informational`` — the metric's schema declares ``gate=False``; it
  is recorded in artifacts and the baseline but never judged.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from ..telemetry.windows import quantile

# A regression must clear BOTH the relative floor and this many
# combined-MAD units — the classic robust-z idiom (MAD ≈ 0.6745 σ for
# a normal distribution, so 4 MADs ≈ 2.7 σ).
MAD_MULTIPLIER = 4.0

# Default relative floor: on the 1–2 core CI boxes this repo measures
# on, back-to-back throughput runs of the same code routinely differ by
# 15–25%; a floor below that would gate on scheduler noise. Individual
# metrics can declare a tighter or looser floor in their schema.
DEFAULT_REL_FLOOR = 0.35


def discard_warmup(samples: Sequence, warmup: int) -> list:
    """Drop the first ``warmup`` entries — the cold repetitions every
    scenario pays once (compile, page cache, thread-pool spin-up)."""
    if warmup < 0:
        raise ValueError("warmup must be >= 0")
    return list(samples[warmup:])


def median(xs: Sequence[float]) -> float:
    # Delegates to THE shared quantile helper (telemetry.windows):
    # q=0.5 under linear rank interpolation is exactly the classic
    # midpoint median, and single-sourcing the math keeps the bench
    # verdicts and the live sketches from ever drifting apart.
    if not xs:
        raise ValueError("median of no samples")
    return quantile(xs, 0.5)


def mad(xs: Sequence[float], center: float | None = None) -> float:
    """Median absolute deviation around ``center`` (default: median)."""
    if not xs:
        raise ValueError("mad of no samples")
    c = median(xs) if center is None else center
    return median([abs(x - c) for x in xs])


@dataclasses.dataclass(frozen=True)
class Summary:
    """One metric's robust summary over a scenario's repetitions."""

    median: float
    mad: float
    n: int

    def to_json(self) -> dict:
        return {"median": self.median, "mad": self.mad, "n": self.n}


def summarize(samples: Sequence[float]) -> Summary:
    m = median(samples)
    return Summary(median=m, mad=mad(samples, m), n=len(samples))


def tolerance(current: Summary, baseline: Summary, *,
              floor: float = DEFAULT_REL_FLOOR,
              k: float = MAD_MULTIPLIER) -> float:
    """Relative noise band around the baseline median.

    Dispersion-derived: ``k`` times the larger of the two *relative*
    dispersions (each side's MAD over its OWN median — the noisier side
    sets the band, so comparing a quiet run against a noisy baseline
    inherits the baseline's uncertainty), floored at the metric's
    declared minimum. Each side normalizes by its own median
    deliberately: normalizing the current MAD by the *baseline* median
    would let a large regression inflate its own tolerance (noise
    scales with the regressed value, so the absolute MAD grows with the
    very change being judged) and pass as within-noise.
    """
    base = abs(baseline.median)
    if base == 0.0:
        return floor
    rel_cur = (
        current.mad / abs(current.median) if current.median else 0.0
    )
    spread = k * max(rel_cur, baseline.mad / base)
    return max(floor, spread)


def classify(direction: str, current: Summary, baseline: Summary | None,
             *, gate: bool = True, floor: float = DEFAULT_REL_FLOOR,
             k: float = MAD_MULTIPLIER) -> dict:
    """Verdict for one metric vs its committed baseline entry.

    Returns ``{"verdict", "rel_change", "tolerance"}`` (the latter two
    absent when there is no baseline). ``direction`` is the schema's
    ``"higher"``/``"lower"``-is-better declaration.
    """
    if direction not in ("higher", "lower"):
        raise ValueError(f"direction must be 'higher' or 'lower', "
                         f"got {direction!r}")
    if not gate:
        return {"verdict": "informational"}
    if baseline is None or baseline.n == 0:
        return {"verdict": "no-baseline"}
    if baseline.median == 0.0:
        # A zero baseline carries no scale to judge against.
        return {"verdict": "no-baseline"}
    tol = tolerance(current, baseline, floor=floor, k=k)
    rel = (current.median - baseline.median) / abs(baseline.median)
    bad = rel < -tol if direction == "higher" else rel > tol
    good = rel > tol if direction == "higher" else rel < -tol
    verdict = "regression" if bad else (
        "improvement" if good else "within-noise"
    )
    return {
        "verdict": verdict,
        "rel_change": round(rel, 4),
        "tolerance": round(tol, 4),
    }

"""The registered scenarios: bench.py's stages, decomposed and gated.

Each scenario isolates one seam of the system the ROADMAP's scale items
need proven numbers for — decode, reader, device feeding, the compiled
step, the observability layers' own overhead, and serving load. Where a
scenario executes a compiled program it builds it through the **audit
entrypoint registry** (the same builders ``dsst audit`` certifies), so
the measured program and the pinned cost budget describe identical XLA
— that is what makes the achieved-FLOPs/s gauges honest.

Declarations here are reconciled against
``telemetry.catalog.KNOWN_BENCH_METRICS`` in both directions by the
``bench-registry`` lint rule: scenario/metric names must be literal.

The ``feeder_e2e`` scenario self-verifies: its measured wall time is
cross-checked against the flight-recorder attribution buckets (the
SAME ``telemetry.catalog.SPAN_ATTRIBUTION`` mapping ``dsst trace
attribution`` uses), and an unexplained gap fails the scenario — a
harness whose own spans stop covering its loop must say so, not emit
numbers nobody can attribute.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

from .core import Metric, Scenario, register_scenario

# Geometry shared by the decode/reader stages: tiny sources so tier-1
# children finish in seconds; throughput at this size is a *relative*
# gate (same work every run), not an absolute claim.
_SRC_SIZE = 32
_CROP = 32
_N_IMAGES = 96
_BATCH = 16


def _tiny_jpegs(n: int, size: int, seed: int = 0) -> list[bytes]:
    """Blocky low-frequency JPEGs: realistic decode entropy (pure noise
    inflates decode cost; flat color deflates it) — bench.py's recipe."""
    import io

    import numpy as np
    from PIL import Image

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        blocks = rng.uniform(0, 255, (8, 8, 3))
        img = np.kron(blocks, np.ones((size // 8, size // 8, 1)))
        buf = io.BytesIO()
        Image.fromarray(img.astype(np.uint8)).save(
            buf, format="JPEG", quality=85
        )
        out.append(buf.getvalue())
    return out


def _transform_spec():
    from ..data.transform import imagenet_transform_spec

    return imagenet_transform_spec(
        resize=_CROP + _CROP // 8, crop=_CROP, output_dtype="uint8"
    )


# -- decode -------------------------------------------------------------------


def _decode_setup():
    jpegs = _tiny_jpegs(_N_IMAGES, _SRC_SIZE)
    spec = _transform_spec()
    probe = {
        "content": jpegs,
        "label_index": [0] * len(jpegs),
    }
    spec(dict(probe))  # warm the decode path (thread pool, caches)
    return {"spec": spec, "probe": probe}


def _decode_measure(ctx) -> dict:
    t0 = time.perf_counter()
    ctx["spec"](dict(ctx["probe"]))
    dt = time.perf_counter() - t0
    return {"decode_images_per_sec": len(ctx["probe"]["content"]) / dt}


register_scenario(Scenario(
    name="decode",
    description="JPEG decode + transform throughput, raw bytes in, "
    "host batch out (no reader, no device)",
    tier="tier1",
    metrics=(
        Metric("decode_images_per_sec", "images/sec", "higher",
               floor=0.6),
    ),
    setup=_decode_setup,
    measure=_decode_measure,
    repetitions=5,
    timeout_s=120.0,
))


# -- reader -------------------------------------------------------------------


def _reader_setup():
    import pyarrow as pa

    from ..data import write_delta

    tmpdir = tempfile.mkdtemp(prefix="dsst_bench_reader_")
    jpegs = _tiny_jpegs(_N_IMAGES, _SRC_SIZE)
    table = pa.table({
        "content": pa.array(jpegs, type=pa.binary()),
        "label_index": pa.array([i % 7 for i in range(len(jpegs))],
                                type=pa.int64()),
    })
    path = os.path.join(tmpdir, "bench_imagenet")
    write_delta(table, path, max_rows_per_file=max(16, len(jpegs) // 4))
    return {"tmpdir": tmpdir, "path": path, "spec": _transform_spec()}


def _reader_measure(ctx) -> dict:
    from ..data import batch_loader

    n_batches = 4
    with batch_loader(
        ctx["path"],
        batch_size=_BATCH,
        num_epochs=None,
        workers_count=2,
        results_queue_size=8,
        transform_spec=ctx["spec"],
    ) as reader:
        it = iter(reader)
        next(it)  # warm: open files, fill the pool
        t0 = time.perf_counter()
        for _ in range(n_batches):
            next(it)
        dt = time.perf_counter() - t0
    return {"reader_images_per_sec": _BATCH * n_batches / dt}


register_scenario(Scenario(
    name="reader",
    description="Delta table -> sharded reader -> decode pool -> host "
    "batches (no device)",
    tier="tier1",
    metrics=(
        Metric("reader_images_per_sec", "images/sec", "higher",
               floor=0.6),
    ),
    setup=_reader_setup,
    teardown=lambda ctx: shutil.rmtree(ctx["tmpdir"], ignore_errors=True),
    repetitions=3,
    measure=_reader_measure,
    timeout_s=240.0,
))


# -- compute (the audited classifier train step) ------------------------------


def _audited_train_step(mesh=None):
    """(compiled, state, batch): the EXACT program ``dsst audit`` pins
    for ``train_step.classifier``, built through the audit registry's
    builder on the same 8-device abstract mesh and AOT-compiled — the
    ONE builder both the compute and feeder_e2e scenarios share, so
    they can never measure different programs while citing one pin."""
    from ..analysis.audit.core import default_audit_mesh
    from ..analysis.audit.entrypoints import train_step_classifier

    spec = train_step_classifier(
        default_audit_mesh() if mesh is None else mesh
    )
    state, batch = spec.args
    compiled = spec.jitted.lower(*spec.args).compile()
    return compiled, state, batch


def _compute_setup():
    compiled, state, batch = _audited_train_step()
    return {"compiled": compiled, "state": state, "batch": batch}


def _compute_measure(ctx) -> dict:
    steps = 10
    state, batch = ctx["state"], ctx["batch"]
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ctx["compiled"](state, batch)
    float(metrics["train_loss"])
    dt = time.perf_counter() - t0
    ctx["state"] = state
    sps = steps / dt
    return {
        "compute_steps_per_sec": sps,
        "compute_images_per_sec": sps * batch["image"].shape[0],
    }


register_scenario(Scenario(
    name="compute",
    description="audited train_step.classifier program (8-device "
    "abstract mesh) steps/sec — prices the audit-pinned FLOPs budget "
    "into the achieved-FLOPs/s gauges",
    tier="tier1",
    metrics=(
        Metric("compute_steps_per_sec", "steps/sec", "higher",
               floor=0.6),
        Metric("compute_images_per_sec", "images/sec", "higher",
               gate=False),
    ),
    setup=_compute_setup,
    measure=_compute_measure,
    repetitions=3,
    timeout_s=420.0,
    needs_mesh=True,
    entrypoint="train_step.classifier",
    steps_metric="compute_steps_per_sec",
))


# -- feeder e2e (traced, self-verifying) --------------------------------------


def _feeder_setup():
    from ..analysis.audit.core import default_audit_mesh

    mesh = default_audit_mesh()
    compiled, state, batch = _audited_train_step(mesh)
    # One throwaway call so the first measured repetition starts from a
    # warm executable (the warmup repetition then covers feeder spin-up).
    state, metrics = compiled(state, batch)
    float(metrics["train_loss"])
    return {
        "mesh": mesh,
        "compiled": compiled,
        "state": state,
        "tmpdir": tempfile.mkdtemp(prefix="dsst_bench_feeder_"),
        "rep": 0,
    }


# Rows per synthetic host batch — MUST match the audited
# train_step.classifier batch shape (the compiled program is
# shape-specialized); also the numerator of e2e_images_per_sec.
_E2E_ROWS = 16


def _host_batches(n: int):
    import numpy as np

    for _ in range(n):
        yield {
            "image": np.zeros((_E2E_ROWS, 16, 16, 3), np.float32),
            "label": np.zeros((_E2E_ROWS,), np.int32),
        }


def _attribution_buckets(tail_path, since: float) -> dict[str, float]:
    """Seconds per attribution bucket over the tail's step-kind spans
    opened after ``since`` — the same SPAN_ATTRIBUTION mapping ``dsst
    trace attribution`` reads, so this cross-check and the CLI tool
    cannot drift apart."""
    from ..telemetry import flightrec
    from ..telemetry.catalog import SPAN_ATTRIBUTION

    complete, _opens = flightrec.reconstruct(
        flightrec.read_events(tail_path)
    )
    buckets = {"data_wait": 0.0, "transfer": 0.0, "compute": 0.0,
               "host": 0.0}
    for e in complete:
        if e.get("kind") != "step" or e.get("ts", 0.0) < since:
            continue
        buckets[SPAN_ATTRIBUTION.get(e.get("name"), "host")] += e.get(
            "dur", 0.0
        )
    return buckets


def _feeder_measure(ctx) -> dict:
    from .. import telemetry
    from ..data.prefetch import MeshFeeder
    from ..telemetry import flightrec

    steps = 8
    ctx["rep"] += 1
    state = ctx["state"]
    # Record onto the recorder's existing tail when one is live (a
    # tracked run, or `dsst bench profile` merging this very trace);
    # otherwise scope a private tail for the cross-check. The `since`
    # mark keeps the bucket read to THIS repetition either way.
    rec = flightrec.get_recorder()
    own_tail = None
    tail = rec.path
    if tail is None:
        own_tail = os.path.join(ctx["tmpdir"], f"tail{ctx['rep']}.jsonl")
        tail = flightrec.enable(own_tail)
    since = time.time()
    try:
        feeder = MeshFeeder(
            _host_batches(steps), ctx["mesh"], depth=3, name="bench-e2e"
        )
        try:
            stall = 0.0
            t0 = time.perf_counter()
            for _ in range(steps):
                s0 = time.perf_counter()
                batch, _prov = next(feeder)
                stall += time.perf_counter() - s0
                with feeder.last_handoff.activate(), \
                        telemetry.span("train_step"):
                    state, metrics = ctx["compiled"](state, batch)
            float(metrics["train_loss"])
            wall = time.perf_counter() - t0
        finally:
            feeder.close()
    finally:
        if own_tail is not None:
            flightrec.disable(own_tail)
    ctx["state"] = state

    buckets = _attribution_buckets(tail, since)
    traced = sum(buckets.values())
    unexplained = max(0.0, wall - traced) / wall if wall > 0 else 0.0
    if unexplained > 0.5:
        # The harness's self-verification: if the spans the attribution
        # tool buckets stop covering this loop (a renamed span, a broken
        # handoff), the number is unattributable — fail loudly instead
        # of shipping it.
        raise RuntimeError(
            f"e2e wall time unexplained by trace attribution: "
            f"{unexplained:.0%} of {wall*1e3:.1f}ms has no span "
            f"(buckets: { {k: round(v*1e3, 1) for k, v in buckets.items()} } "
            "ms) — feeder/step spans or the step handoff broke"
        )
    return {
        "e2e_images_per_sec": _E2E_ROWS * steps / wall,
        "e2e_steps_per_sec": steps / wall,
        "feeder_stall_fraction": stall / wall if wall > 0 else 0.0,
        "e2e_unexplained_fraction": unexplained,
    }


register_scenario(Scenario(
    name="feeder_e2e",
    description="traced MeshFeeder -> audited train step loop; wall "
    "time cross-checked against flight-recorder attribution buckets "
    "(fails on unexplained gap)",
    tier="slow",
    metrics=(
        Metric("e2e_images_per_sec", "images/sec", "higher",
               floor=0.6),
        Metric("e2e_steps_per_sec", "steps/sec", "higher", gate=False),
        Metric("feeder_stall_fraction", "fraction", "lower", gate=False),
        Metric("e2e_unexplained_fraction", "fraction", "lower",
               gate=False),
    ),
    setup=_feeder_setup,
    teardown=lambda ctx: shutil.rmtree(ctx["tmpdir"], ignore_errors=True),
    measure=_feeder_measure,
    repetitions=3,
    timeout_s=420.0,
    needs_mesh=True,
    entrypoint="train_step.classifier",
))


# -- group fit (grid-fused SARIMAX panel) -------------------------------------


def _group_panel(n_sku: int, weeks: int, seed: int = 0):
    """Synthetic demand panel at the BENCH_r05 group-child recipe
    (level + damped random walk + noise, weekly dates), built
    vectorized so 10k-SKU setup is numpy-bound, not loop-bound."""
    import numpy as np
    import pandas as pd

    from ..workloads.forecasting import add_exo_variables

    rng = np.random.default_rng(seed)
    level = rng.uniform(20, 80, (n_sku, 1))
    walk = np.cumsum(rng.normal(0, 1.0, (n_sku, weeks)), axis=1) * 0.5
    noise = rng.normal(0, 3.0, (n_sku, weeks))
    demand = np.maximum(level + walk + noise, 0.0)
    dates = pd.date_range("2020-01-06", periods=weeks, freq="W-MON")
    skus = np.array([f"P{g % 5}_{g:05d}" for g in range(n_sku)])
    frame = pd.DataFrame({
        "Product": np.repeat([f"P{g % 5}" for g in range(n_sku)], weeks),
        "SKU": np.repeat(skus, weeks),
        "Date": np.tile(dates, n_sku),
        "Demand": demand.ravel(),
    })
    return add_exo_variables(frame)


def _group_mesh():
    """The operator mesh for the group-fit launches: every REAL device
    the box has — the shape ``dsst forecast`` runs and the shape
    BENCH_r05's group child measured 1.28 skus/sec on. On an 8-chip box
    this is exactly the audited ``sarimax.batched_fit`` topology; on a
    CPU host the harness's 8-way multiplexed view exists for structural
    audits, not silicon — partitioning the vectorized fit plane across
    fake devices only fragments it, so the launch runs single-device
    there (what the r05 comparison point did). The per-SKU math (and so
    the audit FLOPs pin pricing the launches) is identical either way.
    """
    import jax

    from ..runtime.mesh import make_mesh

    devices = list(jax.devices())
    if devices[0].platform == "cpu":
        devices = devices[:1]
    return make_mesh({"data": len(devices)}, devices=devices)


def _group_fit_setup():
    from ..workloads.forecasting import (
        GROUP_FIT_BENCH_GROUPS,
        GROUP_FIT_BENCH_WEEKS,
    )

    return {
        "mesh": _group_mesh(),
        "panel": _group_panel(GROUP_FIT_BENCH_GROUPS,
                              GROUP_FIT_BENCH_WEEKS),
    }


def _group_fit_measure(ctx) -> dict:
    import numpy as np

    from ..ops.sarimax import grid_orders
    from ..workloads.forecasting import (
        GROUP_FIT_BENCH_CFG,
        GROUP_FIT_BENCH_GROUPS,
        GROUP_FIT_BENCH_HORIZON,
        tune_and_forecast_panel,
    )

    g = GROUP_FIT_BENCH_GROUPS
    t0 = time.perf_counter()
    out = tune_and_forecast_panel(
        ctx["panel"],
        forecast_horizon=GROUP_FIT_BENCH_HORIZON,
        mesh=ctx["mesh"],
        cfg=GROUP_FIT_BENCH_CFG,
        search="grid",
        chunk_size=g,
    )
    wall = time.perf_counter() - t0
    if not np.isfinite(out["Demand_Fitted"]).all():
        raise RuntimeError("group_fit produced non-finite forecasts")
    # The REAL launch count, reported by the grid driver itself: one
    # chunk at this geometry. Anything else means the fused launch
    # family broke apart — fail, don't mis-price the MFU gauge.
    chunks = out.attrs["grid_chunks"]
    if chunks != 1:
        raise RuntimeError(
            f"group_fit expected ONE fused launch, driver reports "
            f"{chunks}"
        )
    k = len(grid_orders(GROUP_FIT_BENCH_CFG))
    return {
        "group_fit_skus_per_sec": g / wall,
        "group_fit_fits_per_sec": g * k / wall,
        "group_fit_launches_per_sec": chunks / wall,
    }


register_scenario(Scenario(
    name="group_fit",
    description="grid-fused SARIMAX group-fit panel (32 SKUs x 40 "
    "weeks x the full 8-order grid of the reduced bench bounds) "
    "through tune_and_forecast_panel on the operator mesh — ONE "
    "launch fits and tunes every SKU via the sarimax.batched_fit "
    "program family, so the audit FLOPs pin prices skus/sec "
    "(BENCH_r05 group-child comparison point: 1.28 skus/sec per-round "
    "TPE at this 32-group geometry)",
    tier="tier1",
    metrics=(
        Metric("group_fit_skus_per_sec", "skus/sec", "higher",
               floor=0.6),
        Metric("group_fit_fits_per_sec", "fits/sec", "higher",
               gate=False),
        Metric("group_fit_launches_per_sec", "launches/sec", "higher",
               gate=False),
    ),
    setup=_group_fit_setup,
    measure=_group_fit_measure,
    repetitions=3,
    timeout_s=420.0,
    entrypoint="sarimax.batched_fit",
    steps_metric="group_fit_launches_per_sec",
))


# 10k-SKU scale smoke: the ROADMAP item 3 target shape ("10k+ SKUs per
# launch family"). A liveness-scale fit config (shorter NM chains than
# the tier-1 gate) keeps the slow-tier wall in minutes on a CPU host;
# the scenario's claim is CHUNKED completion — bounded launches, no
# host-loop fallback — with throughput recorded for trend, not gated.
_10K_SKUS = 10_000
_10K_CHUNK = 1024


def _group_fit_10k_setup():
    import dataclasses

    from ..workloads.forecasting import (
        GROUP_FIT_BENCH_CFG,
        GROUP_FIT_BENCH_WEEKS,
    )

    return {
        "mesh": _group_mesh(),
        "panel": _group_panel(_10K_SKUS, GROUP_FIT_BENCH_WEEKS),
        "cfg": dataclasses.replace(GROUP_FIT_BENCH_CFG, max_iter=16),
    }


def _group_fit_10k_measure(ctx) -> dict:
    import numpy as np

    from ..workloads.forecasting import (
        GROUP_FIT_BENCH_HORIZON,
        tune_and_forecast_panel,
    )

    t0 = time.perf_counter()
    out = tune_and_forecast_panel(
        ctx["panel"],
        forecast_horizon=GROUP_FIT_BENCH_HORIZON,
        mesh=ctx["mesh"],
        cfg=ctx["cfg"],
        search="grid",
        chunk_size=_10K_CHUNK,
    )
    wall = time.perf_counter() - t0
    if not np.isfinite(out["Demand_Fitted"]).all():
        raise RuntimeError("group_fit_10k produced non-finite forecasts")
    groups = out.groupby(["Product", "SKU"]).ngroups
    if groups != _10K_SKUS:
        raise RuntimeError(
            f"group_fit_10k fitted {groups} groups, wanted {_10K_SKUS}"
        )
    # Measured, not assumed: the driver's own launch count — a host
    # loop or a broken chunk bound would show up right here.
    return {
        "group_fit_10k_skus_per_sec": _10K_SKUS / wall,
        "group_fit_10k_chunks": out.attrs["grid_chunks"],
    }


register_scenario(Scenario(
    name="group_fit_10k",
    description="10k-SKU grid-fused panel through the bounded chunked "
    "launch family (1024 groups/launch, liveness fit config) — proves "
    "ROADMAP item 3 scale completes with no host-loop fallback",
    tier="slow",
    metrics=(
        Metric("group_fit_10k_skus_per_sec", "skus/sec", "higher",
               gate=False),
        Metric("group_fit_10k_chunks", "launches", "lower", gate=False),
    ),
    setup=_group_fit_10k_setup,
    measure=_group_fit_10k_measure,
    repetitions=1,
    warmup=0,
    timeout_s=1800.0,
))


# -- recorder overhead --------------------------------------------------------

_EMIT_EVENTS = 1500


def _recorder_setup():
    return {"tmpdir": tempfile.mkdtemp(prefix="dsst_bench_rec_"), "rep": 0}


def _recorder_measure(ctx) -> dict:
    from ..telemetry import flightrec

    rec = flightrec.get_recorder()
    ctx["rep"] += 1
    # The scenario must OWN the recorder target for both halves of the
    # comparison: a live recorder (a tracked run, `dsst bench profile`)
    # would otherwise absorb the ring loop's synthetic events into its
    # tail — measuring tail cost where ring cost was claimed — and the
    # scoped disable below would silently switch that recorder off.
    # Park the previous target and restore it on the way out.
    prev = rec.path
    if prev is not None:
        flightrec.disable(prev)

    def _event(i: int) -> dict:
        return {
            "ph": "X", "name": "train_step", "ts": time.time(),
            "dur": 0.001, "pid": os.getpid(), "tid": 1,
            "thread": "bench", "span": f"{i:08x}",
        }

    tail = os.path.join(ctx["tmpdir"], f"tail{ctx['rep']}.jsonl")
    try:
        t0 = time.perf_counter()
        for i in range(_EMIT_EVENTS):
            rec.emit(_event(i))
        ring_dt = time.perf_counter() - t0

        flightrec.enable(tail)
        try:
            t0 = time.perf_counter()
            for i in range(_EMIT_EVENTS):
                rec.emit(_event(i))
            tail_dt = time.perf_counter() - t0
        finally:
            flightrec.disable(tail)
    finally:
        if prev is not None:
            flightrec.enable(prev)
    tail_bytes = os.path.getsize(tail)
    return {
        "recorder_emit_ring_us": ring_dt / _EMIT_EVENTS * 1e6,
        "recorder_emit_tail_us": tail_dt / _EMIT_EVENTS * 1e6,
        "recorder_tail_bytes_per_event": tail_bytes / _EMIT_EVENTS,
    }


register_scenario(Scenario(
    name="recorder_overhead",
    description="flight-recorder emit cost: in-memory ring vs "
    "write-through JSONL tail, plus bytes per event",
    tier="tier1",
    metrics=(
        Metric("recorder_emit_ring_us", "us/event", "lower", gate=False),
        Metric("recorder_emit_tail_us", "us/event", "lower", gate=False),
        # Bytes/event is deterministic for a fixed event shape — the one
        # recorder metric a shared CI box can gate tightly: it catches
        # event-payload bloat before every tail on every run grows.
        Metric("recorder_tail_bytes_per_event", "bytes", "lower",
               floor=0.25),
    ),
    setup=_recorder_setup,
    teardown=lambda ctx: shutil.rmtree(ctx["tmpdir"], ignore_errors=True),
    measure=_recorder_measure,
    repetitions=5,
    timeout_s=120.0,
))


# -- sanitizer overhead -------------------------------------------------------

_ACQUIRES = 20_000


def _lock_loop(lock, n: int) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        with lock:
            pass
    return time.perf_counter() - t0


def _sanitizer_measure(_ctx) -> dict:
    import threading

    from ..analysis.sanitize import sanitize_scope

    plain_dt = _lock_loop(threading.Lock(), _ACQUIRES)
    with sanitize_scope():
        # Constructed INSIDE the armed scope: instrumentation covers
        # locks created while armed (the dsst sanitize model).
        armed_dt = _lock_loop(threading.Lock(), _ACQUIRES)
    return {
        "sanitizer_plain_acquire_us": plain_dt / _ACQUIRES * 1e6,
        "sanitizer_armed_acquire_us": armed_dt / _ACQUIRES * 1e6,
        "sanitizer_overhead_ratio": (
            armed_dt / plain_dt if plain_dt > 0 else 0.0
        ),
    }


register_scenario(Scenario(
    name="sanitizer_overhead",
    description="dsst sanitize interposition cost per uncontended lock "
    "acquire, armed vs plain",
    tier="tier1",
    metrics=(
        Metric("sanitizer_plain_acquire_us", "us/acquire", "lower",
               gate=False),
        Metric("sanitizer_armed_acquire_us", "us/acquire", "lower",
               gate=False),
        # The ratio cancels host speed; floor 1.5 tolerates scheduler
        # noise while catching an interposition cost blow-up.
        Metric("sanitizer_overhead_ratio", "x", "lower", floor=1.5),
    ),
    measure=_sanitizer_measure,
    repetitions=5,
    timeout_s=120.0,
))


# -- slo overhead -------------------------------------------------------------

_SLO_OBS = 20_000


def _slo_values(n: int) -> list[float]:
    """Deterministic observation values spanning the sketch's decades
    (a single constant would hit one bucket's cache line forever and
    understate the bisect cost)."""
    return [10.0 ** (-5 + (i % 83) / 11.0) for i in range(n)]


def _slo_overhead_measure(_ctx) -> dict:
    from ..telemetry.registry import MetricsRegistry
    from ..telemetry.windows import SlidingQuantile

    reg = MetricsRegistry()
    # dsst: ignore[telemetry-registry] private throwaway registry: a bench probe series, never rendered on /metrics
    hist = reg.histogram("slo_overhead_probe_hist")
    sketch = SlidingQuantile()
    vals = _slo_values(_SLO_OBS)
    # Warm both paths (allocate the first digest, touch the buckets).
    for v in vals[:64]:
        hist.observe(v)
        sketch.observe(v)
    t0 = time.perf_counter()
    for v in vals:
        hist.observe(v)
    hist_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for v in vals:
        sketch.observe(v)
    sketch_dt = time.perf_counter() - t0
    sketch_us = sketch_dt / _SLO_OBS * 1e6
    # The acceptance bound, self-verified like feeder_e2e's attribution
    # cross-check: one windowed emit must cost under 1% of a 1 ms step
    # budget (i.e. <10 µs) — a sketch that got expensive must fail the
    # scenario loudly, not ship a quietly slower hot path.
    frac = sketch_us / 1000.0
    if frac >= 0.01:
        raise RuntimeError(
            f"windowed-sketch emit costs {sketch_us:.2f}us — "
            f"{frac:.1%} of a 1ms step budget (>=1%); the sliding "
            "window stopped being histogram-cheap"
        )
    return {
        "slo_sketch_observe_us": sketch_us,
        "slo_hist_observe_us": hist_dt / _SLO_OBS * 1e6,
        "slo_overhead_ratio": (
            sketch_dt / hist_dt if hist_dt > 0 else 0.0
        ),
        "slo_emit_step_fraction": frac,
    }


register_scenario(Scenario(
    name="slo_overhead",
    description="windowed-sketch emit cost vs plain histogram observe "
    "(the live SLO plane's hot-path tax); self-verifies the sketch "
    "emit stays under 1% of a 1ms step budget",
    tier="tier1",
    metrics=(
        Metric("slo_sketch_observe_us", "us/observe", "lower",
               gate=False),
        Metric("slo_hist_observe_us", "us/observe", "lower", gate=False),
        # The ratio cancels host speed (the sanitizer_overhead idiom);
        # floor 1.5 tolerates scheduler noise while catching a sketch
        # cost blow-up vs the histogram it rides next to.
        Metric("slo_overhead_ratio", "x", "lower", floor=1.5),
        Metric("slo_emit_step_fraction", "fraction", "lower",
               gate=False),
    ),
    measure=_slo_overhead_measure,
    repetitions=5,
    timeout_s=120.0,
))


# -- serving loadgen ----------------------------------------------------------


def _scrape_slo(port: int) -> dict:
    """The stub server's /slo document (schema v1)."""
    import http.client
    import json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", "/slo")
        resp = conn.getresponse()
        return json.loads(resp.read())
    finally:
        conn.close()


def _serving_setup():
    from . import loadgen

    proc, port = loadgen.spawn_stub_server(
        micro_batch=8, score_ms=5.0, batch_window_ms=5.0, queue_depth=64,
    )
    return {"proc": proc, "port": port}


def _serving_teardown(ctx) -> None:
    ctx["proc"].terminate()
    ctx["proc"].wait(15)


def _serving_measure(ctx) -> dict:
    from . import loadgen

    report = loadgen.run_load(
        "127.0.0.1", ctx["port"], b"0", threads=8, duration_s=1.2,
    )
    fill = report["server"]["batch_fill"]["mean"]
    # The live-vs-offline agreement check: the server's windowed p99
    # (the SLO plane's serving_latency_p99 value, fed by the same
    # requests the loadgen just timed) must agree with the loadgen's
    # offline p99 — both route through telemetry.windows.quantile, so
    # the only legitimate gaps are the sketch's bounded bucket error
    # and the client's socket overhead. A wild disagreement means the
    # live plane is measuring something other than what clients see.
    status = _scrape_slo(ctx["port"])
    lat = next(
        (o for o in status.get("objectives", [])
         if o["name"] == "serving_latency_p99"), {},
    )
    live_p99 = lat.get("value")
    offline_p99 = report["latency_s"]["p99"]
    if (
        live_p99 and offline_p99
        and report["requests"] >= 100
        and not (0.2 <= live_p99 / offline_p99 <= 5.0)
    ):
        raise RuntimeError(
            f"live windowed p99 {live_p99 * 1e3:.1f}ms disagrees with "
            f"the loadgen's offline p99 {offline_p99 * 1e3:.1f}ms far "
            "beyond sketch error + client overhead — the live SLO "
            "plane is not measuring what clients experience"
        )
    return {
        "serving_throughput_rps": report["throughput_rps"],
        "serving_p50_ms": (report["latency_s"]["p50"] or 0.0) * 1e3,
        "serving_p99_ms": (offline_p99 or 0.0) * 1e3,
        "serving_batch_fill_mean": fill if fill is not None else 0.0,
        "serving_live_p99_ms": (live_p99 or 0.0) * 1e3,
        # The /slo snapshot rides the artifact so CI can gate on it
        # after the bench: `dsst slo check --report <bench json>`.
        "_extra": {"loadgen": report, "slo": status},
    }


register_scenario(Scenario(
    name="serving",
    description="closed-loop loadgen vs the stub-scorer scheduler "
    "subprocess over real sockets (admission, decode pool, "
    "cross-request batching) — the BENCH_serving.json producer",
    tier="tier1",
    metrics=(
        Metric("serving_throughput_rps", "req/sec", "higher",
               floor=0.6),
        Metric("serving_p50_ms", "ms", "lower", floor=0.6),
        Metric("serving_p99_ms", "ms", "lower", gate=False),
        Metric("serving_batch_fill_mean", "images", "higher", gate=False),
        Metric("serving_live_p99_ms", "ms", "lower", gate=False),
    ),
    setup=_serving_setup,
    teardown=_serving_teardown,
    measure=_serving_measure,
    repetitions=3,
    timeout_s=240.0,
))


# -- LM token serving ---------------------------------------------------------


def _lm_serving_setup():
    from . import loadgen

    # Deadline + inter-token budget armed: the /slo snapshot riding the
    # artifact must show ZERO firing objectives under this load (the
    # acceptance gate `dsst slo check --strict --url` judges).
    proc, port = loadgen.spawn_stub_lm_server(
        slots=8, max_len=96, prefill_buckets="8,16", step_ms=3.0,
        queue_depth=32, deadline_ms=2000.0, inter_token_budget_ms=250.0,
    )
    return {"proc": proc, "port": port}


def _lm_serving_teardown(ctx) -> None:
    ctx["proc"].terminate()
    ctx["proc"].wait(15)


def _lm_serving_measure(ctx) -> dict:
    from . import loadgen

    prompt = [1, 2, 3, 4]
    # 8 concurrent streams vs ONE stream against the same engine: the
    # stub decoder's per-STEP cost is independent of active slots, so
    # the ratio isolates what continuous batching buys — the ISSUE's
    # acceptance bar is >= 2x at 8 streams.
    multi = loadgen.run_lm_load(
        "127.0.0.1", ctx["port"], prompt=prompt, max_new_tokens=16,
        streams=8, duration_s=1.2,
    )
    solo = loadgen.run_lm_load(
        "127.0.0.1", ctx["port"], prompt=prompt, max_new_tokens=16,
        streams=1, duration_s=0.8,
    )
    if multi["requests"] == 0 or solo["requests"] == 0:
        raise RuntimeError(
            f"lm loadgen starved: {multi['requests']} multi-stream / "
            f"{solo['requests']} solo requests completed"
        )
    if multi["trace_propagated"] != multi["requests"]:
        raise RuntimeError(
            "trace propagation broken on /generate: "
            f"{multi['trace_propagated']}/{multi['requests']} streams "
            "echoed the injected trace id"
        )
    speedup = (
        multi["tokens_per_sec"] / solo["tokens_per_sec"]
        if solo["tokens_per_sec"] else 0.0
    )
    status = _scrape_slo(ctx["port"])
    return {
        "lm_tokens_per_sec": multi["tokens_per_sec"],
        "lm_solo_tokens_per_sec": solo["tokens_per_sec"],
        "lm_batching_speedup": round(speedup, 3),
        "lm_ttft_p99_ms": (multi["ttft_s"]["p99"] or 0.0) * 1e3,
        "lm_inter_token_p99_ms": (
            multi["inter_token_s"]["p99"] or 0.0
        ) * 1e3,
        "_extra": {"loadgen": multi, "solo": solo, "slo": status},
    }


register_scenario(Scenario(
    name="lm_serving",
    description="closed-loop streamed-generation loadgen vs the "
    "stub-decoder continuous-batching engine subprocess (slot "
    "admission, bucketed prefill, chunked token streaming) — the "
    "BENCH_lm_serving.json producer; gates tokens/sec and the "
    ">=2x batching speedup at 8 streams",
    tier="tier1",
    metrics=(
        Metric("lm_tokens_per_sec", "tokens/sec", "higher", floor=0.6),
        Metric("lm_solo_tokens_per_sec", "tokens/sec", "higher",
               gate=False),
        Metric("lm_batching_speedup", "x", "higher", floor=0.6),
        Metric("lm_ttft_p99_ms", "ms", "lower", gate=False),
        Metric("lm_inter_token_p99_ms", "ms", "lower", gate=False),
    ),
    setup=_lm_serving_setup,
    teardown=_lm_serving_teardown,
    measure=_lm_serving_measure,
    repetitions=3,
    timeout_s=240.0,
))

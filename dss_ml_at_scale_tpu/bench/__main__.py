"""Isolated scenario child: ``python -m dss_ml_at_scale_tpu.bench``.

One scenario per process — a hung backend, an OOM, or a watchdog kill
takes down this child, never the harness. Protocol (the bench.py child
discipline, now framework-owned): exactly one JSON line on stdout
(``{"scenario", "samples", "extra", "completed"}`` on success,
``{"scenario", "failed": true, "error"}`` on failure), per-repetition
durable partials at ``--partial`` for parent-side salvage, exit 0
either way — the parent judges the JSON, not the return code.

The environment fingerprint is deliberately NOT computed here: the
parent fingerprints once (it may need a jax import this child's
scenario never pays for), and child-side samples are keyed by the
parent's view of the host they both run on.
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback

from .core import get_scenario, measure_scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dss_ml_at_scale_tpu.bench")
    ap.add_argument("--scenario", required=True)
    ap.add_argument("--partial", default=None)
    ap.add_argument("--repetitions", type=int, default=None)
    args = ap.parse_args(argv)
    try:
        sc = get_scenario(args.scenario)
        record = measure_scenario(
            sc, repetitions=args.repetitions, partial_path=args.partial,
            env={},
        )
    except BaseException:  # noqa: BLE001 - the JSON line IS the report
        record = {
            "scenario": args.scenario,
            "failed": True,
            "error": traceback.format_exc(limit=8),
        }
    # dsst: ignore[no-print] the one-JSON-line child protocol: stdout is the parent's only channel
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())

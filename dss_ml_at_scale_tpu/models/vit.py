"""Vision Transformer classifier — the second image-model family.

The reference's deep-learning track fine-tunes torchvision classifiers
(``deep_learning/2.distributed-data-loading-petastorm.py:150`` pins
ResNet-50, with the rest of the torchvision zoo one import away); this
module provides the transformer half of that zoo, built TPU-first:

- **Patchify as one convolution**: a stride-``patch`` conv lowers to a
  single big MXU matmul over NHWC input (the same layout the decode
  pipeline emits) — no im2col, no per-patch gather.
- **Everything after patchify is matmuls**: pre-LN encoder blocks whose
  attention and MLP are einsums XLA tiles straight onto the MXU in
  bf16; no BatchNorm anywhere, so there is no cross-batch state, no
  sync-BN collective, and the DP/TP shardings of the classifier track
  apply unchanged (``ClassifierTask`` handles the empty ``batch_stats``
  collection).
- **Static shapes throughout**: sequence length is fixed by
  ``image/patch`` at init; the CLS token and learned position table are
  ordinary parameters.

Geometry presets mirror the standard ViT family (ViT-Ti/16, ViT-S/16)
at any crop divisible by the patch size.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class ViTBlock(nn.Module):
    """Pre-LN encoder block: LN → MHA → residual, LN → MLP → residual.

    Attention is bidirectional (no causal mask — images, not text),
    computed by ``ops.flash_attention.attention_reference`` — the same
    helper the LM stack's Pallas kernel is verified against, so the
    attention numerics live in exactly one place.
    """

    dim: int
    num_heads: int
    mlp_ratio: int = 4
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # [b, n, dim]
        head_dim = self.dim // self.num_heads
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, dtype=self.dtype, name=name
        )

        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        q = dense(self.dim, "q")(h)
        k = dense(self.dim, "k")(h)
        v = dense(self.dim, "v")(h)

        def heads(t):  # [b, n, dim] -> [b, heads, n, head_dim]
            b, n, _ = t.shape
            return t.reshape(b, n, self.num_heads, head_dim).transpose(
                0, 2, 1, 3
            )

        from ..ops.flash_attention import attention_reference

        q, k, v = heads(q), heads(k), heads(v)
        # Bidirectional (causal=False) — images, not text; same helper
        # as the LM family, so attention numerics live in ONE place.
        out = attention_reference(q, k, v, causal=False)
        b, _, n, _ = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, n, self.dim)
        x = x + dense(self.dim, "attn_out")(out)

        h = nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x)
        h = dense(self.dim * self.mlp_ratio, "mlp_in")(h)
        # Exact (erf) GELU: torch nn.GELU's default, so converted
        # torchvision-layout weights reproduce torch numerics.
        h = nn.gelu(h, approximate=False)
        return x + dense(self.dim, "mlp_out")(h)


class ViT(nn.Module):
    """Vision Transformer over NHWC images.

    ``__call__(images, train=...)`` matches the ``ClassifierTask``
    model contract (``parallel/trainer.py``); ``train`` is accepted for
    interface parity — the architecture is deterministic (no dropout,
    no batch statistics), which is also what makes it embarrassingly
    shardable.
    """

    num_classes: int
    patch: int = 16
    dim: int = 192
    depth: int = 12
    num_heads: int = 3
    mlp_ratio: int = 4
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):  # [b, h, w, 3] NHWC
        b, h, w, _ = x.shape
        if h % self.patch or w % self.patch:
            raise ValueError(
                f"image {h}x{w} not divisible by patch {self.patch}"
            )
        x = x.astype(self.dtype)
        # Patchify: one stride-p conv == one MXU matmul over NHWC.
        x = nn.Conv(
            self.dim,
            kernel_size=(self.patch, self.patch),
            strides=(self.patch, self.patch),
            padding="VALID",
            dtype=self.dtype,
            name="patch_embed",
        )(x)
        n = (h // self.patch) * (w // self.patch)
        x = x.reshape(b, n, self.dim)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, self.dim), jnp.float32
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, self.dim)), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, n + 1, self.dim),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)

        for i in range(self.depth):
            x = ViTBlock(
                dim=self.dim,
                num_heads=self.num_heads,
                mlp_ratio=self.mlp_ratio,
                dtype=self.dtype,
                name=f"block_{i}",
            )(x)

        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        # Classify from the CLS token; logits in f32 for a stable loss.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x[:, 0]
        )


def vit_t16(num_classes: int, **kw) -> ViT:
    """ViT-Ti/16: 192 dim, 12 blocks, 3 heads (~5.7M params)."""
    return ViT(num_classes=num_classes, patch=16, dim=192, depth=12,
               num_heads=3, **kw)


def vit_s16(num_classes: int, **kw) -> ViT:
    """ViT-S/16: 384 dim, 12 blocks, 6 heads (~22M params)."""
    return ViT(num_classes=num_classes, patch=16, dim=384, depth=12,
               num_heads=6, **kw)

"""Pipeline-parallel Transformer LM: the block stack rides the GPipe ring.

Composition of the two beyond-parity pieces (SURVEY.md §2.3 lists PP as
absent from the reference): ``TransformerBlock``s are the uniform-width
stages of :func:`~dss_ml_at_scale_tpu.parallel.pipeline.spmd_pipeline`
— one layer's parameters resident per "pipe" device, microbatches of
embedded activations hopping the ``ppermute`` ring — while the token/
position embeddings and the (untied) head run replicated outside the
pipeline (they are cheap relative to the stack and keep the GPipe
equal-shape contract clean).

``PipelinedLM`` is deliberately NOT a flax module: the stage stacking,
mesh binding, and replicated prologue/epilogue are explicit, so the
whole model is a pytree of arrays plus pure functions — the same shape
as the rest of the framework's jitted programs. ``PipelinedLMTask``
adapts it to the standard Trainer via the ``state_shardings`` /
``batch_size_of`` hooks.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.pipeline import (
    check_same_mesh,
    moment_sharding,
    spmd_pipeline,
    stack_stage_params,
    stage_sharding,
)
from .transformer import TransformerBlock, next_token_loss, rms_norm  # noqa: F401


@dataclasses.dataclass
class PipelinedLM:
    """Decoder-only LM with its layer stack pipelined over a mesh axis.

    ``n_stages = mesh.shape[axis_name]`` transformer blocks, one per
    pipe device. Batches are microbatched: ``tokens`` arrive as
    ``[n_micro, micro_batch, seq]`` int32.
    """

    vocab_size: int
    dim: int
    num_heads: int
    mesh: Mesh
    axis_name: str = "pipe"
    batch_axis: str | None = None
    max_seq: int = 512
    mlp_ratio: int = 4
    dtype: Any = jnp.float32

    def __post_init__(self):
        self.n_stages = self.mesh.shape[self.axis_name]
        for name, val in (("vocab_size", self.vocab_size),
                          ("max_seq", self.max_seq), ("dim", self.dim)):
            # The optimizer-moment sharding heuristic keys on a leading
            # dim equal to n_stages; a collision would mis-shard.
            if val == self.n_stages:
                raise ValueError(
                    f"{name}={val} equals the pipe stage count; pick a "
                    "different size (stage-dim detection would collide)"
                )
        from .transformer import _select_attention

        self._block = TransformerBlock(
            num_heads=self.num_heads,
            dtype=self.dtype,
            mlp_ratio=self.mlp_ratio,
            # Plain-XLA attention: the stage runs inside shard_map + scan,
            # where the differentiable merge-free backend is the safe one.
            attention_fn=_select_attention("reference"),
        )
        self._run = spmd_pipeline(
            lambda p, x: self._block.apply({"params": p}, x),
            self.mesh,
            self.axis_name,
            self.batch_axis,
        )

    # -- params -----------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        k_tok, k_pos, k_stage, k_head = jax.random.split(rng, 4)

        def init_stage(r):
            return self._block.init(
                r, jnp.zeros((1, self.max_seq, self.dim), self.dtype)
            )["params"]

        return {
            "tok": 0.02 * jax.random.normal(
                k_tok, (self.vocab_size, self.dim), jnp.float32
            ),
            "pos": 0.02 * jax.random.normal(
                k_pos, (self.max_seq, self.dim), jnp.float32
            ),
            "stages": stack_stage_params(init_stage, k_stage, self.n_stages),
            "norm_scale": jnp.ones((self.dim,), jnp.float32),
            "head": 0.02 * jax.random.normal(
                k_head, (self.dim, self.vocab_size), jnp.float32
            ),
        }

    def param_shardings(self, params: dict) -> dict:
        """Stages live on the pipe axis; everything else replicates."""
        replicated = NamedSharding(self.mesh, P())
        out = {
            k: jax.tree_util.tree_map(lambda _: replicated, v)
            for k, v in params.items()
            if k != "stages"
        }
        out["stages"] = stage_sharding(
            params["stages"], self.mesh, self.axis_name
        )
        return out

    # -- forward ----------------------------------------------------------

    def apply(self, params: dict, tokens: jax.Array) -> jax.Array:
        """``[n_micro, mb, seq]`` int32 → ``[n_micro, mb, seq, vocab]``."""
        m, mb, s = tokens.shape
        if s > self.max_seq:
            raise ValueError(f"seq {s} > max_seq {self.max_seq}")
        x = (
            params["tok"].astype(self.dtype)[tokens]
            + params["pos"][None, None, :s].astype(self.dtype)
        )
        # [n_micro, mb, s, d] through the stage ring; the pipeline treats
        # axis 0 as the microbatch schedule and shards axis 1 over
        # batch_axis when configured.
        y = self._run(params["stages"], x)
        y32 = rms_norm(y.astype(jnp.float32), params["norm_scale"])
        return y32 @ params["head"]


@dataclasses.dataclass
class PipelinedLMTask:
    """Trainer task: next-token loss over the pipelined LM."""

    model: PipelinedLM
    tx: Any = None
    learning_rate: float = 3e-4
    tokens_key: str = "tokens"

    default_best_metric = "val_loss"
    default_best_mode = "min"

    def __post_init__(self):
        if self.tx is None:
            import optax

            self.tx = optax.adam(self.learning_rate)

    def batch_size_of(self, batch) -> int:
        t = batch[self.tokens_key]
        return int(t.shape[0]) * int(t.shape[1])

    def init_state(self, rng, sample_batch):
        from ..parallel.trainer import TrainState

        params = self.model.init(rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats={},
            opt_state=self.tx.init(params),
        )

    def state_shardings(self, state, mesh: Mesh):
        check_same_mesh(self.model.mesh, mesh, "PipelinedLM")
        replicated = NamedSharding(mesh, P())
        return type(state)(
            step=replicated,
            params=self.model.param_shardings(state.params),
            batch_stats={},
            # Leading-dim==n_stages detection is safe: __post_init__
            # rejects vocab/max_seq/dim colliding with the stage count.
            opt_state=moment_sharding(
                state.opt_state, mesh, self.model.axis_name,
                self.model.n_stages,
            ),
        )

    def _loss(self, params, tokens):
        logits = self.model.apply(params, tokens)
        m, mb, s, v = logits.shape
        return next_token_loss(
            logits.reshape(m * mb, s, v), tokens.reshape(m * mb, s)
        )

    def train_step(self, state, batch):
        import optax

        tokens = jnp.asarray(batch[self.tokens_key])
        loss, grads = jax.value_and_grad(self._loss)(state.params, tokens)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            type(state)(
                step=state.step + 1,
                params=new_params,
                batch_stats=state.batch_stats,
                opt_state=new_opt,
            ),
            {"train_loss": loss, "train_ppl": jnp.exp(loss)},
        )

    def eval_step(self, state, batch):
        tokens = jnp.asarray(batch[self.tokens_key])
        loss = self._loss(state.params, tokens)
        return {"val_loss": loss, "val_ppl": jnp.exp(loss)}

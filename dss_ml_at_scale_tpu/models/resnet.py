"""ResNet in Flax, TPU-first.

Capability parity with the reference's torchvision ``resnet50`` wrapped in
``ImageNetClassificationModel`` (reference
``deep_learning/2.distributed-data-loading-petastorm.py:135-165``). Built
natively rather than ported:

- NHWC layout (TPU's native conv layout; torchvision is NCHW).
- bfloat16 compute / float32 params by default — the MXU's preferred mix.
- BatchNorm batch statistics are computed inside the jitted, batch-sharded
  program, so under a ``data``-sharded mesh the reduction is *global*
  (XLA inserts the cross-chip collective): sync-BN falls out of SPMD for
  free, where DDP needs a separate SyncBatchNorm wrapper.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so each block starts as identity —
        # standard ResNet-v1.5 training recipe.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), (self.strides, self.strides),
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNetBlock(nn.Module):
    """Basic 3x3 -> 3x3 block (ResNet-18/34)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), (self.strides, self.strides), name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet; ``stage_sizes=[3,4,6,3]`` + bottleneck = ResNet-50."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    # torchvision pads stride-2 convs symmetrically ((k-1)//2 each side)
    # where XLA's SAME pads asymmetrically on even inputs. Irrelevant when
    # training from scratch; REQUIRED for numerical parity when loading
    # torchvision-layout pretrained weights (models/pretrained.py).
    torch_padding: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.torch_padding:
            def conv(features, kernel_size, strides=(1, 1), **kw):
                pad = tuple(((k - 1) // 2, (k - 1) // 2) for k in kernel_size)
                return nn.Conv(
                    features, kernel_size, strides, padding=pad,
                    use_bias=False, dtype=self.dtype, **kw,
                )
        else:
            conv = functools.partial(
                nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
            )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="norm_init")(x)
        x = self.act(x)
        x = nn.max_pool(
            x, (3, 3), strides=(2, 2),
            padding=((1, 1), (1, 1)) if self.torch_padding else "SAME",
        )
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)

"""ResNet in Flax, TPU-first.

Capability parity with the reference's torchvision ``resnet50`` wrapped in
``ImageNetClassificationModel`` (reference
``deep_learning/2.distributed-data-loading-petastorm.py:135-165``). Built
natively rather than ported:

- NHWC layout (TPU's native conv layout; torchvision is NCHW).
- bfloat16 compute / float32 params by default — the MXU's preferred mix.
- BatchNorm batch statistics are computed inside the jitted, batch-sharded
  program, so under a ``data``-sharded mesh the reduction is *global*
  (XLA inserts the cross-chip collective): sync-BN falls out of SPMD for
  free, where DDP needs a separate SyncBatchNorm wrapper.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


def _norm_relu(norm, act, fused, y, **kw):
    """norm-then-relu, fused into one op when the fused path is on.

    The single site encoding the fused-vs-unfused activation decision —
    the stem and both block classes all route through it, so the two
    configurations cannot drift apart.
    """
    if fused:
        return norm(act="relu", **kw)(y)
    return act(norm(**kw)(y))


class _Conv1x1Kernel(nn.Module):
    """Parameter-only stand-in for an ``nn.Conv`` whose matmul executes
    inside the fused Pallas kernel (ops/fused_matmul.py).  Same param
    name, shape, dtype, and initializer as ``nn.Conv`` — checkpoints
    and the pretrained-weights converter see an identical tree."""

    features: int

    @nn.compact
    def __call__(self, in_features: int):
        return self.param(
            "kernel", nn.initializers.lecun_normal(),
            (1, 1, in_features, self.features), jnp.float32,
        )


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu
    # Fused path: relu (and the final residual add) execute INSIDE the
    # norm (ops/fused_norm.py) so backward saves no extra activations.
    # "pallas" additionally fuses the middle BN's APPLY into the third
    # (1x1) conv as a Pallas matmul prologue (ops/fused_matmul.py), so
    # that site's normalized activation never exists in HBM.
    fused: bool | str = False
    # Batch-sharded SPMD form of the pallas site: when a mesh is given,
    # the kernel runs per-shard inside shard_map over `pallas_axis`
    # (stats stay global HLO; the op psums its backward sums — see
    # ops/fused_matmul.py).  None = single-device pallas_call.
    pallas_mesh: Any = None
    pallas_axis: str = "data"

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = _norm_relu(self.norm, self.act, self.fused, y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        if self.fused == "pallas":
            from ..ops.fused_matmul import bn_relu_matmul

            # Stats in HLO (module auto-named BatchNorm_1, same tree as
            # the other paths), apply + matmul in the Pallas kernel.
            scale, bias, mean, var = self.norm()(y, stats_only=True)
            kernel = _Conv1x1Kernel(
                self.filters * 4, name="Conv_2"
            )(y.shape[-1])
            eps, running = 1e-5, False
            if hasattr(self.norm, "keywords"):
                eps = self.norm.keywords.get("epsilon", eps)
                running = self.norm.keywords.get(
                    "use_running_average", running
                )
            kernel = kernel.astype(y.dtype)
            # Init traces the body with a tiny (often 1-sample) batch
            # that cannot satisfy shard_map's divisibility; the
            # single-device path is math-identical, so init always
            # takes it.
            if self.pallas_mesh is not None and not self.is_initializing():
                from jax import shard_map
                from jax.sharding import PartitionSpec as P

                axis = self.pallas_axis
                m_global = y.shape[0] * y.shape[1] * y.shape[2]

                def per_shard(y_s, scale, bias, mean, var, kernel):
                    return bn_relu_matmul(
                        y_s, scale, bias, mean, var, kernel, eps=eps,
                        batch_stats=not running, axis_name=axis,
                        global_count=m_global,
                    )

                # check_vma=False: the varying-mesh-axes checker cannot
                # see through pallas_call.
                y = shard_map(
                    per_shard, mesh=self.pallas_mesh,
                    in_specs=(P(axis, None, None, None),
                              P(), P(), P(), P(), P()),
                    out_specs=P(axis, None, None, None),
                    check_vma=False,
                )(y, scale, bias, mean, var, kernel)
            else:
                y = bn_relu_matmul(
                    y, scale, bias, mean, var, kernel,
                    eps=eps,
                    # Eval/frozen BN: stats are constants; the
                    # backward's statistics correction must not apply.
                    batch_stats=not running,
                )
        else:
            y = _norm_relu(self.norm, self.act, self.fused, y)
            y = self.conv(self.filters * 4, (1, 1))(y)
        if residual.shape[-1] != self.filters * 4 or self.strides != 1:
            residual = self.conv(
                self.filters * 4, (1, 1), (self.strides, self.strides),
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        # Zero-init the last BN scale so each block starts as identity —
        # standard ResNet-v1.5 training recipe.
        if self.fused:
            return self.norm(scale_init=nn.initializers.zeros_init(),
                             act="relu")(y, residual=residual)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        return self.act(residual + y)


class ResNetBlock(nn.Module):
    """Basic 3x3 -> 3x3 block (ResNet-18/34)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable = nn.relu
    fused: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = _norm_relu(self.norm, self.act, self.fused, y)
        y = self.conv(self.filters, (3, 3))(y)
        if residual.shape[-1] != self.filters or self.strides != 1:
            residual = self.conv(
                self.filters, (1, 1), (self.strides, self.strides), name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        if self.fused:
            return self.norm(scale_init=nn.initializers.zeros_init(),
                             act="relu")(y, residual=residual)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet; ``stage_sizes=[3,4,6,3]`` + bottleneck = ResNet-50."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    # torchvision pads stride-2 convs symmetrically ((k-1)//2 each side)
    # where XLA's SAME pads asymmetrically on even inputs. Irrelevant when
    # training from scratch; REQUIRED for numerical parity when loading
    # torchvision-layout pretrained weights (models/pretrained.py).
    torch_padding: bool = False
    # Fused BN+relu(+residual) with a minimal-residual custom VJP
    # (ops/fused_norm.py) — cuts the HBM bytes that cap v5e throughput
    # (BASELINE.md). Parameter paths are IDENTICAL to the unfused model,
    # so checkpoints and pretrained weights port both ways.
    # "pallas" (bottleneck blocks only) additionally fuses the middle
    # BN's apply into the third 1x1 conv as a Pallas matmul prologue
    # (ops/fused_matmul.py) — the second HBM byte cut.  Single-device
    # by default; pass pallas_mesh (+ pallas_axis) for the
    # batch-sharded shard_map form under a mesh.
    fused_bn: bool | str = False
    pallas_mesh: Any = None
    pallas_axis: str = "data"

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.torch_padding:
            def conv(features, kernel_size, strides=(1, 1), **kw):
                pad = tuple(((k - 1) // 2, (k - 1) // 2) for k in kernel_size)
                return nn.Conv(
                    features, kernel_size, strides, padding=pad,
                    use_bias=False, dtype=self.dtype, **kw,
                )
        else:
            conv = functools.partial(
                nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
            )
        if self.fused_bn:
            if self.act is not nn.relu:
                raise ValueError("fused_bn supports act=nn.relu only")
            if (self.fused_bn == "pallas"
                    and self.block_cls is not BottleneckBlock):
                # Only the bottleneck block has the 1x1-conv site the
                # Pallas prologue fusion targets; silently running the
                # plain fused path would benchmark the wrong program.
                raise ValueError(
                    "fused_bn='pallas' requires block_cls=BottleneckBlock "
                    "(ResNet-50/101); use fused_bn=True for basic-block "
                    "models"
                )
        if self.pallas_mesh is not None and self.fused_bn != "pallas":
            # Same silent-wrong-program hazard in the other direction.
            raise ValueError(
                "pallas_mesh= requires fused_bn='pallas' (a mesh with "
                "the HLO fused path would be silently ignored)"
            )
        if self.fused_bn:
            from ..ops.fused_norm import BatchNorm as FusedBatchNorm

            norm_cls = FusedBatchNorm
        else:
            norm_cls = nn.BatchNorm
        norm = functools.partial(
            norm_cls,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = _norm_relu(norm, self.act, self.fused_bn, x, name="norm_init")
        x = nn.max_pool(
            x, (3, 3), strides=(2, 2),
            padding=((1, 1), (1, 1)) if self.torch_padding else "SAME",
        )
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                # (pallas implies BottleneckBlock — validated above.)
                block_kw = (
                    {"pallas_mesh": self.pallas_mesh,
                     "pallas_axis": self.pallas_axis}
                    if self.fused_bn == "pallas" else {}
                )
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                    fused=self.fused_bn,
                    **block_kw,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)

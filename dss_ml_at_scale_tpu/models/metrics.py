"""Loss/metric functions, written to be globally correct under SPMD.

The reference computes cross-entropy + torchmetrics multiclass accuracy
per rank (``deep_learning/2...py:167-208``); here every reduction happens
inside the jitted batch-sharded program, so means are automatically global
across chips — no separate metric-sync pass.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax
from jax import lax


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def multiclass_accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Top-1 accuracy (the reference's torchmetrics Accuracy, num_classes=1000)."""
    return (jnp.argmax(logits, axis=-1) == labels).mean()


def topk_accuracy(logits: jnp.ndarray, labels: jnp.ndarray,
                  k: int) -> jnp.ndarray:
    """Top-k accuracy (the standard ImageNet top-5 companion metric).

    ``lax.top_k`` keeps the reduction inside the jitted program, so the
    SPMD globality note in the module docstring applies unchanged.
    """
    if k > logits.shape[-1]:
        raise ValueError(
            f"top-{k} accuracy needs at least {k} classes, got "
            f"{logits.shape[-1]} (check eval_topk)"
        )
    _, top = lax.top_k(logits, k)
    return (top == labels[:, None]).any(axis=-1).mean()

"""Torchvision-layout ResNet weights → Flax param tree.

The reference fine-tunes torchvision's pretrained
``resnet50(weights="IMAGENET1K_V2")`` (reference
``deep_learning/2.distributed-data-loading-petastorm.py:150``). This
module loads publicly-published weights in that layout — a torch
``state_dict`` (.pt/.pth) or an .npz with the same key names — into
:class:`~dss_ml_at_scale_tpu.models.resnet.ResNet`, so ``dsst train
--pretrained <path>`` fine-tunes instead of cold-starting.

Layout mapping (torchvision → this repo's Flax ResNet):

==========================  =======================================
``conv1.weight``            ``conv_init/kernel`` (OIHW → HWIO)
``bn1.weight/bias``         ``norm_init/scale|bias``
``bn1.running_mean/var``    batch_stats ``norm_init/mean|var``
``layerL.i.convK.weight``   ``<Block>_n/Conv_{K-1}/kernel``
``layerL.i.bnK.*``          ``<Block>_n/BatchNorm_{K-1}/*``
``layerL.i.downsample.0``   ``<Block>_n/conv_proj``
``layerL.i.downsample.1``   ``<Block>_n/norm_proj``
``fc.weight/bias``          ``Dense_0/kernel`` (transposed) ``|bias``
==========================  =======================================

with ``n = sum(stage_sizes[:L-1]) + i`` (Flax auto-numbers blocks
globally, torchvision per stage). Load with ``torch_padding=True`` on
the model — torchvision pads stride-2 convs symmetrically where XLA's
SAME does not, and the running BatchNorm statistics embed that choice.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Mapping

import numpy as np


def _to_numpy(v) -> np.ndarray:
    if hasattr(v, "detach"):  # torch.Tensor without importing torch here
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def load_state_dict(path: str | Path) -> dict[str, np.ndarray]:
    """Read a torchvision-layout state dict from .pt/.pth (torch) or .npz.

    Lightning-style checkpoints are unwrapped twice: the ``state_dict``
    envelope, then any uniform submodule-attribute prefix (a
    ``LightningModule`` holding the backbone as ``self.model`` saves keys
    like ``model.conv1.weight``) — detected from wherever
    ``conv1.weight`` actually lives, so the attribute name doesn't
    matter.
    """
    path = Path(path)
    if path.suffix == ".npz":
        with np.load(path) as z:
            return _strip_wrapper_prefix({k: z[k] for k in z.files})
    import torch

    import pickle

    try:
        state = torch.load(path, map_location="cpu", weights_only=True)
    except pickle.UnpicklingError as exc:
        # Real Lightning checkpoints carry benign non-tensor payloads
        # (hyper_parameters as an argparse.Namespace, optimizer_states)
        # that the strict unpickler rejects. Allowlist Namespace — still
        # weights_only, no arbitrary code execution — and retry; anything
        # beyond that should be re-exported as a plain state dict.
        import argparse as _argparse

        safe_globals = getattr(torch.serialization, "safe_globals", None)
        if safe_globals is None:  # torch < 2.4
            raise pickle.UnpicklingError(
                f"{exc} (this torch lacks torch.serialization.safe_globals;"
                " re-export the checkpoint as a plain state dict:"
                " torch.save(model.state_dict(), path))"
            ) from exc
        with safe_globals([_argparse.Namespace]):
            state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, Mapping) and "state_dict" in state:
        state = state["state_dict"]
    return _strip_wrapper_prefix({k: _to_numpy(v) for k, v in state.items()})


# Unlike conv1/bn1 (which recur inside blocks as layerN.M.conv1...), the
# classifier head exists exactly once at the torchvision layout's root.
# ResNets anchor at fc.weight, ViTs at heads.head.weight.
_ANCHORS = ("fc.weight", "heads.head.weight")


def _strip_wrapper_prefix(state: dict) -> dict:
    """Strip a uniform wrapper prefix (``model.``/``module.``/anything)."""
    for anchor in _ANCHORS:
        if anchor in state:
            return state
    for anchor in _ANCHORS:
        prefixes = {k[: -len(anchor)] for k in state if k.endswith(anchor)}
        if len(prefixes) != 1:
            continue  # no (or ambiguous) anchor: try the next family
        prefix = prefixes.pop()
        if not prefix or not prefix.endswith("."):
            # Either no wrapper, or the anchor match is a partial key
            # like ``aux_fc.weight`` — stripping would mangle siblings.
            continue
        return {
            (k[len(prefix):] if k.startswith(prefix) else k): v
            for k, v in state.items()
        }
    return state


def _torch_name(path: tuple[str, ...], stage_sizes) -> tuple[str, str]:
    """(flax collection path) → (torch key, transform tag)."""
    col, *rest = path
    bounds = np.cumsum([0, *stage_sizes])

    def block_pos(name: str) -> tuple[int, int]:
        n = int(name.rsplit("_", 1)[1])
        layer = int(np.searchsorted(bounds, n, side="right"))  # 1-based
        return layer, n - int(bounds[layer - 1])

    if rest[0] == "conv_init":
        return "conv1.weight", "conv"
    if rest[0] == "norm_init":
        return f"bn1.{_bn_leaf(col, rest[-1])}", "none"
    if rest[0] == "Dense_0":
        return ("fc.weight", "dense") if rest[1] == "kernel" else ("fc.bias", "none")
    # Block-level parameters.
    layer, i = block_pos(rest[0])
    inner, leaf = rest[1], rest[-1]
    prefix = f"layer{layer}.{i}"
    if inner.startswith("Conv_"):
        return f"{prefix}.conv{int(inner[5:]) + 1}.weight", "conv"
    if inner.startswith("BatchNorm_"):
        return f"{prefix}.bn{int(inner[10:]) + 1}.{_bn_leaf(col, leaf)}", "none"
    if inner == "conv_proj":
        return f"{prefix}.downsample.0.weight", "conv"
    if inner == "norm_proj":
        return f"{prefix}.downsample.1.{_bn_leaf(col, leaf)}", "none"
    raise KeyError(f"no torchvision mapping for flax path {path}")


def _bn_leaf(collection: str, leaf: str) -> str:
    if collection == "batch_stats":
        return {"mean": "running_mean", "var": "running_var"}[leaf]
    return {"scale": "weight", "bias": "bias"}[leaf]


_TRANSFORMS = {
    "conv": lambda a: np.transpose(a, (2, 3, 1, 0)),  # OIHW -> HWIO
    "dense": lambda a: np.transpose(a, (1, 0)),  # [out,in] -> [in,out]
    "none": lambda a: a,
}


def _fill_template(
    state: Mapping[str, Any],
    variables: Mapping[str, Any],
    resolve,
    *,
    reinit_module: str | None,
):
    """Template-guided conversion shared by both families.

    Walks every leaf of ``variables`` (from ``model.init``); ``resolve``
    maps a flax key path to ``(torch key candidates, transform)``. A
    leaf under the top-level module ``reinit_module`` keeps its fresh
    initialization (the new-class-count fine-tune case).
    """
    import jax

    state = {k: _to_numpy(v) for k, v in state.items()}

    def fill(path, leaf):
        keys = tuple(
            getattr(p, "key", getattr(p, "name", str(p))) for p in path
        )
        if reinit_module is not None and keys[1] == reinit_module:
            return leaf
        candidates, transform = resolve(keys)
        key = next((k for k in candidates if k in state), None)
        if key is None:
            raise KeyError(
                f"pretrained state has none of {candidates!r} "
                f"(for flax {keys})"
            )
        arr = transform(state[key])
        if arr.shape != leaf.shape:
            raise ValueError(
                f"{key}: shape {arr.shape} != model {leaf.shape} "
                f"(flax {keys})"
            )
        return np.asarray(arr, dtype=np.asarray(leaf).dtype)

    return jax.tree_util.tree_map_with_path(fill, dict(variables))


def convert_torchvision_resnet(
    state: Mapping[str, Any],
    variables: Mapping[str, Any],
    stage_sizes,
    *,
    reinit_head: bool = False,
) -> dict:
    """Fill a model's ``variables`` template from a torchvision state dict.

    Template-guided: every leaf of ``variables`` (from ``model.init``)
    must find its torch tensor with the right shape after transform;
    extra torch keys (e.g. ``num_batches_tracked``) are ignored.

    ``reinit_head=True`` keeps the template's (freshly initialized)
    classifier head instead of loading ``fc.*`` — the fine-tune-to-new-
    labels case where the model's class count differs from the
    checkpoint's.
    """

    def resolve(keys):
        torch_key, tag = _torch_name(keys, stage_sizes)
        return [torch_key], _TRANSFORMS[tag]

    return _fill_template(
        state, variables, resolve,
        reinit_module="Dense_0" if reinit_head else None,
    )


def _vit_torch_name(keys: tuple[str, ...]):
    """Flax ViT param path → (torch key candidates, transform tag).

    Torchvision ``VisionTransformer`` layout: ``conv_proj``,
    ``class_token``, ``encoder.pos_embedding``,
    ``encoder.layers.encoder_layer_i.{ln_1, self_attention, ln_2, mlp}``,
    ``encoder.ln``, ``heads.head``.  The fused attention projection
    (``in_proj_weight`` [3d, d]) is split into this repo's separate
    q/k/v Dense rows; the MLP's two Linears appear as Sequential indices
    (``mlp.0`` / ``mlp.3``) on current torchvision and as
    ``mlp.linear_1`` / ``mlp.linear_2`` on older releases — both are
    accepted.
    """
    mod, *rest = keys[1:]  # keys[0] is the collection ("params")
    leaf = keys[-1]
    wb = "weight" if leaf in ("kernel", "scale") else "bias"
    if mod == "patch_embed":
        return ([f"conv_proj.{wb}"], "conv" if leaf == "kernel" else "none")
    if mod == "cls_token":
        return (["class_token"], "none")
    if mod == "pos_embed":
        return (["encoder.pos_embedding"], "none")
    if mod == "ln_final":
        return ([f"encoder.ln.{wb}"], "none")
    if mod == "head":
        return ([f"heads.head.{wb}"],
                "dense" if leaf == "kernel" else "none")
    if mod.startswith("block_"):
        i = int(mod[6:])
        prefix = f"encoder.layers.encoder_layer_{i}"
        inner = rest[0]
        if inner == "ln_attn":
            return ([f"{prefix}.ln_1.{wb}"], "none")
        if inner == "ln_mlp":
            return ([f"{prefix}.ln_2.{wb}"], "none")
        if inner in ("q", "k", "v"):
            part = "in_proj_weight" if leaf == "kernel" else "in_proj_bias"
            tag = f"qkv_{inner}_{'dense' if leaf == 'kernel' else 'bias'}"
            return ([f"{prefix}.self_attention.{part}"], tag)
        if inner == "attn_out":
            return ([f"{prefix}.self_attention.out_proj.{wb}"],
                    "dense" if leaf == "kernel" else "none")
        if inner == "mlp_in":
            return ([f"{prefix}.mlp.0.{wb}", f"{prefix}.mlp.linear_1.{wb}"],
                    "dense" if leaf == "kernel" else "none")
        if inner == "mlp_out":
            return ([f"{prefix}.mlp.3.{wb}", f"{prefix}.mlp.linear_2.{wb}"],
                    "dense" if leaf == "kernel" else "none")
    raise KeyError(f"no torchvision ViT mapping for flax path {keys}")


def _qkv_split(which: str):
    idx = {"q": 0, "k": 1, "v": 2}[which]

    def split(a: np.ndarray) -> np.ndarray:
        d = a.shape[0] // 3
        return a[idx * d:(idx + 1) * d]

    return split


def _vit_transform(tag: str, arr: np.ndarray) -> np.ndarray:
    if tag.startswith("qkv_"):
        _, which, kind = tag.split("_")
        arr = _qkv_split(which)(arr)
        return arr.T if kind == "dense" else arr
    return _TRANSFORMS[tag](arr)


def convert_torchvision_vit(
    state: Mapping[str, Any],
    variables: Mapping[str, Any],
    *,
    reinit_head: bool = False,
) -> dict:
    """Fill a ViT ``variables`` template from a torchvision state dict.

    Template-guided like :func:`convert_torchvision_resnet`: every leaf
    must find a torch tensor of the right post-transform shape; extra
    torch keys are ignored. ``reinit_head=True`` keeps the fresh head.
    """

    def resolve(keys):
        candidates, tag = _vit_torch_name(keys)
        return candidates, lambda a: _vit_transform(tag, a)

    return _fill_template(
        state, variables, resolve,
        reinit_module="head" if reinit_head else None,
    )


def load_pretrained_vit(path: str | Path, model, image_size: int = 224):
    """Path → converted ``{"params"}`` for a :class:`ViT`.

    The position table is sized by ``image_size``; a checkpoint trained
    at a different resolution fails the shape check loudly (position
    interpolation is not implemented). A missing or class-count-
    mismatched ``heads.head`` keeps the fresh initialization.
    """
    import jax
    import jax.numpy as jnp

    template = model.init(
        jax.random.key(0),
        jnp.zeros((1, image_size, image_size, 3)),
        train=False,
    )
    state = load_state_dict(path)
    reinit_head = (
        "heads.head.weight" not in state
        or state["heads.head.weight"].shape[0] != model.num_classes
    )
    return convert_torchvision_vit(state, template, reinit_head=reinit_head)


def export_torchvision(variables: Mapping[str, Any], model,
                       path: str | Path) -> dict[str, np.ndarray]:
    """Inverse converter: Flax variables → torchvision-layout ``.npz``.

    The migration loop runs both ways: a model trained here can be
    handed back to a torch-ecosystem consumer (or to this framework's
    own ``--pretrained``, which reads ``.npz`` in the same layout).
    Transforms are the exact inverses of the load path — HWIO→OIHW,
    [in,out]→[out,in], and for ViT the q/k/v kernels re-fused into
    ``in_proj_weight``/``in_proj_bias``.

    Returns the exported dict (also written to ``path``).
    """
    import jax

    is_vit = "cls_token" in variables.get("params", {})
    out: dict[str, np.ndarray] = {}
    partial_qkv: dict[str, dict[str, np.ndarray]] = {}

    def put(path_keys, leaf):
        keys = tuple(
            getattr(p, "key", getattr(p, "name", str(p))) for p in path_keys
        )
        arr = np.asarray(leaf)
        if is_vit:
            candidates, tag = _vit_torch_name(keys)
            key = candidates[0]
            if tag.startswith("qkv_"):
                # Collect q/k/v parts; fuse once all three are present.
                _, which, kind = tag.split("_")
                slot = partial_qkv.setdefault(key, {})
                slot[which] = arr.T if kind == "dense" else arr
                if len(slot) == 3:
                    out[key] = np.concatenate(
                        [slot["q"], slot["k"], slot["v"]], axis=0
                    )
                return
        else:
            key, tag = _torch_name(keys, model.stage_sizes)
        if tag == "conv":
            arr = np.transpose(arr, (3, 2, 0, 1))  # HWIO -> OIHW
        elif tag == "dense":
            arr = np.transpose(arr, (1, 0))  # [in,out] -> [out,in]
        out[key] = arr

    path = Path(path)
    if path.suffix != ".npz":
        # np.savez silently appends ".npz", writing a different path
        # than the caller asked for; refuse instead of lying.
        raise ValueError(f"export path must end in .npz (got {path})")
    jax.tree_util.tree_map_with_path(put, dict(variables))
    missing = [k for k, v in partial_qkv.items() if len(v) != 3]
    if missing:
        raise ValueError(f"incomplete q/k/v triples for {missing}")
    np.savez(path, **out)
    return out


def load_pretrained_resnet(path: str | Path, model, image_size: int = 224):
    """Path → converted ``{"params", "batch_stats"}`` for ``model``.

    ``model`` should be built with ``torch_padding=True`` for exact
    torchvision numerics (see module docstring). When the model's class
    count differs from the checkpoint's ``fc`` rows, the head is kept at
    its fresh initialization (backbone-only fine-tune).
    """
    import jax
    import jax.numpy as jnp

    template = model.init(
        jax.random.key(0), jnp.zeros((1, image_size, image_size, 3)), train=False
    )
    state = load_state_dict(path)
    # Fresh head when the checkpoint can't supply one that fits: class
    # count differs, or it's a backbone-only export with no fc at all.
    reinit_head = (
        "fc.weight" not in state
        or state["fc.weight"].shape[0] != model.num_classes
    )
    return convert_torchvision_resnet(
        state, template, model.stage_sizes, reinit_head=reinit_head
    )

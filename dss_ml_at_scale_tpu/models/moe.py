"""Mixture-of-Experts MLP with expert parallelism over a mesh axis.

No MoE exists in the reference (SURVEY.md §2.3 lists EP as absent), but
the framework's mesh-based sharding layer is built so expert parallelism
is the same mechanism as DP/TP/SP/PP: experts live on an ``"expert"``
mesh axis and XLA inserts the dispatch/combine all-to-alls from the
sharding annotations alone.

Design (Switch-Transformer-style, dense dispatch — the XLA-friendly
shape):

- Top-1 routing with a float32 router. Each token picks one expert; a
  per-expert capacity ``C = ceil(tokens/E · capacity_factor)`` bounds the
  work per expert so every shape stays static. Tokens over capacity fall
  through the residual (their combine weight is zero) — standard Switch
  semantics, never a runtime error.
- Dispatch and combine are einsums against a ``[tokens, E, C]`` one-hot
  tensor. On an expert-sharded mesh the ``ecd`` operands are sharded on
  ``e`` while token operands are batch-sharded, so GSPMD lowers the two
  einsums to the canonical all-to-all pair riding ICI.
- The expert FFN itself is one batched einsum over the leading expert
  dimension (``[E, C, d] × [E, d, h]``) — E independent MLPs as a single
  MXU-shaped contraction, no Python loop over experts.
- The standard load-balance auxiliary loss (E · Σ fraction·probability)
  is sowed under ``intermediates/aux_loss`` so any trainer can fold
  ``aux_weight * aux`` into its objective without threading extra
  outputs through the stack.

``TransformerLM(ffn="moe", ...)`` swaps this layer in for the dense MLP
in every block (models/transformer.py), giving the LM track an
expert-parallel configuration that rides the identical Trainer/ring
machinery.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _constrain(x, mesh: Mesh | None, spec: P):
    """Sharding hint that is a no-op off-mesh (single device, tests)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


class MoEMLP(nn.Module):
    """Top-1 routed MLP over ``num_experts`` experts.

    Input/output: ``[batch, seq, dim]``. When ``mesh``/``axis_name`` are
    set, expert-dimension operands are sharding-constrained to the axis
    (expert parallelism); otherwise the same program runs on one device.
    """

    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    mesh: Any = None
    axis_name: str = "expert"
    router_noise: float = 0.0  # jitter std at train time (0 = deterministic)

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        b, s, d = x.shape
        e = self.num_experts
        h = self.mlp_ratio * d
        tokens = x.reshape(b * s, d)
        t = tokens.shape[0]
        capacity = max(1, math.ceil(t * self.capacity_factor / e))

        # -- router (f32: softmax over experts must not run in bf16) ------
        logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(tokens.astype(jnp.float32))
        if self.router_noise > 0.0 and not deterministic:
            rng = self.make_rng("router")
            logits = logits + self.router_noise * jax.random.normal(
                rng, logits.shape
            )
        probs = jax.nn.softmax(logits, axis=-1)  # [t, e]
        expert_index = jnp.argmax(probs, axis=-1)  # [t]
        expert_gate = jnp.take_along_axis(
            probs, expert_index[:, None], axis=-1
        )[:, 0]  # [t]

        # -- load-balance aux loss (Switch eq. 4): E · Σ_e f_e · p_e ------
        one_hot = jax.nn.one_hot(expert_index, e, dtype=jnp.float32)  # [t, e]
        fraction = one_hot.mean(axis=0)
        mean_prob = probs.mean(axis=0)
        aux_loss = e * jnp.sum(fraction * mean_prob)
        self.sow("intermediates", "aux_loss", aux_loss)

        # -- capacity assignment ------------------------------------------
        # Position of each token within its chosen expert's queue; tokens
        # whose position exceeds capacity are dropped (combine weight 0).
        position = (jnp.cumsum(one_hot, axis=0) - 1.0) * one_hot  # [t, e]
        pos_in_expert = position.sum(axis=-1)  # [t]
        within = pos_in_expert < capacity
        dispatch = (
            one_hot[:, :, None]
            * jax.nn.one_hot(
                pos_in_expert.astype(jnp.int32), capacity, dtype=jnp.float32
            )[:, None, :]
            * within[:, None, None]
        )  # [t, e, c] one-hot
        combine = dispatch * expert_gate[:, None, None]  # [t, e, c]

        # -- dispatch → batched expert FFN → combine ----------------------
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(self.dtype), tokens.astype(self.dtype)
        )
        expert_in = _constrain(expert_in, self.mesh, P(self.axis_name, None, None))

        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(), (e, d, h), jnp.float32
        ).astype(self.dtype)
        b_up = self.param(
            "b_up", nn.initializers.zeros, (e, 1, h), jnp.float32
        ).astype(self.dtype)
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(), (e, h, d), jnp.float32
        ).astype(self.dtype)
        b_down = self.param(
            "b_down", nn.initializers.zeros, (e, 1, d), jnp.float32
        ).astype(self.dtype)
        w_up = _constrain(w_up, self.mesh, P(self.axis_name, None, None))
        w_down = _constrain(w_down, self.mesh, P(self.axis_name, None, None))

        hidden = nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w_up) + b_up)
        hidden = _constrain(hidden, self.mesh, P(self.axis_name, None, None))
        expert_out = jnp.einsum("ech,ehd->ecd", hidden, w_down) + b_down
        expert_out = _constrain(
            expert_out, self.mesh, P(self.axis_name, None, None)
        )

        out = jnp.einsum(
            "tec,ecd->td", combine.astype(self.dtype), expert_out
        )
        return out.reshape(b, s, d)


def collect_aux_loss(intermediates) -> jax.Array:
    """Sum every sowed ``aux_loss`` in an ``intermediates`` collection."""
    total = 0.0
    for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates):
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if "aux_loss" in keys:
            total = total + jnp.sum(leaf)
    return jnp.asarray(total, jnp.float32)

"""Decoder-only Transformer LM with pluggable attention backends.

No transformer exists in the reference (SURVEY.md §5.7) — this family is
here because long-context is first-class in the TPU build: it is the
workload that exercises flash attention (single device) and ring
attention (sequence-parallel over a mesh axis), the same way ResNet-50
exercises the data-parallel trainer.

TPU-first choices: bf16 activations by default (MXU-native), RMSNorm +
pre-norm residuals, fused-friendly GELU MLP, static shapes throughout,
and attention selected at construction ("flash" | "ring" | "reference")
so the same module runs single-chip or sequence-sharded without code
changes.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.flash_attention import attention_reference, flash_attention


def rms_norm(x, scale, eps: float = 1e-6):
    """The pure RMSNorm expression (f32 math), shared by the flax module
    and non-flax models (PipelinedLM)."""
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps
    )
    return norm * scale


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return rms_norm(x, scale, self.eps).astype(self.dtype)


def _select_attention(kind: str, **ring_kwargs) -> Callable:
    if kind == "flash":
        return lambda q, k, v: flash_attention(q, k, v, causal=True)
    if kind == "reference":
        return lambda q, k, v: attention_reference(q, k, v, causal=True)
    if kind == "ring":
        from ..parallel.ring import ring_attention

        mesh = ring_kwargs.get("mesh")
        axis_name = ring_kwargs.get("axis_name")
        if mesh is None or axis_name is None:
            raise ValueError("attention='ring' needs mesh= and axis_name=")
        return lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, axis_name=axis_name, causal=True
        )
    raise ValueError(f"unknown attention backend {kind!r}")


class TransformerBlock(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    mlp_ratio: int = 4
    attention_fn: Callable = None  # bound by TransformerLM
    # "dense" | "moe" — MoE swaps the MLP for an expert-parallel
    # MoEMLP (models/moe.py) routed top-1 over num_experts.
    ffn: str = "dense"
    num_experts: int = 0
    capacity_factor: float = 1.25
    expert_mesh: Any = None
    expert_axis: str = "expert"
    router_noise: float = 0.0

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True, cache=None,
                 pos=None):
        """Full-context training/eval pass, or — with ``cache``/``pos``
        — a KV-cached pass returning ``(x, new_cache)``: one decode
        step when ``x`` is [b, 1, dim], or a pos-0 prefill writing the
        whole chunk's k/v when longer. All branches call the SAME
        submodules in the SAME order, so the parameter tree is
        identical and trained checkpoints decode without conversion."""
        if self.ffn not in ("dense", "moe"):
            raise ValueError(f"unknown ffn {self.ffn!r}: expected 'dense' or 'moe'")
        if self.ffn == "moe" and self.num_experts < 1:
            raise ValueError("ffn='moe' requires num_experts >= 1")
        b, s, dim = x.shape
        head_dim = dim // self.num_heads

        h = RMSNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * dim, use_bias=False, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [b, s, dim] -> [b, heads, s, head_dim]
            return t.reshape(b, s, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        if cache is not None:
            # Both cached modes write this call's k/v into the cache
            # slab at ``pos``; they differ only in how attn is computed.
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                cache["k"], heads(k), pos, axis=2
            )
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                cache["v"], heads(v), pos, axis=2
            )
            new_cache = {"k": k_cache, "v": v_cache}
            if s == 1:
                # Decode step: attend the single query over the cache
                # with a <= pos mask. Plain einsums — at q_len 1 there
                # is nothing for a kernel to tile.
                scores = jnp.einsum(
                    "bhqd,bhkd->bhqk", heads(q), k_cache,
                    preferred_element_type=jnp.float32,
                ) / jnp.sqrt(head_dim).astype(jnp.float32)
                mask = jnp.arange(k_cache.shape[2]) <= pos
                scores = jnp.where(mask[None, None, None, :], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum(
                    "bhqk,bhkd->bhqd", probs, v_cache.astype(jnp.float32)
                ).astype(self.dtype)
            else:
                # Prefill (pos == 0, enforced by TransformerLM): the
                # whole prompt in ONE causal parallel pass — the
                # training-shaped matmuls, nothing earlier to attend to.
                attn = self.attention_fn(heads(q), heads(k), heads(v))
        else:
            attn = self.attention_fn(heads(q), heads(k), heads(v))
            new_cache = None
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, dim)
        x = x + nn.Dense(dim, use_bias=False, dtype=self.dtype, name="proj")(attn)

        h = RMSNorm(dtype=self.dtype)(x)
        if self.ffn == "moe":
            from .moe import MoEMLP

            x = x + MoEMLP(
                num_experts=self.num_experts,
                mlp_ratio=self.mlp_ratio,
                capacity_factor=self.capacity_factor,
                dtype=self.dtype,
                mesh=self.expert_mesh,
                axis_name=self.expert_axis,
                router_noise=self.router_noise,
                name="moe",
            )(h, deterministic=deterministic)
        else:
            h = nn.Dense(self.mlp_ratio * dim, dtype=self.dtype, name="mlp_up")(h)
            h = nn.gelu(h)
            x = x + nn.Dense(dim, dtype=self.dtype, name="mlp_down")(h)
        return x if cache is None else (x, new_cache)


class TransformerLM(nn.Module):
    """Causal LM: token + learned position embeddings, N pre-norm blocks.

    ``attention``: "flash" (Pallas kernel, single device), "ring"
    (sequence-parallel — pass ``mesh`` and ``axis_name``), or "reference".
    """

    vocab_size: int
    dim: int = 512
    num_heads: int = 8
    num_layers: int = 4
    max_seq: int = 2048
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention: str = "flash"
    mesh: Any = None
    axis_name: str | None = None
    # Expert-parallel MoE FFN (models/moe.py): ffn="moe" with
    # num_experts > 0 swaps every block's MLP; expert_mesh/expert_axis
    # shard the experts (EP) — None runs the same program on one device.
    ffn: str = "dense"
    num_experts: int = 0
    capacity_factor: float = 1.25
    expert_mesh: Any = None
    expert_axis: str = "expert"
    # Router jitter std at train time; needs an apply-time "router" rng
    # and deterministic=False to take effect.
    router_noise: float = 0.0

    @nn.compact
    def __call__(self, tokens, *, deterministic: bool = True, cache=None,
                 pos=None):
        # [b, s] int32 -> [b, s, vocab] f32 logits; with ``cache``/
        # ``pos``: a KV-cached pass returning ``(logits, new_cache)`` —
        # one decode step on [b, 1] tokens (logits [b, vocab]) or a
        # pos-0 prefill on the whole prompt (logits [b, s, vocab]); see
        # ``generate``.
        b, s = tokens.shape
        if s > self.max_seq:
            raise ValueError(f"seq {s} > max_seq {self.max_seq}")
        decoding = cache is not None
        if decoding and self.attention == "ring":
            raise ValueError(
                "KV-cache decode is single-device; a sequence-sharded "
                "(ring) model should decode with attention='flash' or "
                "'reference' on the gathered sequence"
            )
        if decoding and s > 1 and (not isinstance(pos, int) or pos != 0):
            # A multi-token cached pass attends only WITHIN the chunk;
            # continuing from a non-empty cache would silently ignore
            # the cached prefix. Prefill is pos=0 only.
            raise ValueError(
                "multi-token cached calls are prefill only (pos=0); "
                "continue from a prefilled cache one token at a time"
            )
        # Single-token decode needs no parallel attention kernel; the
        # multi-token cases (training pass, or PREFILL writing the
        # prompt's k/v into the cache in one causal pass) do.
        attention_fn = (
            None if decoding and s == 1 else _select_attention(
                self.attention, mesh=self.mesh, axis_name=self.axis_name
            )
        )
        tok = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype, name="tok_embed")
        pos_table = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_seq, self.dim),
        )
        if decoding:
            pos_emb = jax.lax.dynamic_slice_in_dim(pos_table, pos, s)[None]
        else:
            pos_emb = pos_table[None, :s]
        x = tok(tokens) + pos_emb.astype(self.dtype)
        new_cache = []
        for i in range(self.num_layers):
            block = TransformerBlock(
                num_heads=self.num_heads,
                dtype=self.dtype,
                mlp_ratio=self.mlp_ratio,
                attention_fn=attention_fn,
                ffn=self.ffn,
                num_experts=self.num_experts,
                capacity_factor=self.capacity_factor,
                expert_mesh=self.expert_mesh,
                expert_axis=self.expert_axis,
                router_noise=self.router_noise,
                name=f"block_{i}",
            )
            if decoding:
                x, layer_cache = block(
                    x, deterministic=deterministic, cache=cache[i], pos=pos
                )
                new_cache.append(layer_cache)
            else:
                x = block(x, deterministic=deterministic)
        x = RMSNorm(dtype=self.dtype)(x)
        # Logits in f32 for a stable softmax cross-entropy.
        logits = nn.Dense(
            self.vocab_size, use_bias=False, dtype=jnp.float32, name="lm_head"
        )(x)
        if decoding:
            # Single-step callers get the one row; prefill callers get
            # the full [b, s, vocab] (the last row seeds sampling).
            return (logits[:, 0] if s == 1 else logits), tuple(new_cache)
        return logits


def init_kv_cache(model: TransformerLM, batch: int):
    """Zeroed per-layer K/V buffers sized [b, heads, max_seq, head_dim]."""
    head_dim = model.dim // model.num_heads
    shape = (batch, model.num_heads, model.max_seq, head_dim)
    return tuple(
        {"k": jnp.zeros(shape, model.dtype), "v": jnp.zeros(shape, model.dtype)}
        for _ in range(model.num_layers)
    )


def decode_step(model: TransformerLM, variables, tokens, cache, pos):
    """One KV-cache decode step: ``[b, t]`` tokens at ``pos`` → logits.

    The single-token apply that :func:`generate`'s scan iterates — and
    the program the ``dsst audit`` registry lowers with the cache
    donated (the continuous-batching serving tier will hold one live
    cache per slot, so the step must alias it, not copy it). Factored
    out so the audited program and the sampling loop can never diverge.
    """
    return model.apply(variables, tokens, cache=cache, pos=pos)


def generate(
    model: TransformerLM,
    variables,
    prompt: jax.Array,  # [b, p] int32
    n_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Autoregressive sampling: ``[b, p + n_tokens]`` continuations.

    Two phases, both static-shaped: a CHUNKED PREFILL — the whole
    prompt through one causal parallel pass (the training-shaped
    matmuls; flash attention applies) that also writes the prompt's
    k/v into the cache — then one ``lax.scan`` of the single-token
    decode step for sampling. ``temperature=0`` is greedy argmax;
    otherwise softmax sampling at the given temperature, optionally
    truncated to the ``top_k`` most likely tokens.
    """
    b, p = prompt.shape
    cache = init_kv_cache(model, b)
    # Cap against the CACHE SLAB, not the caller's arithmetic: the
    # scatter at position ``pos`` is bounded by the preallocated k/v
    # length (``cache[l]["k"].shape[2]``), so that shape — not whatever
    # budget the caller computed — is the one capacity that matters.
    # (Today the two agree at ``model.max_seq``; deriving from the
    # buffer keeps the guard correct if they ever diverge, e.g. a
    # short-arena cache like the serving tier's slot arenas.)
    max_len = cache[0]["k"].shape[2]
    total = p + int(n_tokens)
    if total > max_len:
        raise ValueError(
            f"prompt + n_tokens = {total} > max_seq {max_len} "
            "(the preallocated KV-cache capacity)"
        )
    if rng is None:
        rng = jax.random.key(0)

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits / temperature
        if top_k is not None:
            kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
            scaled = jnp.where(scaled < kth, -1e30, scaled)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    if n_tokens <= 0:
        return prompt

    try:
        prefill_logits, cache = model.apply(
            variables, prompt, cache=cache, pos=0
        )
    except ValueError:
        # The flash kernel rejects some awkward prompt lengths (block
        # divisibility); the reference path accepts any shape and the
        # cache contents are identical.  Only the flash model gets this
        # fallback: for any other attention mode a ValueError is a real
        # configuration error (e.g. a ring model whose decode step
        # cannot run here anyway) and must stay loud rather than be
        # masked by a retry that would fail later in the scan.
        if getattr(model, "attention", None) != "flash":
            raise
        prefill_logits, cache = model.clone(
            attention="reference"
        ).apply(variables, prompt, cache=cache, pos=0)
    # Prefill returns [b, vocab] for a 1-token prompt (the decode-step
    # shape) and [b, p, vocab] otherwise.
    last_logits = prefill_logits if p == 1 else prefill_logits[:, -1]

    def step(carry, i):
        cache, logits, key = carry
        key, sub = jax.random.split(key)
        nxt = sample(logits, sub)  # the token at position p + i
        logits, cache = decode_step(
            model, variables, nxt[:, None], cache, p + i
        )
        return (cache, logits, key), nxt

    # n_tokens - 1 decode steps; the final token needs no model call
    # (its logits are already in the carry).
    (_, final_logits, key), sampled = jax.lax.scan(
        step, (cache, last_logits, rng), jnp.arange(n_tokens - 1)
    )
    key, sub = jax.random.split(key)
    last = sample(final_logits, sub)
    gen = jnp.concatenate(
        [jnp.swapaxes(sampled, 0, 1), last[:, None]], axis=1
    )
    return jnp.concatenate([prompt, gen], axis=1)


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean cross entropy of positions 0..s-2 predicting tokens 1..s-1."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)

"""Decoder-only Transformer LM with pluggable attention backends.

No transformer exists in the reference (SURVEY.md §5.7) — this family is
here because long-context is first-class in the TPU build: it is the
workload that exercises flash attention (single device) and ring
attention (sequence-parallel over a mesh axis), the same way ResNet-50
exercises the data-parallel trainer.

TPU-first choices: bf16 activations by default (MXU-native), RMSNorm +
pre-norm residuals, fused-friendly GELU MLP, static shapes throughout,
and attention selected at construction ("flash" | "ring" | "reference")
so the same module runs single-chip or sequence-sharded without code
changes.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.flash_attention import attention_reference, flash_attention


def rms_norm(x, scale, eps: float = 1e-6):
    """The pure RMSNorm expression (f32 math), shared by the flax module
    and non-flax models (PipelinedLM)."""
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps
    )
    return norm * scale


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return rms_norm(x, scale, self.eps).astype(self.dtype)


def _select_attention(kind: str, **ring_kwargs) -> Callable:
    if kind == "flash":
        return lambda q, k, v: flash_attention(q, k, v, causal=True)
    if kind == "reference":
        return lambda q, k, v: attention_reference(q, k, v, causal=True)
    if kind == "ring":
        from ..parallel.ring import ring_attention

        mesh = ring_kwargs.get("mesh")
        axis_name = ring_kwargs.get("axis_name")
        if mesh is None or axis_name is None:
            raise ValueError("attention='ring' needs mesh= and axis_name=")
        return lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, axis_name=axis_name, causal=True
        )
    raise ValueError(f"unknown attention backend {kind!r}")


class TransformerBlock(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    mlp_ratio: int = 4
    attention_fn: Callable = None  # bound by TransformerLM
    # "dense" | "moe" — MoE swaps the MLP for an expert-parallel
    # MoEMLP (models/moe.py) routed top-1 over num_experts.
    ffn: str = "dense"
    num_experts: int = 0
    capacity_factor: float = 1.25
    expert_mesh: Any = None
    expert_axis: str = "expert"
    router_noise: float = 0.0

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        if self.ffn not in ("dense", "moe"):
            raise ValueError(f"unknown ffn {self.ffn!r}: expected 'dense' or 'moe'")
        if self.ffn == "moe" and self.num_experts < 1:
            raise ValueError("ffn='moe' requires num_experts >= 1")
        b, s, dim = x.shape
        head_dim = dim // self.num_heads

        h = RMSNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * dim, use_bias=False, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [b, s, dim] -> [b, heads, s, head_dim]
            return t.reshape(b, s, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        attn = self.attention_fn(heads(q), heads(k), heads(v))
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, dim)
        x = x + nn.Dense(dim, use_bias=False, dtype=self.dtype, name="proj")(attn)

        h = RMSNorm(dtype=self.dtype)(x)
        if self.ffn == "moe":
            from .moe import MoEMLP

            x = x + MoEMLP(
                num_experts=self.num_experts,
                mlp_ratio=self.mlp_ratio,
                capacity_factor=self.capacity_factor,
                dtype=self.dtype,
                mesh=self.expert_mesh,
                axis_name=self.expert_axis,
                router_noise=self.router_noise,
                name="moe",
            )(h, deterministic=deterministic)
        else:
            h = nn.Dense(self.mlp_ratio * dim, dtype=self.dtype, name="mlp_up")(h)
            h = nn.gelu(h)
            x = x + nn.Dense(dim, dtype=self.dtype, name="mlp_down")(h)
        return x


class TransformerLM(nn.Module):
    """Causal LM: token + learned position embeddings, N pre-norm blocks.

    ``attention``: "flash" (Pallas kernel, single device), "ring"
    (sequence-parallel — pass ``mesh`` and ``axis_name``), or "reference".
    """

    vocab_size: int
    dim: int = 512
    num_heads: int = 8
    num_layers: int = 4
    max_seq: int = 2048
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention: str = "flash"
    mesh: Any = None
    axis_name: str | None = None
    # Expert-parallel MoE FFN (models/moe.py): ffn="moe" with
    # num_experts > 0 swaps every block's MLP; expert_mesh/expert_axis
    # shard the experts (EP) — None runs the same program on one device.
    ffn: str = "dense"
    num_experts: int = 0
    capacity_factor: float = 1.25
    expert_mesh: Any = None
    expert_axis: str = "expert"
    # Router jitter std at train time; needs an apply-time "router" rng
    # and deterministic=False to take effect.
    router_noise: float = 0.0

    @nn.compact
    def __call__(self, tokens, *, deterministic: bool = True):
        # [b, s] int32 -> [b, s, vocab] f32 logits
        b, s = tokens.shape
        if s > self.max_seq:
            raise ValueError(f"seq {s} > max_seq {self.max_seq}")
        attention_fn = _select_attention(
            self.attention, mesh=self.mesh, axis_name=self.axis_name
        )
        tok = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype, name="tok_embed")
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_seq, self.dim),
        )
        x = tok(tokens) + pos[None, :s].astype(self.dtype)
        for i in range(self.num_layers):
            x = TransformerBlock(
                num_heads=self.num_heads,
                dtype=self.dtype,
                mlp_ratio=self.mlp_ratio,
                attention_fn=attention_fn,
                ffn=self.ffn,
                num_experts=self.num_experts,
                capacity_factor=self.capacity_factor,
                expert_mesh=self.expert_mesh,
                expert_axis=self.expert_axis,
                router_noise=self.router_noise,
                name=f"block_{i}",
            )(x, deterministic=deterministic)
        x = RMSNorm(dtype=self.dtype)(x)
        # Logits in f32 for a stable softmax cross-entropy.
        return nn.Dense(self.vocab_size, use_bias=False, dtype=jnp.float32, name="lm_head")(x)


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean cross entropy of positions 0..s-2 predicting tokens 1..s-1."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)

"""Model zoo (Flax) + metrics. Flagship: ResNet-50 image classifier."""

from .resnet import ResNet, ResNet18, ResNet50, ResNet101  # noqa: F401
from .vit import ViT, vit_t16, vit_s16  # noqa: F401
from .metrics import (  # noqa: F401
    cross_entropy_loss,
    multiclass_accuracy,
    topk_accuracy,
)
from .transformer import (  # noqa: F401
    RMSNorm,
    TransformerLM,
    generate,
    init_kv_cache,
    next_token_loss,
)
from .moe import MoEMLP, collect_aux_loss  # noqa: F401
from .pipelined_lm import PipelinedLM, PipelinedLMTask  # noqa: F401

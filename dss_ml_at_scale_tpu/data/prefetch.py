"""Host→device prefetch with double buffering.

The last hop of the input pipeline: overlap ``device_put`` (DMA to HBM)
of batch N+1 with compute on batch N, so the TPU never waits on transfer.
The reference gets the equivalent overlap for free from torch DataLoader
+ CUDA streams; under JAX the idiom is to keep ``depth`` batches in
flight — dispatch is async, so simply holding references to the next
sharded arrays while the current step runs achieves the overlap.
"""

from __future__ import annotations

import collections
import time
from typing import Iterable, Iterator

import jax
from jax.sharding import Mesh

from .. import telemetry
from ..runtime.mesh import shard_batch_to_mesh


def prefetch_to_mesh(
    it: Iterable,
    mesh: Mesh,
    *,
    axis: str = "data",
    depth: int = 2,
    specs=None,
) -> Iterator:
    """Yield batches placed on ``mesh`` (batch-sharded), ``depth`` ahead.

    ``specs``: per-key ``PartitionSpec`` overrides (see
    :func:`~dss_ml_at_scale_tpu.runtime.mesh.shard_batch_to_mesh`) — how
    sequence-parallel batches shard the sequence dim instead of the batch
    dim.
    """
    if depth < 1:
        raise ValueError("depth must be >= 1")
    # This generator is pull-driven, so buffer occupancy is `depth` by
    # construction and carries no signal; the meaningful number is the
    # host cost of sharding + enqueueing each batch to the mesh (the
    # dispatch is async — time here is host work, not device wait).
    shard_hist = telemetry.histogram(
        "prefetch_shard_seconds",
        "host time to shard + enqueue one batch to the mesh",
    )
    buf = collections.deque()
    it = iter(it)
    for batch in it:
        t0 = time.perf_counter()
        buf.append(shard_batch_to_mesh(batch, mesh, axis=axis, specs=specs))
        shard_hist.observe(time.perf_counter() - t0)
        if len(buf) >= depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def prefetch_to_devices(it: Iterable, *, depth: int = 2) -> Iterator:
    """Single-device variant: plain async device_put pipelining."""
    buf = collections.deque()
    for batch in it:
        buf.append(jax.device_put(batch))
        if len(buf) >= depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()

"""Background feeder pipeline: host→device input work off the step loop.

The last hop of the input pipeline. The reference gets reader/compute
overlap for free from torch DataLoader + CUDA streams; the first JAX
port approximated it with *pull-driven* double buffering
(``prefetch_to_mesh``): the training thread itself still sharded and
enqueued every batch, so that host work — layout staging, sharding
validation, ``device_put`` dispatch — serialized with step dispatch.
``BENCH_r05.json`` put the cost at ~30% of step time on the CI box.

The fix is the tf.data shape (Murray et al., VLDB 2021): a dedicated
**feeder thread per consumer**. The feeder pulls host batches from the
reader, pops the row-provenance side channel (host metadata that must
never reach ``device_put``), places the batch on the mesh through a
cached-sharding batched-transfer placer
(:class:`~dss_ml_at_scale_tpu.runtime.mesh.MeshBatchPlacer`), and hands
finished on-device batches through a bounded queue. The step loop's
per-batch cost collapses to one ``queue.get`` — shard+enqueue time
overlaps step dispatch instead of adding to it, and the bounded queue
gives backpressure (at most ``depth`` batches of HBM in flight).

Telemetry (``/metrics``): ``feeder_depth`` / ``feeder_occupancy``
gauges, ``feeder_stall_seconds_total`` / ``feeder_batches_total``
counters (all labeled by feeder name), and a ``feeder_stage_seconds``
histogram of the feeder-thread cost per batch. Occupancy near ``depth``
means the input side keeps ahead of compute; occupancy pinned at zero
with stall time accruing means training is input-bound.

``prefetch_to_mesh`` / ``prefetch_to_devices`` remain as thin
generator wrappers over a feeder, preserving the old pull-driven API.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Mapping

import jax
from jax.sharding import Mesh

from .. import telemetry
from ..resilience.rollback import PROVENANCE_KEY
from ..runtime.mesh import get_batch_placer
from ..telemetry import tracecontext

_SENTINEL = object()


class _FeederFailure:
    """Wraps an exception raised in the feeder thread for re-raise in
    the consumer (same cross-thread discipline as the reader pool)."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


def split_provenance(batch) -> tuple[Any, Any]:
    """Pop the reader's row-provenance side channel off a batch.

    Provenance is host metadata (a list of RowRanges) — it must never
    reach ``device_put``. Returns ``(batch_without_provenance, prov)``;
    ``prov`` is None for batches without it (in-memory iterables,
    provenance-disabled readers).
    """
    if isinstance(batch, Mapping) and PROVENANCE_KEY in batch:
        prov = batch[PROVENANCE_KEY]
        return {k: v for k, v in batch.items() if k != PROVENANCE_KEY}, prov
    return batch, None


# dsst: ignore[lock-discipline] no lock-guarded state: every producer/consumer crossing rides the bounded Queue or the stop Event; _done/_last_handoff are single-consumer-thread by the iterator contract
class Feeder:
    """Background feeder thread feeding one consumer through a bounded queue.

    Iterating yields ``(device_batch, provenance)`` pairs in source
    order — provenance rides the queue WITH its batch, so consumer-side
    row accounting (the PR 4 health/quarantine machinery) keeps exact
    parity by construction instead of by a separate FIFO.

    Lifecycle: the thread starts at construction and exits when the
    source is exhausted, the source raises (the exception is re-raised
    from the consumer's ``next()``), or :meth:`close` is called.
    ``close`` is idempotent, unblocks a producer stuck on a full queue,
    and joins the thread — callers should close from a ``finally`` (or
    use the context manager) so no feeder thread outlives its loop.
    """

    def __init__(
        self,
        source: Iterable,
        place: Callable[[Any], Any],
        *,
        depth: int = 2,
        name: str = "feeder",
        wait_observer: Callable[[float], None] | None = None,
    ):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._source = iter(source)
        self._place = place
        self.depth = depth
        self.name = name
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._last_handoff = tracecontext.Handoff(None)
        # Bound on the instance so close() still works from a generator
        # finalizer during interpreter shutdown (module globals may be
        # torn down by then — same discipline as the reader pool).
        self._empty_exc = queue.Empty
        self._full_exc = queue.Full
        self._wait_observer = wait_observer
        # Handles bound once; the per-batch cost on both sides is plain
        # method calls on pre-resolved children.
        self._depth_gauge = telemetry.gauge(
            "feeder_depth",
            "configured bound of the feeder's on-device batch queue",
            labels=("feeder",),
        ).labels(feeder=name)
        self._depth_gauge.set(depth)
        self._occupancy = telemetry.gauge(
            "feeder_occupancy",
            "on-device batches queued at last consumer read",
            labels=("feeder",),
        ).labels(feeder=name)
        self._stall_total = telemetry.counter(
            "feeder_stall_seconds_total",
            "cumulative consumer wait on the feeder queue",
            labels=("feeder",),
        ).labels(feeder=name)
        self._batches_total = telemetry.counter(
            "feeder_batches_total",
            "batches staged, sharded, and enqueued by the feeder thread",
            labels=("feeder",),
        ).labels(feeder=name)
        self._stage_hist = telemetry.histogram(
            "feeder_stage_seconds",
            "feeder-thread time to stage + shard + enqueue one batch",
            labels=("feeder",),
        ).labels(feeder=name)
        # The live half of the stall story: windowed waits (per feeder
        # on /metrics) plus the SLO engine's aggregate stall-fraction
        # objective — "are we input-bound NOW", not "were we ever".
        self._stall_window = telemetry.window(
            "feeder_stall_window_seconds",
            "windowed consumer waits on the feeder queue",
            labels=("feeder",),
        ).labels(feeder=name)
        from ..telemetry import slo as _slo

        self._slo_note_wait = _slo.get_engine().note_feeder_wait
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"feeder-{name}"
        )
        self._thread.start()

    # -- producer (feeder thread) -----------------------------------------

    # dsst: hotpath — feeder-thread stage cost is what overlaps step dispatch
    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                # One step trace per batch, born HERE: the feeder is the
                # first thread to touch a step's data, so the step_id
                # covers reader pull → staging/sharding → (via the
                # handoff riding the queue) the consumer's step dispatch.
                with tracecontext.trace(kind="step") as tctx:
                    with telemetry.span("reader.next", feeder=self.name):
                        raw = next(self._source, _SENTINEL)
                    if raw is _SENTINEL:
                        break
                    t0 = time.perf_counter()
                    batch, prov = split_provenance(raw)
                    with telemetry.span("feeder.place", feeder=self.name):
                        device_batch = self._place(batch)
                    self._stage_hist.observe(time.perf_counter() - t0)
                if not self._put(
                    ((device_batch, prov), tracecontext.Handoff(tctx))
                ):
                    return  # closed while blocked on a full queue
                self._batches_total.inc()
        except BaseException as e:
            self._put(_FeederFailure(e))
        finally:
            self._put(_SENTINEL)

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except self._full_exc:
                continue
        return False

    # -- consumer ----------------------------------------------------------

    @property
    def occupancy(self) -> int:
        """On-device batches currently queued (approximate, lock-free)."""
        return self._queue.qsize()

    @property
    def last_handoff(self) -> tracecontext.Handoff:
        """The step-trace handoff of the batch the last ``next()``
        returned — the consumer activates it around its step dispatch so
        the step's spans join the batch's causal timeline. Read it
        before the next ``next()`` (single-consumer, like the iterator
        itself)."""
        return self._last_handoff

    def __iter__(self) -> Iterator[tuple[Any, Any]]:
        return self

    # dsst: hotpath — the consumer's entire per-batch cost: one queue.get
    def __next__(self) -> tuple[Any, Any]:
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                item = self._queue.get(timeout=0.1)
                break
            except self._empty_exc:
                if self._stop.is_set():
                    # Closed under the consumer (abort path); a clean
                    # StopIteration lets an in-flight loop wind down.
                    self._done = True
                    raise StopIteration from None
        wait = time.perf_counter() - t0
        self._stall_total.inc(wait)
        self._stall_window.observe(wait)
        self._slo_note_wait(wait)
        if self._wait_observer is not None:
            self._wait_observer(wait)
        self._occupancy.set(self._queue.qsize())
        if item is _SENTINEL:
            self._done = True
            self._thread.join(timeout=5)
            raise StopIteration
        if isinstance(item, _FeederFailure):
            self._done = True
            self._thread.join(timeout=5)
            raise item.error
        pair, self._last_handoff = item
        return pair

    def close(self) -> None:
        """Stop the feeder thread and join it. Idempotent; safe to call
        from ``finally`` on every exit path (exhaustion, exception,
        abort, preemption) — no daemon thread outlives the loop."""
        self._done = True
        self._stop.set()
        # Drain so a producer blocked on a full queue observes the stop.
        try:
            while True:
                self._queue.get_nowait()
        except self._empty_exc:
            pass
        self._thread.join(timeout=5)
        # Release queued device batches (HBM) and the source promptly.
        try:
            while True:
                self._queue.get_nowait()
        except self._empty_exc:
            pass

    def __enter__(self) -> "Feeder":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class MeshFeeder(Feeder):
    """Feeder that places batches on a mesh, batch-sharded.

    The placer is shared per (mesh, axis, specs) — cached
    ``NamedSharding`` objects and one batched ``device_put`` per batch
    (:func:`~dss_ml_at_scale_tpu.runtime.mesh.get_batch_placer`).
    """

    def __init__(
        self,
        source: Iterable,
        mesh: Mesh,
        *,
        axis: str = "data",
        depth: int = 2,
        specs=None,
        name: str = "feeder",
        wait_observer: Callable[[float], None] | None = None,
    ):
        super().__init__(
            source,
            get_batch_placer(mesh, axis=axis, specs=specs),
            depth=depth,
            name=name,
            wait_observer=wait_observer,
        )


class DeviceFeeder(Feeder):
    """Single-device feeder: plain async ``device_put`` staging."""

    def __init__(
        self,
        source: Iterable,
        *,
        depth: int = 2,
        name: str = "feeder",
        wait_observer: Callable[[float], None] | None = None,
    ):
        super().__init__(
            source, jax.device_put, depth=depth, name=name,
            wait_observer=wait_observer,
        )


def prefetch_to_mesh(
    it: Iterable,
    mesh: Mesh,
    *,
    axis: str = "data",
    depth: int = 2,
    specs=None,
) -> Iterator:
    """Yield batches placed on ``mesh`` (batch-sharded), ``depth`` ahead.

    Compatibility wrapper over :class:`MeshFeeder` — the sharding and
    enqueue now happen on a background feeder thread instead of the
    calling thread. Provenance-tagged batches are stripped (the side
    channel is dropped); callers that need it consume the feeder's
    ``(batch, provenance)`` pairs directly.
    """
    feeder = MeshFeeder(
        it, mesh, axis=axis, depth=depth, specs=specs, name="prefetch"
    )
    try:
        for batch, _prov in feeder:
            yield batch
    finally:
        feeder.close()


def prefetch_to_devices(it: Iterable, *, depth: int = 2) -> Iterator:
    """Single-device variant: feeder-threaded device_put pipelining."""
    feeder = DeviceFeeder(it, depth=depth, name="prefetch")
    try:
        for batch, _prov in feeder:
            yield batch
    finally:
        feeder.close()

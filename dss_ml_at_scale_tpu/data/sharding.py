"""Shard-assignment math for the streaming reader.

Petastorm shards by handing each reader ``cur_shard``/``shard_count`` and
interleaving row groups (reference
``deep_learning/2.distributed-data-loading-petastorm.py:249-250`` passes
``cur_shard=device_id, shard_count=device_count``). The unit of work here
is likewise the Parquet **row group** — the natural Arrow read granule —
assigned round-robin after a seeded per-epoch shuffle so every shard sees
a disjoint, load-balanced, epoch-varying slice.

Kept as pure functions so the assignment is unit-testable without IO
(SURVEY.md §4 calls out "shard assignment math" as a required unit test).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np
import pyarrow.parquet as pq


@dataclasses.dataclass(frozen=True)
class RowGroupUnit:
    """One schedulable unit: a row group within a parquet file."""

    path: str
    row_group: int
    num_rows: int


def list_row_groups(paths: Sequence[str]) -> list[RowGroupUnit]:
    """Enumerate row groups across files (metadata-only reads)."""
    units: list[RowGroupUnit] = []
    for path in paths:
        meta = pq.ParquetFile(path).metadata
        for rg in range(meta.num_row_groups):
            units.append(RowGroupUnit(path, rg, meta.row_group(rg).num_rows))
    return units


def shard_units(
    units: Sequence[RowGroupUnit],
    cur_shard: int,
    shard_count: int,
    *,
    epoch: int = 0,
    shuffle: bool = True,
    seed: int = 0,
) -> list[RowGroupUnit]:
    """This shard's work list for one epoch.

    Deterministic across processes: every shard computes the same permuted
    order (seeded by ``(seed, epoch)``) and takes an interleaved slice, so
    shards are disjoint and together cover all units. With
    ``shuffle=False`` the order is file order (for validation readers).
    """
    if not 0 <= cur_shard < shard_count:
        raise ValueError(f"cur_shard {cur_shard} out of range for {shard_count} shards")
    order = np.arange(len(units))
    if shuffle:
        order = np.random.default_rng((seed, epoch)).permutation(order)
    return [units[i] for i in order[cur_shard::shard_count]]


def shard_row_count(
    units: Sequence[RowGroupUnit], cur_shard: int, shard_count: int
) -> int:
    """Rows this shard will see per epoch (lower bound across epochs).

    Because assignment is by permuted round-robin, per-epoch counts vary
    slightly; epoch accounting should use the *global* row count via
    ``Topology.steps_per_epoch`` (rows // (batch × world)) exactly like
    the reference (``deep_learning/2...py:387-388``). This helper exists
    for diagnostics.
    """
    per = [u.num_rows for u in units]
    return sum(sorted(per)[cur_shard::shard_count])

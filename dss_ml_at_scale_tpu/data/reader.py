"""Sharded streaming Parquet reader with a host decode pool.

Capability target: Petastorm's reader as the reference drives it
(``deep_learning/2.distributed-data-loading-petastorm.py:246-259``):

    make_batch_reader(parquet_files, transform_spec=..., cur_shard=rank,
                      shard_count=world, workers_count=2,
                      reader_pool_type="thread", results_queue_size=20,
                      num_epochs=None)

Semantics preserved:

- ``num_epochs=None`` streams forever; epoch boundaries are the *trainer's*
  job via steps-per-epoch accounting (the reference's central workaround
  for sharded readers of unequal length, prose ``:218-220``).
- ``workers_count`` decode workers feed a results queue bounded at
  ``results_queue_size`` row groups — backpressure bounds host RAM by
  workers × queue × rows-per-rowgroup × rowsize, the documented OOM
  formula (``:338``), exposed here as :meth:`ParquetShardReader.memory_estimate`.
- ``cur_shard``/``shard_count`` give disjoint epoch-reshuffled coverage
  (see :mod:`.sharding`).
- Reader lifecycle is context-managed; re-entering per epoch is allowed
  but unnecessary (the reference must rebuild its loader every epoch to
  dodge Petastorm reader-reuse errors, ``:261-280`` — this reader is
  re-iterable and a single instance serves the whole run).

TPU-first notes: output batches are fixed-shape numpy dicts, so the jitted
train step compiles once; partial trailing batches are dropped by default
(``drop_last``) rather than triggering a recompile.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import queue
import threading
import time
from typing import Iterator, Sequence

import numpy as np
import pyarrow.parquet as pq

from ..resilience.faults import fault_fires, maybe_fail
from ..resilience.retry import RetryPolicy, call_with_retry
from ..resilience.rollback import (
    PROVENANCE_KEY,
    QuarantineList,
    compress_rows,
)
from .sharding import RowGroupUnit, list_row_groups, shard_units
from .transform import TransformSpec

log = logging.getLogger(__name__)

_SENTINEL = object()

# Transient-read retry shape: two quick retries cover an NFS/object-store
# blip without meaningfully delaying a genuinely failed epoch.
_READ_RETRY = RetryPolicy(max_retries=2, base_delay=0.05, max_delay=0.5)


class _WorkerError:
    """Wraps an exception raised in a decode worker for cross-thread rethrow."""

    def __init__(self, error: BaseException):
        self.error = error


# dsst: ignore[lock-discipline] no lock-guarded state: worker results cross threads only via the bounded results Queue and stop Event; _threads/_results are consumer-thread-only (a second concurrent iteration raises), per-worker file handles are thread-local
class ParquetShardReader:
    """Background-threaded, sharded, optionally-infinite batch reader."""

    def __init__(
        self,
        paths: Sequence[str],
        *,
        batch_size: int,
        cur_shard: int = 0,
        shard_count: int = 1,
        workers_count: int = 2,
        results_queue_size: int = 20,
        num_epochs: int | None = None,
        transform_spec: TransformSpec | None = None,
        columns: Sequence[str] | None = None,
        shuffle_row_groups: bool = True,
        seed: int = 0,
        reader_pool_type: str = "thread",
        drop_last: bool = True,
        quarantine: "QuarantineList | str | None" = None,
        emit_provenance: bool = False,
        on_corrupt: str = "raise",
    ):
        """``quarantine``: a poison-row blocklist (path or QuarantineList)
        consulted at every iteration start — blocklisted rows are dropped
        at load time, before decode, so a replay/resume never feeds them
        again. ``emit_provenance``: tag each batch with the RowRanges
        that built it (under ``_provenance``) so a training-health
        supervisor can quarantine the exact rows behind a bad step.
        ``on_corrupt="quarantine"``: a row whose decode/transform raises
        is isolated (per-row retry of the failed group), counted on
        ``corrupt_samples_total``, quarantined (when a list is
        configured), and skipped — instead of killing the reader thread;
        the default ``"raise"`` preserves fail-fast semantics."""
        if reader_pool_type not in ("thread", "dummy"):
            raise ValueError(
                f"reader_pool_type must be 'thread' or 'dummy' (inline), "
                f"got {reader_pool_type!r}"
            )
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if on_corrupt not in ("raise", "quarantine"):
            raise ValueError(
                f"on_corrupt must be 'raise' or 'quarantine', "
                f"got {on_corrupt!r}"
            )
        self._units = list_row_groups(list(paths))
        if len(self._units) < shard_count:
            raise ValueError(
                f"{len(self._units)} row groups cannot feed {shard_count} shards; "
                f"write the dataset with smaller row groups or fewer shards"
            )
        self.batch_size = batch_size
        self.cur_shard = cur_shard
        self.shard_count = shard_count
        self.workers_count = max(1, workers_count)
        self.results_queue_size = results_queue_size
        self.num_epochs = num_epochs
        self.transform_spec = transform_spec
        self.columns = list(columns) if columns is not None else None
        self.shuffle_row_groups = shuffle_row_groups
        self.seed = seed
        self.reader_pool_type = reader_pool_type
        self.drop_last = drop_last
        self.emit_provenance = emit_provenance
        self.on_corrupt = on_corrupt
        self.quarantine = (
            QuarantineList(quarantine)
            if isinstance(quarantine, (str, bytes)) or hasattr(
                quarantine, "__fspath__"
            )
            else quarantine
        )
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._results: queue.Queue | None = None
        # Bound on the instance so stop() still works when invoked from a
        # generator finalizer during interpreter shutdown (module globals
        # like `queue` may already be torn down by then).
        self._empty_exc = queue.Empty
        self._local = threading.local()

    # -- diagnostics ------------------------------------------------------

    @property
    def queue_occupancy(self) -> int:
        """Decoded row groups currently waiting in the results queue."""
        results = self._results
        return results.qsize() if results is not None else 0

    def _telemetry_handles(self):
        """Decode-pipeline gauge/counter children, bound ONCE per reader.

        The import stays lazy (telemetry pulls jax via its device
        module; jax-free paths — datagen subprocesses, pure Delta IO —
        must not touch the device runtime), but re-iterating the reader
        no longer pays a registry lookup per epoch, and the consumer
        loop's per-row-group cost is two pre-bound method calls.
        """
        handles = getattr(self, "_telemetry", None)
        if handles is None:
            from .. import telemetry

            handles = self._telemetry = (
                telemetry.gauge(
                    "reader_queue_depth",
                    "decoded row groups waiting in the results queue at "
                    "last consumer read",
                ),
                telemetry.counter(
                    "reader_stall_seconds_total",
                    "cumulative consumer wait on the decode queue",
                ),
            )
        return handles

    def memory_estimate(self, row_size_bytes: int) -> int:
        """Worst-case host RAM of the decode pipeline, in bytes.

        The reference documents this as
        workers × queue × rows-per-rowgroup × rowsize (``2...py:338``).
        """
        rows_per_group = max(u.num_rows for u in self._units)
        return (
            (self.workers_count + self.results_queue_size)
            * rows_per_group
            * row_size_bytes
        )

    # -- work generation --------------------------------------------------

    def _unit_stream(self) -> Iterator[RowGroupUnit]:
        epochs = itertools.count() if self.num_epochs is None else range(self.num_epochs)
        for epoch in epochs:
            yield from shard_units(
                self._units,
                self.cur_shard,
                self.shard_count,
                epoch=epoch,
                shuffle=self.shuffle_row_groups,
                seed=self.seed,
            )

    def _load_unit(
        self, unit: RowGroupUnit
    ) -> tuple[dict[str, np.ndarray], np.ndarray]:
        """Load + transform one row group → ``(cols, orig_rows)``.

        ``orig_rows`` maps each surviving output row back to its
        original row index within the group — the provenance spine.
        Quarantined rows are dropped BEFORE decode (no cycles spent on
        known-poison bytes); under ``on_corrupt="quarantine"`` a failing
        transform is retried row-by-row to isolate, count, and
        quarantine exactly the corrupt samples.
        """
        # Fault-injection site: a transient failure here (or a real NFS
        # blip / truncated read below) is retried by the worker before it
        # gives up and fails the epoch — see _load_unit_with_retry.
        maybe_fail("reader.next")
        # One ParquetFile handle per (worker thread, path): footers parse
        # once per worker instead of once per row group, and handles are
        # never shared across threads (ParquetFile reads aren't
        # guaranteed thread-safe).
        cache = self._local.__dict__.setdefault("files", {})
        pf = cache.get(unit.path)
        if pf is None:
            pf = cache[unit.path] = pq.ParquetFile(unit.path)
        table = pf.read_row_group(unit.row_group, columns=self.columns)
        cols = {
            name: _column_to_numpy(table.column(i))
            for i, name in enumerate(table.column_names)
        }
        num_rows = len(next(iter(cols.values()))) if cols else 0
        orig_rows = np.arange(num_rows, dtype=np.int64)
        if self.quarantine is not None:
            mask = self.quarantine.keep_mask(
                unit.path, unit.row_group, num_rows
            )
            if mask is not None:
                cols = {k: v[mask] for k, v in cols.items()}
                orig_rows = orig_rows[mask]
        if fault_fires("sample.corrupt"):
            cols = _corrupt_first_sample(cols)
        if self.transform_spec is not None and len(orig_rows):
            try:
                cols = self.transform_spec(cols)
            except Exception:
                if self.on_corrupt != "quarantine":
                    raise
                cols, orig_rows = self._isolate_corrupt_rows(
                    unit, cols, orig_rows
                )
            else:
                n_out = len(next(iter(cols.values()))) if cols else 0
                if n_out != len(orig_rows):
                    if self.emit_provenance or self.quarantine is not None:
                        # Row-level provenance (and therefore quarantine
                        # exclusion) is only meaningful for row-preserving
                        # transforms; a filtering transform would silently
                        # misattribute rows.
                        raise ValueError(
                            f"transform changed the row count "
                            f"({len(orig_rows)} -> {n_out}) in {unit.path}"
                            f"[rg={unit.row_group}]; provenance/quarantine "
                            "require a row-preserving transform"
                        )
                    orig_rows = np.arange(n_out, dtype=np.int64)
        return cols, orig_rows

    def _isolate_corrupt_rows(
        self, unit: RowGroupUnit, cols, orig_rows
    ) -> tuple[dict[str, np.ndarray], np.ndarray]:
        """Per-row transform of a failed group: good rows survive, each
        corrupt row is counted, quarantined, and dropped — the reader
        thread outlives isolated data corruption."""
        from .. import telemetry

        corrupt_counter = telemetry.counter(
            "corrupt_samples_total",
            "undecodable samples skipped (and quarantined) by the reader",
        )
        good: list[dict[str, np.ndarray]] = []
        good_rows: list[int] = []
        bad_rows: list[int] = []
        last_error = "?"
        for i in range(len(orig_rows)):
            row = {k: v[i:i + 1] for k, v in cols.items()}
            try:
                good.append(self.transform_spec(row))
                good_rows.append(int(orig_rows[i]))
            except Exception as e:
                bad_rows.append(int(orig_rows[i]))
                last_error = f"{type(e).__name__}: {e}"
        corrupt_counter.inc(len(bad_rows))
        log.warning(
            "reader: %d corrupt sample(s) in %s[rg=%d] skipped (last "
            "error: %s)", len(bad_rows), unit.path, unit.row_group,
            last_error,
        )
        if self.quarantine is not None and bad_rows:
            self.quarantine.add(
                compress_rows(unit.path, unit.row_group, bad_rows),
                reason=f"undecodable sample ({last_error})",
            )
        if not good:
            return {}, np.empty(0, np.int64)
        out = {
            k: np.concatenate([g[k] for g in good]) for k in good[0]
        }
        return out, np.asarray(good_rows, np.int64)

    def _load_unit_with_retry(
        self, unit: RowGroupUnit
    ) -> tuple[dict[str, np.ndarray], np.ndarray]:
        # A flaky filesystem read should cost a short backoff, not the
        # whole epoch; semantic decode errors (bad bytes, schema
        # mismatch) are deterministic and fail immediately.
        def evict_handle(attempt, exc, delay) -> None:
            # The cached ParquetFile holds an open fd + parsed footer; a
            # stale NFS handle or truncated read poisons it, and retrying
            # through the same handle would just replay the failure.
            # Close it too — dropping the reference alone leaks the fd
            # until GC.
            stale = self._local.__dict__.setdefault("files", {}).pop(
                unit.path, None
            )
            if stale is not None:
                try:
                    stale.close()
                except Exception as close_exc:
                    log.debug("closing evicted reader handle: %r", close_exc)

        return call_with_retry(
            self._load_unit, unit, policy=_READ_RETRY, site="reader.next",
            on_retry=evict_handle,
        )

    # -- thread pool ------------------------------------------------------

    def _worker(self, work: Iterator[RowGroupUnit], lock: threading.Lock, results: queue.Queue):
        def _put(item) -> None:
            while not self._stop.is_set():
                try:
                    results.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        try:
            while not self._stop.is_set():
                with lock:
                    unit = next(work, _SENTINEL)
                if unit is _SENTINEL:
                    break
                _put((self._load_unit_with_retry(unit), unit))
        except BaseException as e:  # propagate to the consumer, don't die silently
            _put(_WorkerError(e))
        finally:
            _put(_SENTINEL)

    def _row_groups(
        self,
    ) -> Iterator[tuple[dict[str, np.ndarray], np.ndarray]]:
        """Stream ``(cols, orig_rows)`` row groups, in arrival order."""
        if self.reader_pool_type == "dummy":
            for unit in self._unit_stream():
                if self._stop.is_set():
                    return
                yield self._load_unit_with_retry(unit), unit
            return

        self._results = results = queue.Queue(maxsize=self.results_queue_size)
        work = self._unit_stream()
        lock = threading.Lock()
        # Decode-pipeline health gauges: queue depth says whether workers
        # keep ahead of the consumer; stall time is the consumer-side
        # cost when they don't (the "is training input-bound?" number).
        queue_gauge, stall_total = self._telemetry_handles()
        self._threads = [
            threading.Thread(
                target=self._worker, args=(work, lock, results), daemon=True,
                name=f"reader-worker-{i}",
            )
            for i in range(self.workers_count)
        ]
        for t in self._threads:
            t.start()
        live = len(self._threads)
        try:
            while live:
                wait_t0 = time.perf_counter()
                item = results.get()
                stall_total.inc(time.perf_counter() - wait_t0)
                queue_gauge.set(results.qsize())
                if item is _SENTINEL:
                    live -= 1
                    continue
                if isinstance(item, _WorkerError):
                    raise RuntimeError(
                        "reader worker failed while decoding"
                    ) from item.error
                yield item
        finally:
            # May run as a generator finalizer during interpreter shutdown,
            # where even stdlib module globals are torn down — nothing
            # raised here is actionable (workers are daemon threads).
            try:
                self.stop()
            # dsst: ignore[bare-except] generator finalizer at interpreter shutdown: nothing raised here is actionable
            except BaseException:
                pass

    # -- batch assembly ---------------------------------------------------

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        if self._threads and any(t.is_alive() for t in self._threads):
            raise RuntimeError(
                "reader is already being iterated; create a second reader "
                "for concurrent streams"
            )
        if self.quarantine is not None:
            # Replay/resume semantics: a fresh iteration always sees the
            # full blocklist, including rows quarantined by another
            # process since this reader was built.
            self.quarantine.refresh()
        self._stop.clear()
        # buf entries: (cols, unit_path, unit_row_group, orig_rows) —
        # provenance rides the buffer so _take can slice it with the rows.
        buf: list[tuple] = []
        buffered = 0
        for (group, orig_rows), unit in self._row_groups():
            if not group or len(orig_rows) == 0:
                continue  # fully quarantined / fully corrupt group
            buf.append((group, unit.path, unit.row_group, orig_rows))
            buffered += _num_rows(group)
            while buffered >= self.batch_size:
                batch, prov, buf, buffered = _take(buf, self.batch_size)
                yield self._finish_batch(batch, prov)
        if buffered and not self.drop_last:
            batch, prov, _, _ = _take(buf, buffered)
            yield self._finish_batch(batch, prov)

    def _finish_batch(self, batch, prov) -> dict[str, np.ndarray]:
        if self.emit_provenance:
            batch[PROVENANCE_KEY] = [
                r
                for path, rg, rows in prov
                for r in compress_rows(path, rg, rows)
            ]
        return batch

    def stop(self) -> None:
        self._stop.set()
        # Drain so workers blocked on a full queue can observe the stop.
        if self._results is not None:
            try:
                while True:
                    self._results.get_nowait()
            except self._empty_exc:
                pass
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def _num_rows(group: dict[str, np.ndarray]) -> int:
    return len(next(iter(group.values())))


def _take(buf, n):
    """Split the buffered row groups into one n-row batch + remainder.

    Buffer entries are ``(cols, path, row_group, orig_rows)``; the
    returned ``prov`` mirrors the batch as ``(path, row_group,
    taken_rows)`` triples so provenance slices exactly with the data.
    """
    taken: dict[str, list[np.ndarray]] = {}
    prov: list[tuple[str, int, np.ndarray]] = []
    need = n
    rest: list[tuple] = []
    for group, path, row_group, orig_rows in buf:
        if need == 0:
            rest.append((group, path, row_group, orig_rows))
            continue
        rows = _num_rows(group)
        use = min(rows, need)
        for k, v in group.items():
            taken.setdefault(k, []).append(v[:use])
        prov.append((path, row_group, orig_rows[:use]))
        if use < rows:
            rest.append((
                {k: v[use:] for k, v in group.items()},
                path, row_group, orig_rows[use:],
            ))
        need -= use
    batch = {k: np.concatenate(v) if len(v) > 1 else v[0] for k, v in taken.items()}
    return batch, prov, rest, sum(_num_rows(g) for g, *_ in rest)


def _corrupt_first_sample(cols: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """``sample.corrupt`` fault: truncate the first byte-valued cell.

    Simulates a torn object-store read / bit-rotted record: downstream
    decode raises on the short payload, exercising the per-row
    isolation + quarantine path deterministically in tier-1. Datasets
    with no byte column get a NaN poke in the first float cell instead.
    """
    for k, v in cols.items():
        if v.dtype == object and len(v) and isinstance(
            v[0], (bytes, bytearray)
        ):
            v = v.copy()
            v[0] = bytes(v[0])[: max(1, len(v[0]) // 2)]
            return {**cols, k: v}
    for k, v in cols.items():
        if np.issubdtype(v.dtype, np.floating) and len(v):
            v = v.copy()
            v[0] = np.nan
            return {**cols, k: v}
    log.warning("sample.corrupt fired but no corruptible column found")
    return cols


def _column_to_numpy(col) -> np.ndarray:
    """Arrow column → numpy; binary/string columns become object arrays."""
    import pyarrow as pa

    combined = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if pa.types.is_binary(combined.type) or pa.types.is_large_binary(combined.type):
        return np.array(combined.to_pylist(), dtype=object)
    if pa.types.is_string(combined.type) or pa.types.is_large_string(combined.type):
        return np.array(combined.to_pylist(), dtype=object)
    return combined.to_numpy(zero_copy_only=False)


def make_batch_reader(paths_or_table, **kwargs) -> ParquetShardReader:
    """Factory accepting a file list, a dataset dir, or a DeltaTable.

    Mirrors petastorm's ``make_batch_reader`` entry point; a Delta table
    path resolves through the Delta log (the reference resolves file lists
    with deltalake-rs for exactly this call, ``2...py:99-112,246``).
    """
    from .delta import DeltaTable

    if isinstance(paths_or_table, DeltaTable):
        paths = paths_or_table.file_uris()
    elif isinstance(paths_or_table, (list, tuple)):
        paths = list(paths_or_table)
    else:
        from pathlib import Path

        p = Path(paths_or_table)
        if (p / "_delta_log").is_dir():
            paths = DeltaTable(p).file_uris()
        elif p.is_dir():
            paths = sorted(str(q) for q in p.glob("**/*.parquet"))
        elif p.is_file():
            paths = [str(p)]
        else:
            raise FileNotFoundError(f"no such dataset: {p}")
        if not paths:
            raise FileNotFoundError(f"no parquet files under {p}")
    return ParquetShardReader(paths, **kwargs)


@contextlib.contextmanager
def batch_loader(paths_or_table, **kwargs):
    """Context-managed reader (the create_dataloader_context analogue,
    reference ``2...py:246-259``) guaranteeing worker teardown."""
    reader = make_batch_reader(paths_or_table, **kwargs)
    try:
        yield reader
    finally:
        reader.stop()

"""On-device training augmentation: RandomResizedCrop + flip, jitted.

The reference's train-time transform is torchvision's
``RandomResizedCrop(224)`` + ``RandomHorizontalFlip`` running on host
CPU workers (``deep_learning/2.distributed-data-loading-petastorm.py``
transform pipeline). On TPU hosts the feeding formula
(``compute_ips / decode_ips_per_core``, see README) makes host cores
the scarce resource — so this framework runs augmentation ON DEVICE,
inside the jitted train step:

- the decode pool keeps emitting deterministic center-crops (cheap,
  cacheable, identical for eval);
- the train step derives a per-step PRNG key by folding ``state.step``
  into a base seed (deterministic across restarts and mesh layouts —
  resume replays the same crop sequence), samples one crop box + flip
  bit per image, and materializes the crop with
  ``jax.image.scale_and_translate`` — a fixed-output-shape bilinear
  gather XLA maps onto the chip, vmapped over the batch;
- eval and predict never augment.

Box sampling is the single-draw variant of torchvision's algorithm:
one (area, log-ratio) draw clamped to fit, instead of the 10-try
rejection loop — rejection loops are data-dependent control flow, which
is exactly what a compiled TPU program should not contain. The sampled
distribution differs only in the rare tail where torchvision's tries
all fail and it falls back to a center crop.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class AugmentConfig:
    """RandomResizedCrop + horizontal-flip parameters (torchvision
    semantics: ``scale`` is the area fraction range, ``ratio`` the
    aspect-ratio range of the sampled box)."""

    scale: tuple[float, float] = (0.08, 1.0)
    ratio: tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0)
    flip: bool = True
    seed: int = 0


def _sample_boxes(key, batch, h, w, cfg: AugmentConfig):
    """Per-image crop boxes: (top, left, box_h, box_w), float32."""
    k_area, k_ratio, k_top, k_left = jax.random.split(key, 4)
    area = h * w * jax.random.uniform(
        k_area, (batch,), minval=cfg.scale[0], maxval=cfg.scale[1]
    )
    log_r = jax.random.uniform(
        k_ratio, (batch,),
        minval=jnp.log(cfg.ratio[0]), maxval=jnp.log(cfg.ratio[1]),
    )
    r = jnp.exp(log_r)
    box_w = jnp.sqrt(area * r)
    box_h = jnp.sqrt(area / r)
    # Clamp to the source extent (the one-draw stand-in for the
    # rejection loop), keeping at least an 8x8 box for stability.
    box_w = jnp.clip(box_w, 8.0, w)
    box_h = jnp.clip(box_h, 8.0, h)
    top = jax.random.uniform(k_top, (batch,)) * (h - box_h)
    left = jax.random.uniform(k_left, (batch,)) * (w - box_w)
    return top, left, box_h, box_w


def random_resized_crop_flip(
    key: jax.Array,
    images: jax.Array,  # [b, h, w, c] float
    crop: int,
    cfg: AugmentConfig = AugmentConfig(),
) -> jax.Array:
    """Augmented ``[b, crop, crop, c]`` batch, fully on device."""
    b, h, w, c = images.shape
    k_box, k_flip = jax.random.split(key)
    top, left, box_h, box_w = _sample_boxes(k_box, b, float(h), float(w), cfg)

    if cfg.flip:
        do_flip = jax.random.bernoulli(k_flip, 0.5, (b,))
        images = jnp.where(
            do_flip[:, None, None, None], images[:, :, ::-1, :], images
        )

    # Map the sampled box onto the fixed output window:
    # out[y, x] = in[top + y * box_h/crop, left + x * box_w/crop].
    scale_y = crop / box_h
    scale_x = crop / box_w

    def one(img, sy, sx, t, l):
        return jax.image.scale_and_translate(
            img,
            shape=(crop, crop, c),
            spatial_dims=(0, 1),
            scale=jnp.stack([sy, sx]),
            translation=jnp.stack([-t * sy, -l * sx]),
            method="bilinear",
        )

    out = jax.vmap(one)(images, scale_y, scale_x, top, left)
    return out.astype(images.dtype)


def augment_for_step(
    step: jax.Array,
    images: jax.Array,
    crop: int,
    cfg: AugmentConfig = AugmentConfig(),
) -> jax.Array:
    """The train-step entry point: a deterministic per-step key.

    ``fold_in(key(seed), step)`` makes the crop sequence a pure function
    of (seed, step): checkpoint resume replays the exact schedule, and
    every process in a multi-host DP run derives the same key (each
    already holds different rows, so crops stay decorrelated across the
    global batch).
    """
    key = jax.random.fold_in(jax.random.key(cfg.seed), step)
    return random_resized_crop_flip(key, images, crop, cfg)


__all__ = [
    "AugmentConfig",
    "augment_for_step",
    "random_resized_crop_flip",
]

"""Data layer: sharded Arrow/Parquet streaming + Delta-log access.

TPU-native replacement for the reference's input stack —
Petastorm ``make_batch_reader``/``DataLoader``/``TransformSpec``
(reference ``deep_learning/2.distributed-data-loading-petastorm.py:246-318``)
and the deltalake-rs file listing (``:99-112``) — built on pyarrow's C++
Parquet engine with a host-side decode worker pool, a bounded results
queue, and a background feeder thread that stages + shards batches to
device so transfer overlaps the step loop (see ``prefetch.py``).
"""

from .delta import DeltaTable, write_delta  # noqa: F401
from .reader import ParquetShardReader, batch_loader, make_batch_reader  # noqa: F401
from .sharding import RowGroupUnit, list_row_groups, shard_units  # noqa: F401
from .transform import TransformSpec  # noqa: F401
# augment imports jax (device-side transform); import it lazily as
# dss_ml_at_scale_tpu.data.augment to keep jax-free paths jax-free.


def __getattr__(name):
    # prefetch imports jax, which initializes the accelerator backend on
    # import; loaded lazily so jax-free paths (datagen subprocesses, pure
    # Delta IO) never touch the device runtime.
    if name in (
        "prefetch_to_mesh", "prefetch_to_devices",
        "Feeder", "MeshFeeder", "DeviceFeeder",
    ):
        from . import prefetch

        return getattr(prefetch, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Minimal Delta-Lake transaction-log reader and writer (no Spark, no JVM).

The reference reads Delta tables two ways: through Spark
(``spark.read.format("delta")``) and — for the training data path, to avoid
the JVM entirely — through deltalake-rs:
``DeltaTable(path).file_uris()`` for the physical Parquet file list and
``get_add_actions()`` for per-file ``num_records`` stats (reference
``deep_learning/2.distributed-data-loading-petastorm.py:99-112``). The row
counts feed steps-per-epoch; the file list feeds the sharded reader.

Since the Delta log is just JSON-lines commits plus optional Parquet
checkpoints, a small pure-Python reader (over pyarrow for checkpoints)
covers the capability. The writer emits spec-compliant commits with
``numRecords`` stats so tables round-trip through real Delta readers.
"""

from __future__ import annotations

import dataclasses
import json
import os
import uuid
from pathlib import Path
from typing import Iterable, Mapping

import pyarrow as pa
import pyarrow.parquet as pq

_LOG_DIR = "_delta_log"


@dataclasses.dataclass(frozen=True)
class AddAction:
    path: str
    size: int
    num_records: int | None
    partition_values: Mapping[str, str]


class DeltaTable:
    """Read-side view of a Delta table's latest snapshot."""

    def __init__(self, table_path: str | os.PathLike):
        self.path = Path(table_path)
        log_dir = self.path / _LOG_DIR
        if not log_dir.is_dir():
            raise FileNotFoundError(f"not a Delta table (no {_LOG_DIR}): {self.path}")
        self._adds, self._version, self._metadata = self._replay(log_dir)

    # -- snapshot construction -------------------------------------------

    def _replay(self, log_dir: Path):
        adds: dict[str, AddAction] = {}
        metadata: dict = {}
        start_version = 0

        ckpt_version = self._last_checkpoint_version(log_dir)
        if ckpt_version is not None:
            for action in self._read_checkpoint(log_dir, ckpt_version):
                self._apply(action, adds, metadata)
            start_version = ckpt_version + 1

        versions = sorted(
            int(p.stem)
            for p in log_dir.glob("*.json")
            if p.stem.isdigit() and int(p.stem) >= start_version
        )
        for v in versions:
            commit = log_dir / f"{v:020d}.json"
            with open(commit, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        self._apply(json.loads(line), adds, metadata)
        version = versions[-1] if versions else (ckpt_version or 0)
        return adds, version, metadata

    @staticmethod
    def _last_checkpoint_version(log_dir: Path) -> int | None:
        marker = log_dir / "_last_checkpoint"
        if not marker.exists():
            return None
        return int(json.loads(marker.read_text())["version"])

    @staticmethod
    def _read_checkpoint(log_dir: Path, version: int) -> Iterable[dict]:
        # Single-part checkpoints only (multi-part is a large-table
        # optimization this framework's writer never produces).
        ckpt = log_dir / f"{version:020d}.checkpoint.parquet"
        table = pq.read_table(ckpt)
        for row in table.to_pylist():
            for key in ("add", "remove", "metaData", "protocol"):
                if row.get(key) is not None:
                    yield {key: row[key]}

    @staticmethod
    def _apply(action: dict, adds: dict, metadata: dict) -> None:
        if "add" in action and action["add"] is not None:
            a = action["add"]
            stats = a.get("stats")
            num_records = None
            if stats:
                if isinstance(stats, str):
                    stats = json.loads(stats)
                num_records = stats.get("numRecords")
            adds[a["path"]] = AddAction(
                path=a["path"],
                size=a.get("size", 0),
                num_records=num_records,
                partition_values=a.get("partitionValues", {}) or {},
            )
        elif "remove" in action and action["remove"] is not None:
            adds.pop(action["remove"]["path"], None)
        elif "metaData" in action and action["metaData"] is not None:
            metadata.update(action["metaData"])

    # -- public surface (parity with deltalake usage in the reference) ---

    def file_uris(self) -> list[str]:
        """Absolute paths of the parquet files in the current snapshot."""
        return [str(self.path / a.path) for a in self._sorted_adds()]

    def get_add_actions(self) -> list[AddAction]:
        return self._sorted_adds()

    def num_records(self) -> int:
        """Total rows from add-action stats (the steps-per-epoch input)."""
        total = 0
        for a in self._adds.values():
            if a.num_records is None:
                raise ValueError(f"add action for {a.path} carries no numRecords stats")
            total += a.num_records
        return total

    def version(self) -> int:
        return self._version

    def schema_json(self) -> dict | None:
        raw = self._metadata.get("schemaString")
        return json.loads(raw) if raw else None

    def _sorted_adds(self) -> list[AddAction]:
        return sorted(self._adds.values(), key=lambda a: a.path)


def write_delta(
    table: pa.Table,
    table_path: str | os.PathLike,
    *,
    mode: str = "error",
    max_rows_per_file: int | None = None,
    compression: str = "none",
) -> DeltaTable:
    """Write an Arrow table as a Delta table (parquet files + JSON log).

    Defaults mirror the reference's ingestion choices: uncompressed parquet
    (``deep_learning/1.data-preparation.py:191,200`` sets
    ``parquet.compression.codec=uncompressed`` so the training-path reader
    spends no CPU on decompression — JPEG bytes don't compress anyway).

    ``mode``: "error" | "overwrite" | "append".
    """
    if mode not in ("error", "overwrite", "append"):
        raise ValueError(f"mode must be 'error', 'overwrite' or 'append', got {mode!r}")
    path = Path(table_path)
    log_dir = path / _LOG_DIR
    exists = log_dir.is_dir()
    if exists and mode == "error":
        raise FileExistsError(f"Delta table already exists: {path}")
    path.mkdir(parents=True, exist_ok=True)
    log_dir.mkdir(exist_ok=True)

    actions: list[dict] = []
    next_version = 0
    if exists and mode in ("overwrite", "append"):
        prior = DeltaTable(path)
        next_version = prior.version() + 1
        if mode == "overwrite":
            actions += [
                {"remove": {"path": a.path, "deletionTimestamp": 0, "dataChange": True}}
                for a in prior.get_add_actions()
            ]
    if next_version == 0:
        actions.append({"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}})
    if next_version == 0 or mode == "overwrite":
        # Overwrites refresh the schema too — the new snapshot must
        # describe the new files, not the replaced table's.
        actions.append(
            {
                "metaData": {
                    "id": str(uuid.uuid4()),
                    "format": {"provider": "parquet", "options": {}},
                    "schemaString": json.dumps(_arrow_schema_to_delta(table.schema)),
                    "partitionColumns": [],
                    "configuration": {},
                    "createdTime": 0,
                }
            }
        )

    chunks = (
        [table]
        if not max_rows_per_file
        else [
            table.slice(i, max_rows_per_file)
            for i in range(0, len(table), max_rows_per_file)
        ]
    )
    for chunk in chunks:
        fname = f"part-{uuid.uuid4().hex}.parquet"
        fpath = path / fname
        pq.write_table(chunk, fpath, compression=compression)
        actions.append(
            {
                "add": {
                    "path": fname,
                    "partitionValues": {},
                    "size": fpath.stat().st_size,
                    "modificationTime": 0,
                    "dataChange": True,
                    "stats": json.dumps({"numRecords": len(chunk)}),
                }
            }
        )

    commit = log_dir / f"{next_version:020d}.json"
    with open(commit, "w", encoding="utf-8") as f:
        for action in actions:
            f.write(json.dumps(action) + "\n")
    return DeltaTable(path)


_ARROW_TO_DELTA = {
    pa.int8(): "byte",
    pa.int16(): "short",
    pa.int32(): "integer",
    pa.int64(): "long",
    pa.float32(): "float",
    pa.float64(): "double",
    pa.bool_(): "boolean",
    pa.string(): "string",
    pa.large_string(): "string",
    pa.binary(): "binary",
    pa.large_binary(): "binary",
    pa.date32(): "date",
}


def _arrow_schema_to_delta(schema: pa.Schema) -> dict:
    fields = []
    for f in schema:
        if isinstance(f.type, pa.TimestampType):
            delta_type = "timestamp"
        else:
            delta_type = _ARROW_TO_DELTA.get(f.type, "string")
        fields.append(
            {"name": f.name, "type": delta_type, "nullable": f.nullable, "metadata": {}}
        )
    return {"type": "struct", "fields": fields}

"""Row-group transform pipeline (the TransformSpec equivalent).

Petastorm's ``TransformSpec`` carries a pandas-level function plus
``edit_fields`` declaring post-transform dtypes/shapes so the reader can
build tensors without inspecting data (reference
``deep_learning/2.distributed-data-loading-petastorm.py:310-318``:
float32 (3,224,224) image + int32 label). Here the contract is columnar:
the function maps a dict of numpy arrays (one row group) to a dict of
numpy arrays, and ``fields`` declares the output schema the trainer can
rely on for jit-stable shapes.
"""

from __future__ import annotations

import dataclasses
import io
from typing import Callable, Mapping, Sequence

import numpy as np

Columnar = Mapping[str, np.ndarray]


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: np.dtype
    shape: tuple[int, ...]  # per-row shape, () for scalar columns


class SubstitutionCounter:
    """Thread-safe tally of corrupt records zero-substituted by a spec."""

    def __init__(self) -> None:
        import threading

        self._n = 0
        self._lock = threading.Lock()

    def add(self, k: int = 1) -> None:
        with self._lock:
            self._n += k

    @property
    def count(self) -> int:
        return self._n

    def __repr__(self) -> str:  # keep dataclass reprs readable
        return f"SubstitutionCounter({self._n})"


@dataclasses.dataclass(frozen=True)
class TransformSpec:
    """Transform + declared output schema.

    ``func`` runs on host CPU inside the reader worker pool — this is
    deliberately where JPEG decode lives (same as the reference: decode on
    host, ship ready tensors to the accelerator).
    """

    func: Callable[[Columnar], Columnar]
    fields: Sequence[Field]
    # Provenance for harness reporting (what decode path / image layout a
    # factory actually resolved to); None for hand-built specs.
    backend: str | None = None
    layout: str | None = None
    # Records replaced by zero images under ``on_error="substitute"``
    # (a mutable counter: the spec itself is frozen).
    substitutions: "SubstitutionCounter" = dataclasses.field(
        default_factory=SubstitutionCounter
    )

    def __call__(self, batch: Columnar) -> dict[str, np.ndarray]:
        out = dict(self.func(batch))
        declared = {f.name: f for f in self.fields}
        if set(out) != set(declared):
            raise ValueError(
                f"transform produced columns {sorted(out)} but declared "
                f"{sorted(declared)}"
            )
        n = None
        for name, arr in out.items():
            f = declared[name]
            arr = np.asarray(arr, dtype=f.dtype)
            want = (len(arr),) + tuple(f.shape)
            if arr.shape != want:
                raise ValueError(
                    f"column {name}: shape {arr.shape} != declared {want}"
                )
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError("transform produced ragged column lengths")
            out[name] = arr
        return out


# -- ImageNet-style image pipeline (reference :282-296) ---------------------

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def decode_resize_crop(
    jpeg_bytes: bytes, resize: int = 256, crop: int = 224, layout: str = "chw"
) -> np.ndarray:
    """JPEG → float32 in [0,1], shorter-side resize then center crop.

    Matches torchvision's Resize(256)/CenterCrop(224)/ToTensor semantics
    used by the reference's ``preprocess`` (``deep_learning/2...py:282-296``).
    ``layout="chw"`` is the torchvision tensor layout; ``"hwc"`` skips the
    transpose (TPU convs are NHWC-native).
    """
    from PIL import Image

    if layout not in ("hwc", "chw"):
        raise ValueError(f"unknown layout {layout!r}")
    img = Image.open(io.BytesIO(jpeg_bytes)).convert("RGB")
    w, h = img.size
    scale = resize / min(w, h)
    img = img.resize((max(1, round(w * scale)), max(1, round(h * scale))), Image.BILINEAR)
    w, h = img.size
    left, top = (w - crop) // 2, (h - crop) // 2
    img = img.crop((left, top, left + crop, top + crop))
    arr = np.asarray(img, np.float32) / 255.0  # HWC
    return arr if layout == "hwc" else arr.transpose(2, 0, 1)


def imagenet_transform_spec(
    *,
    content_column: str = "content",
    label_column: str = "label_index",
    resize: int = 256,
    crop: int = 224,
    normalize: bool = True,
    backend: str = "auto",
    decode_threads: int | None = None,
    layout: str = "hwc",
    output_dtype: str = "float32",
    on_error: str = "raise",
    fast_decode: bool = False,
) -> TransformSpec:
    """The reference's training TransformSpec, columnar.

    Emits ``image`` float32 and ``label`` int32 — the field contract of
    ``deep_learning/2...py:310-318``, except that the default image
    layout is HWC, not torchvision's CHW: TPU convolutions are
    NHWC-native, and emitting NHWC from the decode pool means the jitted
    train step never spends HBM bandwidth transposing every batch
    (``ClassifierTask._images`` accepts either and transposes only CHW).
    Pass ``layout="chw"`` for bit-parity tests against torch pipelines.

    ``backend``: ``"native"`` uses the C++ decode pool
    (:mod:`dss_ml_at_scale_tpu.native` — GIL-free libjpeg + threaded
    resize/crop/normalize), ``"pil"`` the pure-Python path, ``"auto"``
    native when it compiles on this host with per-image PIL fallback for
    codecs the native path rejects (e.g. CMYK JPEGs). The resolved
    backend is exposed as ``spec.backend`` so harnesses can report what
    actually ran.

    ``output_dtype="uint8"`` emits the raw quantized [0, 255] bytes —
    4x less host RAM, queue memory, and host→device transfer than
    float32 — and defers normalization to the device program
    (``ClassifierTask`` normalizes uint8 batches inside the jitted step,
    where XLA fuses it into the first conv). Requires ``normalize=True``
    semantics downstream; ``normalize=False`` + uint8 is the same bytes.

    ``fast_decode=True`` (native backend only; the PIL path ignores it)
    decodes large sources at a DCT-domain m/8 scale covering ``resize``
    — the PIL draft-mode trick — trading exact full-decode pixel parity
    for substantially less IDCT work (measured ~1.7x at 1024px sources,
    ~2.1x at 2048px; neutral at ImageNet's ~500px).

    ``on_error``: ``"raise"`` (default — a corrupt record stops the
    epoch with the worker's exception, the reference stack's behavior)
    or ``"substitute"`` — undecodable records become dataset-MEAN images
    (zeros in post-normalization space; the same training input under
    every dtype/normalize configuration) so a multi-hour run survives
    isolated corruption; substitutions are tallied on
    ``spec.substitutions.count`` (thread-safe) for callers to report.
    """
    if backend not in ("auto", "native", "pil"):
        raise ValueError(f"unknown backend {backend!r}")
    if layout not in ("hwc", "chw"):
        raise ValueError(f"unknown layout {layout!r}")
    if output_dtype not in ("float32", "uint8"):
        raise ValueError(f"unknown output_dtype {output_dtype!r}")
    if on_error not in ("raise", "substitute"):
        raise ValueError(f"unknown on_error {on_error!r}")
    if output_dtype == "uint8" and not normalize:
        # uint8 batches are ALWAYS normalized on device by the task; a
        # normalize=False uint8 spec would silently train on different
        # inputs than the float32 normalize=False path.
        raise ValueError(
            "output_dtype='uint8' defers normalization to the device step "
            "and cannot express normalize=False; use float32 for raw values"
        )
    if crop > resize:
        # crop > resize would mean padding/stretching, and the native and
        # PIL paths disagree on which; the reference never does it (256/224).
        raise ValueError(f"crop ({crop}) must be <= resize ({resize})")

    # Resolve the backend NOW: a missing toolchain fails at spec
    # construction, not in the first reader worker batch, and the lazy g++
    # compile happens here rather than under the hot path's module lock.
    # ``decode_threads`` bounds the C++ pool per call — reader pools running
    # several transforms concurrently should split the host's cores.
    from .. import native

    if backend == "native" and not native.native_available():
        raise RuntimeError(native.load_error() or "native pipeline unavailable")
    use_native = backend == "native" or (
        backend == "auto" and native.native_available()
    )

    def _decode_pil(b: bytes) -> np.ndarray:
        img = decode_resize_crop(b, resize=resize, crop=crop, layout=layout)
        if output_dtype == "uint8":
            # Undo ToTensor's /255: recover the exact quantized bytes.
            return np.round(img * 255.0).astype(np.uint8)
        if normalize:
            stats_shape = (1, 1, 3) if layout == "hwc" else (3, 1, 1)
            img = (img - IMAGENET_MEAN.reshape(stats_shape)) / IMAGENET_STD.reshape(
                stats_shape
            )
        return img

    def _count_substitution(n: int = 1) -> None:
        spec.substitutions.add(n)

    image_shape = (crop, crop, 3) if layout == "hwc" else (3, crop, crop)
    stats_shape = (1, 1, 3) if layout == "hwc" else (3, 1, 1)

    def _substitute_image() -> np.ndarray:
        """The dataset-MEAN image in this spec's output value space, so a
        substituted record is the same training input under every
        (output_dtype, normalize) configuration: zeros post-normalize ==
        IMAGENET_MEAN raw == round(255·mean) uint8 (which the device-side
        normalization maps back to ~0)."""
        if output_dtype == "uint8":
            img = np.round(IMAGENET_MEAN * 255.0).astype(np.uint8)
            return np.broadcast_to(
                img.reshape(stats_shape), image_shape
            ).copy()
        if normalize:
            return np.zeros(image_shape, np.float32)
        return np.broadcast_to(
            IMAGENET_MEAN.reshape(stats_shape).astype(np.float32), image_shape
        ).copy()

    def _decode_pil_or_substitute(b: bytes) -> np.ndarray:
        try:
            return _decode_pil(b)
        except Exception:
            if on_error == "raise":
                raise
            _count_substitution()
            return _substitute_image()

    def _func(batch: Columnar) -> Columnar:
        jpegs = [bytes(b) for b in batch[content_column]]
        if use_native:
            images, ok = native.decode_jpeg_batch(
                jpegs,
                resize=resize,
                crop=crop,
                mean=IMAGENET_MEAN if normalize and output_dtype == "float32" else None,
                std=IMAGENET_STD if normalize and output_dtype == "float32" else None,
                chw=layout == "chw",
                dtype=output_dtype,
                fast_scale=fast_decode,
                num_threads=decode_threads,
            )
            if not ok.all():
                if backend == "native" and on_error == "raise":
                    bad = int((~ok).sum())
                    raise ValueError(f"native decode failed for {bad} images")
                for i in np.flatnonzero(~ok):
                    if backend == "native":  # substitute, no PIL fallback
                        _count_substitution()
                        images[i] = _substitute_image()
                    else:
                        images[i] = _decode_pil_or_substitute(jpegs[i])
        else:
            images = np.stack([_decode_pil_or_substitute(b) for b in jpegs])
        labels = np.asarray(batch[label_column], np.int32)
        return {"image": images, "label": labels}

    spec = TransformSpec(
        func=_func,
        fields=[
            Field("image", np.dtype(output_dtype), image_shape),
            Field("label", np.dtype(np.int32), ()),
        ],
        backend="native" if use_native else "pil",
        layout=layout,
    )
    return spec

"""Row-group transform pipeline (the TransformSpec equivalent).

Petastorm's ``TransformSpec`` carries a pandas-level function plus
``edit_fields`` declaring post-transform dtypes/shapes so the reader can
build tensors without inspecting data (reference
``deep_learning/2.distributed-data-loading-petastorm.py:310-318``:
float32 (3,224,224) image + int32 label). Here the contract is columnar:
the function maps a dict of numpy arrays (one row group) to a dict of
numpy arrays, and ``fields`` declares the output schema the trainer can
rely on for jit-stable shapes.
"""

from __future__ import annotations

import dataclasses
import io
from typing import Callable, Mapping, Sequence

import numpy as np

Columnar = Mapping[str, np.ndarray]


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: np.dtype
    shape: tuple[int, ...]  # per-row shape, () for scalar columns


@dataclasses.dataclass(frozen=True)
class TransformSpec:
    """Transform + declared output schema.

    ``func`` runs on host CPU inside the reader worker pool — this is
    deliberately where JPEG decode lives (same as the reference: decode on
    host, ship ready tensors to the accelerator).
    """

    func: Callable[[Columnar], Columnar]
    fields: Sequence[Field]

    def __call__(self, batch: Columnar) -> dict[str, np.ndarray]:
        out = dict(self.func(batch))
        declared = {f.name: f for f in self.fields}
        if set(out) != set(declared):
            raise ValueError(
                f"transform produced columns {sorted(out)} but declared "
                f"{sorted(declared)}"
            )
        n = None
        for name, arr in out.items():
            f = declared[name]
            arr = np.asarray(arr, dtype=f.dtype)
            want = (len(arr),) + tuple(f.shape)
            if arr.shape != want:
                raise ValueError(
                    f"column {name}: shape {arr.shape} != declared {want}"
                )
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError("transform produced ragged column lengths")
            out[name] = arr
        return out


# -- ImageNet-style image pipeline (reference :282-296) ---------------------

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def decode_resize_crop(jpeg_bytes: bytes, resize: int = 256, crop: int = 224) -> np.ndarray:
    """JPEG → float32 CHW in [0,1], shorter-side resize then center crop.

    Matches torchvision's Resize(256)/CenterCrop(224)/ToTensor semantics
    used by the reference's ``preprocess`` (``deep_learning/2...py:282-296``).
    """
    from PIL import Image

    img = Image.open(io.BytesIO(jpeg_bytes)).convert("RGB")
    w, h = img.size
    scale = resize / min(w, h)
    img = img.resize((max(1, round(w * scale)), max(1, round(h * scale))), Image.BILINEAR)
    w, h = img.size
    left, top = (w - crop) // 2, (h - crop) // 2
    img = img.crop((left, top, left + crop, top + crop))
    arr = np.asarray(img, np.float32) / 255.0  # HWC
    return arr.transpose(2, 0, 1)  # CHW


def imagenet_transform_spec(
    *,
    content_column: str = "content",
    label_column: str = "label_index",
    resize: int = 256,
    crop: int = 224,
    normalize: bool = True,
    backend: str = "auto",
    decode_threads: int | None = None,
) -> TransformSpec:
    """The reference's training TransformSpec, columnar.

    Emits ``image`` float32 (3,crop,crop) and ``label`` int32 — the same
    field contract as ``deep_learning/2...py:310-318``.

    ``backend``: ``"native"`` uses the C++ decode pool
    (:mod:`dss_ml_at_scale_tpu.native` — GIL-free libjpeg + threaded
    resize/crop/normalize), ``"pil"`` the pure-Python path, ``"auto"``
    native when it compiles on this host with per-image PIL fallback for
    codecs the native path rejects (e.g. CMYK JPEGs).
    """
    if backend not in ("auto", "native", "pil"):
        raise ValueError(f"unknown backend {backend!r}")
    if crop > resize:
        # crop > resize would mean padding/stretching, and the native and
        # PIL paths disagree on which; the reference never does it (256/224).
        raise ValueError(f"crop ({crop}) must be <= resize ({resize})")

    # Resolve the backend NOW: a missing toolchain fails at spec
    # construction, not in the first reader worker batch, and the lazy g++
    # compile happens here rather than under the hot path's module lock.
    # ``decode_threads`` bounds the C++ pool per call — reader pools running
    # several transforms concurrently should split the host's cores.
    from .. import native

    if backend == "native" and not native.native_available():
        raise RuntimeError(native.load_error() or "native pipeline unavailable")
    use_native = backend == "native" or (
        backend == "auto" and native.native_available()
    )

    def _decode_pil(b: bytes) -> np.ndarray:
        img = decode_resize_crop(b, resize=resize, crop=crop)
        if normalize:
            img = (img - IMAGENET_MEAN[:, None, None]) / IMAGENET_STD[:, None, None]
        return img

    def _func(batch: Columnar) -> Columnar:
        jpegs = [bytes(b) for b in batch[content_column]]
        if use_native:
            images, ok = native.decode_jpeg_batch(
                jpegs,
                resize=resize,
                crop=crop,
                mean=IMAGENET_MEAN if normalize else None,
                std=IMAGENET_STD if normalize else None,
                chw=True,
                num_threads=decode_threads,
            )
            if not ok.all():
                if backend == "native":
                    bad = int((~ok).sum())
                    raise ValueError(f"native decode failed for {bad} images")
                for i in np.flatnonzero(~ok):
                    images[i] = _decode_pil(jpegs[i])
        else:
            images = np.stack([_decode_pil(b) for b in jpegs])
        labels = np.asarray(batch[label_column], np.int32)
        return {"image": images, "label": labels}

    return TransformSpec(
        func=_func,
        fields=[
            Field("image", np.dtype(np.float32), (3, crop, crop)),
            Field("label", np.dtype(np.int32), ()),
        ],
    )

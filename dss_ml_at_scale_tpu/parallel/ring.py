"""Ring attention: exact attention over sequence-sharded inputs.

Long-context support is first-class in this framework even though the
reference never touches a sequence dimension (SURVEY.md §5.7 — its
workloads are ResNet-50 / SVC / weekly SARIMAX). The sharding layer is
mesh-based precisely so sequence parallelism falls out of the same
mechanism as data/tensor parallelism.

Design (the standard TPU ring schedule):

- q, k, v are sharded over a mesh axis along the sequence dimension; each
  device keeps its q shard resident and the k/v shards rotate one hop per
  step via ``lax.ppermute`` — P-1 hops ride the ICI ring, overlapping the
  next shard's transfer with the current shard's compute (XLA pipelines
  the permute with the chunk matmuls).
- Each step computes blockwise attention of the local q against the
  visiting k/v chunk, returning a normalized chunk output plus its row
  log-sum-exp; chunks merge in f32 with the online-softmax rescaling, so
  the result is bit-comparable to full attention, not an approximation.
- The per-chunk attention is wrapped in ``jax.checkpoint``: the backward
  pass recomputes chunk scores instead of storing P score matrices, so
  peak memory is O(s_local²) per device regardless of ring size. The scan
  over steps is reverse-differentiable, and ``ppermute``'s transpose is
  itself a ppermute — gradients ride the same ring backwards.
- Causality is decided per (q-shard, kv-chunk) pair by global offsets: a
  fully-masked chunk contributes ``lse ≈ -1e30`` and merges with weight
  exp(-1e30 - lse_total) == 0, so no branching is needed inside the scan.

The Pallas flash kernel (:mod:`dss_ml_at_scale_tpu.ops.flash_attention`)
is the single-device fast path for the same math; the ring path keeps its
chunk compute in plain XLA because the merge needs differentiable
log-sum-exp outputs.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map_unchecked

_NEG_INF = -1e30


@functools.partial(jax.checkpoint, static_argnums=(5,))
def _chunk_attention(q, k, v, q_off, k_off, causal):
    """Attention of a local q shard against one visiting k/v chunk.

    Returns ``(out, lse)``: the chunk-normalized output (f32) and the row
    log-sum-exp (f32) needed to merge chunks exactly. ``q_off``/``k_off``
    are the chunks' global sequence offsets (traced values — causality is
    masked, not branched).
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (1.0 / math.sqrt(d))
    if causal:
        qi = q_off + jnp.arange(q.shape[2])[:, None]
        ki = k_off + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)) / l
    lse = (m + jnp.log(l))[..., 0]  # (b, h, sq_local)
    return out, lse


def _merge(o1, lse1, o2, lse2):
    """Exact combination of two chunk-normalized attention outputs."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = w1 + w2
    out = (o1 * w1[..., None] + o2 * w2[..., None]) / denom[..., None]
    return out, m + jnp.log(denom)


def _ring_local(q_l, k_l, v_l, *, axis_name, causal):
    p_sz = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q_l.shape[2]
    perm = [(j, (j + 1) % p_sz) for j in range(p_sz)]

    def step(carry, i):
        # Permute first, then compute: the local (hop-0) chunk is handled
        # outside the scan, so the ring pays exactly p_sz - 1 hops — XLA
        # cannot DCE a trailing collective inside a scan body.
        out, lse, k_c, v_c = carry
        k_c, v_c = jax.lax.ppermute((k_c, v_c), axis_name, perm)
        src = (my - i) % p_sz  # which global chunk is visiting this step
        o_c, lse_c = _chunk_attention(
            q_l, k_c, v_c, my * s_local, src * s_local, causal
        )
        out, lse = _merge(out, lse, o_c, lse_c)
        return (out, lse, k_c, v_c), None

    out0, lse0 = _chunk_attention(
        q_l, k_l, v_l, my * s_local, my * s_local, causal
    )
    (out, _, _, _), _ = jax.lax.scan(
        step, (out0, lse0, k_l, v_l), jnp.arange(1, p_sz)
    )
    return out.astype(q_l.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact (flash-equivalent) attention, sequence-sharded over ``axis_name``.

    ``q``, ``k``, ``v``: ``[batch, heads, seq, head_dim]`` global arrays
    (jit-traced values are fine); seq must divide evenly by the axis size.
    Returns the attention output with the same sharding layout.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [batch, heads, seq, head_dim], got {q.shape}")
    p_sz = mesh.shape[axis_name]
    if q.shape[2] % p_sz or k.shape[2] % p_sz:
        raise ValueError(
            f"seq lengths {q.shape[2]}/{k.shape[2]} not divisible by "
            f"mesh axis {axis_name!r} size {p_sz}"
        )
    if q.shape[2] != k.shape[2]:
        raise ValueError("ring attention requires sq == sk (self-attention)")
    spec = P(None, None, axis_name, None)
    local = functools.partial(_ring_local, axis_name=axis_name, causal=causal)
    fn = shard_map_unchecked(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return fn(q, k, v)

"""jax-version compatibility shims shared by the shard_map-based strategies."""

from __future__ import annotations

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def shard_map_unchecked(fn, *, mesh, in_specs, out_specs):
    """``shard_map`` with replication checking off across jax versions.

    The flag was renamed ``check_rep`` → ``check_vma`` in jax 0.8; both
    ring attention and the GPipe pipeline need it off (their per-device
    programs are deliberately non-replicated along the strategy axis).
    """
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return shard_map(fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax
        return shard_map(fn, check_rep=False, **kwargs)

"""Device-pinned parallel trials executor — the SparkTrials replacement.

Reference behavior (``SparkTrials(parallelism=N)``,
``hyperopt/1. hyperopt.py:121-136``): the driver's TPE proposes trials,
up to N evaluate concurrently on executors, results stream back into the
shared history, and a failing trial doesn't kill the sweep.

TPU-native shape: one process per host already owns all local chips, so
trials run on a thread pool with each trial **pinned to one local device**
via ``jax.default_device`` — N chips, N concurrent trials, no Spark, no
serialization of the objective (closures ship by reference in-process;
see :mod:`dss_ml_at_scale_tpu.hpo.shipping` for the larger-data modes).

Async proposal semantics match SparkTrials: a proposal sees whatever
history has completed at submit time (the sweep is therefore not
bit-identical to sequential TPE — same as SparkTrials vs Trials).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

import jax

from ..hpo.fmin import Trials, _call_objective, _log_trial


class DeviceTrials(Trials):
    """Run trials concurrently, each pinned to one accelerator device."""

    def __init__(
        self,
        parallelism: int | None = None,
        devices=None,
        pin_devices: bool = True,
    ):
        super().__init__()
        self.devices = list(devices) if devices is not None else jax.local_devices()
        self.parallelism = parallelism or len(self.devices)
        self.pin_devices = pin_devices

    def run(self, objective, space, algo, max_evals, rng, tracker=None) -> None:
        # Pool is local to each run: a resumed sweep (fmin again with the
        # same trials object) must not duplicate device entries, or two
        # trials could pin the same chip while another idles.
        device_pool: queue.SimpleQueue = queue.SimpleQueue()
        for d in self.devices:
            device_pool.put(d)
        lock = threading.Lock()  # guards trial history + rng for proposals

        def evaluate(tid: int, point: dict) -> tuple[int, dict, dict, float]:
            t0 = time.time()
            if self.pin_devices:
                device = device_pool.get()
                try:
                    with jax.default_device(device):
                        result = _call_objective(objective, space, point)
                finally:
                    device_pool.put(device)
            else:
                result = _call_objective(objective, space, point)
            return tid, point, result, t0

        next_tid = len(self.trials)
        submitted = next_tid
        with ThreadPoolExecutor(max_workers=self.parallelism) as pool:
            pending = set()
            while submitted < max_evals or pending:
                while submitted < max_evals and len(pending) < self.parallelism:
                    with lock:
                        point = algo(space, self._history(), rng)
                    pending.add(pool.submit(evaluate, submitted, point))
                    submitted += 1
                done, pending = wait(pending, return_when=FIRST_COMPLETED)
                for fut in done:
                    tid, point, result, t0 = fut.result()
                    with lock:
                        self._record(tid, point, result, t0)
                    if tracker is not None:
                        _log_trial(tracker, tid, point, result)
        self.trials.sort(key=lambda t: t["tid"])

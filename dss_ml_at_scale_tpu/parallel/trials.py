"""Device-pinned parallel trials executor — the SparkTrials replacement.

Reference behavior (``SparkTrials(parallelism=N)``,
``hyperopt/1. hyperopt.py:121-136``): the driver's TPE proposes trials,
up to N evaluate concurrently on executors, results stream back into the
shared history, and a failing trial doesn't kill the sweep.

TPU-native shape: one process per host already owns all local chips, so
trials run on a thread pool with each trial **pinned to one local device**
via ``jax.default_device`` — N chips, N concurrent trials, no Spark, no
serialization of the objective (closures ship by reference in-process;
see :mod:`dss_ml_at_scale_tpu.hpo.shipping` for the larger-data modes).

Async proposal semantics match SparkTrials: a proposal sees whatever
history has completed at submit time (the sweep is therefore not
bit-identical to sequential TPE — same as SparkTrials vs Trials).
"""

from __future__ import annotations

import logging
import queue
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

import jax

from .. import telemetry
from ..hpo.fmin import Trials, _call_objective, _log_trial
from ..telemetry import tracecontext

log = logging.getLogger(__name__)


class DeviceTrials(Trials):
    """Run trials concurrently, each pinned to one accelerator device."""

    def __init__(
        self,
        parallelism: int | None = None,
        devices=None,
        pin_devices: bool = True,
    ):
        super().__init__()
        self.devices = list(devices) if devices is not None else jax.local_devices()
        self.parallelism = parallelism or len(self.devices)
        self.pin_devices = pin_devices

    def run(self, objective, space, algo, max_evals, rng, tracker=None) -> None:
        # Pool is local to each run: a resumed sweep (fmin again with the
        # same trials object) must not duplicate device entries, or two
        # trials could pin the same chip while another idles.
        device_pool: queue.SimpleQueue = queue.SimpleQueue()
        for d in self.devices:
            device_pool.put(d)

        def evaluate(tid: int, point: dict) -> tuple[int, dict, dict, float]:
            t0 = time.time()
            if self.pin_devices:
                device = device_pool.get()
                try:
                    with jax.default_device(device), telemetry.span(
                        "trial", tid=tid, device=str(device)
                    ):
                        result = _call_objective(objective, space, point)
                finally:
                    device_pool.put(device)
            else:
                with telemetry.span("trial", tid=tid):
                    result = _call_objective(objective, space, point)
            return tid, point, result, t0

        _run_async_pool(
            self, evaluate, algo, space, max_evals, rng, tracker,
            self.parallelism,
        )


def _run_async_pool(
    trials, evaluate, algo, space, max_evals, rng, tracker, parallelism
) -> None:
    """SparkTrials-style async driver loop shared by the parallel executors.

    Proposes from whatever history has completed, keeps up to
    ``parallelism`` evaluations in flight, records results as they land.
    Proposals and recording happen only on the calling thread;
    ``evaluate(tid, point) -> (tid, point, result, t0)`` runs on pool
    threads and must not touch the trial store.
    """
    outcomes = telemetry.counter(
        "hpo_trials_total", "completed HPO trials by outcome",
        labels=("status",),
    )

    def _traced(handoff: tracecontext.Handoff, tid: int, point: dict):
        # Worker-pool boundary: the trial's trace was minted on the
        # driver thread at proposal time; the pool thread adopts it so
        # the trial span joins the same timeline as trial.submit.
        with handoff.activate():
            return evaluate(tid, point)

    submitted = len(trials.trials)
    with ThreadPoolExecutor(max_workers=parallelism) as pool:
        pending = set()
        while submitted < max_evals or pending:
            while submitted < max_evals and len(pending) < parallelism:
                handoff = tracecontext.Handoff.root(kind="trial")
                with handoff.activate(), telemetry.span(
                    "trial.submit", tid=submitted
                ):
                    point = algo(space, trials._history(), rng)
                pending.add(
                    pool.submit(_traced, handoff, submitted, point)
                )
                submitted += 1
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for fut in done:
                tid, point, result, t0 = fut.result()
                trials._record(tid, point, result, t0)
                outcomes.labels(
                    status=str(result.get("status", "unknown"))
                ).inc()
                if tracker is not None:
                    _log_trial(tracker, tid, point, result)
    trials.trials.sort(key=lambda t: t["tid"])


# ---------------------------------------------------------------------------
# Multi-host trials over the RPC control plane (SURVEY.md §5.8)
# ---------------------------------------------------------------------------

def objective_ref(fn) -> str:
    """Importable ``module:qualname`` reference for a trial objective.

    The wire carries a *reference*, not code: workers import the same
    package and resolve it — the moral equivalent of Spark shipping a
    pickled function to executors, minus arbitrary-code pickles. Closures
    and lambdas therefore can't cross hosts; module-level functions can
    (bind data via the :mod:`dss_ml_at_scale_tpu.hpo.shipping` modes).
    """
    if isinstance(fn, str):
        return fn
    qualname = getattr(fn, "__qualname__", "")
    if not qualname or "<locals>" in qualname or "<lambda>" in qualname:
        raise ValueError(
            f"objective {fn!r} is not importable by reference; move it to "
            "module level (data can ship via hpo.shipping)"
        )
    return f"{fn.__module__}:{qualname}"


def resolve_objective(ref: str):
    import importlib

    module, _, qualname = ref.partition(":")
    obj = importlib.import_module(module)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def serve_trial_worker(
    bind: str = "127.0.0.1:0",
    block: bool = True,
    secret: bytes | str | None = None,
    allow_insecure: bool = False,
    announce=None,
):
    """Run a trial-evaluation worker (one per host, like a Spark executor).

    Exposes ``evaluate({"objective": ref, "args": kwargs}) -> result``,
    ``ping``, and the telemetry pull handlers (``telemetry_snapshot`` /
    ``telemetry_spans``) so a coordinator can collect this host's
    counters and spans over the same control plane. Objectives run under
    the trial-result protocol, so a raising objective returns a ``fail``
    result instead of killing the worker. Non-loopback binds require
    ``secret`` (HMAC handshake; see
    :mod:`dss_ml_at_scale_tpu.runtime.rpc`) unless ``allow_insecure``.

    ``announce`` is called with the bound ``host:port`` line (the CLI
    passes ``print`` — a user starting a worker needs the OS-assigned
    port on stdout); library callers default to the module logger.
    """
    from ..hpo.fmin import call_with_protocol
    from ..runtime.rpc import RpcServer

    host, _, port = bind.rpartition(":")

    def _evaluate(payload):
        fn = resolve_objective(payload["objective"])
        # Worker-side trial span: this is what a coordinator's
        # telemetry_spans pull sees for the host's trial timeline.
        with telemetry.span("trial", objective=payload["objective"]):
            return call_with_protocol(fn, payload["args"])

    server = RpcServer(
        {
            "evaluate": _evaluate,
            "ping": lambda _: "pong",
            **telemetry.rpc_handlers(),
        },
        host or "127.0.0.1",
        int(port),
        secret=secret,
        allow_insecure=allow_insecure,
    )
    message = (
        f"trial worker listening on {server.address[0]}:{server.address[1]}"
    )
    if announce is not None:
        announce(message)
    else:
        log.info("%s", message)
    if block:
        server.serve_forever()
        return None
    return server.serve_background()


class HostTrials(Trials):
    """Distribute trials across worker hosts (the multi-host SparkTrials).

    ``workers`` are ``host:port`` addresses of :func:`serve_trial_worker`
    processes. The driver's TPE proposes; up to ``parallelism`` trials
    evaluate concurrently, each call pinned to one worker from a pool so
    load spreads evenly.

    Failure semantics (the Spark-parity part):

    - An *objective* exception (the worker responded; the handler
      raised) fails that trial only — same isolation as today's
      SparkTrials; retrying a deterministic failure would just repeat it.
    - A *transport* failure (dead peer, timeout, truncated stream) does
      NOT consume the eval: the worker is dropped from the pool and the
      trial requeues onto another worker, up to ``max_retries`` times
      with jittered backoff (``retry_total{site=trial.evaluate}``).
    - Dropped workers get a background heartbeat probe and are
      re-admitted when they recover (``worker_readmitted_total``)
      instead of being gone for the rest of the sweep.
    """

    accepts_objective_ref = True

    def __init__(
        self,
        workers,
        parallelism: int | None = None,
        rpc_timeout: float = 600.0,
        validate_ref: bool = True,
        secret: bytes | str | None = None,
        max_retries: int = 2,
        heartbeat_interval: float = 0.5,
        dead_grace: float = 1.0,
    ):
        super().__init__()
        if not workers:
            raise ValueError("HostTrials needs at least one worker address")
        self.workers = list(workers)
        self.parallelism = parallelism or len(self.workers)
        self.rpc_timeout = rpc_timeout
        self.validate_ref = validate_ref
        self.secret = secret
        self.max_retries = max_retries
        self.heartbeat_interval = heartbeat_interval
        self.dead_grace = dead_grace

    def run(self, objective, space, algo, max_evals, rng, tracker=None) -> None:
        from ..hpo.space import space_eval
        from ..resilience.retry import RetryPolicy, call_with_retry
        from ..resilience.workers import WorkerPool
        from ..runtime.rpc import (
            RpcAuthError,
            RpcHandshakeTimeout,
            RpcRemoteError,
            rpc_call,
        )

        ref = objective_ref(objective)
        if self.validate_ref:
            # Workers run the same package, so a typo'd ref that cannot
            # resolve here would fail every single trial remotely; raise
            # once up front instead (validate_ref=False for worker-only
            # objective modules).
            try:
                resolve_objective(ref)
            except Exception as e:
                raise ValueError(
                    f"objective ref {ref!r} does not resolve on the driver: "
                    f"{e!r}"
                ) from e

        # Heartbeat probe: a plain ping with a short timeout. Probes run
        # on background threads against workers already dropped, so they
        # never hold up a trial; they go through rpc_call like any call
        # (their fault site is rpc.send.ping — armable separately from
        # the evaluate path).
        def probe(worker) -> None:
            rpc_call(
                worker, "ping",
                timeout=min(5.0, self.rpc_timeout), secret=self.secret,
            )

        # Pool is local to each run, like the device pool above: a
        # resumed sweep must not duplicate worker entries or inherit a
        # previous run's dropped/probing state.
        pool = WorkerPool(
            self.workers,
            probe=probe,
            heartbeat_interval=self.heartbeat_interval,
            dead_grace=self.dead_grace,
        )
        policy = RetryPolicy(max_retries=self.max_retries, base_delay=0.1,
                             max_delay=1.0)

        class _Requeue(ConnectionError):
            """Transport failure already handled (worker dropped); the
            retry wrapper should re-run the attempt on another worker."""

        def attempt(tid: int, point: dict) -> dict:
            worker = pool.get(timeout=self.rpc_timeout)
            if worker is None:
                # Permanent pool death is not retryable: every remaining
                # attempt would see the same empty pool.
                return {
                    "status": "fail",
                    "error": "no live workers (all busy, dead, or timed out)",
                }
            try:
                # Driver-side trial span: covers the whole remote round
                # trip (the worker records its own compute-only span).
                with telemetry.span("trial", tid=tid, worker=str(worker)):
                    result = rpc_call(
                        worker,
                        "evaluate",
                        {"objective": ref, "args": space_eval(space, point)},
                        timeout=self.rpc_timeout,
                        secret=self.secret,
                    )
            except RpcRemoteError as e:
                # The worker responded — it is healthy; the handler raised
                # (e.g. unresolvable ref, a raising objective outside the
                # result protocol). Permanent: trial fails, worker returns.
                pool.put(worker)
                return {"status": "fail", "error": f"worker {worker}: {e}"}
            except RpcAuthError as e:
                if isinstance(e, RpcHandshakeTimeout):
                    # A stalled handshake is NOT provably a wrong secret:
                    # a hung-but-accepting host looks exactly like this.
                    # Transport semantics — drop (heartbeat probes it)
                    # and requeue — so a zombie worker doesn't stay
                    # pooled burning 10 s per trial.
                    pool.drop(worker)
                    raise _Requeue(
                        f"worker {worker} dropped: handshake stalled: {e}"
                    ) from e
                # Digest rejection: deterministic misconfiguration, not a
                # transport outage — retrying or heartbeat-probing with
                # the same wrong secret can never succeed. Fail the trial
                # loudly, naming auth, and keep the worker pooled so the
                # sweep fails fast everywhere rather than masking the
                # cause behind dropped-worker noise.
                pool.put(worker)
                return {
                    "status": "fail",
                    "error": f"worker {worker} auth failure: {e}",
                }
            except Exception as e:
                # Transport failure: the worker is dead, or still chewing
                # on the evaluation we just abandoned (timeout). Returning
                # it would stack concurrent evaluations on a struggling
                # host — drop it (heartbeat re-admits on recovery) and
                # requeue the trial onto another worker. A worker that
                # timed out MID-EVALUATION gets a probe cool-down of the
                # full rpc_timeout: its threaded server would answer a
                # ping instantly while still computing the abandoned
                # evaluation, and an immediate re-admission would pile a
                # second one on top. Connect-phase timeouts raise
                # RpcConnectTimeout (a ConnectionError, not TimeoutError)
                # — nothing was delivered, so probe immediately.
                pool.drop(
                    worker,
                    cooldown=(
                        self.rpc_timeout
                        if isinstance(e, TimeoutError) else 0.0
                    ),
                )
                raise _Requeue(
                    f"worker {worker} dropped: {type(e).__name__}: {e}"
                ) from e
            else:
                pool.put(worker)
            return result

        def evaluate(tid: int, point: dict):
            t0 = time.time()
            try:
                result = call_with_retry(
                    attempt, tid, point,
                    policy=policy,
                    retryable=lambda e: isinstance(e, _Requeue),
                    site="trial.evaluate",
                )
            except _Requeue as e:
                result = {
                    "status": "fail",
                    "error": f"{e} (transport retries exhausted)",
                }
            return tid, point, result, t0

        try:
            _run_async_pool(
                self, evaluate, algo, space, max_evals, rng, tracker,
                self.parallelism,
            )
        finally:
            pool.close()

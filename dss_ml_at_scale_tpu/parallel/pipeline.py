"""Pipeline parallelism: GPipe-style SPMD microbatch pipeline over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.3 — its only
gradient parallelism is DDP data parallel), but the framework's sharding
layer is mesh-based precisely so every parallelism family falls out of
the same mechanism. This module adds the PP column: N sequential stages
laid out over a ``"pipe"`` mesh axis, microbatches streamed through with
one ``lax.ppermute`` hop per tick riding the ICI ring.

Design (the standard TPU SPMD pipeline schedule):

- Stage parameters are *stacked* on a leading stage dimension and sharded
  over the pipe axis — device i holds only stage i's weights. There is no
  per-stage program: every device runs the SAME jitted computation
  (SPMD), applying its resident stage to whatever activation is currently
  in flight on it.
- A scan over ``n_micro + n_stages - 1`` ticks drives the schedule.
  Each tick: device 0 ingests the next microbatch, every device applies
  its stage, the last device banks its finished microbatch, and all
  activations shift one hop along the ring (``ppermute``). The first
  ``n_stages - 1`` ticks are the classic GPipe bubble: utilization is
  ``n_micro / (n_micro + n_stages - 1)``, so callers pick
  ``n_micro >> n_stages``.
- The whole schedule is reverse-differentiable: ``ppermute``'s transpose
  is the reverse ppermute, so ``jax.grad`` through the pipeline yields
  the 1F1B-style backward sweep automatically — gradients visit stages
  in reverse order over the same ring, with XLA overlapping the hop with
  each stage's backward matmuls. Each stage application is wrapped in
  ``jax.checkpoint`` so the backward pass rematerializes stage compute
  instead of storing every tick's activations.

``spmd_pipeline`` is deliberately functional — ``stage_fn(params, x)``
is any jittable per-stage function (a Flax ``Module.apply`` bound to
stacked params, a bare matmul, a transformer block) — and composes with
data parallelism via ``batch_axis``: on a ``{"pipe": P, "data": D}``
mesh the within-microbatch batch dimension is sharded over "data", so
each of the D columns pipelines its own batch shard (PP × DP).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "PipelinedTask",
    "check_same_mesh",
    "moment_sharding",
    "pipeline_utilization",
    "spmd_pipeline",
    "stack_stage_params",
    "stage_sharding",
]

from ._compat import shard_map_unchecked


def check_same_mesh(task_mesh: Mesh, mesh: Mesh, what: str) -> None:
    """Require ``mesh`` to be the mesh a pipeline schedule was built on.

    A distinct mesh with equal axis sizes but a different device order
    would pass a shape-only check and then silently place state on one
    device assignment while ``shard_map`` executes over another —
    per-step resharding single-host, wrong placement multi-host. Equal
    axis names AND an identical device array are both required.
    """
    import numpy as np

    if mesh is task_mesh:
        return
    if dict(mesh.shape) != dict(task_mesh.shape) or not np.array_equal(
        mesh.devices, task_mesh.devices
    ):
        raise ValueError(
            f"Trainer mesh {dict(mesh.shape)} (devices "
            f"{mesh.devices.ravel().tolist()}) != {what} mesh "
            f"{dict(task_mesh.shape)} (devices "
            f"{task_mesh.devices.ravel().tolist()}); construct the task "
            "with the Trainer's mesh"
        )


def stack_stage_params(init_fn: Callable[[jax.Array], Any], rng: jax.Array,
                       n_stages: int):
    """Initialize ``n_stages`` independent stage params, stacked on axis 0.

    ``init_fn(rng) -> pytree`` initializes ONE stage; the result's leaves
    gain a leading ``[n_stages, ...]`` dimension, ready to shard over the
    pipe axis with :func:`stage_sharding`.
    """
    return jax.vmap(init_fn)(jax.random.split(rng, n_stages))


def stage_sharding(params: Any, mesh: Mesh, axis_name: str = "pipe"):
    """NamedSharding tree placing each stacked leaf's stage dim on the axis."""
    def leaf(l):
        ndim = getattr(l, "ndim", 0)
        if ndim < 1:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(*([axis_name] + [None] * (ndim - 1))))

    return jax.tree_util.tree_map(leaf, params)


def spmd_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    axis_name: str = "pipe",
    batch_axis: str | None = None,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Build ``run(stacked_params, microbatches) -> outputs``.

    ``stacked_params``: pytree with a leading stage dimension of size
    ``mesh.shape[axis_name]`` on every array leaf (see
    :func:`stack_stage_params`), sharded or shardable over the axis.

    ``microbatches``: ``[n_micro, micro_batch, ...]`` activations; the
    output has the same shape after every microbatch passed through all
    stages in order. ``stage_fn`` must preserve the activation shape
    (equal widths — the GPipe regime; unequal-width stages belong to
    tensor sharding, not the pipeline).

    ``batch_axis``: optional second mesh axis carrying data parallelism —
    the per-microbatch batch dimension (``microbatches`` axis 1) is
    sharded over it, so a ``{"pipe": P, "data": D}`` mesh runs D
    batch-shards through P stages concurrently (PP × DP). When None the
    activations are replicated over every non-pipe axis.
    """
    n = mesh.shape[axis_name]
    fwd = [(i, (i + 1) % n) for i in range(n)]
    checkpointed = jax.checkpoint(stage_fn)

    def local(stacked, xs):
        # stacked leaves arrive as [1, ...] local shards — drop the stage dim.
        params = jax.tree_util.tree_map(lambda l: l[0], stacked)
        idx = jax.lax.axis_index(axis_name)
        n_micro = xs.shape[0]
        state = jnp.zeros_like(xs[0])
        ys = jnp.zeros_like(xs)

        def tick(carry, t):
            state, ys = carry
            # Device 0 ingests microbatch t (a clipped gather keeps the
            # index in range through the drain ticks; the value is unused
            # once t >= n_micro because those outputs are never banked).
            x_in = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            state = jnp.where(idx == 0, x_in, state)
            out = checkpointed(params, state)
            # After applying stage ``idx`` at tick t, device idx holds
            # microbatch t - idx processed through stages 0..idx; the last
            # device therefore banks microbatch t - (n-1).
            t_out = t - (n - 1)
            banked = jax.lax.dynamic_update_index_in_dim(
                ys, out, jnp.clip(t_out, 0, n_micro - 1), 0
            )
            ys = jnp.where((idx == n - 1) & (t_out >= 0), banked, ys)
            state = jax.lax.ppermute(out, axis_name, fwd)
            return (state, ys), None

        (state, ys), _ = jax.lax.scan(
            tick, (state, ys), jnp.arange(n_micro + n - 1)
        )
        # Only the last stage holds real outputs; the masked psum over the
        # pipe axis broadcasts them so the result is replicated along
        # "pipe" (and stays sharded over ``batch_axis`` if one was given).
        return jax.lax.psum(
            jnp.where(idx == n - 1, ys, jnp.zeros_like(ys)), axis_name
        )

    stage_spec = P(axis_name)  # leading stage dim on every leaf
    # Microbatch activations: replicated along the pipe axis, optionally
    # batch-sharded over ``batch_axis`` (axis 1 = within-microbatch batch).
    io_spec = P(None, batch_axis) if batch_axis is not None else P()

    def run(stacked, xs):
        specs = (
            jax.tree_util.tree_map(lambda _: stage_spec, stacked),
            io_spec,
        )
        fn = shard_map_unchecked(
            local, mesh=mesh, in_specs=specs, out_specs=io_spec
        )
        return fn(stacked, xs)

    return run


def pipeline_utilization(n_micro: int, n_stages: int) -> float:
    """GPipe bubble accounting: fraction of ticks doing useful work."""
    return n_micro / (n_micro + n_stages - 1)


def moment_sharding(tree, mesh: Mesh, axis_name: str, n_stages: int):
    """Sharding tree for optimizer state mirroring stacked stage params.

    Adam moments mirror param shapes, so any leaf with a leading
    ``n_stages`` dim is a stage stack (callers must guarantee no other
    leaf leads with that size — see PipelinedLM's collision guard);
    scalars and optax counters replicate.
    """
    replicated = NamedSharding(mesh, P())

    def leaf(l):
        ndim = getattr(l, "ndim", 0)
        shape = getattr(l, "shape", ())
        if ndim >= 1 and shape[0] == n_stages:
            return NamedSharding(
                mesh, P(axis_name, *([None] * (ndim - 1)))
            )
        return replicated

    return jax.tree_util.tree_map(leaf, tree)


class PipelinedTask:
    """Pipeline-parallel regression task for the standard Trainer loop.

    The PP analogue of ``LMTask``/``ClassifierTask``: stage parameters
    are stacked and STAGE-SHARDED over ``axis_name`` (declared via the
    ``state_shardings`` hook the Trainer honors — PP params are the one
    task family that must not be replicated), and every train step runs
    the GPipe microbatch schedule end-to-end with the optimizer update.

    Batches: ``{"x": [n_micro, micro_batch, d], "y": like x}``; loss is
    MSE of the pipeline output against ``y``. With a ``batch_axis``, pass
    ``TrainerConfig(batch_specs={"x": P(None, axis), "y": P(None, axis)})``
    so batch placement matches the pipeline's PP × DP layout.
    """

    def __init__(self, stage_fn, init_stage_fn, mesh: Mesh,
                 axis_name: str = "pipe", batch_axis: str | None = None,
                 tx=None, learning_rate: float = 1e-2):
        import optax

        self.stage_fn = stage_fn
        self.init_stage_fn = init_stage_fn
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_stages = mesh.shape[axis_name]
        self.tx = tx if tx is not None else optax.adam(learning_rate)
        self.run = spmd_pipeline(stage_fn, mesh, axis_name, batch_axis)

    # Lower is better for the Trainer's best-checkpoint tracking.
    default_best_metric = "val_loss"
    default_best_mode = "min"

    def batch_size_of(self, batch) -> int:
        """Examples per batch = n_micro × micro_batch (Trainer hook)."""
        x = batch["x"]
        n_micro = int(x.shape[0])
        # The bubble fraction is fixed by (n_micro, n_stages); publish it
        # whenever batch geometry is (re)observed so operators see when a
        # too-small microbatch count is wasting ticks.
        from .. import telemetry

        telemetry.gauge(
            "pipeline_utilization",
            "GPipe schedule utilization n_micro/(n_micro+n_stages-1)",
        ).set(pipeline_utilization(n_micro, self.n_stages))
        return n_micro * int(x.shape[1])

    def init_state(self, rng, sample_batch):
        from .trainer import TrainState

        params = stack_stage_params(self.init_stage_fn, rng, self.n_stages)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats={},
            opt_state=self.tx.init(params),
        )

    def state_shardings(self, state, mesh: Mesh):
        """Stage-shard params AND the mirrored optimizer moments; scalars
        (step, optax counters) replicate."""
        # The schedule (self.run) was built against self.mesh; a Trainer
        # running a different mesh would place state on one mesh and
        # execute shard_map over another.
        check_same_mesh(self.mesh, mesh, "PipelinedTask")
        replicated = NamedSharding(mesh, P())
        return type(state)(
            step=replicated,
            params=stage_sharding(state.params, mesh, self.axis_name),
            batch_stats=jax.tree_util.tree_map(lambda _: replicated,
                                               state.batch_stats),
            opt_state=moment_sharding(
                state.opt_state, mesh, self.axis_name, self.n_stages
            ),
        )

    def train_step(self, state, batch):
        import optax

        xs, ys = batch["x"], batch["y"]

        def loss_fn(params):
            return jnp.mean((self.run(params, xs) - ys) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            type(state)(
                step=state.step + 1,
                params=new_params,
                batch_stats=state.batch_stats,
                opt_state=new_opt,
            ),
            {"train_loss": loss},
        )

    def eval_step(self, state, batch):
        loss = jnp.mean((self.run(state.params, batch["x"]) - batch["y"]) ** 2)
        return {"val_loss": loss}

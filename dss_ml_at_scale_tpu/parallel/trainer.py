"""Data-parallel trainer: explicit jitted step loop over a device mesh.

Replaces the reference's PyTorch-Lightning ``Trainer(strategy="ddp")`` +
``TorchDistributor`` stack (reference
``deep_learning/2.distributed-data-loading-petastorm.py:351-415``) with the
TPU-native shape: one jitted train step compiled over a batch-sharded mesh.
Gradient averaging needs no NCCL and no ``psum`` written by hand — the loss
is a mean over the *global* (sharded) batch, so XLA emits the cross-chip
reduction on ICI as part of backprop.

Semantics carried over from the reference driver:

- epoch boundaries by step count on an infinite reader:
  ``steps_per_epoch = rows // (batch × world)`` (``:387-388``), the
  Lightning ``limit_train_batches`` trick made explicit;
- eval every epoch, capped at ``limit_val_batches`` (``:402-405``);
- no sanity-val prologue (``num_sanity_val_steps=0``);
- per-epoch wall-clock + throughput reporting (``:183-188``);
- checkpoint each epoch, best tracked on a val metric, best path returned
  (``:407-415``) — here via Orbax sharded checkpoints with resume.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import logging
import math
import threading
import time
from pathlib import Path
from typing import Any, Callable, Iterable, Mapping

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry
from ..telemetry import tracecontext
from ..data.prefetch import MeshFeeder, split_provenance
from ..resilience import checkpoint as integrity
from ..resilience import durability
from ..resilience import health
from ..resilience.faults import maybe_fail
from ..resilience.preemption import PreemptionGuard
from ..models.metrics import (
    cross_entropy_loss,
    multiclass_accuracy,
    topk_accuracy,
)
from ..runtime.mesh import make_mesh
from ..runtime.topology import local_topology
from ..utils.profiling import StepTimer

log = logging.getLogger(__name__)

Batch = Mapping[str, Any]

# 1-in-N sampling for the per-step histograms (step time, data wait):
# distribution estimates don't need every step, and the exact totals
# ride counters (feeder_stall_seconds_total) / per-epoch StepTimer
# summaries instead.
_HIST_SAMPLE_EVERY = 4


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any


@dataclasses.dataclass
class ClassifierTask:
    """Image-classification task: Flax model + optax optimizer.

    The functional analogue of the reference's
    ``ImageNetClassificationModel(pl.LightningModule)``
    (``deep_learning/2...py:135-208``): Adam(lr=1e-5) default, softmax
    cross-entropy, top-1 accuracy on eval.

    Expects batches with ``image`` (NHWC or NCHW) and ``label`` (int).
    The decode pipeline emits NHWC by default (TPU convs are NHWC-native,
    so the hot path never transposes on device); CHW input
    (``layout="chw"`` torchvision-parity specs) is transposed once here.
    uint8 images (``output_dtype="uint8"`` specs — 4x cheaper to queue
    and transfer) are raw [0, 255] bytes: they are scaled and normalized
    with ``norm_mean``/``norm_std`` inside the jitted step, where XLA
    fuses the arithmetic into the first convolution.
    """

    model: Any
    tx: optax.GradientTransformation | None = None
    learning_rate: float = 1e-5
    image_key: str = "image"
    label_key: str = "label"
    # Device-side normalization constants for uint8 input — the SAME
    # arrays the host-side float path uses, so the two dtypes can never
    # normalize differently.
    norm_mean: Any = None
    norm_std: Any = None
    # On-device train-time augmentation (RandomResizedCrop + flip inside
    # the jitted step, keyed by state.step — see data/augment.py). None
    # disables; eval/predict are never augmented.
    augment: Any = None
    # Extra top-k accuracies for eval (e.g. (5,) adds val_top5_acc —
    # the standard ImageNet companion metric). Empty keeps epoch
    # summaries unchanged.
    eval_topk: tuple = ()

    @property
    def _norm_constants(self):
        from ..data.transform import IMAGENET_MEAN, IMAGENET_STD

        mean = IMAGENET_MEAN if self.norm_mean is None else self.norm_mean
        std = IMAGENET_STD if self.norm_std is None else self.norm_std
        return mean, std

    # Best-checkpoint selection when TrainerConfig doesn't specify one.
    default_best_metric = "val_acc"
    default_best_mode = "max"

    def __post_init__(self):
        if self.tx is None:
            self.tx = optax.adam(self.learning_rate)

    # -- state ------------------------------------------------------------

    def init_state(self, rng, sample_batch: Batch) -> TrainState:
        images = self._images(sample_batch)
        return self.state_from_variables(
            self.model.init(rng, images[:1], train=False)
        )

    def state_from_variables(self, variables: Mapping[str, Any]) -> TrainState:
        """TrainState from externally-supplied variables (pretrained
        weights — reference fine-tunes torchvision IMAGENET1K_V2,
        ``deep_learning/2...py:150``) with a fresh optimizer."""
        params = variables["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats", FrozenDict()),
            opt_state=self.tx.init(params),
        )

    def _images(self, batch: Batch):
        x = jnp.asarray(batch[self.image_key])
        if x.ndim == 4 and x.shape[1] in (1, 3) and x.shape[-1] not in (1, 3):
            x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW -> NHWC
        if x.dtype == jnp.uint8:
            mean, std = self._norm_constants
            x = (
                x.astype(jnp.float32) / 255.0 - jnp.asarray(mean, jnp.float32)
            ) / jnp.asarray(std, jnp.float32)
        return x

    # -- steps (pure; jitted by the Trainer) ------------------------------

    def train_step(self, state: TrainState, batch: Batch):
        images, labels = self._images(batch), jnp.asarray(batch[self.label_key])
        if self.augment is not None:
            from ..data.augment import augment_for_step

            images = augment_for_step(
                state.step, images, images.shape[1], self.augment
            )
        # Stat-free models (ViT: no BatchNorm anywhere) carry an empty
        # batch_stats collection; passing it to apply (or asking for it
        # back via mutable) would be a Flax error. Emptiness is static
        # pytree structure, so this branch resolves at trace time.
        has_stats = bool(state.batch_stats)

        def loss_fn(params):
            if has_stats:
                logits, updates = self.model.apply(
                    {"params": params, "batch_stats": state.batch_stats},
                    images,
                    train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = self.model.apply(
                    {"params": params}, images, train=True
                )
                new_stats = state.batch_stats
            loss = cross_entropy_loss(logits, labels)
            return loss, (logits, new_stats)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "train_loss": loss,
            "train_acc": multiclass_accuracy(logits, labels),
            # Global grad-norm: a standard training-curve diagnostic,
            # and one of the two fused health signals (with the loss)
            # the health supervisor's isfinite reduction watches.
            "grad_norm": optax.global_norm(grads),
        }
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                batch_stats=new_stats,
                opt_state=new_opt,
            ),
            metrics,
        )

    def eval_step(self, state: TrainState, batch: Batch):
        images, labels = self._images(batch), jnp.asarray(batch[self.label_key])
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = self.model.apply(variables, images, train=False)
        out = {
            "val_loss": cross_entropy_loss(logits, labels),
            "val_acc": multiclass_accuracy(logits, labels),
        }
        for k in self.eval_topk:
            out[f"val_top{k}_acc"] = topk_accuracy(logits, labels, k)
        return out


@dataclasses.dataclass
class LMTask:
    """Causal language-model task for the same Trainer loop.

    The classifier track is the reference's only trained model family;
    the LM task extends the trainer to the transformer stack (flash /
    ring attention) so sequence-parallel training rides the identical
    epoch/step/checkpoint machinery. Batches carry ``tokens`` [B, S]
    int32; loss is next-token cross entropy.
    """

    model: Any
    tx: optax.GradientTransformation | None = None
    learning_rate: float = 3e-4
    tokens_key: str = "tokens"
    # MoE models sow a load-balance loss under intermediates/aux_loss
    # (models/moe.py); a positive weight folds it into the objective.
    aux_loss_weight: float = 0.0

    def __post_init__(self):
        if self.tx is None:
            self.tx = optax.adam(self.learning_rate)

    # Best-checkpoint selection when TrainerConfig doesn't specify one:
    # language models track validation loss (lower is better).
    default_best_metric = "val_loss"
    default_best_mode = "min"

    def init_state(self, rng, sample_batch: Batch) -> TrainState:
        tokens = jnp.asarray(sample_batch[self.tokens_key])
        params = self.model.init(rng, tokens[:1])["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=FrozenDict(),
            opt_state=self.tx.init(params),
        )

    def train_step(self, state: TrainState, batch: Batch):
        from ..models.transformer import next_token_loss

        tokens = jnp.asarray(batch[self.tokens_key])

        def loss_fn(params):
            if self.aux_loss_weight > 0.0:
                from ..models.moe import collect_aux_loss

                logits, inter = self.model.apply(
                    {"params": params}, tokens, mutable=["intermediates"]
                )
                aux = collect_aux_loss(inter["intermediates"])
                return next_token_loss(logits, tokens) + self.aux_loss_weight * aux
            logits = self.model.apply({"params": params}, tokens)
            return next_token_loss(logits, tokens)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                batch_stats=state.batch_stats,
                opt_state=new_opt,
            ),
            {
                "train_loss": loss,
                "train_ppl": jnp.exp(loss),
                # Health signal (see ClassifierTask.train_step).
                "grad_norm": optax.global_norm(grads),
            },
        )

    def eval_step(self, state: TrainState, batch: Batch):
        from ..models.transformer import next_token_loss

        tokens = jnp.asarray(batch[self.tokens_key])
        logits = self.model.apply({"params": state.params}, tokens)
        loss = next_token_loss(logits, tokens)
        return {"val_loss": loss, "val_ppl": jnp.exp(loss)}


def health_state_shardings(replicated):
    """The replicated sharding tree for the health supervisor's EWMA
    carry — the ONE definition :func:`make_train_step`'s out_shardings,
    ``Trainer.fit``'s ``device_put``, and the audit registry all share,
    so the carry's placement can never diverge from the jitted
    program's contract."""
    return jax.tree_util.tree_map(
        lambda _: replicated, health.HealthState.create()
    )


def make_train_step(task, state_shardings, replicated, health_cfg=None):
    """The ONE train-step program constructor.

    ``Trainer.fit`` and ``dsst audit`` both compile exactly this jit —
    so what the auditor certifies (params+opt_state donation, dtype
    discipline, collective shapes, the program-baseline hash) is the
    program production runs, not a parallel reconstruction that could
    drift. Donating argnum 0 (the :class:`TrainState`) is the contract
    the audit's ``donation`` rule holds this function to.

    With ``health_cfg`` the SAME task step is wrapped by the health
    supervisor's commit-or-discard guard and the jitted program carries
    the (state, HealthState) pair as its donated carry.
    """
    if health_cfg is None:
        return jax.jit(task.train_step, donate_argnums=0,
                       out_shardings=(state_shardings, replicated))
    h_shardings = health_state_shardings(replicated)
    return jax.jit(
        health.guard_train_step(task.train_step, health_cfg),
        donate_argnums=0,
        out_shardings=((state_shardings, h_shardings), replicated),
    )


def make_eval_step(task, replicated):
    """The eval-step program constructor shared by ``Trainer.fit`` and
    ``dsst audit`` (eval donates nothing: the state must survive the
    call)."""
    return jax.jit(task.eval_step, out_shardings=replicated)


@dataclasses.dataclass
class TrainerConfig:
    max_epochs: int = 2                      # reference MAX_EPOCHS (2...py:343)
    steps_per_epoch: int | None = None       # else rows // (batch × world)
    total_train_rows: int | None = None
    limit_val_batches: int | None = 5        # reference :405
    log_every_steps: int = 10
    checkpoint_dir: str | None = None
    keep_checkpoints: int = 2
    # None = use the task's default_best_metric/default_best_mode
    # (val_acc/max for classifiers, val_loss/min for LMs).
    best_metric: str | None = None
    best_mode: str | None = None
    resume: bool = False
    # Crash-only restart entry point (dsst train/lm --resume-auto, the
    # watchdog's `runs doctor --resume`, and the future arbiter's
    # revive path): resume from the newest manifest-intact checkpoint
    # when one exists — falling back past torn steps, quarantining
    # wreckage, sweeping stranded tmp files — and start FRESH (instead
    # of erroring) when nothing restorable survives. Unlike `resume`,
    # it never needs the operator to know whether the previous process
    # got as far as a checkpoint.
    resume_auto: bool = False
    # Bound of the background feeder's on-device batch queue (HBM held:
    # feeder_depth batches beyond the in-flight step). ``prefetch_depth``
    # is the legacy name for the same knob; ``feeder_depth`` wins when
    # both are set.
    feeder_depth: int | None = None
    prefetch_depth: int = 2
    # jax.profiler trace capture (SURVEY.md §5.1): when profile_dir is
    # set, a trace covering steps [profile_start_step,
    # profile_start_step + profile_num_steps) is written there.
    profile_dir: str | None = None
    profile_start_step: int = 5
    profile_num_steps: int = 5
    # ZeRO-1-style optimizer-state sharding over the mesh axis: each
    # leaf of opt_state is split along its largest divisible dimension
    # instead of replicated, cutting optimizer memory by ~world size.
    # Pure GSPMD — the same train_step, with XLA inserting the
    # gather/scatter around the update. The reference has no analogue
    # (DDP replicates optimizer state on every rank).
    shard_opt_state: bool = False
    shard_axis: str = "data"
    # Per-key PartitionSpec overrides for batch placement (default: shard
    # the leading dim over "data"). Sequence-parallel LM training passes
    # {"tokens": P(None, "sp")} so batches shard the sequence dimension
    # and ring attention sees its expected layout.
    batch_specs: Mapping[str, Any] | None = None
    # Training-health supervision (resilience.health.HealthConfig), or
    # None (default) for the unsupervised loop — identical hot path to
    # before, no per-step verdict fetch. With a config, every train step
    # carries fused isfinite(loss/grad-norm) + EWMA loss-z-score signals
    # on device, bad updates are discarded before commit, and the
    # skip -> rollback -> abort policy ladder handles streaks.
    health: Any = None


@dataclasses.dataclass
class FitResult:
    state: TrainState
    best_checkpoint_step: int | None
    best_metric_value: float | None
    history: list[dict]
    best_checkpoint_path: str | None = None
    # True when fit stopped early on SIGTERM (spot/TPU-VM eviction): the
    # in-flight step finished and a resumable checkpoint was saved;
    # fit(resume=True) continues from exactly that step.
    preempted: bool = False
    # Health-supervisor accounting (0 when TrainerConfig.health is None):
    # updates discarded for non-finite signals / loss spikes, and
    # checkpoint rollbacks performed.
    skipped_steps: int = 0
    health_rollbacks: int = 0
    # True only when resume_auto actually RESTORED a checkpoint — False
    # when it found nothing, or found only wreckage and fell back to a
    # fresh start (an operator reading "auto_resumed" must be able to
    # trust that prior work continued).
    auto_resumed: bool = False


# dsst: ignore[lock-discipline] no lock-guarded state: the manifest-finalizer thread shares no mutable attribute with fit — _manifest_thread is written and joined only on the fit thread, and the finalizer body touches files + the RunStore journal (which declares its own contract)
class Trainer:
    """Explicit epoch/step loop, one compiled train step, mesh-sharded."""

    def __init__(self, config: TrainerConfig, mesh: Mesh | None = None,
                 tracker=None):
        self.config = config
        self.mesh = mesh if mesh is not None else make_mesh()
        self.tracker = tracker
        self.topology = local_topology()

    # -- accounting -------------------------------------------------------

    @staticmethod
    def _feeder_depth(cfg: TrainerConfig) -> int:
        return (
            cfg.feeder_depth
            if cfg.feeder_depth is not None
            else cfg.prefetch_depth
        )

    def _steps_per_epoch(self, per_process_batch: int) -> int:
        cfg = self.config
        if cfg.steps_per_epoch is not None:
            return cfg.steps_per_epoch
        if cfg.total_train_rows is None:
            raise ValueError(
                "TrainerConfig needs steps_per_epoch or total_train_rows "
                "(row counts come from DeltaTable.num_records())"
            )
        global_batch = per_process_batch * self.topology.process_count
        steps = cfg.total_train_rows // global_batch
        if steps == 0:
            raise ValueError(
                f"total_train_rows={cfg.total_train_rows} < global batch "
                f"{global_batch}; no full step per epoch"
            )
        return steps

    # -- fit --------------------------------------------------------------

    def fit(
        self,
        task,
        train_data: Iterable[Batch],
        val_data_factory: Callable[[], Iterable[Batch]] | None = None,
        *,
        rng: jax.Array | None = None,
        state: TrainState | None = None,
        epoch_callback: Callable[[dict], None] | None = None,
    ) -> FitResult:
        """``epoch_callback`` (if given) receives a copy of each epoch's
        summary dict right after it is appended to the history — the
        Lightning-callback seam (reference trains under
        ``pl.Trainer(...callbacks=...)``,
        ``deep_learning/2...py:190-208``) for progress artifacts,
        early-stop bookkeeping, or external monitors. Exceptions
        propagate: a broken callback should fail the run loudly."""
        # Resolve task-default best metric into a LOCAL cfg only — the same
        # Trainer may fit different task types, so self.config must keep
        # its None sentinels.
        cfg = self.config
        if cfg.best_metric is None or cfg.best_mode is None:
            cfg = dataclasses.replace(
                cfg,
                best_metric=cfg.best_metric
                or getattr(task, "default_best_metric", "val_acc"),
                best_mode=cfg.best_mode
                or getattr(task, "default_best_mode", "max"),
            )
        mesh = self.mesh
        rng = rng if rng is not None else jax.random.key(0)

        train_iter = iter(train_data)
        raw_first = next(train_iter)
        # Provenance is stripped by the feeder; this peek only sizes and
        # initializes, so the side channel is popped locally too.
        first, _ = split_provenance(raw_first)
        # Examples per batch: the leading dim by default; tasks whose
        # batches aren't [batch, ...] (PipelinedTask: [n_micro, mb, ...])
        # declare a ``batch_size_of`` hook so steps/epoch and throughput
        # accounting stay correct.
        size_hook = getattr(task, "batch_size_of", None)
        per_process_batch = (
            size_hook(first) if size_hook is not None
            else len(next(iter(first.values())))
        )
        steps_per_epoch = self._steps_per_epoch(per_process_batch)

        replicated = NamedSharding(mesh, P())
        if state is None:
            state = task.init_state(rng, first)
        # Tasks whose parameters are NOT replicated (pipeline stages live
        # on their own devices; a fully tensor-sharded model would too)
        # declare their layout via a ``state_shardings(state, mesh)``
        # hook; everything else defaults to replicated params.
        shardings_hook = getattr(task, "state_shardings", None)
        if shardings_hook is not None:
            if cfg.shard_opt_state:
                # ZeRO-1 would overwrite the task's own optimizer layout
                # (e.g. stage-sharded Adam moments) — conflicting intents.
                raise ValueError(
                    "shard_opt_state=True conflicts with a task that "
                    "declares its own state_shardings; the task's layout "
                    "already places the optimizer state"
                )
            state_shardings = shardings_hook(state, mesh)
        else:
            state_shardings = jax.tree_util.tree_map(
                lambda _: replicated, state
            )
            if cfg.shard_opt_state:
                state_shardings = state_shardings.replace(
                    opt_state=_zero1_shardings(
                        state.opt_state, mesh, cfg.shard_axis
                    )
                )
        state = jax.device_put(state, state_shardings)

        supervisor = (
            health.HealthSupervisor(cfg.health)
            if cfg.health is not None else None
        )
        hstate = None
        if supervisor is None:
            train_step = make_train_step(task, state_shardings, replicated)
        else:
            # Health-supervised step: the SAME task train_step with the
            # on-device isfinite/z-score signals and the commit-or-
            # discard select fused into the one jitted program. The tiny
            # EWMA HealthState rides the carry, replicated.
            train_step = make_train_step(
                task, state_shardings, replicated, health_cfg=cfg.health
            )
            h_shardings = health_state_shardings(replicated)
            hstate = jax.device_put(health.HealthState.create(), h_shardings)
        eval_step = make_eval_step(task, replicated)

        # Track-best only matters when something produces the metric.
        # Pass the RESOLVED cfg — self.config keeps None sentinels.
        manager = self._checkpoint_manager(
            cfg, use_best=val_data_factory is not None
        )
        if manager is not None:
            # Journal the checkpoint dir BEFORE any training: a run
            # killed during startup or inside its very first save window
            # must still be revivable by `runs doctor --resume` (the
            # committed-step events alone land only after a manifest).
            self._journal(
                "config",
                checkpoint_dir=str(Path(cfg.checkpoint_dir).absolute()),
            )
        start_epoch = 0
        auto_resumed = False
        resume_requested = cfg.resume or cfg.resume_auto
        if manager is not None and resume_requested and (
            self.topology.process_index == 0
        ):
            # Crash-only hygiene: a hard-killed predecessor may have
            # stranded durable-write tmps (torn manifest staging) or a
            # half-written orbax tmp step dir; recovery owns the sweep.
            # Process 0 only — N processes sweeping one shared
            # checkpoint FS would race each other (the sweeper's
            # single-sweeper contract), same discipline as manifest
            # writes and step quarantine.
            swept = durability.sweep_stranded_tmp(cfg.checkpoint_dir)
            if swept:
                log.warning(
                    "resume: removed %d stranded tmp artifact(s) under %s",
                    len(swept), cfg.checkpoint_dir,
                )
        if manager is not None and resume_requested and (
            manager.latest_step() is not None
        ):
            try:
                state = self._restore(manager, state)
            except FileNotFoundError:
                if not cfg.resume_auto:
                    raise
                # Nothing restorable survived the crash (every step torn
                # or pre-manifest damage). Crash-only semantics: rename
                # the wreckage aside and converge to a fresh start —
                # the same outcome as if no checkpoint had ever landed.
                log.warning(
                    "--resume-auto: no intact checkpoint under %s; "
                    "quarantining remains and starting fresh",
                    cfg.checkpoint_dir,
                )
                manager = self._drop_stale_steps(
                    manager, cfg, -1,
                    use_best=val_data_factory is not None,
                )
            else:
                manager = self._drop_stale_steps(
                    manager, cfg, int(state.step),
                    use_best=val_data_factory is not None,
                )
                # A preemption checkpoint lands mid-epoch: the resumed
                # first epoch runs only the REMAINING steps (the
                # step-driven inner loop below), so the final step count
                # matches an uninterrupted run exactly.
                start_epoch = int(state.step) // steps_per_epoch
                if cfg.resume_auto:
                    auto_resumed = True
                    telemetry.counter(
                        "auto_resume_total",
                        "fits that auto-resumed from a journaled "
                        "checkpoint without an operator-named step",
                    ).inc()
                self._journal("resume", step=int(state.step))
                self._repair_manifest(cfg, int(state.step))

        history: list[dict] = []
        best_value, best_step = self._prior_best(manager, cfg)
        sign = 1.0 if cfg.best_mode == "max" else -1.0
        step = int(state.step)  # host-side mirror, synced once before the loop
        data_exhausted = False
        # Telemetry series (process registry): step time, data wait,
        # throughput, compile events. Handles hoisted out of the loop
        # and the two step-rate histograms SAMPLED (1-in-N observes;
        # exact totals ride the feeder's counters) — the per-step cost
        # is one queue.get, one clock read, and a cache probe; no device
        # sync on the hot path.
        step_hist = telemetry.histogram(
            "train_step_seconds", "wall time between dispatched train steps"
        )
        wait_hist = telemetry.histogram(
            "train_data_wait_seconds",
            "per-step time blocked on the input pipeline",
        )
        throughput_gauge = telemetry.gauge(
            "train_throughput_rows_per_sec",
            "last epoch's global training throughput",
        )
        compiles = telemetry.CompileTracker(
            train_step,
            telemetry.counter(
                "train_compile_events_total",
                "train_step executable compiles (first step + retraces)",
            ),
        )
        # Step times feed three sinks: the sampled cumulative histogram
        # (cheap long-run distribution), the sliding-window sketch
        # (live p95 on /metrics), and the SLO engine's step-time
        # objective. The window/SLO observes are full-rate on purpose —
        # a windowed p95 sampled 1-in-8 would lag exactly the
        # regressions it exists to catch — and each costs one bisect.
        _sampled_step = telemetry.SampledObserver(
            step_hist, _HIST_SAMPLE_EVERY
        ).observe
        _step_window = telemetry.window(
            "train_step_window_seconds",
            "windowed wall time between dispatched train steps",
        )
        _slo_note_step = telemetry.slo.get_engine().note_train_step

        def _observe_step(dt: float) -> None:
            _sampled_step(dt)
            _step_window.observe(dt)
            _slo_note_step(dt)

        step_timer = StepTimer(observer=_observe_step)
        tracing = False
        preempted = False
        guard = PreemptionGuard()

        # The background feeder: pulls reader batches, strips row
        # provenance (it rides the queue WITH its device batch, so the
        # supervised loop's row accounting keeps exact parity), stages +
        # shards them through the cached placer, and overlaps all of it
        # with step dispatch. Closed in the ``finally`` on EVERY exit —
        # exhaustion, health abort, preemption — so no feeder thread
        # outlives fit.
        feeder = MeshFeeder(
            itertools.chain([raw_first], train_iter),
            mesh,
            depth=self._feeder_depth(cfg),
            specs=cfg.batch_specs,
            name="train",
            wait_observer=telemetry.SampledObserver(
                wait_hist, _HIST_SAMPLE_EVERY
            ).observe,
        )

        # The run's root span: a "fit" begin event hits the flight
        # recorder before the first step, so ANY kill from here on
        # leaves at least one open span naming the run that died.
        # ExitStack (not a with-block) keeps the 200-line loop body at
        # its current indentation; closed FIRST in the finally so the
        # span closes even on a health abort.
        trace_scope = contextlib.ExitStack()
        trace_scope.enter_context(tracecontext.trace(kind="run"))
        trace_scope.enter_context(
            telemetry.span("fit", max_epochs=cfg.max_epochs)
        )
        step_handoff = tracecontext.Handoff(None)
        try:
            with guard:
                for epoch in range(start_epoch, cfg.max_epochs):
                    if data_exhausted:
                        log.warning(
                            "train data exhausted at step %d; stopping before "
                            "epoch %d of %d", step, epoch, cfg.max_epochs,
                        )
                        break
                    t0_wall = time.time()
                    t0 = time.perf_counter()
                    metrics = {}
                    epoch_steps = 0
                    # Step-driven (not iteration-driven) epoch boundary: the
                    # epoch ends when `step` COMMITTED steps exist, so a
                    # health-discarded update pulls a make-up batch instead
                    # of silently shrinking the epoch (this is what makes a
                    # poisoned run's update sequence identical to a clean run
                    # whose reader excluded the poison rows), and a rollback
                    # simply re-runs the restored span. Mid-epoch resume
                    # falls out of the same arithmetic.
                    epoch_end_step = (epoch + 1) * steps_per_epoch
                    # dsst: hotpath — per-step cost budget is one queue.get (host-sync lint enforces it)
                    while step < epoch_end_step:
                        # One queue.get: the feeder already staged,
                        # sharded, and enqueued the batch (and accounted
                        # the wait into train_data_wait_seconds /
                        # feeder_stall_seconds_total).
                        try:
                            batch, prov = next(feeder)
                        except StopIteration:
                            data_exhausted = True
                            break
                        if cfg.profile_dir is not None and not tracing and (
                            step >= cfg.profile_start_step
                        ):
                            jax.profiler.start_trace(cfg.profile_dir)
                            tracing = True
                            trace_stop_at = step + cfg.profile_num_steps
                        # The step runs under the batch's OWN trace (born
                        # on the feeder thread): reader pull, staging,
                        # and this dispatch share one step_id, and the
                        # begin event makes a kill mid-step leave an
                        # open train_step span in the flight recorder.
                        step_handoff = feeder.last_handoff
                        with step_handoff.activate(), telemetry.span(
                            "train_step", step=step
                        ):
                            if supervisor is None:
                                state, metrics = train_step(state, batch)
                                action = "commit"
                            else:
                                inject = supervisor.next_injection()
                                (state, hstate), step_metrics = train_step(
                                    (state, hstate), batch, inject
                                )
                                # One scalar fetch: the verdict (and on a
                                # bad step, the loss/z diagnostics). This
                                # is the supervised loop's per-step
                                # metrics fetch; the discard already
                                # happened on device.
                                action = supervisor.observe(
                                    step + 1, step_metrics, prov
                                )
                                if action == "commit":
                                    metrics = step_metrics
                        if action == "commit":
                            epoch_steps += 1
                            step += 1  # host-side mirror: no device sync
                            step_timer.tick()
                            compiles.update()
                            if tracing and step >= trace_stop_at:
                                # dsst: ignore[host-sync] profiler stop: one deliberate sync when the trace window closes
                                jax.block_until_ready(state.params)
                                jax.profiler.stop_trace()
                                tracing = False
                                cfg = dataclasses.replace(cfg, profile_dir=None)
                            if step % cfg.log_every_steps == 0:
                                self._log(
                                    # dsst: ignore[host-sync] deliberate scalar fetch, throttled to log_every_steps
                                    {k: float(v) for k, v in metrics.items()},
                                    step,
                                )
                        elif action == "skip":
                            # Update discarded on device; step not committed.
                            # The executable still ran — keep compile
                            # accounting honest.
                            compiles.update()
                        elif action == "rollback":
                            state, hstate, manager, step = self._health_rollback(
                                manager, cfg, state, h_shardings, supervisor,
                                step + 1, use_best=val_data_factory is not None,
                            )
                            if best_step is not None and best_step > step:
                                # The best step may have been rolled over
                                # (quarantined aside as <step>.corrupt, or
                                # itself the corruption that forced the
                                # fallback) — re-derive from the steps the
                                # rebuilt manager still holds, or
                                # best_checkpoint_path would point at a
                                # ghost.
                                best_value, best_step = (
                                    self._best_from_manager(manager, cfg)
                                )
                        else:  # abort
                            raise supervisor.abort(
                                step + 1,
                                f"{supervisor.bad_streak} consecutive unhealthy "
                                f"steps under policy {cfg.health.policy!r} "
                                f"({supervisor.rollbacks}/"
                                f"{cfg.health.max_rollbacks} rollbacks used)",
                                cfg.checkpoint_dir,
                            )
                        if guard.triggered:
                            break
                    if guard.triggered:
                        # Preemption (SIGTERM): the in-flight step finished
                        # above; save a resumable checkpoint NOW — mid-epoch —
                        # and hand back a result marked preempted so the
                        # caller's --resume continues from this exact step.
                        preempted = True
                        telemetry.counter(
                            "preemption_signals_total",
                            "preemption signals honored by Trainer.fit",
                        ).inc()
                        jax.block_until_ready(state.params)
                        latest = (
                            manager.latest_step() if manager is not None else None
                        )
                        if manager is not None and step > (
                            latest if latest is not None else -1
                        ):
                            # use_best=False deliberately: a metrics-carrying
                            # save would rank -inf under best_fn retention and
                            # orbax would prune the preemption step IMMEDIATELY
                            # (verified against the installed version); a
                            # metrics-less save is exempt from best-ranking
                            # retention, so the preserved work survives until
                            # --resume. synchronous: the eviction grace window
                            # is the one place the trainer must not return
                            # before the write (and its manifest) commit.
                            self._save(
                                manager, cfg, state, step,
                                metric_val=None,
                                use_best=False,
                                synchronous=True,
                                trace=step_handoff,
                            )
                        log.warning(
                            "preempted at step %d (epoch %d); resumable "
                            "checkpoint %s", step, epoch,
                            "saved" if manager is not None else
                            "NOT saved (no checkpoint_dir)",
                        )
                        break
                    if epoch_steps == 0:
                        break
                    jax.block_until_ready(state.params)
                    dt = time.perf_counter() - t0
                    # dsst: ignore[span-discipline] args (step count) are only known at close; a raw record keeps the exact legacy start/duration semantics
                    telemetry.get_span_log().record(
                        "train_epoch", t0_wall, dt, epoch=epoch, steps=epoch_steps
                    )
                    images_per_sec = (
                        epoch_steps
                        * per_process_batch
                        * self.topology.process_count
                        / dt
                    )
                    throughput_gauge.set(images_per_sec)
                    epoch_summary = {
                        "epoch": epoch,
                        "epoch_time_s": dt,
                        "images_per_sec": images_per_sec,
                        **step_timer.summary(),
                        **{k: float(v) for k, v in metrics.items()},
                    }
                    step_timer.reset()

                    if val_data_factory is not None:
                        with telemetry.span("eval", epoch=epoch):
                            epoch_summary.update(
                                self._evaluate(eval_step, state, val_data_factory)
                            )

                    history.append(epoch_summary)
                    self._log(
                        {k: v for k, v in epoch_summary.items() if k != "epoch"},
                        step,
                    )
                    if epoch_callback is not None:
                        epoch_callback(dict(epoch_summary))

                    metric_val = epoch_summary.get(cfg.best_metric)
                    is_best = metric_val is not None and (
                        best_value is None or sign * metric_val > sign * best_value
                    )
                    if is_best:
                        best_value, best_step = metric_val, step
                    if manager is not None:
                        self._save(
                            manager, cfg, state, step,
                            metric_val=metric_val,
                            use_best=val_data_factory is not None,
                            trace=step_handoff,
                        )
        finally:
            # Teardown runs on EVERY exit, including a health abort
            # (TrainingHealthError is an expected, caught-by-the-CLI
            # exception): the feeder thread must be stopped and joined
            # (a daemon thread must not outlive fit, and a producer
            # blocked on a full queue must be unblocked), a live
            # profiler trace must be closed, and the in-flight async
            # save + manifest finalizer joined, or the process continues
            # with a truncated trace and a checkpoint whose manifest
            # never lands.
            trace_scope.close()
            feeder.close()
            if tracing:
                jax.block_until_ready(state.params)
                jax.profiler.stop_trace()
            if manager is not None:
                # Join the last step's manifest finalizer FIRST — it is
                # itself inside manager.wait_until_finished(), which must
                # not run concurrently with ours. It must land before
                # callers read (or verify) the checkpoint dir.
                self._join_manifest_writer()
                manager.wait_until_finished()
        return FitResult(
            state=state,
            best_checkpoint_step=best_step,
            best_metric_value=best_value,
            history=history,
            best_checkpoint_path=(
                str(Path(cfg.checkpoint_dir) / str(best_step))
                if manager is not None and best_step is not None
                else None
            ),
            preempted=preempted,
            skipped_steps=(
                supervisor.skipped_steps if supervisor is not None else 0
            ),
            health_rollbacks=(
                supervisor.rollbacks if supervisor is not None else 0
            ),
            auto_resumed=auto_resumed,
        )

    # -- eval -------------------------------------------------------------

    def _evaluate(self, eval_step, state, val_data_factory) -> dict:
        cfg = self.config
        totals: dict[str, float] = {}
        count = 0
        val_data = val_data_factory()
        feeder = None
        try:
            # Limit BEFORE the feeder so no extra batches are decoded and
            # shipped to HBM just to be discarded.
            source = iter(val_data)
            if cfg.limit_val_batches is not None:
                source = itertools.islice(source, cfg.limit_val_batches)
            feeder = MeshFeeder(
                source, self.mesh, depth=self._feeder_depth(cfg),
                specs=cfg.batch_specs, name="eval",
            )
            for batch, _prov in feeder:
                m = eval_step(state, batch)
                for k, v in m.items():
                    totals[k] = totals.get(k, 0.0) + float(v)
                count += 1
        finally:
            # Join the feeder thread, then stop streaming readers
            # eagerly — limit_val_batches may leave the source
            # mid-stream with worker threads still decoding.
            if feeder is not None:
                feeder.close()
            stop = getattr(val_data, "stop", None)
            if callable(stop):
                stop()
        return {k: v / max(count, 1) for k, v in totals.items()}

    # -- checkpointing ----------------------------------------------------

    def _checkpoint_manager(self, cfg: TrainerConfig, use_best: bool):
        # cfg must be the fit()-resolved config: self.config may still hold
        # the best_metric/best_mode None sentinels, which orbax rejects.
        if cfg.checkpoint_dir is None:
            return None
        ocp = _ocp()
        options = ocp.CheckpointManagerOptions(
            max_to_keep=cfg.keep_checkpoints,
            # best_fn only when metrics will actually be saved: with best_fn
            # configured and metrics=None, orbax keeps every step (verified
            # against the installed version) and retention silently breaks.
            best_fn=(lambda m: m[cfg.best_metric]) if use_best else None,
            best_mode=cfg.best_mode,
        )
        return ocp.CheckpointManager(Path(cfg.checkpoint_dir).absolute(), options=options)

    def _prior_best(
        self, manager, cfg: TrainerConfig
    ) -> tuple[float | None, int | None]:
        """Recover best-so-far from a resumed manager so a worse post-resume
        epoch can't claim best_checkpoint_path.

        The best step may no longer exist on disk (retention pruned it, an
        operator cleaned it, or its files went corrupt); recover from the
        metrics of the steps that DO remain rather than erroring or
        pointing best_checkpoint_path at a ghost.
        """
        if manager is None or not cfg.resume:
            return None, None
        return self._best_from_manager(manager, cfg)

    def _best_from_manager(
        self, manager, cfg: TrainerConfig
    ) -> tuple[float | None, int | None]:
        """Best (value, step) among the steps the manager still holds."""
        sign = 1.0 if cfg.best_mode == "max" else -1.0
        try:
            steps = set(manager.all_steps())
            best_step = manager.best_step()
            if best_step is not None and best_step in steps:
                all_metrics = manager.metrics(best_step)
                return (all_metrics or {}).get(cfg.best_metric), best_step
            candidates = []
            for s in steps:
                try:
                    m = (manager.metrics(s) or {}).get(cfg.best_metric)
                except Exception:
                    continue  # unreadable per-step metrics: skip that step
                if m is not None and math.isfinite(m):
                    candidates.append((sign * m, s))
            if not candidates:
                return None, None
            _, s = max(candidates)
            return (manager.metrics(s) or {}).get(cfg.best_metric), s
        except Exception:
            return None, None

    def _save(self, manager, cfg: TrainerConfig, state: TrainState,
              step: int, *, metric_val, use_best: bool,
              synchronous: bool = False,
              trace: tracecontext.Handoff | None = None) -> None:
        """One checkpoint step + its integrity manifest.

        The manifest must checksum the COMMITTED files, which means
        waiting out orbax's async write before hashing — but neither
        belongs on the training thread (that would forfeit the
        async-save/next-epoch overlap). The wait + hash run on a
        background finalizer thread; the next save joins the previous
        finalizer (long done by then), and ``fit`` joins the last one
        before returning. ``synchronous=True`` (preemption) does it all
        inline — the process is about to exit.
        """
        if use_best:
            # With best-tracking on, every epoch save needs the metric or
            # orbax retention stops pruning; a missing value ranks worst
            # so it never wins "best". (Preemption saves pass
            # use_best=False instead: a -inf-ranked step would be pruned
            # at save time, losing the preserved work.)
            sign = 1.0 if cfg.best_mode == "max" else -1.0
            save_metrics = {
                cfg.best_metric: metric_val
                if metric_val is not None
                else sign * float("-inf")
            }
        else:
            save_metrics = None
        # Join the previous step's finalizer BEFORE driving the manager
        # again: its wait_until_finished() must not run concurrently with
        # this save (orbax's async internals aren't documented
        # thread-safe). By now it is long done — an epoch has passed.
        self._join_manifest_writer()
        # The save runs under the committing step's trace (the feeder's
        # step_id): checkpoint dispatch, the async finalizer below, and
        # the train step that produced the weights share one timeline.
        handoff = trace if trace is not None else tracecontext.Handoff(None)
        with handoff.activate(), telemetry.span("checkpoint", step=step):
            maybe_fail("checkpoint.save")
            manager.save(
                step,
                args=_ocp().args.StandardSave(_to_pytree(state)),
                metrics=save_metrics,
            )

        def finalize() -> None:
            # The finalizer thread adopts the step's handoff: its begin
            # event means a SIGKILL inside the save window leaves an
            # open checkpoint.finalize span naming the torn step.
            with handoff.activate(), telemetry.span(
                "checkpoint.finalize", step=step
            ):
                try:
                    manager.wait_until_finished()
                    # Process 0 only — the manifest is one file per
                    # step, not per host.
                    if self.topology.process_index == 0:
                        step_dir = Path(str(manager.directory)) / str(step)
                        if step_dir.is_dir():
                            integrity.write_manifest(step_dir)
                            # Manifest landed => the step is durably
                            # committed: record it in the run journal so
                            # a fresh process (doctor, --resume-auto,
                            # the arbiter) knows the last committed step
                            # without walking the checkpoint dir.
                            self._journal(
                                "checkpoint", step=step,
                                checkpoint_dir=str(manager.directory),
                            )
                except Exception:
                    # A failed manifest leaves the step "unverified"
                    # (still restorable), never a crashed training run.
                    log.exception(
                        "manifest write failed for step %d", step
                    )

        if synchronous:
            finalize()
        else:
            self._manifest_thread = threading.Thread(
                target=finalize, daemon=True, name=f"ckpt-manifest-{step}"
            )
            self._manifest_thread.start()

    def _join_manifest_writer(self) -> None:
        thread = getattr(self, "_manifest_thread", None)
        if thread is not None:
            thread.join()
            self._manifest_thread = None

    def _restore(self, manager, state: TrainState) -> TrainState:
        restored, _ = _restore_with_fallback(manager, _to_pytree(state))
        return TrainState(**restored)

    def _repair_manifest(self, cfg: TrainerConfig, step: int) -> None:
        """Recovery repairs proof: a restored step with no manifest (its
        writer was killed inside the save window) just demonstrated its
        bytes load — hash them NOW so the step verifies "intact" from
        here on instead of staying "unverified" forever. Journaled as
        ``manifest_repair`` (not ``checkpoint``: nothing new was
        committed)."""
        if self.topology.process_index != 0:
            return
        step_dir = Path(cfg.checkpoint_dir) / str(step)
        if not step_dir.is_dir() or (
            step_dir / integrity.MANIFEST_NAME
        ).exists():
            return
        try:
            integrity.write_manifest(step_dir)
        except Exception:
            log.exception("manifest repair failed for step %d", step)
            return
        self._journal(
            "manifest_repair", step=step,
            checkpoint_dir=str(Path(cfg.checkpoint_dir).absolute()),
        )

    def _drop_stale_steps(self, manager, cfg: TrainerConfig,
                          restored_step: int, *, use_best: bool):
        """Quarantine checkpoint steps newer than ``restored_step``.

        After a fallback restore (corrupt latest on resume, or a health
        rollback) the run will re-reach those step numbers, and
        ``manager.save`` would crash on "step already exists" (and the
        preemption-save gate would compare against a corrupt latest).
        Rename them aside (``<step>.corrupt``) and rebuild the manager so
        its step cache forgets them. Returns the (possibly rebuilt)
        manager. (Process 0 renames, same discipline as manifest writes;
        single-host in CI.)
        """
        stale = [s for s in manager.all_steps() if s > restored_step]
        if not stale:
            return manager
        if self.topology.process_index == 0:
            for s in stale:
                integrity.quarantine_step(Path(cfg.checkpoint_dir) / str(s))
        # Multi-host: no collective barrier here — instead every process
        # waits (bounded) until process 0's renames are VISIBLE on the
        # shared checkpoint FS before rebuilding its manager, so no
        # rebuilt manager can still list a stale step. Single-host: the
        # renames already happened synchronously above and the loop
        # exits immediately.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and any(
            (Path(cfg.checkpoint_dir) / str(s)).exists() for s in stale
        ):
            time.sleep(0.2)
        leftover = [
            s for s in stale
            if (Path(cfg.checkpoint_dir) / str(s)).exists()
        ]
        if leftover:
            log.warning(
                "stale checkpoint steps still visible after quarantine "
                "wait: %s — a later save of those step numbers may fail",
                leftover,
            )
        return self._checkpoint_manager(cfg, use_best=use_best)

    def _health_rollback(self, manager, cfg: TrainerConfig,
                         state: TrainState, h_shardings,
                         supervisor, at_step: int, *, use_best: bool):
        """Policy-ladder rollback: restore the newest manifest-intact
        checkpoint, reset the spike detector, free the rolled-over step
        numbers. Returns ``(state, hstate, manager, step)``; escalates to
        the supervisor's abort when no checkpoint can be restored."""
        if manager is None:
            raise supervisor.abort(
                at_step,
                "rollback requested but no checkpoint_dir is configured",
                None,
            )
        t0_wall = time.time()
        t0 = time.perf_counter()
        # The in-flight manifest finalizer owns manager.wait_until_
        # finished(); join it before driving the manager again.
        self._join_manifest_writer()
        manager.wait_until_finished()
        try:
            restored, rstep = _restore_with_fallback(
                manager, _to_pytree(state)
            )
        except FileNotFoundError as e:
            raise supervisor.abort(
                at_step,
                f"rollback found no intact checkpoint: {e}",
                cfg.checkpoint_dir,
            ) from e
        state = TrainState(**restored)
        # Fresh detector: the restored trajectory's loss level may differ
        # from the EWMA the poisoned span accumulated.
        hstate = jax.device_put(health.HealthState.create(), h_shardings)
        manager = self._drop_stale_steps(
            manager, cfg, rstep, use_best=use_best
        )
        supervisor.record_rollback(
            at_step, rstep, t0_wall, time.perf_counter() - t0
        )
        return state, hstate, manager, rstep

    def _log(self, metrics: dict, step: int) -> None:
        if self.tracker is not None:
            self.tracker.log_metrics(metrics, step)

    def _journal(self, event: str, **fields) -> None:
        """Append to the tracker's run journal, if the tracker keeps one
        (RunStore does; foreign trackers may not — duck-typed so the
        Trainer stays tracker-agnostic)."""
        if event == "checkpoint":
            hook = getattr(self.tracker, "journal_checkpoint", None)
            if hook is not None:
                hook(fields["step"], fields["checkpoint_dir"])
            return
        hook = getattr(self.tracker, "journal_event", None)
        if hook is not None:
            hook(event, **fields)


def _zero1_shardings(opt_state, mesh: Mesh, axis: str):
    """ZeRO-1 sharding tree for an optimizer state.

    Each array leaf is split along its largest dimension divisible by the
    mesh axis size (Adam moments mirror param shapes, so conv kernels
    split along their channel dims); indivisible leaves (scalars, odd
    shapes) stay replicated. Because the update is elementwise per leaf,
    GSPMD keeps the math identical — only the layout (and the memory)
    changes.
    """
    if axis not in mesh.shape:
        raise ValueError(
            f"shard_opt_state: shard_axis {axis!r} is not an axis of the "
            f"mesh {dict(mesh.shape)}; set TrainerConfig.shard_axis to one "
            f"of {list(mesh.shape)}"
        )
    n = mesh.shape[axis]

    def leaf(l):
        shape = getattr(l, "shape", ())
        best = None  # (size, dim)
        for dim, size in enumerate(shape):
            if size % n == 0 and size > 0 and (best is None or size > best[0]):
                best = (size, dim)
        if best is None:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[best[1]] = axis
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map(leaf, opt_state)


def _restore_with_fallback(manager, template, *, steps=None):
    """Restore the newest usable step, walking past corrupt ones.

    ``steps`` (default: all steps, newest first) is the preference
    order. Each candidate is verified against its integrity manifest
    first; corrupt steps — and steps whose restore raises anyway (damage
    a manifest can't see, or a pre-manifest step gone bad) — are skipped
    with a ``checkpoint_fallback_total`` count and a warning, exactly
    the behavior that turns "latest checkpoint truncated by the
    preemption" from a crashed run into a one-step rollback. Returns
    ``(restored_pytree, step)``.
    """
    ocp = _ocp()
    directory = Path(str(manager.directory))
    if steps is None:
        steps = sorted(manager.all_steps(), reverse=True)
    last_exc = None
    for step in steps:
        status, problems = integrity.verify_step(directory / str(step))
        if status == "corrupt":
            integrity.record_fallback(step, "; ".join(problems))
            continue
        try:
            maybe_fail("checkpoint.restore")
            restored = manager.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        except Exception as e:
            integrity.record_fallback(
                step, f"restore raised {type(e).__name__}: {e}"
            )
            last_exc = e
            continue
        return restored, int(step)
    raise FileNotFoundError(
        f"no intact checkpoint step under {directory} "
        f"(candidates: {list(steps)})"
    ) from last_exc


def restore_state(
    task,
    sample_batch: Batch,
    checkpoint_dir: str,
    *,
    step: int | None = None,
    prefer: str = "best",
    best_metric: str | None = None,
    best_mode: str | None = None,
    rng: jax.Array | None = None,
) -> tuple[TrainState, int]:
    """Restore a Trainer checkpoint outside the Trainer (inference/export).

    ``prefer="best"`` picks the best step by the tracked metric (task
    defaults apply) and falls back to the latest step when no metrics
    were saved; ``step=`` pins an explicit step. Returns
    ``(state, step_restored)``.

    Steps are verified against their integrity manifests: the preferred
    step being corrupt falls back to the newest intact one (same walk as
    ``Trainer`` resume), while an explicitly pinned ``step=`` that fails
    verification raises — the caller asked for that step by name, and
    silently serving different weights would be worse than an error.

    The restore is structure-matched against the task's full TrainState,
    optimizer state included (orbax restores whole templates) — callers
    that only infer should drop ``state.opt_state`` right away to free
    the extra ~2x-params memory.
    """
    if prefer not in ("best", "latest"):
        raise ValueError(f"prefer must be 'best' or 'latest', got {prefer!r}")
    ocp = _ocp()
    metric = best_metric or getattr(task, "default_best_metric", "val_acc")
    mode = best_mode or getattr(task, "default_best_mode", "max")
    manager = ocp.CheckpointManager(
        Path(checkpoint_dir).absolute(),
        options=ocp.CheckpointManagerOptions(
            best_fn=lambda m: m[metric], best_mode=mode,
            # Read-only usage: never prune on restore.
            max_to_keep=None,
        ),
    )
    state = task.init_state(
        rng if rng is not None else jax.random.key(0), sample_batch
    )
    if step is not None:
        status, problems = integrity.verify_step(
            Path(checkpoint_dir).absolute() / str(step)
        )
        if status == "corrupt":
            raise ValueError(
                f"pinned checkpoint step {step} under {checkpoint_dir} "
                f"fails integrity verification: {'; '.join(problems)}"
            )
        restored = manager.restore(
            step, args=ocp.args.StandardRestore(_to_pytree(state))
        )
        return TrainState(**restored), int(step)
    all_steps = sorted(manager.all_steps(), reverse=True)
    if not all_steps:
        raise FileNotFoundError(f"no checkpoints under {checkpoint_dir}")
    preferred = manager.best_step() if prefer == "best" else None
    order = (
        [preferred] if preferred is not None else []
    ) + [s for s in all_steps if s != preferred]
    restored, used = _restore_with_fallback(
        manager, _to_pytree(state), steps=order
    )
    return TrainState(**restored), used


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _to_pytree(state: TrainState) -> dict:
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }

"""Distribution strategies: DP trainer, HPO executor, group-apply engine,
ring attention (sequence parallelism), GPipe-style pipeline parallelism."""

from .ring import ring_attention  # noqa: F401
from .pipeline import (  # noqa: F401
    PipelinedTask,
    moment_sharding,
    pipeline_utilization,
    spmd_pipeline,
    stack_stage_params,
    stage_sharding,
)

from .trainer import (  # noqa: F401
    ClassifierTask,
    LMTask,
    Trainer,
    TrainerConfig,
    TrainState,
    restore_state,
)
from .trials import (  # noqa: F401
    DeviceTrials,
    HostTrials,
    objective_ref,
    serve_trial_worker,
)
from .group_apply import (  # noqa: F401
    PaddedGroups,
    batched_fmin,
    device_put_groups,
    group_apply,
    pad_groups,
)

"""Distribution strategies: DP trainer, HPO executor, group-apply engine,
ring attention (sequence parallelism)."""

from .ring import ring_attention  # noqa: F401

from .trainer import (  # noqa: F401
    ClassifierTask,
    Trainer,
    TrainerConfig,
    TrainState,
)
from .trials import DeviceTrials  # noqa: F401
from .group_apply import (  # noqa: F401
    PaddedGroups,
    batched_fmin,
    device_put_groups,
    group_apply,
    pad_groups,
)

"""Distribution strategies: DP trainer, HPO executor, group-apply engine."""

from .trainer import (  # noqa: F401
    ClassifierTask,
    Trainer,
    TrainerConfig,
    TrainState,
)
from .trials import DeviceTrials  # noqa: F401

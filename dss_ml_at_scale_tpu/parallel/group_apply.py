"""Group-apply engine — the ``groupBy().applyInPandas()`` replacement.

Reference contract (SURVEY.md §2.2 X3, §3.3): hash-partition rows so each
(Product, SKU) group lands in its own Spark task, run an arbitrary
pandas→pandas function per group, union the results
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:516-528``). Two
TPU-native execution paths replace that:

1. :func:`group_apply` — the **host path**: groups hash-sharded across
   processes (multi-host) and a worker pool within each process. Runs
   any Python function per group, exactly like ``applyInPandas``; this
   is the compatibility surface. ``executor="process"`` runs each group
   in a subprocess pool — the reference's actual execution shape (one
   Python worker process per Spark task) and the right choice for
   GIL-bound pure-Python group functions; it requires ``fn`` to be
   importable by reference, the same contract as remote HPO objectives.
2. :func:`pad_groups` + :func:`device_put_groups` + :func:`batched_fmin`
   — the **device path**: groups padded to a rectangle, stacked, sharded
   over a ``Mesh`` axis, and fitted by ONE ``vmap``-compiled program.
   Thousands of per-SKU fits become a single XLA launch instead of
   thousands of Python processes; per-group sequential HPO becomes
   per-round batched proposals (same TPE semantics, different execution
   shape — SURVEY.md §7 build-plan step 7).
"""

from __future__ import annotations

import hashlib
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Callable, NamedTuple, Sequence

import numpy as np
import pandas as pd

from ..hpo.tpe import TPE


def stable_group_hash(key: tuple) -> int:
    """Deterministic cross-process hash of a group key (Spark-shuffle-like)."""
    digest = hashlib.md5(repr(key).encode()).digest()
    return int.from_bytes(digest[:8], "little")


def shard_of(key: tuple, process_count: int) -> int:
    return stable_group_hash(key) % process_count


def _run_group_by_ref(args):
    """Subprocess worker: resolve ``fn`` by module:qualname and run it.

    Module-level so it pickles by reference into pool workers; the group
    frame ships pickled, the function ships as a name — the moral
    equivalent of Spark sending Arrow batches to Python worker processes.
    The ref resolves with a plain importlib lookup (not
    ``trials.resolve_objective``) so spawn workers don't also pay the
    jax-importing ``trials``/``hpo.fmin`` module chain.
    """
    ref, group, on_error = args
    import importlib

    module, _, qualname = ref.partition(":")
    fn = importlib.import_module(module)
    for part in qualname.split("."):
        fn = getattr(fn, part)
    try:
        return fn(group)
    except Exception:
        if on_error == "raise":
            raise
        return None


def group_apply(
    df: pd.DataFrame,
    keys: str | Sequence[str],
    fn: Callable[[pd.DataFrame], pd.DataFrame],
    *,
    num_workers: int | None = None,
    process_index: int = 0,
    process_count: int = 1,
    on_error: str = "raise",
    executor: str = "thread",
) -> pd.DataFrame:
    """Apply ``fn`` to each key-group of ``df``; concat the results.

    Multi-host: each process computes the same deterministic key→shard
    hash and runs only its own groups; callers concatenate per-host
    outputs (or write them to a common Parquet dataset, the usual sink).
    ``on_error='skip'`` gives SparkTrials-style per-group failure
    isolation: a failing group is dropped, the rest proceed.

    ``executor``: ``"thread"`` (default — right for fns that release the
    GIL, e.g. anything calling jitted kernels or numpy), ``"process"``
    (one subprocess per worker — right for GIL-bound pure-Python fns;
    requires ``fn`` importable by reference, like remote HPO objectives),
    or ``"inline"`` (sequential, for debugging).
    """
    if on_error not in ("raise", "skip"):
        raise ValueError(f"on_error must be 'raise' or 'skip', got {on_error!r}")
    if executor not in ("thread", "process", "inline"):
        raise ValueError(
            f"executor must be 'thread', 'process', or 'inline', got {executor!r}"
        )
    keys = [keys] if isinstance(keys, str) else list(keys)
    groups = [
        (k if isinstance(k, tuple) else (k,), g)
        for k, g in df.groupby(keys, sort=True)
    ]
    mine = [(k, g) for k, g in groups if shard_of(k, process_count) == process_index]

    def run(item):
        key, g = item
        try:
            return fn(g.reset_index(drop=True))
        except Exception:
            if on_error == "raise":
                raise
            return None

    if executor == "process":
        import multiprocessing

        from .trials import objective_ref

        ref = objective_ref(fn)  # raises early on closures/lambdas
        work = [(ref, g.reset_index(drop=True), on_error) for _, g in mine]
        # spawn, not fork: the caller has usually initialized JAX/XLA by
        # now, and forking a process whose runtime threads may hold locks
        # can deadlock the child. Spawned workers persist across groups,
        # amortizing their interpreter startup.
        with ProcessPoolExecutor(
            max_workers=num_workers,
            mp_context=multiprocessing.get_context("spawn"),
        ) as pool:
            outs = list(pool.map(_run_group_by_ref, work))
    elif executor == "thread" and (num_workers is None or num_workers > 1):
        with ThreadPoolExecutor(max_workers=num_workers) as pool:
            outs = list(pool.map(run, mine))
    else:
        outs = [run(item) for item in mine]
    outs = [o for o in outs if o is not None]
    if not outs:
        return pd.DataFrame()
    return pd.concat(outs, ignore_index=True)


# -- device path: pad → stack → shard → vmap ---------------------------------


class PaddedGroups(NamedTuple):
    """A rectangularized group panel ready for a vmapped fit."""

    values: dict[str, np.ndarray]  # column -> (G, L) float32, zero-padded
    n_valid: np.ndarray  # (G,) true length per group
    keys: pd.DataFrame  # (G, len(keys)) group keys, row i = group i
    n_groups: int  # true group count (before any mesh padding)


def pad_groups(
    df: pd.DataFrame,
    keys: str | Sequence[str],
    columns: Sequence[str],
    sort_by: str | None = None,
    max_len: int | None = None,
) -> PaddedGroups:
    """Stack per-group columns into (G, L) arrays with validity lengths.

    The tail is zero-padded; consumers use ``n_valid`` masks (the ops
    kernels take ``n_valid`` directly). ``sort_by`` orders rows within a
    group first — the reference sorts by Date (``02...py:422``).
    """
    keys = [keys] if isinstance(keys, str) else list(keys)
    grouped = [
        (k if isinstance(k, tuple) else (k,), g) for k, g in df.groupby(keys, sort=True)
    ]
    if sort_by is not None:
        grouped = [(k, g.sort_values(sort_by)) for k, g in grouped]
    lengths = np.array([len(g) for _, g in grouped])
    L = int(max_len or lengths.max())
    if (lengths > L).any():
        raise ValueError(f"group length {lengths.max()} exceeds max_len {L}")
    G = len(grouped)
    values = {c: np.zeros((G, L), np.float32) for c in columns}
    for i, (_, g) in enumerate(grouped):
        for c in columns:
            values[c][i, : lengths[i]] = g[c].to_numpy(np.float32, copy=False)
    key_frame = pd.DataFrame([k for k, _ in grouped], columns=keys)
    return PaddedGroups(values, lengths, key_frame, G)


def pad_to_multiple(arr: np.ndarray, multiple: int) -> np.ndarray:
    """Pad axis 0 with copies of row 0 so G divides the mesh axis evenly.

    Dummy groups are real (duplicate) work discarded by the caller via
    ``PaddedGroups.n_groups`` — simpler and cheaper than masking inside
    the compiled fit.
    """
    g = arr.shape[0]
    pad = (-g) % multiple
    if pad == 0:
        return arr
    return np.concatenate([arr, np.repeat(arr[:1], pad, axis=0)], axis=0)


def device_put_groups(tree, mesh, axis_name: str = "data"):
    """Shard a pytree of (G, ...) arrays over one mesh axis (group-parallel).

    Pads G to a multiple of the axis size (duplicating group 0), then
    ``device_put``s with ``NamedSharding(P(axis_name))`` so a following
    ``jit(vmap(fit))`` runs SPMD across the slice — the pjit-across-pod
    execution SURVEY.md §2.3 assigns to group parallelism.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis_name]
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(pad_to_multiple(np.asarray(a), n), sharding), tree
    )


# -- nested HPO, batched ------------------------------------------------------


def batched_fmin(
    evaluate_batch: Callable[[list[dict]], np.ndarray],
    space,
    max_evals: int,
    n_groups: int,
    rstate: int | np.random.Generator | Sequence = 123,
    algo: TPE | None = None,
) -> tuple[list[dict], list[list[tuple[dict, float]]]]:
    """Run ``n_groups`` independent TPE searches with batched evaluation.

    The reference nests a sequential ``fmin(max_evals=10)`` inside every
    SKU's pandas UDF (``02...py:461-469``). Here each round proposes one
    point per group (host-side TPE, cheap) and ``evaluate_batch`` scores
    ALL groups at once — built to be one vmapped SARIMAX fit per round.
    Search semantics per group are unchanged: each group keeps its own
    history and proposal stream (the reference even seeds every SKU with
    the same rstate=123, reproduced by the scalar-``rstate`` default).

    Returns per-group best points and full histories. Groups whose
    evaluation returns a non-finite loss record it as a failed trial
    (excluded from history), preserving trial-failure isolation.
    """
    algo = algo or TPE()
    if isinstance(rstate, (int, np.integer)):
        rngs = [np.random.default_rng(rstate) for _ in range(n_groups)]
    elif isinstance(rstate, np.random.Generator):
        # One shared generator would entangle the groups' proposal
        # streams; spawn independent children instead.
        rngs = rstate.spawn(n_groups)
    else:
        rngs = list(rstate)
        if len(rngs) != n_groups:
            raise ValueError(f"need {n_groups} rstates, got {len(rngs)}")

    histories: list[list[tuple[dict, float]]] = [[] for _ in range(n_groups)]
    for _ in range(max_evals):
        points = [algo.suggest(space, histories[g], rngs[g]) for g in range(n_groups)]
        losses = np.asarray(evaluate_batch(points), float)
        if losses.shape != (n_groups,):
            raise ValueError(f"evaluate_batch returned {losses.shape}, want ({n_groups},)")
        for g in range(n_groups):
            if np.isfinite(losses[g]):
                histories[g].append((points[g], float(losses[g])))

    best = []
    for g in range(n_groups):
        if not histories[g]:
            raise ValueError(f"group {g}: no successful trials")
        best.append(min(histories[g], key=lambda pl: pl[1])[0])
    return best, histories

"""Group-apply engine — the ``groupBy().applyInPandas()`` replacement.

Reference contract (SURVEY.md §2.2 X3, §3.3): hash-partition rows so each
(Product, SKU) group lands in its own Spark task, run an arbitrary
pandas→pandas function per group, union the results
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:516-528``). Two
TPU-native execution paths replace that:

1. :func:`group_apply` — the **host path**: groups hash-sharded across
   processes (multi-host) and a worker pool within each process. Runs
   any Python function per group, exactly like ``applyInPandas``; this
   is the compatibility surface. ``executor="process"`` runs each group
   in a subprocess pool — the reference's actual execution shape (one
   Python worker process per Spark task) and the right choice for
   GIL-bound pure-Python group functions; it requires ``fn`` to be
   importable by reference, the same contract as remote HPO objectives.
2. :func:`pad_groups` + :func:`make_grid_fit` / :func:`grid_fit_panel`
   — the **device path**: groups padded to a rectangle, stacked, sharded
   over a ``Mesh`` axis, and fit-tune-scored by a bounded family of
   grid-fused XLA launches. The discrete HPO space (75 ``(p, d, q)``
   orders) is enumerated INSIDE the program — ``vmap`` over the
   flattened (group x order) plane, per-group argmin reduced on device
   — so thousands of per-SKU tuned fits cost a handful of launches
   instead of thousands of Python processes or one launch per TPE
   round. :func:`batched_fmin` + :func:`device_put_groups` remain as
   the per-round TPE compatibility path (same search semantics as the
   reference's nested Hyperopt, one launch per round).
"""

from __future__ import annotations

import functools
import hashlib
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Callable, NamedTuple, Sequence

import numpy as np
import pandas as pd

from .. import telemetry
from ..hpo.tpe import TPE


def stable_group_hash(key: tuple) -> int:
    """Deterministic cross-process hash of a group key (Spark-shuffle-like)."""
    digest = hashlib.md5(repr(key).encode()).digest()
    return int.from_bytes(digest[:8], "little")


def shard_of(key: tuple, process_count: int) -> int:
    return stable_group_hash(key) % process_count


def _run_group_by_ref(args):
    """Subprocess worker: resolve ``fn`` by module:qualname and run it.

    Module-level so it pickles by reference into pool workers; the group
    frame ships pickled, the function ships as a name — the moral
    equivalent of Spark sending Arrow batches to Python worker processes.
    The ref resolves with a plain importlib lookup (not
    ``trials.resolve_objective``) so spawn workers don't also pay the
    jax-importing ``trials``/``hpo.fmin`` module chain.
    """
    ref, group, on_error = args
    import importlib

    module, _, qualname = ref.partition(":")
    fn = importlib.import_module(module)
    for part in qualname.split("."):
        fn = getattr(fn, part)
    try:
        return fn(group)
    except Exception:
        if on_error == "raise":
            raise
        return None


def group_apply(
    df: pd.DataFrame,
    keys: str | Sequence[str],
    fn: Callable[[pd.DataFrame], pd.DataFrame],
    *,
    num_workers: int | None = None,
    process_index: int = 0,
    process_count: int = 1,
    on_error: str = "raise",
    executor: str = "thread",
) -> pd.DataFrame:
    """Apply ``fn`` to each key-group of ``df``; concat the results.

    Multi-host: each process computes the same deterministic key→shard
    hash and runs only its own groups; callers concatenate per-host
    outputs (or write them to a common Parquet dataset, the usual sink).
    ``on_error='skip'`` gives SparkTrials-style per-group failure
    isolation: a failing group is dropped, the rest proceed.

    ``executor``: ``"thread"`` (default — right for fns that release the
    GIL, e.g. anything calling jitted kernels or numpy), ``"process"``
    (one subprocess per worker — right for GIL-bound pure-Python fns;
    requires ``fn`` importable by reference, like remote HPO objectives),
    or ``"inline"`` (sequential, for debugging).
    """
    if on_error not in ("raise", "skip"):
        raise ValueError(f"on_error must be 'raise' or 'skip', got {on_error!r}")
    if executor not in ("thread", "process", "inline"):
        raise ValueError(
            f"executor must be 'thread', 'process', or 'inline', got {executor!r}"
        )
    keys = [keys] if isinstance(keys, str) else list(keys)
    groups = [
        (k if isinstance(k, tuple) else (k,), g)
        for k, g in df.groupby(keys, sort=True)
    ]
    mine = [(k, g) for k, g in groups if shard_of(k, process_count) == process_index]

    def run(item):
        key, g = item
        try:
            return fn(g.reset_index(drop=True))
        except Exception:
            if on_error == "raise":
                raise
            return None

    if executor == "process":
        import multiprocessing

        from .trials import objective_ref

        ref = objective_ref(fn)  # raises early on closures/lambdas
        work = [(ref, g.reset_index(drop=True), on_error) for _, g in mine]
        # spawn, not fork: the caller has usually initialized JAX/XLA by
        # now, and forking a process whose runtime threads may hold locks
        # can deadlock the child. Spawned workers persist across groups,
        # amortizing their interpreter startup.
        with ProcessPoolExecutor(
            max_workers=num_workers,
            mp_context=multiprocessing.get_context("spawn"),
        ) as pool:
            outs = list(pool.map(_run_group_by_ref, work))
    elif executor == "thread" and (num_workers is None or num_workers > 1):
        with ThreadPoolExecutor(max_workers=num_workers) as pool:
            outs = list(pool.map(run, mine))
    else:
        outs = [run(item) for item in mine]
    outs = [o for o in outs if o is not None]
    if not outs:
        return pd.DataFrame()
    return pd.concat(outs, ignore_index=True)


# -- device path: pad → stack → shard → vmap ---------------------------------


class PaddedGroups(NamedTuple):
    """A rectangularized group panel ready for a vmapped fit."""

    values: dict[str, np.ndarray]  # column -> (G, L) float32, zero-padded
    n_valid: np.ndarray  # (G,) true length per group
    keys: pd.DataFrame  # (G, len(keys)) group keys, row i = group i
    n_groups: int  # true group count (before any mesh padding)


def pad_groups(
    df: pd.DataFrame,
    keys: str | Sequence[str],
    columns: Sequence[str],
    sort_by: str | None = None,
    max_len: int | None = None,
) -> PaddedGroups:
    """Stack per-group columns into (G, L) arrays with validity lengths.

    The tail is zero-padded; consumers use ``n_valid`` masks (the ops
    kernels take ``n_valid`` directly). ``sort_by`` orders rows within a
    group first (stably) — the reference sorts by Date (``02...py:422``).

    The build is one vectorized scatter per column — group codes +
    within-group positions computed once for the whole frame — rather
    than a Python loop over G x len(columns) slices, so assembling a
    10k-SKU panel is pandas/numpy-bound, not interpreter-bound.
    """
    keys = [keys] if isinstance(keys, str) else list(keys)
    with telemetry.span("panel.build"):
        codes = df.groupby(keys, sort=True).ngroup().to_numpy()
        if codes.dtype.kind == "f":
            # Null group keys: groupby drops those groups, so ngroup()
            # marks their rows NaN — exclude the rows before the
            # scatter, mirroring the per-group iteration this replaced.
            keep = ~np.isnan(codes)
            df = df.loc[keep]
            codes = codes[keep]
        codes = codes.astype(np.int64)
        n = len(codes)
        if n == 0:
            raise ValueError("pad_groups: empty frame has no groups")
        G = int(codes.max()) + 1
        if sort_by is not None:
            order = np.lexsort((df[sort_by].to_numpy(), codes))
        else:
            order = np.lexsort((np.arange(n), codes))
        codes_s = codes[order]
        lengths = np.bincount(codes_s, minlength=G)
        L = int(max_len or lengths.max())
        if (lengths > L).any():
            raise ValueError(
                f"group length {lengths.max()} exceeds max_len {L}"
            )
        starts = np.concatenate([[0], np.cumsum(lengths)[:-1]])
        pos = np.arange(n) - starts[codes_s]
        values = {}
        for c in columns:
            buf = np.zeros((G, L), np.float32)
            buf[codes_s, pos] = df[c].to_numpy(np.float32)[order]
            values[c] = buf
        key_frame = df.iloc[order[starts]][keys].reset_index(drop=True)
    return PaddedGroups(values, lengths, key_frame, G)


def pad_to_multiple(arr: np.ndarray, multiple: int) -> np.ndarray:
    """Pad axis 0 with copies of row 0 so G divides the mesh axis evenly.

    Dummy groups are real (duplicate) work discarded by the caller via
    ``PaddedGroups.n_groups`` — simpler and cheaper than masking inside
    the compiled fit.
    """
    g = arr.shape[0]
    pad = (-g) % multiple
    if pad == 0:
        return arr
    return np.concatenate([arr, np.repeat(arr[:1], pad, axis=0)], axis=0)


def device_put_groups(tree, mesh, axis_name: str = "data"):
    """Shard a pytree of (G, ...) arrays over one mesh axis (group-parallel).

    Pads G to a multiple of the axis size (duplicating group 0), then
    ``device_put``s with ``NamedSharding(P(axis_name))`` so a following
    ``jit(vmap(fit))`` runs SPMD across the slice — the pjit-across-pod
    execution SURVEY.md §2.3 assigns to group parallelism.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis_name]
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(pad_to_multiple(np.asarray(a), n), sharding), tree
    )


# -- grid-fused group fit: chunk → shard → one launch per chunk --------------

# Bound on groups per launch: caps live panel + fit-plane memory on
# device (a chunk holds chunk_size x K simultaneous fits) and keeps the
# launch family at ONE compiled shape — every chunk, including the
# ragged tail, is padded to exactly this many rows.
DEFAULT_GRID_CHUNK = 1024


class GridPanelResult(NamedTuple):
    """Host-side (G, ...) results of a chunked grid-fused panel fit."""

    order: np.ndarray  # (G, 3) winning (p, d, q) per group
    params: np.ndarray  # (G, n_params) packed params at the winner
    loss: np.ndarray  # (G,) selection score at the winner
    loglike: np.ndarray  # (G,) exact loglike of the winning fit
    pred: np.ndarray  # (G, L) full-range predictions at the winner
    n_iter: np.ndarray  # (G,) NM iterations summed over the grid
    converged: np.ndarray  # (G,) winning fit convergence
    chunks: int  # launches it took (the whole launch family)


# One jitted program per (cfg, select, mesh, axis_name, donate) — the
# handful of grid-fit configurations a process runs, each reused for
# every chunk of every panel; bounded by construction like the fused-op
# caches.
@functools.lru_cache(maxsize=None)
# dsst: ignore[retrace-hazard] config-keyed program cache: a process uses a handful of grid-fit configs and every chunk of every panel reuses its entry
def make_grid_fit(
    cfg,
    select: str = "mse",
    mesh=None,
    axis_name: str = "data",
    donate: bool = True,
):
    """The grid-fused group-fit program: ONE jitted launch fitting the
    full order grid for a whole chunk of groups.

    ``vmap`` over the group axis of :func:`..ops.sarimax.sarimax_fit_grid`
    (itself ``vmap`` over the order axis) flattens the (group x order)
    fit plane into one batched program; the per-group argmin is reduced
    on device, so the launch returns winners only. With ``mesh`` the
    group axis is sharded ``P(axis_name)`` (in AND out — pinned
    ``out_shardings`` keep donation intact under committed inputs, the
    decode-step lesson) and the audit's sharding-collectives rule proves
    the groups stay independent in the lowered HLO. ``donate`` donates
    the demand panel ``y``, which XLA aliases to the like-shaped
    predictions output — the chunk's dominant round-trip buffer is
    reused in place. (``exog`` has no like-shaped output to alias, so
    donating it would only buy a warning.)

    Signature of the returned callable:
    ``(y (G, L), exog (G, L, E), n_train (G,), n_valid (G,),
    orders (K, 3)) -> SarimaxGridResult`` with a leading G axis on every
    field. Cached per configuration: the audit registry pins EXACTLY
    this program (``sarimax.batched_fit``), so the certified IR and the
    production launches cannot drift apart.
    """
    import jax

    from ..ops.sarimax import sarimax_fit_grid

    def fit_chunk(y, exog, n_train, n_valid, orders):
        return jax.vmap(
            lambda yg, eg, ntg, nvg: sarimax_fit_grid(
                cfg, yg, eg, orders, ntg, nvg, select=select
            ),
        )(y, exog, n_train, n_valid)

    kwargs: dict = {}
    if donate:
        kwargs["donate_argnums"] = (0,)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        groups = NamedSharding(mesh, P(axis_name))
        replicated = NamedSharding(mesh, P())
        kwargs["in_shardings"] = (groups, groups, groups, groups,
                                  replicated)
        from ..ops.sarimax import SarimaxGridResult

        kwargs["out_shardings"] = SarimaxGridResult(
            order=groups, params=groups, loss=groups, loglike=groups,
            pred=groups, n_iter=groups, converged=groups,
        )
    return jax.jit(fit_chunk, **kwargs)


def grid_fit_panel(
    cfg,
    y: np.ndarray,
    exog: np.ndarray,
    n_train: np.ndarray,
    n_valid: np.ndarray,
    *,
    orders: np.ndarray | None = None,
    select: str = "mse",
    mesh=None,
    axis_name: str = "data",
    chunk_size: int | None = None,
    donate: bool = True,
) -> GridPanelResult:
    """Fit-tune-score every group over the full order grid in bounded
    chunked launches — the host driver of the grid-fused engine.

    Replaces the per-round HPO shape (10 TPE rounds = 10 ``eval_batch``
    launches + a host-side per-group TPE loop + a fresh ``device_put``
    of orders per round, then a refit launch) with
    ``ceil(G / chunk_size)`` launches total: each chunk is padded to the
    one compiled shape (duplicating group 0 — discarded work, no masking
    inside the program), placed sharded over ``axis_name`` when ``mesh``
    is given, and fitted by :func:`make_grid_fit`'s program with the
    demand panel donated. Orders default to the full
    :func:`..ops.sarimax.grid_orders` grid of ``cfg``.
    """
    import jax

    from ..ops.sarimax import grid_orders

    G = int(y.shape[0])
    if not (len(exog) == len(n_train) == len(n_valid) == G):
        raise ValueError(
            f"group-axis mismatch: y {G}, exog {len(exog)}, "
            f"n_train {len(n_train)}, n_valid {len(n_valid)}"
        )
    n_shards = int(mesh.shape[axis_name]) if mesh is not None else 1
    C = int(chunk_size or min(G, DEFAULT_GRID_CHUNK))
    C = max(-(-C // n_shards) * n_shards, n_shards)
    order_grid = np.asarray(
        grid_orders(cfg) if orders is None else orders, np.int32
    )

    fit = make_grid_fit(
        cfg, select=select, mesh=mesh, axis_name=axis_name, donate=donate
    )
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        chunk_sharding = NamedSharding(mesh, P(axis_name))
        orders_dev = jax.device_put(
            order_grid, NamedSharding(mesh, P())
        )
    else:
        chunk_sharding = None
        orders_dev = order_grid

    fitted_counter = telemetry.counter(
        "skus_fitted_total", "groups fitted by the grid-fused engine"
    )
    outs: list[tuple] = []
    n_chunks = 0
    for lo in range(0, G, C):
        hi = min(lo + C, G)
        chunk = tuple(
            pad_to_multiple(a[lo:hi], C)
            for a in (y, exog, n_train, n_valid)
        )
        with telemetry.span("grid.chunk", groups=hi - lo, orders=len(order_grid)):
            if chunk_sharding is not None:
                chunk = tuple(
                    jax.device_put(a, chunk_sharding) for a in chunk
                )
            res = fit(*chunk, orders_dev)
            outs.append(tuple(
                np.asarray(leaf)[: hi - lo] for leaf in res
            ))
        fitted_counter.inc(hi - lo)
        n_chunks += 1
    return GridPanelResult(
        *(np.concatenate(parts) for parts in zip(*outs)),
        chunks=n_chunks,
    )


# -- nested HPO, batched ------------------------------------------------------


def batched_fmin(
    evaluate_batch: Callable[[list[dict]], np.ndarray],
    space,
    max_evals: int,
    n_groups: int,
    rstate: int | np.random.Generator | Sequence = 123,
    algo: TPE | None = None,
) -> tuple[list[dict], list[list[tuple[dict, float]]]]:
    """Run ``n_groups`` independent TPE searches with batched evaluation.

    The reference nests a sequential ``fmin(max_evals=10)`` inside every
    SKU's pandas UDF (``02...py:461-469``). Here each round proposes one
    point per group (host-side TPE, cheap) and ``evaluate_batch`` scores
    ALL groups at once — built to be one vmapped SARIMAX fit per round.
    Search semantics per group are unchanged: each group keeps its own
    history and proposal stream (the reference even seeds every SKU with
    the same rstate=123, reproduced by the scalar-``rstate`` default).

    Returns per-group best points and full histories. Groups whose
    evaluation returns a non-finite loss record it as a failed trial
    (excluded from history), preserving trial-failure isolation.
    """
    algo = algo or TPE()
    if isinstance(rstate, (int, np.integer)):
        rngs = [np.random.default_rng(rstate) for _ in range(n_groups)]
    elif isinstance(rstate, np.random.Generator):
        # One shared generator would entangle the groups' proposal
        # streams; spawn independent children instead.
        rngs = rstate.spawn(n_groups)
    else:
        rngs = list(rstate)
        if len(rngs) != n_groups:
            raise ValueError(f"need {n_groups} rstates, got {len(rngs)}")

    histories: list[list[tuple[dict, float]]] = [[] for _ in range(n_groups)]
    for _ in range(max_evals):
        points = [algo.suggest(space, histories[g], rngs[g]) for g in range(n_groups)]
        losses = np.asarray(evaluate_batch(points), float)
        if losses.shape != (n_groups,):
            raise ValueError(f"evaluate_batch returned {losses.shape}, want ({n_groups},)")
        for g in range(n_groups):
            if np.isfinite(losses[g]):
                histories[g].append((points[g], float(losses[g])))

    best = []
    for g in range(n_groups):
        if not histories[g]:
            raise ValueError(f"group {g}: no successful trials")
        best.append(min(histories[g], key=lambda pl: pl[1])[0])
    return best, histories

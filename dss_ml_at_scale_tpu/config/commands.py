"""`dsst` workload subcommands.

Each subcommand is the CLI face of one reference notebook track
(SURVEY.md §3): ``datagen`` replaces the widget-driven generator
notebooks (``group_apply/_resources/01-data-generator.py``), ``forecast``
the scaled fit-tune-score notebook
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:341-556``),
``train`` the distributed-training driver
(``deep_learning/2.distributed-data-loading-petastorm.py:342-470``), and
``hpo`` the data-size playbook (``hyperopt/2. hyperopt on diff sizes of
data.py``). The ``pipeline`` subcommand (the RUNME job-DAG equivalent)
lives in :mod:`.pipeline`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path


# --------------------------------------------------------------------------
# datagen
# --------------------------------------------------------------------------

def register_datagen(sub: argparse._SubParsersAction) -> None:
    gen = sub.add_parser(
        "datagen", help="synthetic data generators (demand / bom / regression)"
    )
    gsub = gen.add_subparsers(dest="generator", required=True)

    demand = gsub.add_parser("demand", help="ARMA weekly demand panel → Delta")
    demand.add_argument("--out", required=True, help="Delta table path")
    demand.add_argument("--skus-per-product", type=int, default=10)
    demand.add_argument("--years", type=int, default=3)
    demand.add_argument("--seed", type=int, default=123)
    demand.set_defaults(fn=_cmd_datagen_demand)

    bom = gsub.add_parser("bom", help="random 3-level BoM DAG per SKU → Delta")
    bom.add_argument(
        "--demand", required=True, help="demand Delta table to take SKUs from"
    )
    bom.add_argument("--out", required=True, help="bom Delta table path")
    bom.add_argument("--mapper-out", required=True, help="sku_mapper Delta path")
    bom.add_argument("--depth", type=int, default=3)
    bom.add_argument("--seed", type=int, default=123)
    bom.set_defaults(fn=_cmd_datagen_bom)

    reg = gsub.add_parser(
        "regression", help="byte-targeted synthetic regression → npz"
    )
    reg.add_argument("--bytes", type=float, required=True, dest="n_bytes")
    reg.add_argument("--out", required=True, help="output .npz path")
    reg.set_defaults(fn=_cmd_datagen_regression)

    img = gsub.add_parser(
        "images",
        help="labeled JPEG gratings → Delta (quick-start training data; "
        "each class a distinct orientation/frequency)",
    )
    img.add_argument("--out", required=True, help="Delta table path")
    img.add_argument("--n", type=int, default=1024)
    img.add_argument("--classes", type=int, default=10)
    img.add_argument("--size", type=int, default=64)
    img.add_argument("--seed", type=int, default=0)
    img.add_argument(
        "--label-noise", type=float, default=0.0,
        help="fraction of stored labels replaced by uniform draws; caps "
        "best achievable accuracy at exactly (1-p)+p/classes, making "
        "accuracy curves regression-discriminating",
    )
    img.set_defaults(fn=_cmd_datagen_images)

    ph = gsub.add_parser(
        "photos",
        help="real-photograph JPEG crops (sklearn's CC-BY sample photos) "
        "as an ImageNet-style file tree for dsst ingest",
    )
    ph.add_argument("--out", required=True, help="tree root (files go in Data/)")
    ph.add_argument("--n", type=int, default=192)
    ph.add_argument("--size", type=int, default=96)
    ph.add_argument("--seed", type=int, default=0)
    ph.set_defaults(fn=_cmd_datagen_photos)


def _cmd_datagen_demand(args: argparse.Namespace) -> int:
    # The ARMA sampler runs through JAX; for a datagen-sized workload the
    # host CPU is the right backend — don't claim (or wait on) an
    # accelerator from a data-prep subprocess.
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized by the calling process

    from ..datagen.demand import DemandConfig, generate_demand, write_demand_delta

    cfg = DemandConfig(
        n_skus_per_product=args.skus_per_product,
        ts_length_years=args.years,
        seed=args.seed,
    )
    df = generate_demand(cfg)
    write_demand_delta(df, args.out)
    print(
        f"demand: {df['SKU'].nunique()} SKUs × "
        f"{df['Date'].nunique()} weeks = {len(df)} rows -> {args.out}"
    )
    return 0


def _cmd_datagen_bom(args: argparse.Namespace) -> int:
    from ..datagen.bom import generate_bom, write_bom_delta

    skus = sorted(set(_read_delta_pandas(args.demand, columns=["SKU"])["SKU"]))
    tables = generate_bom(skus, depth=args.depth, seed=args.seed)
    write_bom_delta(tables, args.out, args.mapper_out)
    print(
        f"bom: {len(tables.bom)} edges, {len(tables.sku_mapper)} sku mappings "
        f"-> {args.out}, {args.mapper_out}"
    )
    return 0


def _cmd_datagen_regression(args: argparse.Namespace) -> int:
    from ..datagen.regression import gen_data
    from ..hpo.shipping import save_shared

    X_train, X_test, y_train, y_test = gen_data(int(args.n_bytes))
    path = save_shared(
        args.out, X_train=X_train, X_test=X_test, y_train=y_train, y_test=y_test
    )
    print(f"regression: {len(X_train)}+{len(X_test)} samples -> {path}")
    return 0


def _cmd_datagen_images(args: argparse.Namespace) -> int:
    from ..datagen.images import write_image_delta

    labels = write_image_delta(
        args.out, args.n, classes=args.classes, size=args.size,
        seed=args.seed, label_noise=args.label_noise, mode="overwrite",
    )
    noise = f", label noise {args.label_noise}" if args.label_noise else ""
    print(
        f"images: {len(labels)} JPEGs, {args.classes} classes, "
        f"{args.size}px{noise} -> {args.out}"
    )
    return 0


def _cmd_datagen_photos(args: argparse.Namespace) -> int:
    from ..datagen.photos import CLASSES, write_photo_tree

    n = write_photo_tree(args.out, args.n, size=args.size, seed=args.seed)
    print(
        f"photos: {n} real-photo JPEG crops, {len(CLASSES)} classes, "
        f"{args.size}px -> {args.out}"
    )
    return 0


# --------------------------------------------------------------------------
# forecast
# --------------------------------------------------------------------------

def register_forecast(sub: argparse._SubParsersAction) -> None:
    fc = sub.add_parser(
        "forecast", help="per-SKU SARIMAX tune + fit + score over a demand table"
    )
    fc.add_argument("--data", required=True, help="demand Delta table")
    fc.add_argument("--out", required=True, help="forecast Delta table to write")
    fc.add_argument(
        "--search", choices=("grid", "tpe"), default="grid",
        help="grid: fuse the full (p,d,q) order grid into chunked "
        "launches with on-device argmin (exact optimum, fewest "
        "launches); tpe: the reference's per-round batched TPE "
        "(compatibility path)",
    )
    fc.add_argument(
        "--chunk-size", type=int, default=None,
        help="groups per grid-fused launch (default: min(G, 1024), "
        "rounded up to the mesh axis)",
    )
    fc.add_argument("--max-evals", type=int, default=10,
                    help="TPE rounds (--search tpe only)")
    fc.add_argument("--horizon", type=int, default=40, help="holdout weeks")
    fc.add_argument("--rstate", type=int, default=123)
    fc.add_argument(
        "--no-mesh", action="store_true",
        help="keep the group axis on one device (debug)",
    )
    _add_tracking_args(fc, "forecasting")
    fc.add_argument("--max-p", type=int, default=4, help="AR order bound")
    fc.add_argument("--max-d", type=int, default=2, help="differencing bound")
    fc.add_argument("--max-q", type=int, default=4, help="MA order bound")
    fc.add_argument("--max-iter", type=int, default=200, help="Nelder-Mead iters")
    fc.set_defaults(fn=_cmd_forecast)


def _cmd_forecast(args: argparse.Namespace) -> int:
    import pyarrow as pa

    from ..data.delta import write_delta
    from ..ops import SarimaxConfig
    from ..runtime import make_mesh
    from ..workloads.forecasting import (
        EXO_FIELDS,
        add_exo_variables,
        tune_and_forecast_panel,
    )

    t0 = time.perf_counter()
    df = _read_delta_pandas(args.data)
    enriched = add_exo_variables(df)
    mesh = None if args.no_mesh else make_mesh()
    cfg = SarimaxConfig(
        max_p=args.max_p, max_d=args.max_d, max_q=args.max_q,
        k_exog=len(EXO_FIELDS), max_iter=args.max_iter,
    )
    out = tune_and_forecast_panel(
        enriched,
        max_evals=args.max_evals,
        forecast_horizon=args.horizon,
        rstate=args.rstate,
        mesh=mesh,
        cfg=cfg,
        search=args.search,
        chunk_size=args.chunk_size,
    )
    write_delta(
        pa.Table.from_pandas(out, preserve_index=False), args.out, mode="overwrite"
    )
    dt = time.perf_counter() - t0
    err = out["Demand"] - out["Demand_Fitted"]
    mse = float((err**2).mean())
    groups = out.groupby(["Product", "SKU"]).ngroups
    _finish_tracker(
        _open_tracker(args, "forecast"),
        params={"search": args.search, "max_evals": args.max_evals,
                "horizon": args.horizon, "groups": groups},
        metrics={"mse": mse, "wall_s": dt}, step=0,
    )
    print(
        f"forecast: {groups} groups, {len(out)} rows, mse {mse:.2f}, "
        f"{dt:.1f}s -> {args.out}"
    )
    return 0


# --------------------------------------------------------------------------
# eda (single-SKU model selection)
# --------------------------------------------------------------------------

def register_eda(sub: argparse._SubParsersAction) -> None:
    eda = sub.add_parser(
        "eda", help="single-SKU model comparison: Holt-Winters vs SARIMAX vs tuned"
    )
    eda.add_argument("--data", required=True, help="demand Delta table")
    eda.add_argument("--product", default=None)
    eda.add_argument("--sku", default=None, help="defaults to the first SKU")
    eda.add_argument("--horizon", type=int, default=40)
    eda.add_argument("--seasonal-periods", type=int, default=52)
    eda.add_argument("--max-evals", type=int, default=10)
    eda.add_argument("--parallelism", type=int, default=10)
    eda.add_argument("--max-iter", type=int, default=200)
    eda.add_argument(
        "--polish", action="store_true",
        help="refine the single-SKU SARIMAX fits with the host-side "
        "float64 polish (closes the f32 unit-root corner)",
    )
    eda.add_argument(
        "--plot", default=None, metavar="PATH",
        help="write the reference-style comparison figure (actual series "
        "+ top models' holdout predictions) to this PNG",
    )
    _add_tracking_args(eda, "eda")
    eda.set_defaults(fn=_cmd_eda)


def _cmd_eda(args: argparse.Namespace) -> int:
    from ..ops import SarimaxConfig
    from ..workloads.eda import run_eda
    from ..workloads.forecasting import EXO_FIELDS

    df = _read_delta_pandas(args.data)
    tracker = _open_tracker(args, "eda")
    report = run_eda(
        df,
        product=args.product,
        sku=args.sku,
        horizon=args.horizon,
        seasonal_periods=args.seasonal_periods,
        max_evals=args.max_evals,
        parallelism=args.parallelism,
        cfg=SarimaxConfig(k_exog=len(EXO_FIELDS), max_iter=args.max_iter),
        polish=args.polish,
        return_curves=args.plot is not None,
        tracker=tracker,
    )
    print(f"EDA for Product={report.product} SKU={report.sku} "
          f"(holdout {args.horizon} weeks)")
    print(report.scores.to_string(index=False))
    print(f"best SARIMAX order: {report.best_order} (mse {report.best_order_mse:.2f})")
    _finish_tracker(
        tracker,
        params={"product": report.product, "sku": report.sku,
                "max_evals": args.max_evals, "horizon": args.horizon},
        metrics={"best_order_mse": report.best_order_mse},
        step=args.max_evals,
    )
    if args.plot:
        report.plot(args.plot)
        print(f"comparison figure -> {args.plot}")
    return 0


# --------------------------------------------------------------------------
# ingest
# --------------------------------------------------------------------------

def register_ingest(sub: argparse._SubParsersAction) -> None:
    ing = sub.add_parser(
        "ingest", help="image dataset directory → Delta table with stable ids"
    )
    ing.add_argument("--data-root", required=True)
    ing.add_argument("--out", required=True, help="Delta table path")
    ing.add_argument("--pattern", default="*.JPEG")
    ing.add_argument(
        "--label-from", choices=["path", "annotation"], default="path"
    )
    ing.add_argument("--rows-per-fragment", type=int, default=1024)
    ing.add_argument("--append", action="store_true")
    ing.add_argument(
        "--allow-unlabeled", action="store_true",
        help="ingest rows with no determinable label as label_index=-1 "
        "instead of failing (filter them before training)",
    )
    ing.set_defaults(fn=_cmd_ingest)


def _cmd_ingest(args: argparse.Namespace) -> int:
    from ..ingest import ingest_image_dataset

    table = ingest_image_dataset(
        args.data_root,
        args.out,
        file_pattern=args.pattern,
        label_from=args.label_from,
        rows_per_fragment=args.rows_per_fragment,
        mode="append" if args.append else "overwrite",
        on_missing_label="keep" if args.allow_unlabeled else "error",
    )
    print(f"ingested {table.num_records()} rows -> {args.out}")
    return 0


# --------------------------------------------------------------------------
# train
# --------------------------------------------------------------------------

def register_train(sub: argparse._SubParsersAction) -> None:
    tr = sub.add_parser(
        "train", help="data-parallel image-classifier training from a Delta table"
    )
    tr.add_argument("--data", required=True, help="train Delta table (content/label_index)")
    tr.add_argument("--val-data", default=None, help="validation Delta table")
    tr.add_argument("--epochs", type=int, default=2)
    tr.add_argument("--batch-size", type=int, default=212)
    tr.add_argument("--learning-rate", type=float, default=1e-5)
    tr.add_argument(
        "--lr-schedule", choices=["constant", "cosine"], default=None,
        help="constant reproduces the reference recipe (Adam 1e-5, "
        "2...py:383); cosine adds linear warmup to --learning-rate then "
        "cosine decay to 0 over the current run's total steps — the "
        "standard from-scratch ResNet schedule. Default: the value "
        "persisted in the checkpoint dir (a flag-less --resume keeps the "
        "trained schedule's optimizer structure), else constant",
    )
    tr.add_argument(
        "--warmup-steps", type=int, default=None,
        help="warmup length for --lr-schedule cosine (default: 5%% of "
        "total steps)",
    )
    tr.add_argument("--num-classes", type=int, default=1000)
    tr.add_argument("--crop", type=int, default=224)
    tr.add_argument(
        "--model",
        choices=["resnet50", "tiny", "tiny-bottleneck", "vit-t16",
                 "vit-s16", "vit-tiny"],
        default="resnet50",
    )
    tr.add_argument(
        "--pretrained", default=None, metavar="PATH",
        help="torchvision-layout state dict (.pt/.pth/.npz) to fine-tune "
        "from instead of cold-starting (reference 2...py:150); builds the "
        "model with torch_padding=True for numerical parity; a head whose "
        "class count differs from --num-classes is freshly initialized",
    )
    tr.add_argument(
        "--torch-padding", action=argparse.BooleanOptionalAction, default=None,
        help="force torchvision-style symmetric stride-2 padding (or "
        "--no-torch-padding to force it off); needed when resuming a "
        "--pretrained run without re-passing --pretrained (the "
        "checkpoint's BatchNorm statistics embed the padding choice); "
        "default: True with --pretrained, else the value persisted in "
        "the checkpoint dir, else False",
    )
    tr.add_argument(
        "--fused-bn", action=argparse.BooleanOptionalAction, default=True,
        help="fused BN+relu(+residual) with a minimal-residual custom "
        "VJP (ops/fused_norm.py): same math and parameter tree, ~30%% "
        "fewer HBM bytes per step — the v5e throughput lever. "
        "--no-fused-bn falls back to flax BatchNorm",
    )
    tr.add_argument(
        "--pallas-fused", action="store_true",
        help="second byte lever on top of --fused-bn (bottleneck models "
        "only): the middle BN's apply fused into the 1x1 conv as a "
        "Pallas matmul prologue (ops/fused_matmul.py) — the normalized "
        "activation never exists in HBM; same parameter tree, "
        "single-chip training path",
    )
    tr.add_argument(
        "--eval-topk", type=int, nargs="*", default=[],
        help="extra top-k val accuracies (e.g. --eval-topk 5 adds "
        "val_top5_acc, the standard ImageNet companion metric)",
    )
    tr.add_argument(
        "--augment", action="store_true",
        help="on-device train-time RandomResizedCrop + horizontal flip "
        "inside the jitted step (data/augment.py): the reference's "
        "torchvision train transform, run on the chip instead of host "
        "decode workers; keyed by the training step, so resume replays "
        "the identical crop schedule. Eval/predict never augment",
    )
    tr.add_argument("--workers", type=int, default=2)
    tr.add_argument("--queue-size", type=int, default=20)
    tr.add_argument(
        "--feeder-depth", type=int, default=2,
        help="bound of the background feeder's on-device batch queue "
        "(host-side shard + transfer overlaps step dispatch; HBM held "
        "is depth extra batches). Occupancy/stall are exposed as "
        "feeder_* series on /metrics and in dsst telemetry",
    )
    tr.add_argument(
        "--shard-opt-state", action="store_true",
        help="ZeRO-1: shard optimizer state over the data axis instead of "
        "replicating it (same math, ~world-size less optimizer memory)",
    )
    tr.add_argument(
        "--image-dtype", choices=["float32", "uint8"], default="float32",
        help="uint8 ships raw quantized bytes to the device (4x less host "
        "RAM / queue memory / transfer) and normalizes inside the jitted "
        "step; float32 normalizes on the host (torchvision parity)",
    )
    tr.add_argument(
        "--decode-backend", choices=["auto", "native", "pil"], default="auto",
        help="JPEG decode path: the C++ pool, pure-PIL, or auto (native "
        "when it compiles, per-image PIL fallback); the resolved backend "
        "is reported in the run summary",
    )
    tr.add_argument(
        "--fast-decode", action="store_true",
        help="DCT-domain scaled decode for large sources (PIL draft-mode "
        "equivalent; native backend only): ~2x decode throughput at "
        "2048px sources, pixel values slightly off full-decode parity",
    )
    tr.add_argument(
        "--on-decode-error", choices=["raise", "substitute"], default="raise",
        help="substitute: a corrupt record becomes a zero image (tallied "
        "in the run summary) instead of stopping the epoch — lets a "
        "multi-hour run survive isolated data corruption",
    )
    tr.add_argument(
        "--shuffle", action=argparse.BooleanOptionalAction, default=True,
        help="shuffle row groups per epoch (seeded); --no-shuffle gives "
        "every table pass the identical batch order — what makes a "
        "killed-and-auto-resumed run bitwise-reproduce an uninterrupted "
        "one (the dsst chaos invariant)",
    )
    tr.add_argument("--limit-val-batches", type=int, default=5)
    tr.add_argument("--checkpoint-dir", default=None)
    tr.add_argument("--resume", action="store_true")
    _add_resume_auto_arg(tr)
    tr.add_argument("--profile-dir", default=None)
    _add_health_args(tr)
    _add_tracking_args(tr, "imagenet")
    tr.add_argument(
        "--coordinator", default=None,
        help="host:port for multi-host rendezvous (process 0)",
    )
    tr.set_defaults(fn=_cmd_train)


def _cmd_train(args: argparse.Namespace) -> int:
    import optax

    from ..data import DeltaTable, batch_loader
    from ..data.transform import imagenet_transform_spec
    from ..parallel import ClassifierTask, Trainer, TrainerConfig
    from ..runtime import initialize_distributed, local_topology, make_mesh

    if getattr(args, "pallas_fused", False):
        if not args.fused_bn:
            print("--pallas-fused builds on the fused path; drop "
                  "--no-fused-bn")
            return 1
        if args.model not in ("resnet50", "tiny-bottleneck"):
            # ViT has no BN (the flag would be silently inert); basic-
            # block ResNets have no 1x1 site (the model would raise a
            # deep flax traceback).  Loud and early instead.
            print("--pallas-fused applies to bottleneck ResNets only "
                  "(resnet50, tiny-bottleneck); drop the flag for "
                  f"--model {args.model}")
            return 1
        # Scoring paths map this back to the (math-identical) HLO fused
        # model via resolve_checkpoint's bool(); training uses the
        # Pallas prologue-fused program.  (The multi-chip guard runs
        # AFTER initialize_distributed below: touching the backend here
        # would break jax.distributed.initialize, and the pre-init
        # local count is the wrong topology anyway.)
        args.fused_bn = "pallas"

    initialize_distributed(coordinator_address=args.coordinator)
    # Each process reads a disjoint shard (the reference's
    # cur_shard=rank / shard_count=WORLD, 2...py:249-250); the mesh
    # assembles per-process rows into the global batch.
    topo = local_topology()

    if args.fused_bn == "pallas":
        import jax

        if (topo.global_device_count > 1
                and jax.devices()[0].platform != "cpu"):
            # Compiled pallas_call has no GSPMD partitioning rule yet —
            # multi-chip would compile-error or replicate the batch.
            # (CPU interpret mode lowers to plain HLO, which GSPMD
            # partitions fine — the simulated-mesh CI path.)
            print("--pallas-fused is single-chip for now; use plain "
                  "--fused-bn for multi-chip training")
            return 1

    table = DeltaTable(args.data)
    rows = table.num_records()
    spec = imagenet_transform_spec(
        crop=args.crop, backend=args.decode_backend,
        output_dtype=args.image_dtype, on_error=args.on_decode_error,
        fast_decode=args.fast_decode,
    )
    # Pretrained torchvision weights embed symmetric stride-2 padding in
    # their BatchNorm statistics; the model must match (models/pretrained.py).
    # The choice is persisted next to the checkpoint so a later --resume
    # that omits both flags still rebuilds the same architecture.
    meta_path = (
        Path(args.checkpoint_dir) / "dsst_model.json"
        if args.checkpoint_dir
        else None
    )
    # One read; merged (not replaced) on rewrite so a resume whose --data
    # table carries no labels.json keeps the persisted label_names.
    meta = (
        json.loads(meta_path.read_text())
        if meta_path is not None and meta_path.exists()
        else {}
    )
    if args.torch_padding is not None:
        torch_padding = args.torch_padding
    elif args.pretrained:
        torch_padding = True
    else:
        torch_padding = bool(meta.get("torch_padding", False))
    # Same steps/epoch arithmetic the Trainer uses (rows // global
    # batch), so a fresh cosine trajectory matches the run length.
    steps_per_epoch = rows // (args.batch_size * topo.process_count)
    lr = _resolve_lr_schedule(
        args, meta, total_steps=steps_per_epoch * args.epochs
    )
    meta.update(
        torch_padding=torch_padding,
        model=args.model,
        num_classes=args.num_classes,
        crop=args.crop,
        fused_bn=args.fused_bn,
    )
    # Tables from dsst ingest carry their label vocabulary; persist
    # it WITH the checkpoint (position = model output index), so
    # predict names classes by the vocabulary the model was trained
    # on — never by whatever table it later scores.
    train_labels = Path(args.data) / "labels.json"
    if train_labels.exists():
        vocab = json.loads(train_labels.read_text())
        names = [None] * args.num_classes
        for name, idx in vocab.items():
            if 0 <= int(idx) < args.num_classes:
                names[int(idx)] = name
        meta["label_names"] = names
    if meta_path is not None and topo.process_index == 0:
        meta_path.parent.mkdir(parents=True, exist_ok=True)
        meta_path.write_text(json.dumps(meta))
    model = _build_classifier_model(
        args.model, num_classes=args.num_classes, torch_padding=torch_padding,
        fused_bn=args.fused_bn,
    )
    for k in args.eval_topk:
        # Fail BEFORE training, not at the first eval a whole epoch in.
        if not 1 <= k <= args.num_classes:
            raise SystemExit(
                f"--eval-topk {k} must be in [1, num_classes="
                f"{args.num_classes}]"
            )
    augment = None
    if args.augment:
        from ..data.augment import AugmentConfig

        augment = AugmentConfig()
    task = ClassifierTask(model=model, tx=optax.adam(lr), augment=augment,
                          eval_topk=tuple(args.eval_topk))

    init_state = None
    if args.pretrained and (args.resume_auto or not _has_checkpoint(args)):
        # With --resume and an existing checkpoint the restore would
        # overwrite these weights anyway — skip the conversion. Under
        # --resume-auto the conversion must happen regardless: when
        # every step on disk turns out torn, the trainer falls back to
        # a FRESH start, and that start must be the requested
        # pretrained weights, not a silent random init (a successful
        # restore still overwrites them, costing only the conversion).
        if args.model.startswith("vit"):
            from ..models.pretrained import load_pretrained_vit as _load
        else:
            from ..models.pretrained import load_pretrained_resnet as _load

        variables = _load(args.pretrained, model, image_size=args.crop)
        init_state = task.state_from_variables(variables)

    _mark_interrupted_predecessors(args)
    tracker = _open_tracker(args, "train")
    if tracker is not None:
        tracker.log_params(_args_params(args))

    health_cfg, quarantine = _health_config(args)
    trainer = Trainer(
        TrainerConfig(
            max_epochs=args.epochs,
            total_train_rows=rows,
            limit_val_batches=args.limit_val_batches,
            checkpoint_dir=args.checkpoint_dir,
            resume=args.resume,
            resume_auto=args.resume_auto,
            profile_dir=args.profile_dir,
            shard_opt_state=args.shard_opt_state,
            feeder_depth=args.feeder_depth,
            health=health_cfg,
        ),
        mesh=make_mesh(),
        tracker=tracker,
    )

    val_factory = None
    if args.val_data:
        val_table = DeltaTable(args.val_data)

        def val_factory():
            return batch_loader(
                val_table, batch_size=args.batch_size, num_epochs=1,
                transform_spec=spec, shuffle_row_groups=False,
                cur_shard=topo.process_index, shard_count=topo.process_count,
            ).__enter__()

    from ..resilience.health import TrainingHealthError

    with batch_loader(
        table,
        batch_size=args.batch_size,
        num_epochs=None,
        workers_count=args.workers,
        results_queue_size=args.queue_size,
        transform_spec=spec,
        shuffle_row_groups=args.shuffle,
        cur_shard=topo.process_index,
        shard_count=topo.process_count,
        # Under supervision, the reader tags every batch with its row
        # provenance (so a discarded step quarantines exact rows),
        # consults the blocklist, and survives corrupt samples by
        # quarantining them instead of dying.
        quarantine=quarantine,
        emit_provenance=health_cfg is not None,
        on_corrupt="quarantine" if health_cfg is not None else "raise",
    ) as train_reader:
        try:
            result = trainer.fit(
                task, train_reader, val_data_factory=val_factory,
                state=init_state,
            )
        except TrainingHealthError as e:
            # Operator-facing abort: a clean machine-parseable line (the
            # bundle has the forensics), FAILED run status, exit 3.
            fail_active_tracker()
            print(json.dumps({
                "aborted": True,
                "reason": str(e),
                "diagnostic_bundle": e.bundle_path,
                "quarantine_file": (
                    str(quarantine.path) if quarantine is not None else None
                ),
            }))
            return 3

    last = result.history[-1] if result.history else {}
    # Epoch metrics were logged by the Trainer as they happened; the
    # close prints the "run ->" pointer BEFORE the JSON summary so the
    # last stdout line stays machine-parseable.
    _finish_tracker(tracker)
    print(
        json.dumps(
            {
                "steps": int(result.state.step),
                "epochs": len(result.history),
                "images_per_sec": round(last.get("images_per_sec", 0.0), 2),
                "train_loss": last.get("train_loss"),
                "val_acc": last.get("val_acc"),
                # --eval-topk metrics surface in the summary too.
                **{f"val_top{k}_acc": last.get(f"val_top{k}_acc")
                   for k in args.eval_topk},
                "best_checkpoint": result.best_checkpoint_path,
                "decode_backend": spec.backend,
                "decode_substitutions": spec.substitutions.count,
                # True when a SIGTERM (spot/TPU-VM eviction) cut the run
                # short; rerun with --resume to continue from the saved step.
                "preempted": result.preempted,
                # True when --resume-auto actually RESTORED a prior
                # checkpoint (the Trainer's verdict) — False when it
                # started fresh, including the found-only-wreckage
                # fallback; operators must be able to trust this flag.
                "auto_resumed": result.auto_resumed,
                # Health-supervisor accounting (0s with --health-policy off).
                **(
                    {
                        "skipped_steps": result.skipped_steps,
                        "health_rollbacks": result.health_rollbacks,
                        "quarantined": (
                            len(quarantine) if quarantine is not None else 0
                        ),
                    }
                    if health_cfg is not None else {}
                ),
            }
        )
    )
    return 0


def _has_checkpoint(args: argparse.Namespace) -> bool:
    """True when --resume will actually restore something — the same
    orbax ``latest_step()`` predicate Trainer.fit uses, so the two can't
    disagree about whether a restore will happen."""
    if not (
        (args.resume or getattr(args, "resume_auto", False))
        and args.checkpoint_dir
    ):
        return False
    ckpt = Path(args.checkpoint_dir)
    if not ckpt.is_dir():
        return False
    import orbax.checkpoint as ocp

    try:
        return ocp.CheckpointManager(ckpt.absolute()).latest_step() is not None
    except Exception:
        return False


# --------------------------------------------------------------------------
# predict (beyond parity: score a Delta table with a trained checkpoint)
# --------------------------------------------------------------------------

def _build_classifier_model(name, **kw):
    from .checkpoints import build_classifier_model

    return build_classifier_model(name, **kw)


def register_predict(sub: argparse._SubParsersAction) -> None:
    pr = sub.add_parser(
        "predict",
        help="classify a Delta table of images with a trained checkpoint "
        "and write predictions to a Delta table",
    )
    pr.add_argument("--data", required=True, help="Delta table (content/label_index)")
    pr.add_argument(
        "--checkpoint-dir", required=True,
        help="a dsst train checkpoint dir (model architecture is read "
        "from its dsst_model.json)",
    )
    pr.add_argument("--out", required=True, help="predictions Delta table")
    pr.add_argument(
        "--step", type=int, default=None,
        help="explicit checkpoint step (default: the best step by the "
        "tracked metric, else the latest)",
    )
    pr.add_argument("--batch-size", type=int, default=64)
    pr.add_argument("--crop", type=int, default=None,
                    help="default: the crop persisted in dsst_model.json, "
                    "else 224")
    pr.add_argument("--decode-backend", choices=["auto", "native", "pil"],
                    default="auto")
    pr.set_defaults(fn=_cmd_predict)


def _checkpoint_task(checkpoint_dir, crop_override=None):
    """CLI face of :func:`..config.checkpoints.resolve_checkpoint`:
    prints the missing-meta diagnosis and returns None (callers just
    ``return 1``); a crop/architecture conflict exits with the message.
    """
    from .checkpoints import resolve_checkpoint

    try:
        return resolve_checkpoint(checkpoint_dir, crop_override)
    except FileNotFoundError as e:
        print(e)
        return None
    except (json.JSONDecodeError, KeyError) as e:
        # Corrupt dsst_model.json (truncated write, foreign file) or one
        # missing a required key: same was-this-written-by-dsst-train
        # diagnosis as a missing meta file, not a raw traceback.
        print(
            f"unreadable model metadata in {checkpoint_dir}/dsst_model.json"
            f" ({type(e).__name__}: {e}) — was this checkpoint written by"
            " `dsst train`?"
        )
        return None
    except ValueError as e:
        raise SystemExit(str(e))


def _cmd_predict(args: argparse.Namespace) -> int:
    import numpy as np
    import pyarrow as pa

    import jax
    import jax.numpy as jnp

    from ..data import DeltaTable, batch_loader, write_delta
    from ..data.transform import imagenet_transform_spec
    from ..parallel import restore_state

    resolved = _checkpoint_task(args.checkpoint_dir, args.crop)
    if resolved is None:
        return 1
    meta, crop, model, task = resolved

    table = DeltaTable(args.data)
    spec = imagenet_transform_spec(crop=crop, backend=args.decode_backend)
    predict = None
    rows_label: list[np.ndarray] = []
    rows_pred: list[np.ndarray] = []
    rows_prob: list[np.ndarray] = []
    state = None
    correct = total = 0
    with batch_loader(
        table, batch_size=args.batch_size, num_epochs=1,
        transform_spec=spec, shuffle_row_groups=False, drop_last=False,
        # One worker: multi-threaded readers stream row groups in
        # ARRIVAL order, which would make the emitted "row" index a lie.
        # With one worker and shuffling off, rows stream in table order.
        workers_count=1,
    ) as reader:
        for batch in reader:
            if predict is None:
                state, step = restore_state(
                    task, batch, args.checkpoint_dir, step=args.step
                )
                # Inference never touches the optimizer; free its memory
                # (the structure-matched restore still had to read it).
                params, batch_stats = state.params, state.batch_stats
                state = None
                variables = {"params": params}
                if batch_stats:  # stat-free models (ViT) have none
                    variables["batch_stats"] = batch_stats
                from .checkpoints import make_scorer

                # The SAME jitted scorer dsst serve uses — parity by
                # construction, not by parallel maintenance.
                predict = make_scorer(task, variables)

            pred, prob = predict(batch["image"])
            pred, prob = np.asarray(pred), np.asarray(prob)
            labels = np.asarray(batch["label"])
            rows_label.append(labels)
            rows_pred.append(pred)
            rows_prob.append(prob)
            correct += int((pred == labels).sum())
            total += len(pred)

    if total == 0:
        print("no rows to score")
        return 1
    preds = np.concatenate(rows_pred).astype(np.int64)
    columns = {
        "row": pa.array(np.arange(total, dtype=np.int64)),
        "label_index": pa.array(np.concatenate(rows_label).astype(np.int64)),
        "pred_index": pa.array(preds),
        "pred_prob": pa.array(np.concatenate(rows_prob).astype(np.float64)),
    }
    # Map indices to names via the vocabulary persisted WITH the
    # checkpoint at train time (the reference's predictions are wnid
    # strings for the same reason). Deliberately NOT the scoring table's
    # labels.json: a different table's first-encounter order would
    # silently mislabel.
    names = meta.get("label_names")
    if names:
        columns["pred_label"] = pa.array(
            [names[i] if 0 <= i < len(names) else None for i in preds],
            type=pa.string(),
        )
    out_table = pa.table(columns)
    write_delta(out_table, args.out)
    print(
        json.dumps(
            {
                "rows": total,
                "checkpoint_step": step,
                "accuracy_vs_label_index": round(correct / total, 4),
                "out": str(args.out),
            }
        )
    )
    return 0


# --------------------------------------------------------------------------
# lm (beyond parity: transformer LM on the same Trainer machinery)
# --------------------------------------------------------------------------

def register_lm(sub: argparse._SubParsersAction) -> None:
    lm = sub.add_parser(
        "lm",
        help="train a Transformer LM on a synthetic Markov token stream "
        "(flash attention; optional expert-parallel MoE FFN)",
    )
    lm.add_argument("--vocab", type=int, default=256)
    lm.add_argument("--dim", type=int, default=128)
    lm.add_argument("--heads", type=int, default=4)
    lm.add_argument("--layers", type=int, default=2)
    lm.add_argument("--seq", type=int, default=128)
    lm.add_argument("--batch-size", type=int, default=8)
    lm.add_argument("--epochs", type=int, default=2)
    lm.add_argument("--steps-per-epoch", type=int, default=50)
    lm.add_argument("--learning-rate", type=float, default=3e-4)
    lm.add_argument(
        "--attention", choices=["flash", "reference"], default="flash",
        help="single-chip attention backend; the sequence-parallel ring "
        "path is exercised via the API / driver dry run (it needs a "
        "sequence-sharded mesh, not a batch-sharded one)",
    )
    lm.add_argument(
        "--ffn", choices=["dense", "moe"], default="dense",
        help="moe swaps every block's MLP for a top-1 routed "
        "mixture-of-experts (models/moe.py) with the load-balance aux "
        "loss folded into the objective; experts are sharded over the "
        "mesh (EP) when the device count divides --num-experts, else "
        "replicated",
    )
    lm.add_argument("--num-experts", type=int, default=8)
    lm.add_argument("--aux-loss-weight", type=float, default=0.01)
    lm.add_argument(
        "--concentration", type=float, default=0.05,
        help="Dirichlet concentration of the Markov source's transition "
        "rows; lower = more predictable = lower entropy floor",
    )
    lm.add_argument("--seed", type=int, default=0)
    lm.add_argument("--limit-val-batches", type=int, default=5)
    lm.add_argument(
        "--sample", type=int, default=0, metavar="N",
        help="after training, greedy-generate N tokens from the trained "
        "model (KV-cached decode) and report the mean TRUE-chain "
        "probability of the generated transitions - an end-to-end "
        "sanity number (uniform chance is 1/vocab)",
    )
    lm.add_argument(
        "--lr-schedule", choices=["constant", "cosine"], default=None,
        help="cosine: linear warmup then cosine decay to 0 over the "
        "run's total steps. Default: the value persisted in the "
        "checkpoint dir (flag-less --resume keeps the trained "
        "schedule's optimizer structure), else constant",
    )
    lm.add_argument(
        "--warmup-steps", type=int, default=None,
        help="warmup length for --lr-schedule cosine (default: 5%% of "
        "total steps)",
    )
    lm.add_argument("--checkpoint-dir", default=None)
    lm.add_argument("--resume", action="store_true")
    _add_resume_auto_arg(lm)
    lm.add_argument(
        "--feeder-depth", type=int, default=2,
        help="bound of the background feeder's on-device batch queue "
        "(see dsst train --feeder-depth)",
    )
    _add_health_args(lm)
    _add_tracking_args(lm, "lm")
    lm.add_argument(
        "--coordinator", default=None,
        help="host:port for multi-host rendezvous (process 0)",
    )
    lm.set_defaults(fn=_cmd_lm)


def _cmd_lm(args: argparse.Namespace) -> int:
    import optax

    from ..datagen.tokens import TokenStreamConfig, entropy_floor, token_batches
    from ..models import TransformerLM
    from ..parallel import LMTask, Trainer, TrainerConfig
    from ..runtime import initialize_distributed, local_topology, make_mesh

    initialize_distributed(coordinator_address=args.coordinator)
    topo = local_topology()

    stream = TokenStreamConfig(
        vocab_size=args.vocab,
        batch_size=args.batch_size,
        seq_len=args.seq,
        concentration=args.concentration,
        seed=args.seed,
    )
    floor = entropy_floor(stream)

    mesh = make_mesh()
    # Expert parallelism rides the same devices as DP: expert-dimension
    # operands are sharding-constrained over the "data" axis when the
    # expert count divides it (models/moe.py inserts the all-to-alls).
    n_dev = mesh.shape["data"]
    shard_experts = (
        args.ffn == "moe" and n_dev > 1 and args.num_experts % n_dev == 0
    )
    model = TransformerLM(
        vocab_size=args.vocab,
        dim=args.dim,
        num_heads=args.heads,
        num_layers=args.layers,
        max_seq=args.seq,
        attention=args.attention,
        ffn=args.ffn,
        num_experts=args.num_experts if args.ffn == "moe" else 0,
        expert_mesh=mesh if shard_experts else None,
        expert_axis="data",
    )
    # Schedule trajectory persists beside the checkpoint and resolves
    # exactly like dsst train's (shared _resolve_lr_schedule).
    lm_meta_path = (
        Path(args.checkpoint_dir) / "dsst_lm.json"
        if args.checkpoint_dir
        else None
    )
    lm_meta = (
        json.loads(lm_meta_path.read_text())
        if lm_meta_path is not None and lm_meta_path.exists()
        else {}
    )
    lr = _resolve_lr_schedule(
        args, lm_meta, total_steps=args.steps_per_epoch * args.epochs
    )
    if lm_meta_path is not None and topo.process_index == 0:
        lm_meta_path.parent.mkdir(parents=True, exist_ok=True)
        lm_meta_path.write_text(json.dumps(lm_meta))
    task = LMTask(
        model=model,
        tx=optax.adam(lr),
        aux_loss_weight=args.aux_loss_weight if args.ffn == "moe" else 0.0,
    )

    _mark_interrupted_predecessors(args)
    tracker = _open_tracker(args, "lm")
    if tracker is not None:
        tracker.log_params(_args_params(args))
        tracker.log_params({"entropy_floor": floor})

    health_cfg, quarantine = _health_config(args)
    trainer = Trainer(
        TrainerConfig(
            max_epochs=args.epochs,
            steps_per_epoch=args.steps_per_epoch,
            limit_val_batches=args.limit_val_batches,
            checkpoint_dir=args.checkpoint_dir,
            resume=args.resume,
            resume_auto=args.resume_auto,
            feeder_depth=args.feeder_depth,
            health=health_cfg,
        ),
        mesh=mesh,
        tracker=tracker,
    )

    from ..resilience.health import TrainingHealthError

    # Per-process sample seeds: every host draws a DISJOINT trajectory of
    # the SAME chain (the multi-host analogue of cur_shard/shard_count —
    # without it each process would train on identical batches and the
    # global batch would carry no extra information). Eval rides a third
    # seed range, shared across processes.
    try:
        result = trainer.fit(
            task,
            token_batches(
                stream, sample_seed=args.seed + 1 + topo.process_index
            ),
            val_data_factory=lambda: token_batches(
                stream, num_batches=args.limit_val_batches,
                sample_seed=args.seed + 100_000,
            ),
        )
    except TrainingHealthError as e:
        fail_active_tracker()
        print(json.dumps({
            "aborted": True,
            "reason": str(e),
            "diagnostic_bundle": e.bundle_path,
        }))
        return 3
    _finish_tracker(tracker)
    last = result.history[-1] if result.history else {}
    summary = {
        "steps": int(result.state.step),
        "train_loss": last.get("train_loss"),
        "val_loss": last.get("val_loss"),
        "val_ppl": last.get("val_ppl"),
        "entropy_floor_nats": round(floor, 4),
        "best_checkpoint": result.best_checkpoint_path,
    }
    if args.health_policy != "off":
        summary["skipped_steps"] = result.skipped_steps
        summary["health_rollbacks"] = result.health_rollbacks
    if args.sample > 0:
        # KV-cached greedy decode from the trained weights; scored
        # against the TRUE chain (the generator is the fixture, so the
        # sampled continuation has a computable quality number).
        import numpy as np

        import jax.numpy as jnp

        from ..datagen.tokens import transition_matrix
        from ..models import generate

        if args.seq <= 4:
            raise SystemExit(
                "--sample needs --seq > 4 (4 prompt tokens + at least "
                "one generated token must fit in max_seq)"
            )
        first = next(token_batches(
            stream, num_batches=1, sample_seed=args.seed + 200_000
        ))
        prompt = jnp.asarray(first["tokens"][:1, :4], jnp.int32)
        n = min(args.sample, args.seq - 4)
        if n < args.sample:
            summary["sample_truncated_to"] = n
        out = np.asarray(generate(
            model, {"params": result.state.params}, prompt, n_tokens=n
        ))
        t = transition_matrix(stream)
        probs = [
            float(t[int(out[0, i]), int(out[0, i + 1])])
            for i in range(3, out.shape[1] - 1)
        ]
        summary["sample_tokens"] = out[0].tolist()
        summary["sample_mean_true_prob"] = round(float(np.mean(probs)), 4)
        summary["sample_chance_prob"] = round(1.0 / args.vocab, 4)
    print(json.dumps(summary))
    return 0


# --------------------------------------------------------------------------
# hpo (the data-size playbook demo)
# --------------------------------------------------------------------------

def register_hpo(sub: argparse._SubParsersAction) -> None:
    hp_ = sub.add_parser(
        "hpo", help="distributed TPE sweep over a Lasso objective (size playbook)"
    )
    hp_.add_argument(
        "--data", default=None,
        help=".npz from `datagen regression` (shared-FS shipping); "
        "omit to generate in-process (closure shipping)",
    )
    hp_.add_argument("--bytes", type=float, default=1e6, dest="n_bytes")
    hp_.add_argument("--parallelism", type=int, default=2)
    hp_.add_argument("--max-evals", type=int, default=4)
    hp_.add_argument(
        "--workers", default=None,
        help="comma-separated trial-worker host:port addresses; runs the "
        "sweep over the RPC control plane (requires --data on a path "
        "every worker can read)",
    )
    hp_.add_argument(
        "--secret-file", default=None,
        help="file holding the shared RPC secret (or env DSST_RPC_SECRET); "
        "enables the HMAC handshake with the workers",
    )
    hp_.add_argument(
        "--max-retries", type=int, default=2,
        help="(--workers mode) transport-failure requeues per trial before "
        "it fails; objective exceptions are never retried",
    )
    hp_.add_argument(
        "--resume-auto", action="store_true",
        help="continue a killed sweep: mark this experiment's dead "
        "RUNNING runs INTERRUPTED (journal-based), reload the completed "
        "trials from the newest interrupted run's journal, and run only "
        "the remaining evals (requires tracking enabled)",
    )
    _add_tracking_args(hp_, "hpo")
    hp_.set_defaults(fn=_cmd_hpo)


def _rpc_secret(args: argparse.Namespace) -> bytes | None:
    """Shared RPC secret from --secret-file or env DSST_RPC_SECRET."""
    path = getattr(args, "secret_file", None)
    if path:
        secret = Path(path).read_bytes().strip()
        if not secret:
            raise SystemExit(f"--secret-file {path} is empty")
        return secret
    env = os.environ.get("DSST_RPC_SECRET")
    return env.encode() if env else None


def register_trial_worker(sub: argparse._SubParsersAction) -> None:
    tw = sub.add_parser(
        "trial-worker",
        help="serve HPO trial evaluations for a remote driver (one per host)",
    )
    tw.add_argument(
        "--bind", default="127.0.0.1:0",
        help="host:port to listen on (port 0 = OS-assigned, printed)",
    )
    tw.add_argument(
        "--secret-file", default=None,
        help="file holding the shared RPC secret (or env DSST_RPC_SECRET); "
        "required for non-loopback binds unless --insecure",
    )
    tw.add_argument(
        "--insecure", action="store_true",
        help="allow a non-loopback bind without a secret (trusted isolated "
        "network only; the RPC wire executes pickle on receipt)",
    )
    tw.set_defaults(fn=_cmd_trial_worker)


def _cmd_trial_worker(args: argparse.Namespace) -> int:
    from ..parallel.trials import serve_trial_worker

    serve_trial_worker(
        args.bind,
        block=True,
        secret=_rpc_secret(args),
        allow_insecure=args.insecure,
        # The user (or an orchestrator reading the pipe) needs the
        # OS-assigned port on stdout NOW — serve_forever() never
        # returns, so without the explicit flush a block-buffered pipe
        # would hold the line forever. Library callers get the module
        # logger instead.
        announce=lambda m: print(m, flush=True),
    )
    return 0


def _journaled_trials(root: str, experiment: str) -> list[dict]:
    """Completed trials of ``experiment``'s interrupted runs, rebuilt
    from their journals (``trial`` events) into the fmin store format —
    the resume state for ``dsst hpo --resume-auto``.

    Merged across ALL interrupted runs, newest first per tid: a sweep
    killed twice leaves its early trials journaled in run A and its
    later ones in run B, and progress must compound instead of the
    survivor re-running (and re-journaling) what A already paid for.
    Only the contiguous tid prefix is kept: the async pool may have
    journaled tid 3 while tid 2 died with the process, and the driver
    re-proposes from ``len(trials)`` — a gap would collide.
    """
    from ..tracking import read_journal, sweep_interrupted

    if not Path(root).is_dir():
        return []
    report = sweep_interrupted(root, experiment)
    candidates = sorted(
        (c for c in report if c["effective_status"] == "INTERRUPTED"),
        key=lambda c: c.get("start_time") or 0.0,
        reverse=True,
    )
    by_tid: dict[int, dict] = {}
    sources: list[str] = []
    for c in candidates:
        contributed = False
        for e in read_journal(c["run_dir"]):
            if e.get("event") != "trial" or int(e["tid"]) in by_tid:
                continue
            contributed = True
            by_tid[int(e["tid"])] = {
                "tid": int(e["tid"]),
                "point": dict(e.get("point") or {}),
                "result": {"loss": e.get("loss"),
                           "status": e.get("status")},
                "book_time": e.get("time"),
                "duration": 0.0,
            }
        if contributed:
            sources.append(f"{c['experiment']}/{c['run_id']}")
    trials = []
    for tid in range(len(by_tid)):
        if tid not in by_tid:
            break
        trials.append(by_tid[tid])
    if trials:
        print(
            f"hpo --resume-auto: continuing from {len(trials)} "
            f"journaled trial(s) of {', '.join(sources)}"
        )
    return trials


def _cmd_hpo(args: argparse.Namespace) -> int:
    from ..datagen.regression import gen_data, train_and_eval, tune_alpha
    from ..hpo.shipping import load_shared

    resumed: list[dict] = []
    if args.resume_auto:
        if args.no_tracking or not args.tracking_root:
            print("--resume-auto needs tracking enabled (the run journal "
                  "IS the resume state)")
            return 2
        resumed = _journaled_trials(args.tracking_root, args.experiment)

    if args.workers:
        # Remote mode: objective ships by module reference, data by
        # shared FS — the multi-host SparkTrials shape. Validate BEFORE
        # opening a tracker: a usage error must not litter an orphaned
        # RUNNING run.
        if not args.data:
            print("--workers requires --data (shared-FS npz every worker can read)")
            return 2
        tracker = _open_tracker(args, "hpo")
        import numpy as np

        from ..hpo import fmin, hp
        from ..parallel import HostTrials

        space = {
            "alpha": hp.uniform("alpha", 0.0, 10.0),
            "data_path": hp.choice("data_path", [str(args.data)]),
        }
        trials = HostTrials(
            args.workers.split(","),
            parallelism=args.parallelism,
            secret=_rpc_secret(args),
            max_retries=args.max_retries,
        )
        trials.trials.extend(resumed)
        best = fmin(
            "dss_ml_at_scale_tpu.hpo.objectives:lasso_shared",
            space,
            max_evals=args.max_evals,
            trials=trials,
            rstate=np.random.default_rng(0),
            tracker=tracker,
        )
        ok = sum(1 for t in trials.trials if t["result"]["status"] == "ok")
        _finish_tracker(
            tracker, params={"mode": "remote", "workers": args.workers}
        )
        print(
            f"hpo (remote, {len(trials.workers)} workers): best alpha "
            f"{best['alpha']:.4f} ({ok}/{len(trials.trials)} trials ok)"
        )
        return 0

    tracker = _open_tracker(args, "hpo")
    if args.data:
        arrays = load_shared(args.data)
        data = (
            arrays["X_train"], arrays["X_test"],
            arrays["y_train"], arrays["y_test"],
        )
        mode = "shared-fs"
    else:
        data = gen_data(int(args.n_bytes))
        mode = "closure"

    def objective(alpha):
        return train_and_eval(data, alpha)

    trials = None
    if resumed:
        from ..parallel import DeviceTrials

        trials = DeviceTrials(parallelism=args.parallelism)
        trials.trials.extend(resumed)
    best = tune_alpha(
        objective, parallelism=args.parallelism, max_evals=args.max_evals,
        tracker=tracker, trials=trials,
    )
    _finish_tracker(tracker, params={"mode": mode, "best_alpha": best})
    print(f"hpo ({mode}): best alpha {best:.4f}")
    return 0


# --------------------------------------------------------------------------
# shared helpers
# --------------------------------------------------------------------------

DEFAULT_TRACKING_ROOT = "dsst_runs"


def _add_tracking_args(parser, experiment: str) -> None:
    """Tracking flags with autologging ON by default.

    The reference logs every SparkTrials trial under an active MLflow run
    with zero user code (``hyperopt/1. hyperopt.py:130-136``); the
    equivalent default here is a RunStore under ./dsst_runs unless
    --no-tracking (or --tracking-root '') opts out. The env var
    DSST_TRACKING_ROOT overrides the default root (read per invocation,
    so wrappers and test harnesses can redirect every run — including
    subprocess pipelines — without threading a flag through)."""
    parser.add_argument("--experiment", default=experiment)
    root = os.environ.get("DSST_TRACKING_ROOT", DEFAULT_TRACKING_ROOT)
    parser.add_argument(
        "--tracking-root", default=root,
        help=f"run-store root (default ./{DEFAULT_TRACKING_ROOT}, or env "
        "DSST_TRACKING_ROOT)",
    )
    parser.add_argument(
        "--no-tracking", action="store_true",
        help="disable the default run/trial autologging",
    )


# The one tracker a CLI invocation may have open. cli.main closes it as
# FAILED when a command raises, so a crashed run (bad table, OOM,
# Ctrl-C) never lingers in RUNNING state in the run store.
_active_tracker = None

# The dsst argv of this invocation (cli.main stashes it before
# dispatch): journaled into each run's start event so `dsst runs doctor
# --resume` can re-execute exactly what was interrupted.
_invocation_argv: list[str] | None = None


def set_invocation_argv(argv: list[str] | None) -> None:
    global _invocation_argv
    _invocation_argv = list(argv) if argv is not None else None


def _open_tracker(args: argparse.Namespace, run_name: str):
    """RunStore for a CLI run, or None when tracking is opted out."""
    global _active_tracker
    if getattr(args, "no_tracking", False) or not getattr(
        args, "tracking_root", None
    ):
        return None
    from ..tracking import RunStore, set_run_cmdline

    set_run_cmdline(_invocation_argv)
    _active_tracker = RunStore(
        args.tracking_root, args.experiment, run_name=run_name
    )
    return _active_tracker


def fail_active_tracker() -> None:
    """Close a command's still-open run as FAILED (crash path)."""
    global _active_tracker
    if _active_tracker is not None:
        try:
            _active_tracker.finish("FAILED")
        finally:
            _active_tracker = None


def _args_params(args: argparse.Namespace) -> dict:
    """CLI invocation as loggable run params (internals and Nones dropped)."""
    skip = {"fn", "no_tracking", "tracking_root"}
    return {
        k: v for k, v in vars(args).items() if k not in skip and v is not None
    }


def _add_resume_auto_arg(parser) -> None:
    parser.add_argument(
        "--resume-auto", action="store_true",
        help="crash-only restart: resume from the newest manifest-intact "
        "checkpoint if one exists (falling back past torn steps, "
        "quarantining wreckage, sweeping stranded .tmp files), else "
        "start fresh — never errors on an empty dir and never needs a "
        "step name. Also marks this experiment's dead RUNNING runs "
        "INTERRUPTED (journal-based) before starting. The entry point "
        "watchdogs (`dsst runs doctor --resume`) and the chaos soak use",
    )


def _mark_interrupted_predecessors(args: argparse.Namespace) -> None:
    """--resume-auto's store hygiene: flip this experiment's dead-PID
    RUNNING runs to INTERRUPTED before opening a new run, so the store
    converges without waiting for an explicit doctor sweep."""
    if not getattr(args, "resume_auto", False):
        return
    if getattr(args, "no_tracking", False) or not getattr(
        args, "tracking_root", None
    ):
        return
    from ..tracking import sweep_interrupted

    if Path(args.tracking_root).is_dir():
        sweep_interrupted(args.tracking_root, args.experiment)


def _add_health_args(parser) -> None:
    """Training-health supervisor flags, shared by train and lm."""
    parser.add_argument(
        "--health-policy", choices=["off", "skip", "rollback", "abort"],
        default="off",
        help="supervise every train step with on-device non-finite "
        "(loss/grad-norm isfinite) and EWMA loss-spike detection: a bad "
        "update is discarded before commit and its batch quarantined; "
        "past a --max-consecutive-skips streak, 'skip' aborts (a fully "
        "poisoned stream must not spin) while 'rollback' restores the "
        "newest intact checkpoint (then aborts after --max-rollbacks); "
        "'abort' stops on the first bad step with a diagnostic bundle. "
        "Default off (the unsupervised loop needs no per-step verdict "
        "fetch)",
    )
    parser.add_argument(
        "--spike-zscore", type=float, default=6.0,
        help="loss-spike threshold: |loss - ewma_mean| > Z * ewma_std",
    )
    parser.add_argument(
        "--health-warmup", type=int, default=20,
        help="healthy steps observed before the spike detector arms "
        "(non-finite detection is always armed)",
    )
    parser.add_argument(
        "--max-consecutive-skips", type=int, default=3,
        help="consecutive bad steps tolerated as skips; one more "
        "escalates skip -> rollback (or abort)",
    )
    parser.add_argument(
        "--max-rollbacks", type=int, default=2,
        help="checkpoint rollbacks before the run aborts with a "
        "diagnostic bundle",
    )


def _health_config(args: argparse.Namespace):
    """``(HealthConfig | None, QuarantineList | None)`` from the flags.

    The quarantine blocklist lives next to the checkpoints
    (``<checkpoint_dir>/quarantine.jsonl``) so resume, replay, and
    ``dsst quarantine`` all find it; without a checkpoint dir, bad
    batches are still discarded and counted, just not persisted.
    """
    if getattr(args, "health_policy", "off") == "off":
        return None, None
    from ..resilience.health import HealthConfig
    from ..resilience.rollback import QuarantineList

    quarantine = None
    if getattr(args, "checkpoint_dir", None):
        quarantine = QuarantineList(
            Path(args.checkpoint_dir) / "quarantine.jsonl"
        )
    return HealthConfig(
        policy=args.health_policy,
        spike_zscore=args.spike_zscore,
        warmup_steps=args.health_warmup,
        max_consecutive_skips=args.max_consecutive_skips,
        max_rollbacks=args.max_rollbacks,
        quarantine=quarantine,
    ), quarantine


def _resolve_lr_schedule(args: argparse.Namespace, meta: dict,
                         total_steps: int):
    """Resolve --lr-schedule/--warmup-steps against persisted metadata.

    Returns the optax learning rate (float or schedule) and mutates
    ``meta`` with the full trajectory (lr_schedule, warmup_steps,
    decay_steps). A scheduled adam has a different opt_state STRUCTURE,
    and the restored step count lands ON the schedule curve — so a
    flag-less --resume must rebuild not just a schedule-shaped optimizer
    but the SAME warmup/decay trajectory, or the LR would jump
    discontinuously mid-run. Passing --lr-schedule explicitly redefines
    the trajectory from the current invocation's run length.
    """
    explicit = args.lr_schedule is not None
    schedule = args.lr_schedule if explicit else meta.get(
        "lr_schedule", "constant"
    )
    if schedule != "cosine":
        meta["lr_schedule"] = "constant"
        meta.pop("warmup_steps", None)
        meta.pop("decay_steps", None)
        return args.learning_rate

    import optax

    if explicit or "decay_steps" not in meta:
        decay = max(1, total_steps)
        warmup = (
            args.warmup_steps
            if args.warmup_steps is not None
            else max(1, decay // 20)
        )
    else:
        decay = int(meta["decay_steps"])
        warmup = (
            args.warmup_steps
            if args.warmup_steps is not None
            else int(meta.get("warmup_steps", max(1, decay // 20)))
        )
    warmup = min(warmup, decay)
    meta.update(lr_schedule="cosine", warmup_steps=warmup, decay_steps=decay)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=args.learning_rate,
        warmup_steps=warmup,
        decay_steps=decay,
    )


def _finish_tracker(tracker, params: dict | None = None,
                    metrics: dict | None = None, step: int | None = None):
    """The one place a CLI run is closed: final params/metrics, the
    telemetry archive (counter snapshot + span JSONL — what `dsst
    telemetry` reads back), FINISHED status, and the 'run ->' pointer
    the user needs to find it."""
    global _active_tracker
    if tracker is None:
        return
    if params:
        tracker.log_params(params)
    if metrics:
        tracker.log_metrics(metrics, step=step)
    from .. import telemetry

    tracker.log_telemetry()
    span_log = telemetry.get_span_log()
    if span_log.events():
        tracker.log_text(span_log.to_jsonl(), "spans.jsonl")
    tracker.finish()
    if tracker is _active_tracker:
        _active_tracker = None
    print(f"run -> {tracker.path}")


def _read_delta_pandas(path: str, columns: list[str] | None = None):
    """Whole-table read through the Delta log (no Spark; reference reads
    the same tables with ``spark.read.format("delta")``)."""
    import pyarrow.parquet as pq

    from ..data.delta import DeltaTable

    table = DeltaTable(path)
    import pyarrow as pa

    parts = [pq.read_table(uri, columns=columns) for uri in table.file_uris()]
    return pa.concat_tables(parts).to_pandas()


def register_export(sub: argparse._SubParsersAction) -> None:
    ex = sub.add_parser(
        "export",
        help="trained checkpoint → torchvision-layout .npz state dict "
        "(readable by torch-ecosystem consumers and by this CLI's own "
        "--pretrained; BN num_batches_tracked is not emitted — use "
        "load_state_dict(strict=False) on the torch side)",
    )
    ex.add_argument("--checkpoint-dir", required=True,
                    help="a dsst train checkpoint dir (dsst_model.json)")
    ex.add_argument("--out", required=True, help=".npz output path")
    ex.add_argument("--step", type=int, default=None,
                    help="explicit checkpoint step (default: best, else latest)")
    ex.set_defaults(fn=_cmd_export)


def _cmd_export(args: argparse.Namespace) -> int:
    import numpy as np

    from ..models.pretrained import export_torchvision
    from ..parallel import restore_state

    if not args.out.endswith(".npz"):
        # export_torchvision also enforces this; failing before the
        # (slow) restore gives the error immediately.
        raise SystemExit(f"--out must end in .npz (got {args.out!r})")
    resolved = _checkpoint_task(args.checkpoint_dir)
    if resolved is None:
        return 1
    _meta, crop, model, task = resolved
    sample = {
        "image": np.zeros((1, crop, crop, 3), np.float32),
        "label": np.zeros((1,), np.int32),
    }
    state, step = restore_state(task, sample, args.checkpoint_dir,
                                step=args.step)
    # Export never touches the optimizer; free its ~2x-params memory
    # before materializing the numpy copies (restore_state's guidance).
    variables = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    state = None
    exported = export_torchvision(variables, model, args.out)
    print(json.dumps({
        "checkpoint_step": step,
        "tensors": len(exported),
        "out": args.out,
    }))
    return 0


def register_serve(sub: argparse._SubParsersAction) -> None:
    sv = sub.add_parser(
        "serve",
        help="HTTP inference server over a trained checkpoint: "
        "GET /healthz + /readyz, POST /predict (raw JPEG body or JSON "
        '{"instances": ["<base64 jpeg>", ...]}); scheduler-mediated '
        "scoring (bounded admission queue, cross-request dynamic "
        "batching into one fixed-shape compiled scorer, graceful "
        "drain), label names from the trained vocabulary",
    )
    sv.add_argument("--checkpoint-dir", required=True,
                    help="a dsst train checkpoint dir (dsst_model.json)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8008)
    sv.add_argument("--step", type=int, default=None,
                    help="explicit checkpoint step (default: best, else latest)")
    sv.add_argument("--micro-batch", type=int, default=8,
                    help="compiled scoring batch; the batcher coalesces "
                    "waiting images across requests up to it")
    sv.add_argument(
        "--queue-depth", type=int, default=64,
        help="max admitted-but-unscored images; beyond it requests get "
        "429 with a measured Retry-After",
    )
    sv.add_argument(
        "--batch-window-ms", type=float, default=5.0,
        help="max wait for an under-filled batch to gain company — the "
        "latency/throughput dial of the cross-request batcher",
    )
    sv.add_argument(
        "--deadline-ms", type=float, default=2000.0,
        help="per-request deadline: work not scored in time is dropped "
        "with 503 instead of scored late (0 disables)",
    )
    sv.add_argument(
        "--drain-timeout", type=float, default=10.0,
        help="graceful-shutdown bound: seconds to finish queued work "
        "after Ctrl-C before the server closes anyway",
    )
    sv.add_argument(
        "--decode-workers", type=int, default=2,
        help="JPEG decode threads feeding the batcher (host-side work, "
        "off the scoring thread)",
    )
    sv.add_argument(
        "--access-log", default=None, metavar="JSONL",
        help="structured request log: one JSON line per /predict "
        "(request_id matching the X-DSST-Trace response header, "
        "status, queue_ms, batch_fill)",
    )
    sv.set_defaults(fn=_cmd_serve)


def _cmd_serve(args: argparse.Namespace) -> int:
    from ..serving import SchedulerConfig
    from ..workloads.serving import Predictor, serve_in_thread

    # Resolve the metadata FIRST (narrowly scoped corrupt-meta
    # diagnosis, same as predict/export); a KeyError from the much
    # larger Predictor construction below — e.g. an orbax tree that
    # doesn't match the model — must NOT be misattributed to
    # dsst_model.json. The resolved tuple is handed to Predictor so
    # startup resolves the checkpoint exactly once.
    resolved = _checkpoint_task(args.checkpoint_dir)
    if resolved is None:
        return 1
    try:
        predictor = Predictor(args.checkpoint_dir, step=args.step,
                              micro_batch=args.micro_batch,
                              resolved=resolved)
    except FileNotFoundError as e:
        # Missing orbax steps: print the diagnosis and exit like
        # predict/export, no traceback.
        print(e)
        return 1
    config = SchedulerConfig(
        queue_depth=args.queue_depth,
        batch_window_ms=args.batch_window_ms,
        deadline_ms=args.deadline_ms,
        drain_timeout_s=args.drain_timeout,
        decode_workers=args.decode_workers,
    )
    # The accept loop runs in the handle's thread so Ctrl-C lands here,
    # where close() can drain WHILE the server still answers (/readyz
    # flips 503, queued work finishes, in-flight responses complete).
    handle = serve_in_thread(predictor, args.host, args.port, config=config,
                             access_log=args.access_log)
    print(json.dumps({
        "serving": handle.address,
        "model": predictor.meta.get("model"),
        "checkpoint_step": predictor.step,
        "crop": predictor.crop,
        "micro_batch": predictor.micro_batch,
        "queue_depth": config.queue_depth,
        "batch_window_ms": config.batch_window_ms,
        "deadline_ms": config.deadline_ms,
    }), flush=True)
    try:
        while handle.thread.is_alive():
            handle.thread.join(1.0)
    except KeyboardInterrupt:
        print(json.dumps({"draining": True,
                          "pending_images": handle.scheduler.pending}),
              flush=True)
    finally:
        handle.close(args.drain_timeout)
    return 0


def register_serve_lm(sub: argparse._SubParsersAction) -> None:
    sv = sub.add_parser(
        "serve-lm",
        help="HTTP token-streaming LM server: continuous-batching decode "
        "over preallocated KV slots; POST /generate streams one chunked "
        "NDJSON line per token (plus a terminal done-line carrying the "
        "trace id), GET /healthz + /readyz + /slo ride the same "
        "keep-alive handler as dsst serve",
    )
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8008)
    sv.add_argument(
        "--slots", type=int, default=8,
        help="preallocated KV slots — the max generations decoding "
        "concurrently in one slot_decode dispatch",
    )
    sv.add_argument(
        "--max-len", type=int, default=256,
        help="per-slot KV capacity; prompt + max_new_tokens beyond it "
        "is rejected with 400 before admission",
    )
    sv.add_argument(
        "--prefill-buckets", default="16,32,64", metavar="CSV",
        help="padded prompt lengths the prefill program compiles for; "
        "a prompt is padded up to the smallest bucket that fits",
    )
    sv.add_argument(
        "--queue-depth", type=int, default=32,
        help="max admitted-but-unslotted generations; beyond it "
        "requests get 429 with a measured Retry-After",
    )
    sv.add_argument(
        "--deadline-ms", type=float, default=0.0,
        help="per-generation deadline: a slot past it is retired with "
        "a streamed error instead of decoding late (0 disables); also "
        "arms the ttft_p99 SLO budget",
    )
    sv.add_argument(
        "--inter-token-budget-ms", type=float, default=0.0,
        help="arms the inter_token_p99 SLO budget (0 leaves it "
        "informational)",
    )
    sv.add_argument(
        "--drain-timeout", type=float, default=10.0,
        help="graceful-shutdown bound: seconds for in-flight streams "
        "to finish after Ctrl-C before the server closes anyway",
    )
    sv.add_argument(
        "--stub", action="store_true",
        help="serve the deterministic stub decoder instead of a "
        "TransformerLM — the full engine + streaming stack with no "
        "device work (what the chaos/CI harnesses spawn)",
    )
    sv.add_argument(
        "--step-ms", type=float, default=2.0,
        help="stub-only: simulated wall time of one decode step "
        "(charged once per step, not per active slot)",
    )
    sv.add_argument("--vocab", type=int, default=256,
                    help="model/stub vocabulary size")
    sv.add_argument("--dim", type=int, default=128)
    sv.add_argument("--heads", type=int, default=4)
    sv.add_argument("--layers", type=int, default=2)
    sv.add_argument("--attention", choices=["flash", "reference"],
                    default="reference")
    sv.add_argument("--seed", type=int, default=0,
                    help="init seed for the random-weight TransformerLM "
                    "(no LM checkpoint format yet; serving a trained LM "
                    "is gated on the lm checkpoint loader)")
    sv.add_argument(
        "--access-log", default=None, metavar="JSONL",
        help="structured request log: one JSON line per /generate "
        "(request_id matching the X-DSST-Trace header and the "
        "done-line's trace field, status, tokens, ttft_ms)",
    )
    _add_tracking_args(sv, "serve-lm")
    sv.set_defaults(fn=_cmd_serve_lm)


def _cmd_serve_lm(args: argparse.Namespace) -> int:
    from ..serving.lm import LMConfig, LMEngine, StubLMDecoder
    from ..workloads.serving import serve_lm_in_thread

    try:
        buckets = tuple(
            int(b) for b in str(args.prefill_buckets).split(",") if b
        )
        config = LMConfig(
            slots=args.slots,
            max_len=args.max_len,
            prefill_buckets=buckets,
            queue_depth=args.queue_depth,
            deadline_ms=args.deadline_ms,
            inter_token_budget_ms=args.inter_token_budget_ms,
            drain_timeout_s=args.drain_timeout,
        )
    except ValueError as e:
        print(e)
        return 1
    if args.stub:
        decoder = StubLMDecoder(
            vocab_size=args.vocab, step_ms=args.step_ms,
            slots=args.slots, max_len=args.max_len,
            buckets=config.prefill_buckets,
        )
    else:
        import jax
        import jax.numpy as jnp

        from ..models import TransformerLM
        from ..serving.lm import TransformerDecoder

        model = TransformerLM(
            vocab_size=args.vocab, dim=args.dim, num_heads=args.heads,
            num_layers=args.layers, max_seq=args.max_len,
            attention=args.attention,
        )
        variables = model.init(
            jax.random.PRNGKey(args.seed),
            jnp.zeros((1, config.prefill_buckets[0]), jnp.int32),
        )
        decoder = TransformerDecoder(
            model, variables, slots=args.slots, max_len=args.max_len,
            buckets=config.prefill_buckets,
        )
    # The tracker's journaled start event (pid + boot id) is what lets
    # `dsst runs doctor` classify a SIGKILL'd replica as INTERRUPTED —
    # the chaos drill's whole observability story.
    tracker = _open_tracker(args, "serve-lm")
    if tracker is not None:
        tracker.log_params(_args_params(args))
    engine = LMEngine(decoder, config).start()
    handle = serve_lm_in_thread(engine, args.host, args.port,
                                access_log=args.access_log)
    print(json.dumps({
        "serving": handle.address,
        "port": handle.port,
        "decoder": type(decoder).__name__,
        "slots": config.slots,
        "max_len": config.max_len,
        "prefill_buckets": list(config.prefill_buckets),
        "queue_depth": config.queue_depth,
        "deadline_ms": config.deadline_ms,
    }), flush=True)
    try:
        while handle.thread.is_alive():
            handle.thread.join(1.0)
    except KeyboardInterrupt:
        print(json.dumps({"draining": True, "pending": engine.pending}),
              flush=True)
    finally:
        handle.close(args.drain_timeout)
        _finish_tracker(tracker)
    return 0


def register_checkpoints(sub: argparse._SubParsersAction) -> None:
    ck = sub.add_parser(
        "checkpoints",
        help="checkpoint maintenance: verify per-step integrity manifests",
    )
    csub = ck.add_subparsers(dest="checkpoints_cmd", required=True)
    vf = csub.add_parser(
        "verify",
        help="walk a checkpoint dir's steps and report intact / corrupt / "
        "unverified per the dsst_manifest.json content checksums — the "
        "operator-facing face of the restore-fallback integrity layer",
    )
    vf.add_argument("dir", help="a dsst train/lm checkpoint directory")
    vf.add_argument(
        "--json", action="store_true",
        help="emit the full report as one JSON document instead of lines",
    )
    vf.set_defaults(fn=_cmd_checkpoints_verify)


def _cmd_checkpoints_verify(args: argparse.Namespace) -> int:
    from ..resilience import verify_checkpoint_dir

    if not Path(args.dir).is_dir():
        print(f"no such checkpoint directory: {args.dir}")
        return 2
    report = verify_checkpoint_dir(args.dir)
    counts = {"intact": 0, "corrupt": 0, "unverified": 0}
    for entry in report:
        counts[entry["status"]] += 1
    if args.json:
        print(json.dumps({"dir": args.dir, "steps": report, **counts}))
    else:
        if not report:
            print(f"no checkpoint steps under {args.dir}")
        for entry in report:
            line = f"step {entry['step']}: {entry['status']}"
            if entry["problems"]:
                line += " (" + "; ".join(entry["problems"]) + ")"
            print(line)
        if report:
            print(
                f"{counts['intact']} intact, {counts['corrupt']} corrupt, "
                f"{counts['unverified']} unverified (no manifest)"
            )
    return 1 if counts["corrupt"] else 0


def register_quarantine(sub: argparse._SubParsersAction) -> None:
    qr = sub.add_parser(
        "quarantine",
        help="manage the poison-batch blocklist written by the training "
        "health supervisor (rows excluded from replay/resume)",
    )
    qsub = qr.add_subparsers(dest="quarantine_cmd", required=True)

    target_help = (
        "a quarantine .jsonl file, or a checkpoint dir containing "
        "quarantine.jsonl (where `dsst train --health-policy` writes it)"
    )
    ls = qsub.add_parser(
        "list", help="print quarantined row ranges, one JSON line each"
    )
    ls.add_argument("target", help=target_help)
    ls.set_defaults(fn=_cmd_quarantine_list)

    cl = qsub.add_parser(
        "clear",
        help="drop every entry (the rows rejoin the next replay/resume)",
    )
    cl.add_argument("target", help=target_help)
    cl.set_defaults(fn=_cmd_quarantine_clear)


def _quarantine_target(target: str) -> Path:
    p = Path(target)
    return p / "quarantine.jsonl" if p.is_dir() else p


def _cmd_quarantine_list(args: argparse.Namespace) -> int:
    from ..resilience.rollback import QuarantineList

    path = _quarantine_target(args.target)
    if not path.exists():
        print(f"no quarantine list at {path}")
        return 1
    q = QuarantineList(path)
    rows = 0
    for entry in q.entries:
        rows += int(entry["row_hi"]) - int(entry["row_lo"])
        print(json.dumps(entry))
    print(f"{len(q)} entries, {rows} rows quarantined ({path})",
          file=sys.stderr)
    return 0


def _cmd_quarantine_clear(args: argparse.Namespace) -> int:
    from ..resilience.rollback import QuarantineList

    path = _quarantine_target(args.target)
    if not path.exists():
        print(f"no quarantine list at {path}")
        return 1
    n = QuarantineList(path).clear()
    print(f"cleared {n} entries from {path}")
    return 0


def register_runs(sub: argparse._SubParsersAction) -> None:
    rn = sub.add_parser(
        "runs",
        help="browse the tracking store (the mlflow-ui equivalent for a "
        "plain-FS root): list runs, show one run's params/metrics",
    )
    rsub = rn.add_subparsers(dest="runs_cmd", required=True)
    # Same flag name, default, and env override as every writing command
    # (_add_tracking_args), so the browser reads where the writers wrote.
    root = os.environ.get("DSST_TRACKING_ROOT", DEFAULT_TRACKING_ROOT)
    root_help = (
        f"run-store root (default ./{DEFAULT_TRACKING_ROOT}, or env "
        "DSST_TRACKING_ROOT)"
    )

    ls = rsub.add_parser("list", help="one JSON line per run, newest first")
    ls.add_argument("--tracking-root", default=root, help=root_help)
    ls.add_argument("--experiment", default=None)
    ls.set_defaults(fn=_cmd_runs_list)

    sh = rsub.add_parser(
        "show", help="full record of one run (meta, params, last metrics)"
    )
    sh.add_argument("run", help="EXPERIMENT/RUN_ID (as `runs list` prints)")
    sh.add_argument("--tracking-root", default=root, help=root_help)
    sh.set_defaults(fn=_cmd_runs_show)

    dr = rsub.add_parser(
        "doctor",
        help="crash-only store sweep: classify every run from its "
        "journal (PID + boot id), durably mark dead RUNNING runs "
        "INTERRUPTED, clean stranded .tmp files, and report resumable "
        "checkpoints; --resume relaunches each interrupted run's "
        "recorded command with --resume-auto",
    )
    dr.add_argument("--tracking-root", default=root, help=root_help)
    dr.add_argument("--experiment", default=None)
    dr.add_argument(
        "--json", action="store_true",
        help="emit the full classification report as one JSON document",
    )
    dr.add_argument(
        "--resume", action="store_true",
        help="after the sweep, re-execute the recorded dsst command of "
        "each interrupted run that has a resumable checkpoint (or a "
        "journaled HPO trial log), with --resume-auto ensured — "
        "sequentially, newest run per checkpoint dir first; what "
        "tpu_watchdog.sh runs so a recovered TPU VM re-enters training "
        "instead of idling",
    )
    dr.set_defaults(fn=_cmd_runs_doctor)


def _cmd_runs_list(args: argparse.Namespace) -> int:
    from ..tracking import list_runs

    runs = list_runs(args.tracking_root, args.experiment)
    for meta in runs:
        print(json.dumps(meta))
    if not runs:
        print(f"no runs under {args.tracking_root}"
              + (f" (experiment {args.experiment})" if args.experiment
                 else ""),
              file=sys.stderr)
    return 0


def _cmd_runs_show(args: argparse.Namespace) -> int:
    from ..tracking import load_run

    if "/" not in args.run:
        print(f"expected EXPERIMENT/RUN_ID, got {args.run!r}")
        return 1
    experiment, run_id = args.run.split("/", 1)
    try:
        print(json.dumps(
            load_run(args.tracking_root, experiment, run_id), indent=1
        ))
    except (OSError, json.JSONDecodeError, KeyError):
        # Missing run, stray file in the path, a truncated meta.json
        # from a killed writer, or a metrics line missing name/value/step
        # (foreign writer) — same friendly diagnosis either way.
        print(f"no readable run {args.run} under {args.tracking_root}")
        return 1
    return 0


def _cmd_runs_doctor(args: argparse.Namespace) -> int:
    from ..tracking import sweep_interrupted

    if not Path(args.tracking_root).is_dir():
        print(f"no run store at {args.tracking_root}")
        return 0
    report = sweep_interrupted(args.tracking_root, args.experiment)
    if args.json:
        print(json.dumps({"root": str(args.tracking_root),
                          "runs": report}))
    else:
        for cls in report:
            line = (
                f"{cls['experiment']}/{cls['run_id']}: "
                f"{cls['effective_status']}"
            )
            if cls.get("marked"):
                line += f" (was RUNNING, pid {cls['pid']} dead; marked)"
            if cls.get("resumable_step") is not None:
                line += (
                    f" — resumable: step {cls['resumable_step']} in "
                    f"{cls['checkpoint_dir']}"
                )
            if (
                cls["effective_status"] == "INTERRUPTED"
                and cls.get("trace_file")
                and Path(cls["trace_file"]).exists()
            ):
                line += (
                    f" — flight recorder: {cls['trace_file']} "
                    "(dsst trace tail)"
                )
            if (
                cls["effective_status"] == "INTERRUPTED"
                and cls.get("firing_alerts")
            ):
                line += (
                    " — SLO alerts firing at death: "
                    + ", ".join(cls["firing_alerts"])
                )
            print(line)
        n_marked = sum(1 for c in report if c.get("marked"))
        print(
            f"{len(report)} run(s), {n_marked} newly marked INTERRUPTED, "
            f"{sum(1 for c in report if c.get('resumable_step') is not None)}"
            " resumable"
        )
    if not args.resume:
        return 0
    return _doctor_resume(report)


def _doctor_resume(report: list[dict]) -> int:
    """Re-execute interrupted runs' recorded commands with --resume-auto.

    One relaunch per checkpoint dir (the newest run wins — older
    interrupted runs of the same dir are superseded by the resumed one);
    journal-only HPO runs resume once per experiment. Sequential on
    purpose: on a freshly recovered TPU VM the device lease is single-
    owner.
    """
    import subprocess

    resumable = [
        c for c in report
        if c["effective_status"] == "INTERRUPTED" and c.get("cmdline")
        and (c.get("resumable_step") is not None
             or c.get("checkpoint_dir")  # journaled at fit start: a run
             # killed before its first committed step revives as a
             # fresh --resume-auto start instead of idling
             or _journal_has_trials(c["run_dir"]))
    ]
    resumable.sort(key=lambda c: c.get("start_time") or 0.0, reverse=True)
    seen_targets: set[str] = set()
    rc = 0
    for cls in resumable:
        target = cls.get("checkpoint_dir") or f"exp:{cls['experiment']}"
        if target in seen_targets:
            continue
        seen_targets.add(target)
        argv = _resume_argv(cls["cmdline"])
        if argv is None:
            continue
        print(f"doctor --resume: {cls['experiment']}/{cls['run_id']} -> "
              + " ".join(argv))
        # DSST_FAULT_PLAN must not leak into revived runs: cli.main
        # exports it on every armed invocation, so a doctor running in
        # a post-chaos environment would otherwise re-arm the very
        # faults (including kN self-kills) that interrupted the run.
        env = {k: v for k, v in os.environ.items()
               if k != "DSST_FAULT_PLAN"}
        # Relative --data/--checkpoint-dir/--tracking-root in the
        # recorded argv only mean what they meant from the dying
        # process's working directory — the journal records it, so the
        # revival runs there, not wherever the doctor happens to be.
        cwd = cls.get("cwd")
        if cwd and not os.path.isdir(cwd):
            print(f"doctor --resume: recorded cwd {cwd} is gone; "
                  "skipping " + cls["run_id"])
            rc = rc or 1
            continue
        proc = subprocess.run(
            [sys.executable, "-m", "dss_ml_at_scale_tpu.config.cli",
             *argv],
            env=env,
            cwd=cwd,
        )
        rc = rc or proc.returncode
    if not resumable:
        print("doctor --resume: nothing resumable")
    return rc


def _journal_has_trials(run_dir: str) -> bool:
    from ..tracking import read_journal

    return any(e.get("event") == "trial" for e in read_journal(run_dir))


def _resume_argv(cmdline: list[str]) -> list[str] | None:
    """Recorded dsst argv → relaunch argv: --resume-auto ensured for the
    resumable subcommands, --fault-plan stripped (a chaos-armed run must
    not re-arm its own faults on doctor revival)."""
    argv: list[str] = []
    skip_next = False
    for tok in cmdline:
        if skip_next:
            skip_next = False
            continue
        if tok == "--fault-plan":
            skip_next = True
            continue
        if tok.startswith("--fault-plan="):
            continue
        argv.append(tok)
    subcommands = {"train", "lm", "hpo"}
    if not any(tok in subcommands for tok in argv):
        return None
    if "--resume-auto" not in argv:
        argv.append("--resume-auto")
    return argv


def register_chaos(sub: argparse._SubParsersAction) -> None:
    ch = sub.add_parser(
        "chaos",
        help="SIGKILL chaos soak: run dsst train/hpo/serve as "
        "subprocesses, hard-kill them on a seeded schedule (including "
        "inside the checkpoint-save window via kN fs.* fault entries), "
        "restart with --resume-auto, and assert the crash-only "
        "invariants (bitwise final-params parity with an uninterrupted "
        "run, clean manifest walk, zero stranded .tmp files, every run "
        "terminal)",
    )
    ch.add_argument("--workdir", required=True,
                    help="scratch directory for data/checkpoints/runs/logs")
    ch.add_argument("--workload", choices=["train", "hpo", "serve"],
                    default="train")
    ch.add_argument("--cycles", type=int, default=5,
                    help="SIGKILL cycles before the final uninterrupted run")
    ch.add_argument("--seed", type=int, default=0)
    ch.add_argument("--kill-min", type=float, default=1.0,
                    help="delay-mode kill window lower bound (seconds)")
    ch.add_argument("--kill-max", type=float, default=6.0)
    ch.add_argument("--epochs", type=int, default=3)
    ch.add_argument("--rows", type=int, default=48)
    ch.add_argument("--batch-size", type=int, default=16)
    ch.add_argument("--image-size", type=int, default=32)
    ch.add_argument("--max-evals", type=int, default=8,
                    help="(hpo workload) sweep size")
    ch.add_argument("--checkpoint-dir", default=None,
                    help="(serve workload) trained checkpoint to serve")
    ch.add_argument("--timeout", type=float, default=300.0,
                    help="per-child wall bound (seconds)")
    ch.add_argument("--json", action="store_true",
                    help="emit the full soak report as one JSON document")
    ch.set_defaults(fn=_cmd_chaos)


def _cmd_chaos(args: argparse.Namespace) -> int:
    from ..resilience.chaos import ChaosConfig, run_chaos

    report = run_chaos(ChaosConfig(
        workdir=args.workdir,
        workload=args.workload,
        cycles=args.cycles,
        seed=args.seed,
        kill_min_s=args.kill_min,
        kill_max_s=args.kill_max,
        epochs=args.epochs,
        rows=args.rows,
        batch_size=args.batch_size,
        image_size=args.image_size,
        max_evals=args.max_evals,
        checkpoint_dir=args.checkpoint_dir,
        timeout_s=args.timeout,
    ))
    if args.json:
        print(json.dumps(report))
    else:
        for c in report.get("cycles", []):
            print(f"cycle {c.get('cycle')}: mode={c.get('mode')} "
                  f"rc={c.get('returncode')} wall={c.get('wall_s')}s")
        for name, res in report["invariants"].items():
            print(f"invariant {name}: {'OK' if res.get('ok') else 'FAIL'}"
                  + ("" if res.get("ok") else f" {json.dumps(res)}"))
        print(f"chaos soak: {'OK' if report['ok'] else 'FAILED'}")
    return 0 if report["ok"] else 1


def register_telemetry(sub: argparse._SubParsersAction) -> None:
    tl = sub.add_parser(
        "telemetry",
        help="inspect a run's archived telemetry snapshot and export "
        "span logs as Chrome/Perfetto traces",
    )
    tl.add_argument(
        "--run", default=None, metavar="DIR",
        help="run directory (<root>/<experiment>/<run_id>, as `runs "
        "list` points at) whose telemetry.json to print",
    )
    tl.add_argument(
        "--json", action="store_true",
        help="print the raw snapshot JSON instead of a table",
    )
    tl.add_argument(
        "--export-perfetto", default=None, metavar="OUT",
        help="write a Chrome trace_event JSON (loads in ui.perfetto.dev) "
        "converted from a span JSONL (--spans, or the --run's archived "
        "artifacts/spans.jsonl)",
    )
    tl.add_argument(
        "--spans", default=None, metavar="JSONL",
        help="span JSONL to convert (default: <--run>/artifacts/spans.jsonl)",
    )
    tl.set_defaults(fn=_cmd_telemetry)


def _cmd_telemetry(args: argparse.Namespace) -> int:
    did_something = False
    rc = 0
    # Snapshot first: a missing/empty span archive must not swallow a
    # perfectly readable telemetry.json.
    if args.run:
        snap_file = Path(args.run) / "telemetry.json"
        if not snap_file.exists():
            print(f"no telemetry.json under {args.run} (was the run "
                  "finished by a telemetry-aware dsst?)")
            rc = 1
        else:
            snapshot = json.loads(snap_file.read_text())
            if args.json:
                print(json.dumps(snapshot, indent=1))
            else:
                _print_snapshot_table(snapshot)
            did_something = True
    if args.export_perfetto:
        from ..telemetry import export_perfetto

        spans = args.spans or (
            str(Path(args.run) / "artifacts" / "spans.jsonl")
            if args.run else None
        )
        if spans is None:
            print("--export-perfetto needs --spans (or --run with an "
                  "archived spans.jsonl)")
            return 2
        if not Path(spans).exists():
            print(f"no span log at {spans}")
            return 1
        n = export_perfetto(spans, args.export_perfetto)
        print(f"perfetto trace: {n} events -> {args.export_perfetto}")
        did_something = True
    if not did_something and rc == 0:
        print("nothing to do: pass --run and/or --export-perfetto")
        return 2
    return rc


def _print_snapshot_table(snapshot: dict) -> None:
    rows = []
    for m in snapshot.get("metrics", []):
        labels = m.get("labels") or {}
        name = m["name"] + (
            "{" + ",".join(f'{k}={v}' for k, v in labels.items()) + "}"
            if labels else ""
        )
        if m.get("type") == "histogram":
            count = m.get("count", 0)
            mean = (m.get("sum", 0.0) / count) if count else 0.0
            value = (f"count={count} sum={m.get('sum', 0.0):.6g} "
                     f"mean={mean:.6g}")
        elif m.get("type") == "window":
            qs = " ".join(
                f"p{float(q) * 100:g}="
                + (f"{v:.6g}" if v is not None else "-")
                for q, v in sorted(m.get("quantiles", {}).items())
            )
            value = (f"count={m.get('count', 0)} {qs} "
                     f"[{m.get('window_s', 0):g}s window]")
        else:
            value = f"{m.get('value', 0.0):.6g}"
        rows.append((name, m.get("type", "?"), value))
    if not rows:
        print("(empty snapshot)")
        return
    width = max(len(r[0]) for r in rows)
    print(f"{'METRIC':<{width}}  {'TYPE':<9}  VALUE")
    for name, kind, value in rows:
        print(f"{name:<{width}}  {kind:<9}  {value}")


def register_lint(sub: argparse._SubParsersAction) -> None:
    ln = sub.add_parser(
        "lint",
        help="run the JAX-aware static-analysis suite (trace-safety, "
        "retrace hazards, host-sync-in-hotpath, lock discipline, "
        "registries) over the package",
    )
    ln.add_argument(
        "--rules", default=None, metavar="R1,R2",
        help="comma-separated subset of rules to run (default: all; "
        "see --list-rules)",
    )
    ln.add_argument(
        "--json", action="store_true",
        help="machine-readable output (schema documented in README "
        "'Static analysis'; stable across versions via its 'version' "
        "field) instead of text",
    )
    ln.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline file of accepted pre-existing findings "
        "(default: LINT_BASELINE.json at the repo root)",
    )
    ln.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings: existing "
        "entries keep their authored reason, new ones take --reason, "
        "stale ones are dropped",
    )
    ln.add_argument(
        "--reason", default=None, metavar="TEXT",
        help="justification recorded for entries newly added by "
        "--update-baseline (mandatory when any exist)",
    )
    ln.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    ln.add_argument(
        "--changed", nargs="?", const="HEAD", default=None, metavar="REF",
        help="lint only files changed vs the given git ref (default "
        "HEAD: staged+unstaged+untracked) — the fast pre-commit mode. "
        "Whole-package registry rules (telemetry-registry, fault-sites) "
        "are skipped: they reconcile call sites against a registry "
        "across ALL files and would misfire on a subset",
    )
    ln.set_defaults(fn=_cmd_lint)


def _cmd_lint(args: argparse.Namespace) -> int:
    from ..analysis import (
        DEFAULT_BASELINE,
        LintUsageError,
        checker_catalog,
        load_baseline,
        run_lint,
        write_baseline,
    )

    try:
        if args.list_rules:
            for name, desc in checker_catalog():
                print(f"{name:20s} {desc}")
            return 0
        rules = (
            [r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None
        )
        baseline = (
            Path(args.baseline) if args.baseline else DEFAULT_BASELINE
        )
        paths = None
        if args.changed is not None:
            if args.update_baseline:
                raise LintUsageError(
                    "--changed cannot --update-baseline: a partial scan "
                    "must never rewrite the whole-package baseline"
                )
            paths = _changed_python_files(args.changed)
            if not paths and not args.json:
                # --json keeps its machine contract even on an empty
                # change set: fall through to an empty-scope run so
                # stdout is still one parseable document.
                print(f"dsst lint --changed {args.changed}: no changed "
                      "Python files in scope; nothing to lint")
                return 0
        res = run_lint(rules, baseline_path=baseline, paths=paths)
        if args.update_baseline:
            # Everything currently reported (active + already-baselined)
            # becomes the new baseline; stale keys simply don't survive
            # the rewrite. Entries of rules OUTSIDE this run's selection
            # are preserved verbatim — a --rules subset update must not
            # wipe what it never re-checked.
            old = load_baseline(baseline)
            selected = set(res.rules) | {"suppression"}
            preserved = {
                k: e for k, e in old.items()
                if e.get("rule") not in selected
            }
            added = write_baseline(
                baseline, res.findings + res.baselined, old, args.reason,
                preserved=preserved,
            )
            print(
                f"baseline {baseline}: {len(res.findings)} added "
                f"({added} with new reason), {len(res.baselined)} kept, "
                f"{len(preserved)} preserved (other rules), "
                f"{len(res.stale_baseline)} stale dropped"
            )
            return 0
        print(res.render_json() if args.json else res.render_text())
        # Exit codes are part of the CI contract: 0 clean, 1 findings
        # (or stale baseline ballast), 2 usage error.
        return res.exit_code
    except LintUsageError as e:
        print(f"dsst lint: {e}", file=sys.stderr)
        return 2


def _changed_python_files(ref: str) -> list:
    """Package/scripts ``.py`` files changed vs ``ref`` (plus untracked
    ones) — the ``dsst lint --changed`` scope. Deleted files drop out
    naturally (they no longer exist to lint)."""
    import subprocess

    from ..analysis.core import REPO_ROOT, default_roots

    def git(*argv: str) -> list[str]:
        out = subprocess.run(
            ["git", *argv], cwd=REPO_ROOT, capture_output=True, text=True,
        )
        if out.returncode != 0:
            from ..analysis import LintUsageError

            raise LintUsageError(
                f"git {' '.join(argv)} failed: {out.stderr.strip()}"
            )
        return [line for line in out.stdout.splitlines() if line.strip()]

    names = set(git("diff", "--name-only", ref))
    names.update(git("ls-files", "--others", "--exclude-standard"))
    # Scope to the lint scan roots so --changed and the full scan agree
    # on what is lintable — derived, not hardcoded, so a new scan root
    # is picked up here automatically.
    prefixes = []
    for _, root in default_roots():
        try:
            rel = Path(root).resolve().relative_to(REPO_ROOT).as_posix()
        except ValueError:
            continue
        prefixes.append(rel + "/")
    out = []
    for name in sorted(names):
        p = REPO_ROOT / name
        if p.suffix == ".py" and p.exists() and name.startswith(
            tuple(prefixes)
        ):
            out.append(p)
    return out


def register_audit(sub: argparse._SubParsersAction) -> None:
    au = sub.add_parser(
        "audit",
        help="IR-level program audit: trace the registry of real "
        "compiled entrypoints on an abstract 8-device mesh and check "
        "donation, dtypes, collectives, host callbacks, and the "
        "compiled-program baseline (AUDIT_BASELINE.json)",
    )
    au.add_argument(
        "--entrypoints", default=None, metavar="E1,E2",
        help="comma-separated subset of registry entrypoints "
        "(default: all; see --list-entrypoints)",
    )
    au.add_argument(
        "--rules", default=None, metavar="R1,R2",
        help="comma-separated subset of audit rules (default: all; "
        "see --list-rules)",
    )
    au.add_argument(
        "--json", action="store_true",
        help="machine-readable output (schema documented in README "
        "'Program audit') instead of text",
    )
    au.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="program/finding baseline (default: AUDIT_BASELINE.json "
        "at the repo root)",
    )
    au.add_argument(
        "--update-baseline", action="store_true",
        help="re-pin every entrypoint's program hash and cost budgets "
        "to the current build and rewrite accepted findings (existing "
        "entries keep their authored reason, new ones take --reason)",
    )
    au.add_argument(
        "--reason", default=None, metavar="TEXT",
        help="justification recorded for entries newly added by "
        "--update-baseline (mandatory when any exist)",
    )
    au.add_argument(
        "--list-rules", action="store_true",
        help="print the audit rule catalog and exit",
    )
    au.add_argument(
        "--list-entrypoints", action="store_true",
        help="print the entrypoint registry and exit",
    )
    au.set_defaults(fn=_cmd_audit)


def _cmd_audit(args: argparse.Namespace) -> int:
    # The abstract mesh needs >=8 devices; on a CPU host that means
    # multiplexing the host platform BEFORE backend init. Setting the
    # flag is safe even if another backend wins (TPU hosts have >=8
    # real devices; default_audit_mesh validates either way).
    import os

    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()

    from ..analysis.audit import (
        DEFAULT_AUDIT_BASELINE,
        AuditUsageError,
        entrypoint_names,
        load_audit_baseline,
        rule_catalog,
        run_audit,
        write_audit_baseline,
    )

    try:
        if args.list_rules:
            for name, desc in rule_catalog():
                print(f"{name:22s} {desc}")
            return 0
        if args.list_entrypoints:
            for name in entrypoint_names():
                print(name)
            return 0
        entrypoints = (
            [e.strip() for e in args.entrypoints.split(",") if e.strip()]
            if args.entrypoints else None
        )
        rules = (
            [r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None
        )
        baseline = (
            Path(args.baseline) if args.baseline
            else DEFAULT_AUDIT_BASELINE
        )
        if args.update_baseline and (entrypoints or rules):
            # Same contract as `lint --changed`: the baseline is a
            # whole-registry truth. write_audit_baseline rebuilds
            # 'programs' from this run alone, so a subset update would
            # silently drop every pin (and, under --rules without
            # program-baseline, every cost budget) it didn't re-check.
            raise AuditUsageError(
                "--update-baseline needs the full audit: an "
                "--entrypoints/--rules subset must never rewrite the "
                "whole-registry baseline"
            )
        res = run_audit(entrypoints, rules=rules, baseline_path=baseline)
        if args.update_baseline:
            old = load_audit_baseline(baseline)
            added = write_audit_baseline(baseline, res, old, args.reason)
            print(
                f"audit baseline {baseline}: {len(res.programs)} "
                f"program(s) pinned, {added} finding(s) newly accepted, "
                f"{len(res.stale_baseline)} stale dropped"
            )
            return 0
        print(res.render_json() if args.json else res.render_text())
        return res.exit_code
    except AuditUsageError as e:
        print(f"dsst audit: {e}", file=sys.stderr)
        return 2


def register_sanitize(sub: argparse._SubParsersAction) -> None:
    sz = sub.add_parser(
        "sanitize",
        help="runtime thread sanitizer (third analysis tier): run named "
        "workloads with lock/thread instrumentation armed and report "
        "lock-order cycles (potential deadlocks, with both acquisition "
        "stacks), guarded-by violations, unjoined threads, and leaked "
        "locks against SANITIZE_BASELINE.json",
    )
    sz.add_argument(
        "--workloads", default=None, metavar="W1,W2",
        help="comma-separated subset of workloads to run (default: all; "
        "see --list-workloads). Subset runs skip stale-baseline "
        "enforcement — they cannot prove an unexercised finding gone",
    )
    sz.add_argument(
        "--json", action="store_true",
        help="machine-readable output (schema documented in README "
        "'Runtime sanitizer') instead of text",
    )
    sz.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline of accepted pre-existing findings (default: "
        "SANITIZE_BASELINE.json at the repo root)",
    )
    sz.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings: existing "
        "entries keep their authored reason, new ones take --reason, "
        "stale ones are dropped (full workload set only)",
    )
    sz.add_argument(
        "--reason", default=None, metavar="TEXT",
        help="justification recorded for entries newly added by "
        "--update-baseline (mandatory when any exist)",
    )
    sz.add_argument(
        "--list-workloads", action="store_true",
        help="print the workload catalog and exit",
    )
    sz.add_argument(
        "--list-rules", action="store_true",
        help="print the sanitizer rule catalog and exit",
    )
    sz.set_defaults(fn=_cmd_sanitize)


def _cmd_sanitize(args: argparse.Namespace) -> int:
    from ..analysis.sanitize import (
        DEFAULT_SANITIZE_BASELINE,
        RULES,
        SanitizeUsageError,
        build_result,
        run_workloads,
        sanitize_scope,
        workload_catalog,
        workload_names,
    )
    from ..analysis.sanitize.report import update_baseline

    try:
        if args.list_workloads:
            for name, desc in workload_catalog():
                print(f"{name:12s} {desc}")
            return 0
        if args.list_rules:
            for name, desc in sorted(RULES.items()):
                print(f"{name:16s} {desc}")
            return 0
        names = (
            [w.strip() for w in args.workloads.split(",") if w.strip()]
            if args.workloads else workload_names()
        )
        unknown = sorted(set(names) - set(workload_names()))
        if unknown:
            raise SanitizeUsageError(
                f"unknown workload(s) {', '.join(unknown)}; known: "
                f"{', '.join(workload_names())}"
            )
        full_run = set(names) == set(workload_names())
        if args.update_baseline and not full_run:
            # The baseline is a whole-suite truth (the lint --changed /
            # audit-subset discipline): a subset run would drop every
            # entry its workloads never exercised.
            raise SanitizeUsageError(
                "--update-baseline needs the full workload set: a "
                "subset run must never rewrite the whole baseline"
            )
        baseline = (
            Path(args.baseline) if args.baseline
            else DEFAULT_SANITIZE_BASELINE
        )
        with sanitize_scope() as scope:
            run_workloads(names)
        res = build_result(
            scope, names, baseline_path=baseline, full_run=full_run,
        )
        if args.update_baseline:
            added = update_baseline(baseline, res, args.reason)
            print(
                f"sanitize baseline {baseline}: "
                f"{len(res.findings)} added ({added} with new reason), "
                f"{len(res.baselined)} kept, "
                f"{len(res.stale_baseline)} stale dropped"
            )
            return 0
        print(res.render_json() if args.json else res.render_text())
        return res.exit_code
    except SanitizeUsageError as e:
        print(f"dsst sanitize: {e}", file=sys.stderr)
        return 2


def register_trace(sub: argparse._SubParsersAction) -> None:
    tr = sub.add_parser(
        "trace",
        help="causal tracing tools over a run's flight-recorder tail "
        "(or any span JSONL): tail reconstructs a dead run's last "
        "events including spans still open at the kill, export writes "
        "a Perfetto trace with cross-thread flow arrows per trace id, "
        "attribution breaks each training step into "
        "data-wait/transfer/compute/host and flags step-time anomalies "
        "with their causal children",
    )
    tsub = tr.add_subparsers(dest="trace_cmd", required=True)

    def _add_source(p):
        p.add_argument(
            "--run", default=None, metavar="DIR",
            help="run directory (<root>/<experiment>/<run_id>): reads "
            "the flight-recorder tail its journal registered "
            "(flightrec.jsonl)",
        )
        p.add_argument(
            "--file", default=None, metavar="JSONL",
            help="explicit flight-recorder tail or span JSONL "
            "(overrides --run)",
        )

    tl = tsub.add_parser(
        "tail",
        help="the last events of a (possibly SIGKILLed) run; "
        "begin-only spans are flagged OPEN — the in-flight work at "
        "the kill",
    )
    _add_source(tl)
    tl.add_argument("-n", "--events", type=int, default=32,
                    help="how many trailing events to show")
    tl.add_argument("--json", action="store_true",
                    help="one JSON object per line instead of the table")
    tl.set_defaults(fn=_cmd_trace_tail)

    ex = tsub.add_parser(
        "export",
        help="Perfetto trace_event JSON: labeled process/thread lanes "
        "(ph M) and flow arrows (ph s/f) stitching each trace id "
        "across threads; loads in ui.perfetto.dev",
    )
    _add_source(ex)
    ex.add_argument(
        "--merge", nargs="+", default=None, metavar="JSONL",
        help="merge N replicas' recorder files into ONE timeline: each "
        "file gets its own pid band + process lane, and propagated "
        "trace ids draw flow arrows ACROSS files (overrides "
        "--run/--file)",
    )
    ex.add_argument("--out", required=True, metavar="OUT",
                    help="output trace file")
    ex.set_defaults(fn=_cmd_trace_export)

    at = tsub.add_parser(
        "attribution",
        help="per-step breakdown (data-wait / transfer / compute / "
        "host) from the step traces, plus z-score step-time anomalies "
        "with the anomalous step's causal children",
    )
    _add_source(at)
    at.add_argument("--zscore", type=float, default=3.0,
                    help="|z| threshold flagging a step-time anomaly")
    at.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON document")
    at.set_defaults(fn=_cmd_trace_attribution)


def _trace_source(args: argparse.Namespace) -> Path | None:
    """Resolve tail|export|attribution's input file; None + message on
    failure (callers exit 2)."""
    if args.file:
        p = Path(args.file)
        if not p.exists():
            print(f"no trace file at {p}")
            return None
        return p
    if args.run:
        from ..tracking import classify_run

        cls = classify_run(args.run)
        candidates = [
            Path(cls["trace_file"]) if cls.get("trace_file") else None,
            Path(args.run) / "flightrec.jsonl",
        ]
        for p in candidates:
            if p is not None and p.exists():
                return p
        print(f"no flight-recorder tail under {args.run} (was the run "
              "started by a trace-aware dsst?)")
        return None
    print("pass --run DIR or --file JSONL")
    return None


def _cmd_trace_tail(args: argparse.Namespace) -> int:
    from ..telemetry import flightrec

    path = _trace_source(args)
    if path is None:
        return 2
    events = flightrec.read_events(path)
    if not events:
        print(f"no parseable events in {path}")
        return 1
    complete, opens = flightrec.reconstruct(events)
    # Trailing window: the last N closed spans, then EVERY open span —
    # the open ones are the point (in-flight work at the kill). The
    # window can be zero (opens alone fill -n); list[-0:] would be the
    # WHOLE list, so slice from an explicit start index.
    n_closed = max(args.events - len(opens), 0)
    rows = complete[len(complete) - min(n_closed, len(complete)):] \
        if n_closed else []
    rows = rows + [{**o, "open": True} for o in opens]
    if args.json:
        for r in rows:
            print(json.dumps(r))
        return 0
    print(f"{path}: {len(complete)} closed span(s), {len(opens)} open")
    for r in rows:
        ts = time.strftime("%H:%M:%S", time.localtime(r.get("ts", 0.0)))
        dur = "OPEN" if r.get("open") else f"{r.get('dur', 0.0)*1e3:9.2f}ms"
        trace = r.get("trace", "-")
        kindtag = f"[{r['kind']}]" if r.get("kind") else ""
        argstr = ""
        if r.get("args"):
            argstr = " " + ",".join(
                f"{k}={v}" for k, v in r["args"].items() if k != "open"
            )
        print(f"{ts} {r.get('thread', '?'):<22} {r.get('name', '?'):<20} "
              f"{dur:>12} trace={trace} {kindtag}{argstr}")
    if opens:
        print(f"{len(opens)} span(s) were OPEN when recording stopped "
              "(in-flight at the kill)")
    return 0


def _cmd_trace_export(args: argparse.Namespace) -> int:
    from ..telemetry.spans import (
        load_span_jsonl,
        merge_replica_spans,
        to_perfetto,
    )

    process_names = None
    if getattr(args, "merge", None):
        missing = [p for p in args.merge if not Path(p).exists()]
        if missing:
            print(f"no trace file at {missing[0]}")
            return 2
        events, process_names = merge_replica_spans(args.merge)
        src = f"{len(args.merge)} file(s)"
    else:
        path = _trace_source(args)
        if path is None:
            return 2
        events = load_span_jsonl(path)
        src = str(path)
    if not events:
        print(f"no parseable events in {src}")
        return 1
    # Build in memory, count from the dict, write once — re-reading the
    # file just written (possibly tens of MB) to count flows is waste.
    trace = to_perfetto(events, process_names=process_names)
    flows = sum(
        1 for e in trace["traceEvents"] if e.get("ph") in ("s", "f")
    )
    out_path = Path(args.out)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    print(f"perfetto trace: {len(events)} span(s), {flows} flow "
          f"event(s) -> {args.out}")
    return 0


def _cmd_trace_attribution(args: argparse.Namespace) -> int:
    from ..telemetry import flightrec

    # Attribution buckets: span name -> where a step's wall time went.
    # Sourced from telemetry.catalog.SPAN_ATTRIBUTION — the ONE mapping
    # this command and the bench harness's e2e cross-check share (names
    # held to KNOWN_SPANS by the span-discipline lint), so the two
    # consumers cannot drift apart. Imported at command time: loading
    # the telemetry package pulls jax, which every other subcommand's
    # startup must not pay.
    from ..telemetry.catalog import SPAN_ATTRIBUTION as _ATTRIBUTION

    path = _trace_source(args)
    if path is None:
        return 2
    complete, opens = flightrec.reconstruct(flightrec.read_events(path))
    by_trace: dict[str, list[dict]] = {}
    for e in complete:
        if e.get("kind") == "step" and e.get("trace"):
            by_trace.setdefault(e["trace"], []).append(e)
    steps = []
    for trace_id, spans in by_trace.items():
        compute = [s for s in spans if s["name"] == "train_step"]
        if not compute:
            continue  # eval/warmup batches: staged but never stepped
        buckets = {"data_wait": 0.0, "transfer": 0.0, "compute": 0.0,
                   "host": 0.0}
        for s in spans:
            buckets[_ATTRIBUTION.get(s["name"], "host")] += s.get(
                "dur", 0.0
            )
        steps.append({
            "step": (compute[0].get("args") or {}).get("step"),
            "trace": trace_id,
            "ts": compute[0].get("ts", 0.0),
            **{k: round(v * 1e3, 3) for k, v in buckets.items()},
            "total": round(sum(buckets.values()) * 1e3, 3),
            "spans": [
                {"name": s["name"], "thread": s.get("thread"),
                 "dur_ms": round(s.get("dur", 0.0) * 1e3, 3)}
                for s in sorted(spans, key=lambda s: s.get("ts", 0.0))
            ],
        })
    if not steps:
        print(f"no step traces in {path} (is this a training run's "
              "flight recorder?)")
        return 1
    steps.sort(key=lambda s: s["ts"])
    # Anomalies are flagged on TOTAL traced step time: a data-wait or
    # transfer spike IS a step-time anomaly (the feeder-stall case this
    # tool exists to surface) even when compute stays nominal.
    durs = [s["total"] for s in steps]
    mean = sum(durs) / len(durs)
    var = sum((d - mean) ** 2 for d in durs) / len(durs)
    std = var ** 0.5
    anomalies = []
    for s in steps:
        z = (s["total"] - mean) / std if std > 0 else 0.0
        s["z"] = round(z, 2)
        if abs(z) >= args.zscore:
            anomalies.append(s)
    report = {
        "file": str(path),
        "steps": len(steps),
        "total_ms_mean": round(mean, 3),
        "total_ms_std": round(std, 3),
        "compute_ms_mean": round(
            sum(s["compute"] for s in steps) / len(steps), 3
        ),
        "data_wait_ms_mean": round(
            sum(s["data_wait"] for s in steps) / len(steps), 3
        ),
        "transfer_ms_mean": round(
            sum(s["transfer"] for s in steps) / len(steps), 3
        ),
        "host_ms_mean": round(
            sum(s["host"] for s in steps) / len(steps), 3
        ),
        "zscore_threshold": args.zscore,
        "anomalies": anomalies,
        "open_spans": [o.get("name") for o in opens],
    }
    if args.json:
        report["per_step"] = [
            {k: v for k, v in s.items() if k != "spans"} for s in steps
        ]
        print(json.dumps(report))
        return 0
    print(f"{len(steps)} step(s): total {mean:.3f}ms ± {std:.3f}ms, "
          f"compute {report['compute_ms_mean']}ms, "
          f"data-wait {report['data_wait_ms_mean']}ms, "
          f"transfer {report['transfer_ms_mean']}ms, "
          f"host {report['host_ms_mean']}ms (means per step)")
    hdr = (f"{'STEP':>6} {'DATA':>9} {'XFER':>9} {'COMPUTE':>9} "
           f"{'HOST':>9} {'TOTAL':>9} {'Z':>6}")
    print(hdr)
    for s in steps:
        print(f"{str(s['step']):>6} {s['data_wait']:>9.3f} "
              f"{s['transfer']:>9.3f} {s['compute']:>9.3f} "
              f"{s['host']:>9.3f} {s['total']:>9.3f} {s['z']:>6.2f}")
    for a in anomalies:
        print(f"anomaly: step {a['step']} (z={a['z']}) — causal children:")
        for s in a["spans"]:
            print(f"    {s['name']:<20} {s['dur_ms']:>9.3f}ms "
                  f"on {s['thread']}")
    if not anomalies:
        print(f"no |z| >= {args.zscore:g} step-time anomalies")
    return 0


def register_bench(sub: argparse._SubParsersAction) -> None:
    bn = sub.add_parser(
        "bench",
        help="performance regression harness (fourth analysis tier): "
        "run registered scenarios in isolated children with "
        "noise-aware repetitions and judge them against the "
        "environment-fingerprinted BENCH_BASELINE.json; `dsst bench "
        "profile <scenario>` merges flight-recorder spans with a "
        "jax.profiler trace into one Perfetto timeline",
    )
    bn.add_argument(
        "--scenarios", default=None, metavar="S1,S2",
        help="comma-separated subset of scenarios (default: every "
        "non-tpu scenario; see --list-scenarios)",
    )
    bn.add_argument(
        "--tier", default=None, metavar="TIER",
        help="run one tier (tier1 | slow | tpu) instead of naming "
        "scenarios — tier1 is the CI smoke subset",
    )
    bn.add_argument(
        "--repetitions", type=int, default=None, metavar="N",
        help="override every selected scenario's repetition count",
    )
    bn.add_argument(
        "--in-process", action="store_true",
        help="measure inline instead of per-scenario child processes "
        "(debugging; loses crash isolation)",
    )
    bn.add_argument(
        "--json", action="store_true",
        help="machine-readable output (schema documented in README "
        "'Benchmarking') instead of text",
    )
    bn.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline file (default: BENCH_BASELINE.json at the repo "
        "root)",
    )
    bn.add_argument(
        "--update-baseline", action="store_true",
        help="record this run's summaries for the current environment "
        "fingerprint: existing entries keep their authored reason, new "
        "ones take --reason, stale ones are dropped; other "
        "fingerprints' entries are preserved verbatim",
    )
    bn.add_argument(
        "--require-baseline", action="store_true",
        help="strict gating: a gated metric with NO committed entry "
        "under this host's fingerprint is a failing finding instead of "
        "a silent 'no-baseline' pass — for preflights that must never "
        "run ungated on a new host",
    )
    bn.add_argument(
        "--reason", default=None, metavar="TEXT",
        help="justification recorded for entries newly added by "
        "--update-baseline (mandatory when any exist)",
    )
    bn.add_argument(
        "--list-scenarios", action="store_true",
        help="print the scenario registry and exit",
    )
    bsub = bn.add_subparsers(dest="bench_cmd")
    pf = bsub.add_parser(
        "profile",
        help="run one scenario under the flight recorder AND "
        "jax.profiler; merge both into ONE Perfetto file (host "
        "handoffs and device ops on the same timeline, flow arrows "
        "intact)",
    )
    pf.add_argument("scenario", help="scenario to profile")
    pf.add_argument("--out", required=True, metavar="FILE",
                    help="merged Perfetto trace output path")
    # Own dest: a subparser option sharing dest="repetitions" would
    # apply ITS default over a value already parsed by the parent
    # (`dsst bench --repetitions 5 profile ...` silently became 1).
    pf.add_argument("--repetitions", type=int, default=None,
                    dest="profile_repetitions",
                    help="repetitions to trace (default: 1, or the "
                    "parent --repetitions when given before 'profile')")
    pf.add_argument(
        "--min-profiler-dur-us", type=float, default=5.0,
        help="drop jax.profiler complete events shorter than this "
        "(the runtimes emit ~1M sub-microsecond TraceMes per traced "
        "second; dropped count is reported). 0 keeps everything",
    )
    bn.set_defaults(fn=_cmd_bench)


def _cmd_bench(args: argparse.Namespace) -> int:
    # Scenarios that execute audited entrypoints need the same >=8
    # abstract devices `dsst audit` multiplexes — set before backend
    # init (children inherit; profile runs in-process). MESH_FLAG is
    # the ONE definition the parent and the needs_mesh child runner
    # share: disagreeing would silently fork the fingerprint's device
    # count. (bench.core imports no jax at module level, so this stays
    # cheap at command time.)
    import os

    from ..bench.core import MESH_FLAG

    if MESH_FLAG not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + MESH_FLAG
        ).strip()

    from ..bench import (
        DEFAULT_BENCH_BASELINE,
        BenchUsageError,
        load_bench_baseline,
        run_bench,
        scenario_catalog,
        write_bench_baseline,
    )

    try:
        if getattr(args, "bench_cmd", None) == "profile":
            from ..bench.profile import profile_scenario

            reps = args.profile_repetitions
            if reps is None:
                reps = args.repetitions if args.repetitions else 1
            report = profile_scenario(
                args.scenario, args.out, repetitions=reps,
                min_profiler_dur_us=args.min_profiler_dur_us,
            )
            print(
                f"merged perfetto trace: {report['spans']} span(s), "
                f"{report['flows']} flow event(s), "
                f"{report['profiler_events']} profiler event(s) "
                f"(+{report['profiler_events_dropped']} dropped under "
                f"{args.min_profiler_dur_us:g}us) -> {report['out']}"
            )
            if report.get("mfu"):
                b = report["mfu"]
                util = b.get("utilization")
                print(
                    f"achieved FLOPs/s ({b['entrypoint']}): "
                    f"{b['achieved_flops_per_sec']:.4g}"
                    + (f" ({util:.2%} of peak)" if util is not None else "")
                )
            return 0
        if args.list_scenarios:
            for name, tier, desc in scenario_catalog():
                print(f"{name:20s} [{tier:5s}] {desc}")
            return 0
        scenarios = (
            [s.strip() for s in args.scenarios.split(",") if s.strip()]
            if args.scenarios else None
        )
        if scenarios and args.tier:
            raise BenchUsageError(
                "--scenarios and --tier are exclusive selections"
            )
        baseline = (
            Path(args.baseline) if args.baseline else DEFAULT_BENCH_BASELINE
        )
        res = run_bench(
            scenarios, tier=args.tier, repetitions=args.repetitions,
            baseline_path=baseline, isolation=not args.in_process,
            require_baseline=args.require_baseline,
        )
        if args.update_baseline:
            old = load_bench_baseline(baseline)
            added = write_bench_baseline(baseline, res, old, args.reason)
            print(
                f"bench baseline {baseline}: {len(res.results)} "
                f"scenario(s) recorded under {res.fingerprint_key} "
                f"({added} with new reason)"
            )
            return 0
        print(res.render_json() if args.json else res.render_text())
        return res.exit_code
    except BenchUsageError as e:
        print(f"dsst bench: {e}", file=sys.stderr)
        return 2


# --------------------------------------------------------------------------
# slo / top (the live monitoring plane's CLI face)
# --------------------------------------------------------------------------

def _add_slo_source_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--url", default="http://127.0.0.1:8008", metavar="URL",
        help="a running dsst serve process (its /slo endpoint is "
        "scraped); default matches `dsst serve`'s default port",
    )
    p.add_argument(
        "--report", default=None, metavar="JSON",
        help="judge a saved document instead of a live process: either "
        "a raw /slo status JSON or a `dsst bench --json` artifact "
        "whose serving scenario embedded one (results.serving.extra."
        "slo) — what CI runs after the serving bench",
    )


def register_slo(sub: argparse._SubParsersAction) -> None:
    so = sub.add_parser(
        "slo",
        help="live SLOs: declared objectives, windowed values, "
        "burn rates, and alert states — baseline-free (the objectives "
        "are code, telemetry.slo.default_objectives)",
    )
    ssub = so.add_subparsers(dest="slo_cmd", required=True)
    st = ssub.add_parser(
        "status", help="one status frame: every objective's live "
        "value, budget remaining, burn rates, and alert state",
    )
    _add_slo_source_args(st)
    _add_fleet_args(st)
    st.add_argument("--json", action="store_true",
                    help="print the raw /slo document (schema v1)")
    st.set_defaults(fn=_cmd_slo_status)
    ck = ssub.add_parser(
        "check", help="gate on the SLO plane: exit 1 if any objective "
        "is firing (CI runs this after the serving bench so a TPU "
        "claim can't ship while an SLO burns)",
    )
    _add_slo_source_args(ck)
    _add_fleet_args(ck)
    ck.add_argument("--json", action="store_true")
    ck.add_argument(
        "--strict", action="store_true",
        help="also fail on objectives in the pending state",
    )
    ck.set_defaults(fn=_cmd_slo_check)
    wa = ssub.add_parser(
        "watch", help="poll /slo and redraw the status frame",
    )
    _add_slo_source_args(wa)
    _add_fleet_args(wa)
    wa.add_argument("--interval", type=float, default=2.0,
                    metavar="SECONDS")
    wa.add_argument(
        "--iterations", type=int, default=0, metavar="N",
        help="stop after N frames (0 = until Ctrl-C)",
    )
    wa.set_defaults(fn=_cmd_slo_watch)


def _slo_parse_url(url: str) -> tuple[str, int]:
    if "://" in url and not url.startswith("http://"):
        # A clear refusal beats the int() parse error https:// would
        # otherwise surface as.
        raise ValueError(
            f"only http:// URLs are supported, got {url!r}"
        )
    hostport = url.removeprefix("http://").rstrip("/")
    host, _, port_s = hostport.partition(":")
    return host or "127.0.0.1", int(port_s or 8008)


def _slo_http_json(url: str, path: str) -> dict:
    import http.client

    host, port = _slo_parse_url(url)
    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
    finally:
        conn.close()
    if resp.status != 200:
        raise OSError(f"GET {path} -> HTTP {resp.status}")
    return json.loads(body)


def _slo_fetch_status(args: argparse.Namespace) -> dict | None:
    """The /slo document from --report or --url; None (with a message
    on stderr) when the source is unusable — callers exit 2."""
    if args.report:
        try:
            doc = json.loads(Path(args.report).read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"dsst slo: cannot read --report {args.report}: {e}",
                  file=sys.stderr)
            return None
        if "objectives" not in doc:
            # A dsst bench --json artifact: the serving scenario embeds
            # the stub server's /slo snapshot in its extra block.
            doc = (
                doc.get("results", {}).get("serving", {})
                .get("extra", {}).get("slo")
            )
        if not isinstance(doc, dict) or "objectives" not in doc:
            print(
                f"dsst slo: {args.report} carries no SLO status "
                "document (expected a /slo JSON or a bench artifact "
                "with results.serving.extra.slo)",
                file=sys.stderr,
            )
            return None
        return doc
    try:
        return _slo_http_json(args.url, "/slo")
    except (OSError, ValueError) as e:
        print(f"dsst slo: cannot scrape {args.url}/slo: {e}",
              file=sys.stderr)
        return None


def _slo_fmt_value(obj: dict) -> str:
    v = obj.get("value")
    if v is None:
        return "-"
    if obj.get("unit") == "s":
        return f"{v * 1000:.1f}ms"
    return f"{v:.4g}"


def _slo_fmt_budget(obj: dict) -> str:
    b = obj.get("budget")
    if b is None:
        return "unarmed"
    if obj.get("unit") == "s":
        return f"{b * 1000:g}ms"
    return f"{b:g}"


def _slo_render_text(doc: dict) -> list[str]:
    rows = [
        (
            o["name"], o["state"], _slo_fmt_value(o), _slo_fmt_budget(o),
            f"{o['burn_fast']:.2f}/{o['burn_slow']:.2f}",
            ("-" if o.get("budget_remaining") is None
             else f"{o['budget_remaining']:.2f}"),
            str(o.get("samples", 0)),
        )
        for o in doc.get("objectives", [])
    ]
    header = ("OBJECTIVE", "STATE", "VALUE", "BUDGET", "BURN f/s",
              "REMAINING", "SAMPLES")
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows
        else len(header[i])
        for i in range(len(header))
    ]
    lines = ["  ".join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    firing = doc.get("firing", [])
    lines.append(
        "firing: " + (", ".join(firing) if firing else "(none)")
    )
    return lines


# -- fleet mode (slo --fleet / top --fleet) ---------------------------------


def _add_fleet_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--fleet", nargs="+", default=None, metavar="ENDPOINT",
        help="fleet mode: scrape N replicas' /telemetry endpoints "
        "(host:port ...), merge their registries and SLO windows, and "
        "judge the FLEET instead of one process",
    )
    p.add_argument(
        "--fleet-timeout", type=float, default=2.0, metavar="SECONDS",
        help="per-cycle scrape budget; a replica that doesn't answer "
        "inside it costs its column, never the cycle",
    )
    p.add_argument(
        "--fleet-journal", default=None, metavar="JSONL",
        help="journal each fleet scrape cycle crash-durably to this "
        "path (outcome per replica, merged firing set)",
    )


def _fleet_aggregator(args: argparse.Namespace):
    from ..telemetry import federation

    return federation.FleetAggregator(
        args.fleet,
        timeout_s=args.fleet_timeout,
        journal_path=args.fleet_journal,
    )


def _fleet_replica_rows(view) -> list[str]:
    """Per-replica columns: liveness, that replica's OWN live p99 +
    request count (off its raw window wire), staleness, scrape cost."""
    from ..telemetry import windows as _windows

    rows = []
    for r in view.replicas:
        p99 = reqs = None
        if r.doc is not None:
            for m in r.doc.get("metrics", ()):
                if m.get("name") == "serving_request_window_seconds":
                    wire = m.get("wire") or {}
                    try:
                        p99 = _windows.quantile_of_wire(wire, 0.99)
                        reqs = int(wire.get("count", 0))
                    except (ValueError, TypeError, KeyError):
                        pass
                    break
        rows.append((
            r.endpoint,
            r.outcome,
            "-" if p99 is None else f"{p99 * 1000:.1f}ms",
            "-" if reqs is None else str(reqs),
            ("-" if r.staleness_s is None
             else f"{r.staleness_s:.0f}s"),
            f"{r.elapsed_s * 1000:.0f}ms",
        ))
    header = ("REPLICA", "STATE", "p99", "REQS", "STALE", "SCRAPE")
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows
        else len(header[i])
        for i in range(len(header))
    ]
    lines = ["  ".join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return lines


def _fleet_window_rows(view) -> list[str]:
    """The MERGED windowed quantile series — the fleet-wide sibling of
    `dsst top`'s per-process windows section."""
    rows = []
    for fam in view.registry.families():
        if fam.kind != "window":
            continue
        for labels, sample in fam._series():
            label_txt = ",".join(
                f"{k}={v}" for k, v in sorted(labels.items())
            )
            name = fam.name + (f"{{{label_txt}}}" if label_txt else "")
            cells = " ".join(
                f"p{float(q) * 100:g}="
                + ("-" if v is None else f"{v * 1000:.2f}ms")
                for q, v in sorted(sample.get("quantiles", {}).items())
            )
            rows.append(
                f"  {name:<44} {cells}  n={sample.get('count', 0)}"
            )
    return rows


def _fleet_doc(view) -> dict:
    """The fleet status document (--json shape): per-replica outcomes
    plus the merged SLO judgment."""
    return {
        "version": 1,
        "ts": round(view.ts, 3),
        "up": view.up,
        "replicas": [
            {
                "endpoint": r.endpoint,
                "up": r.up,
                "outcome": r.outcome,
                "elapsed_ms": round(r.elapsed_s * 1000, 1),
                "staleness_s": (
                    round(r.staleness_s, 1)
                    if r.staleness_s is not None else None
                ),
                **({"error": r.error} if r.error else {}),
            }
            for r in view.replicas
        ],
        "merged_series": view.merged_series,
        "slo": view.slo,
    }


def _fleet_frame(agg, view, *, windows: bool = False) -> list[str]:
    lines = [
        f"dsst fleet — {len(agg.endpoints)} endpoint(s), "
        f"{view.up} up  {time.strftime('%H:%M:%S')}",
        "",
    ]
    lines.extend(_fleet_replica_rows(view))
    lines.append("")
    lines.extend(_slo_render_text(view.slo))
    if windows:
        rows = _fleet_window_rows(view)
        if rows:
            lines.append("")
            lines.append("fleet windows (merged):")
            lines.extend(rows)
    return lines


def _cmd_slo_status(args: argparse.Namespace) -> int:
    if args.fleet:
        agg = _fleet_aggregator(args)
        view = agg.scrape()
        if args.json:
            print(json.dumps(_fleet_doc(view), indent=1))
        else:
            for line in _fleet_frame(agg, view):
                print(line)
        return 0
    doc = _slo_fetch_status(args)
    if doc is None:
        return 2
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        for line in _slo_render_text(doc):
            print(line)
    return 0


def _cmd_slo_check(args: argparse.Namespace) -> int:
    if args.fleet:
        from ..telemetry import federation

        agg = _fleet_aggregator(args)
        view = agg.scrape()
        if view.up == 0:
            print("dsst slo: no replica answered the fleet scrape",
                  file=sys.stderr)
            return 2
        # One-shot judgment: a fresh state machine has had no cycles
        # to debounce pending→firing, so "burning" is the raw
        # two-window condition (federation.burning) — plus anything
        # already firing in the merged judgment.
        bad = federation.burning(view.slo)
        if args.strict:
            bad = sorted(set(bad) | {
                o["name"] for o in view.slo.get("objectives", [])
                if o.get("state") == "pending"
            })
        if args.json:
            print(json.dumps({
                **_fleet_doc(view),
                "ok": not bad,
                "failing": bad,
            }, indent=1))
        else:
            for line in _fleet_frame(agg, view):
                print(line)
            print("fleet slo check: "
                  + ("OK" if not bad else "FAILING " + ", ".join(bad)))
        return 1 if bad else 0
    doc = _slo_fetch_status(args)
    if doc is None:
        return 2
    bad = list(doc.get("firing", []))
    if args.strict:
        bad += [
            o["name"] for o in doc.get("objectives", [])
            if o.get("state") == "pending"
        ]
    if args.json:
        print(json.dumps({
            "version": doc.get("version", 1),
            "ok": not bad,
            "failing": sorted(set(bad)),
            "objectives": doc.get("objectives", []),
        }, indent=1))
    else:
        for line in _slo_render_text(doc):
            print(line)
        print("slo check: "
              + ("OK" if not bad else "FAILING " + ", ".join(sorted(set(bad)))))
    return 1 if bad else 0


def _cmd_slo_watch(args: argparse.Namespace) -> int:
    frames = 0
    # ONE aggregator across frames: the fleet alert state machine and
    # staleness clocks must persist or pending can never reach firing.
    agg = _fleet_aggregator(args) if args.fleet else None
    try:
        while True:
            if agg is not None:
                view = agg.scrape()
                print("\x1b[2J\x1b[H", end="")
                for line in _fleet_frame(agg, view):
                    print(line)
            else:
                doc = _slo_fetch_status(args)
                if doc is None:
                    return 2
                print("\x1b[2J\x1b[H", end="")
                print(f"dsst slo watch — {args.report or args.url}  "
                      f"{time.strftime('%H:%M:%S')}")
                for line in _slo_render_text(doc):
                    print(line)
            frames += 1
            if args.iterations and frames >= args.iterations:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def register_top(sub: argparse._SubParsersAction) -> None:
    tp = sub.add_parser(
        "top",
        help="live terminal view of a serving process: windowed "
        "latency quantiles, SLO budget remaining, firing alerts, and "
        "the scheduler/feeder gauges, fused from /slo + /metrics",
    )
    tp.add_argument(
        "--url", default="http://127.0.0.1:8008", metavar="URL",
        help="the dsst serve process to watch",
    )
    _add_fleet_args(tp)
    tp.add_argument("--interval", type=float, default=2.0,
                    metavar="SECONDS")
    tp.add_argument(
        "--once", action="store_true",
        help="print one frame and exit (scripting/tests)",
    )
    tp.set_defaults(fn=_cmd_top)


def _top_parse_metrics(text: str) -> tuple[dict, dict]:
    """Prometheus text → (plain series, labeled series).

    ``plain`` maps bare series names to floats; ``labeled`` maps name →
    list of ``(label_dict, value)``.
    """
    import re

    plain: dict[str, float] = {}
    labeled: dict[str, list] = {}
    label_re = re.compile(r'(\w+)="([^"]*)"')
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        name, _, value_s = line.rpartition(" ")
        name = name.strip()
        try:
            value = float(value_s)
        except ValueError:
            continue
        if "{" in name:
            base, _, rest = name.partition("{")
            labels = dict(label_re.findall(rest))
            labeled.setdefault(base, []).append((labels, value))
        else:
            plain[name] = value
    return plain, labeled


_TOP_GAUGES = (
    "serving_queue_depth",
    "admission_service_rate_ewma",
    "admission_est_queue_wait_ms",
    "slo_alerts_firing",
)


def _top_frame(url: str) -> list[str]:
    doc = _slo_http_json(url, "/slo")
    import http.client

    host, port = _slo_parse_url(url)
    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        text = resp.read().decode()
    finally:
        conn.close()
    plain, labeled = _top_parse_metrics(text)

    lines = [f"dsst top — {url}  {time.strftime('%H:%M:%S')}", ""]
    lines.extend(_slo_render_text(doc))
    lines.append("")
    # Windowed quantile series: every summary family on /metrics (the
    # window kind renders quantile-labeled samples). The _count join
    # must follow the same label split: a labeled family's _count line
    # carries the labels too, so it parses into `labeled`, keyed by
    # the identical non-quantile label tuple.
    labeled_counts: dict[tuple[str, tuple], float] = {}
    for lname, series in labeled.items():
        if not lname.endswith("_count"):
            continue
        for labels, value in series:
            labeled_counts[
                (lname[: -len("_count")],
                 tuple(sorted(labels.items())))
            ] = value
    window_rows = []
    for base, series in sorted(labeled.items()):
        by_labels: dict[tuple, dict] = {}
        for labels, value in series:
            q = labels.get("quantile")
            if q is None:
                continue
            rest = tuple(
                sorted((k, v) for k, v in labels.items()
                       if k != "quantile")
            )
            by_labels.setdefault(rest, {})[q] = value
        for rest, qs in sorted(by_labels.items()):
            label_txt = ",".join(f"{k}={v}" for k, v in rest)
            name = base + (f"{{{label_txt}}}" if label_txt else "")
            cells = " ".join(
                f"p{float(q) * 100:g}="
                + ("-" if v != v else f"{v * 1000:.2f}ms")
                for q, v in sorted(qs.items())
            )
            count = (
                labeled_counts.get((base, rest)) if rest
                else plain.get(f"{base}_count")
            )
            window_rows.append(
                f"  {name:<44} {cells}"
                + (f"  n={count:g}" if count is not None else "")
            )
    if window_rows:
        lines.append("windows:")
        lines.extend(window_rows)
        lines.append("")
    gauge_cells = [
        f"{g}={plain[g]:g}" for g in _TOP_GAUGES if g in plain
    ]
    if gauge_cells:
        lines.append("gauges: " + "  ".join(gauge_cells))
    return lines


def _cmd_top(args: argparse.Namespace) -> int:
    # Fleet mode holds ONE aggregator across frames (persistent alert
    # state machine + staleness clocks), and its frame adds the merged
    # fleet windows under the per-replica columns.
    agg = _fleet_aggregator(args) if args.fleet else None
    try:
        while True:
            if agg is not None:
                view = agg.scrape()
                frame = _fleet_frame(agg, view, windows=True)
            else:
                try:
                    frame = _top_frame(args.url)
                except (OSError, ValueError) as e:
                    print(f"dsst top: cannot scrape {args.url}: {e}",
                          file=sys.stderr)
                    return 2
            if not args.once:
                print("\x1b[2J\x1b[H", end="")
            for line in frame:
                print(line)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def register_all(sub: argparse._SubParsersAction) -> None:
    register_datagen(sub)
    register_forecast(sub)
    register_eda(sub)
    register_ingest(sub)
    register_train(sub)
    register_predict(sub)
    register_export(sub)
    register_serve(sub)
    register_serve_lm(sub)
    register_lm(sub)
    register_hpo(sub)
    register_trial_worker(sub)
    register_checkpoints(sub)
    register_quarantine(sub)
    register_runs(sub)
    register_chaos(sub)
    register_telemetry(sub)
    register_trace(sub)
    register_lint(sub)
    register_audit(sub)
    register_sanitize(sub)
    register_bench(sub)
    register_slo(sub)
    register_top(sub)
    from .pipeline import register_pipeline

    register_pipeline(sub)


if __name__ == "__main__":  # pragma: no cover
    from .cli import main

    sys.exit(main())

"""Config + CLI (replaces dbutils.widgets / RUNME job JSON)."""

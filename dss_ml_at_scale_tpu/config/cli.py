"""`dsst` command-line entry point.

Replaces the reference's three config surfaces — ``dbutils.widgets``,
module-level constant cells, and the RUNME job JSON (SURVEY.md §5.6) —
with ordinary subcommands. Subcommands register here as workloads land.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dsst",
        description="dss_ml_at_scale_tpu: TPU-native scale-out ML framework",
    )
    parser.add_argument(
        "--platform", default=None, metavar="NAME",
        help="force the jax platform (e.g. cpu) before any backend use — "
        "the env var JAX_PLATFORMS is overridden by accelerator plugins "
        "on some hosts, so this applies the in-process config update "
        "that actually sticks",
    )
    # Site list generated from the one registry the tier-1 lint
    # (scripts/check_fault_sites.py) holds the code to, so this help
    # text cannot drift from the actual injection surface.
    from ..resilience.faults import KNOWN_SITES

    parser.add_argument(
        "--fault-plan", default=None, metavar="SPEC",
        help="arm deterministic fault injection for this invocation, e.g. "
        "'rpc.send=2;grads.nonfinite=1@5;reader.next=p0.1;seed=7' "
        f"(sites: {', '.join(sorted(KNOWN_SITES))}; N = fail the first N "
        "hits, N@K = skip K hits then fail N, pX = seeded per-hit "
        "probability, kN/kN@K = SIGKILL the process at the hit — the "
        "power-cut mode dsst chaos arms at the fs.* sites: "
        "fs.torn_write leaves a truncated staged .tmp, "
        "fs.crash_after_tmp a complete .tmp that never publishes, "
        "fs.fsync an EIO-style fsync failure; suffix .<kind> scopes one "
        "publish family, e.g. fs.crash_after_tmp.manifest=k1). "
        "Default: env DSST_FAULT_PLAN; chaos testing only",
    )
    sub = parser.add_subparsers(dest="command")
    info = sub.add_parser("info", help="show runtime topology and devices")
    info.add_argument(
        "--probe", type=_positive_seconds, default=None, metavar="SECONDS",
        help="query devices in a watchdog subprocess with this timeout "
        "instead of in-process — reports an unreachable accelerator "
        "(e.g. a hung TPU tunnel, which blocks jax.devices() forever) "
        "as a diagnostic instead of hanging",
    )
    info.set_defaults(fn=_cmd_info)

    from .commands import register_all

    register_all(sub)
    return parser


def _positive_seconds(s: str) -> float:
    v = float(s)
    if v <= 0:
        raise argparse.ArgumentTypeError(
            f"must be a positive number of seconds, got {s!r}"
        )
    return v


def _cmd_info(args: argparse.Namespace) -> int:
    if getattr(args, "probe", None) is not None:
        import subprocess

        try:
            # The child is this same CLI without --probe, so both paths
            # print identical output by construction.
            proc = subprocess.run(
                [sys.executable, "-m", "dss_ml_at_scale_tpu.config.cli",
                 "info"],
                timeout=args.probe, capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            print(
                f"accelerator unreachable: device query did not return "
                f"within {args.probe:g}s (hung backend tunnel?)"
            )
            return 3
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        return proc.returncode

    import jax

    from ..runtime import local_topology

    topo = local_topology()
    print(f"process {topo.process_index}/{topo.process_count}")
    print(f"devices {topo.local_device_count} local / {topo.global_device_count} global")
    for d in jax.local_devices():
        print(f"  {d}")
    return 0


def main(argv: list[str] | None = None) -> int:
    import os

    parser = build_parser()
    args = parser.parse_args(argv)
    # Stash the exact invocation for the run journal: what `dsst runs
    # doctor --resume` re-executes (with --resume-auto) to revive a run
    # this process may leave interrupted.
    from .commands import set_invocation_argv

    set_invocation_argv(argv if argv is not None else sys.argv[1:])
    fault_spec = args.fault_plan or os.environ.get("DSST_FAULT_PLAN")
    if fault_spec:
        # Armed before any subcommand work, and exported so subprocess
        # workers (which inherit the env and re-enter main here) arm the
        # same plan — a --fault-plan chaos run must not silently test
        # only the driver process.
        os.environ["DSST_FAULT_PLAN"] = fault_spec
        from ..resilience.faults import install_from_spec

        install_from_spec(fault_spec)
    if os.environ.get("DSST_SANITIZE") and args.command != "sanitize":
        # Armed before any subcommand constructs its locks/threads (and
        # exported to subprocess workers via the inherited env): the
        # runtime thread sanitizer rides ANY dsst command in
        # observation mode — findings to stderr at exit, exit code
        # untouched. `dsst sanitize` itself manages its own scope.
        from ..analysis.sanitize import arm_observation_mode

        arm_observation_mode()
    if args.platform:
        import jax

        # Read initialized-ness WITHOUT triggering initialization: a
        # default_backend() probe here would claim the device (and can
        # hang on a dead tunnel) before any subcommand watchdog runs.
        already_up = bool(
            getattr(
                getattr(jax, "_src", None) and jax._src.xla_bridge,
                "_backends",
                None,
            )
        )
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass  # older jax raises once the backend is initialized
        # Newer jax silently ignores the update after backend init, so
        # compare the (already-cached, cheap) effective backend; a
        # caller that asked for cpu must not keep running on the
        # accelerator unawares. --platform may be a comma-separated
        # priority list; honored means the winner is any listed entry.
        if already_up and jax.default_backend() not in args.platform.split(","):
            print(
                f"warning: --platform {args.platform} ignored — JAX "
                f"backend already initialized as "
                f"{jax.default_backend()!r} in this process",
                file=sys.stderr,
            )
    if not getattr(args, "fn", None):
        parser.print_help()
        return 2
    try:
        return args.fn(args)
    except BaseException:
        # A crashed command must not leave its (default-on) tracking run
        # in RUNNING state — close it as FAILED before propagating.
        from .commands import fail_active_tracker

        fail_active_tracker()
        raise


if __name__ == "__main__":
    sys.exit(main())

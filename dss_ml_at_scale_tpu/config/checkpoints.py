"""Checkpoint-meta resolution shared by predict / export / serve.

``dsst train`` persists ``dsst_model.json`` beside its orbax steps;
every consumer (CLI commands and the serving library) resolves it
through this ONE module, so restore-critical branches — the
schedule-shaped optimizer template, fused-BN fidelity, the ViT
training-crop pin — cannot drift between entry points. Library
semantics: failures RAISE (``FileNotFoundError`` / ``ValueError``);
the CLI layer turns them into prints and exit codes.
"""

from __future__ import annotations

import json
from pathlib import Path


def build_classifier_model(name: str, *, num_classes: int,
                           torch_padding: bool,
                           fused_bn: bool | str = True):
    """The train/predict/export/serve-shared model factory
    ("resnet50" | "tiny" | "tiny-bottleneck" | "vit-t16" | "vit-s16" |
    "vit-tiny").  ``fused_bn`` accepts the ResNet levels: False, True
    (HLO fused), or "pallas" (prologue-fused bottleneck)."""
    if name.startswith("vit"):
        # torch_padding / fused_bn are conv/BN concepts; a ViT has
        # neither, so the flags are inert for these choices.
        from ..models import ViT, vit_s16, vit_t16

        if name == "vit-t16":
            return vit_t16(num_classes)
        if name == "vit-s16":
            return vit_s16(num_classes)
        # "vit-tiny": a CI-sized geometry (patch 8 suits small crops).
        return ViT(num_classes=num_classes, patch=8, dim=32, depth=2,
                   num_heads=2)
    from ..models import ResNet50

    if name == "resnet50":
        return ResNet50(num_classes=num_classes, torch_padding=torch_padding,
                        fused_bn=fused_bn)
    from ..models.resnet import BottleneckBlock, ResNet, ResNetBlock

    # "tiny-bottleneck": same CI geometry with the ResNet-50 block
    # structure — the one small model that exercises fused_bn="pallas".
    return ResNet(
        stage_sizes=[1, 1],
        block_cls=(BottleneckBlock if name == "tiny-bottleneck"
                   else ResNetBlock),
        num_classes=num_classes, num_filters=8,
        torch_padding=torch_padding, fused_bn=fused_bn,
    )


def resolve_checkpoint(checkpoint_dir, crop_override: int | None = None):
    """``(meta, crop, model, task)`` for a dsst-train checkpoint.

    Raises ``FileNotFoundError`` when the directory carries no
    ``dsst_model.json`` and ``ValueError`` when a crop override fights
    a ViT's training crop (its position table is sized by it; a
    different scoring crop would surface as a raw orbax structure
    mismatch — ResNet pools globally and tolerates the override).
    """
    meta_path = Path(checkpoint_dir) / "dsst_model.json"
    if not meta_path.exists():
        raise FileNotFoundError(
            f"no dsst_model.json under {checkpoint_dir}; "
            "was this checkpoint written by dsst train?"
        )
    meta = json.loads(meta_path.read_text())
    crop = crop_override or int(meta.get("crop", 224))
    if (
        str(meta.get("model", "")).startswith("vit")
        and meta.get("crop")
        and crop != int(meta["crop"])
    ):
        raise ValueError(
            f"--crop {crop} differs from the training crop "
            f"{meta['crop']}: ViT checkpoints must be scored at the "
            "crop they were trained with"
        )
    from ..parallel import ClassifierTask

    model = build_classifier_model(
        meta.get("model", "resnet50"),
        num_classes=int(meta["num_classes"]),
        torch_padding=bool(meta.get("torch_padding", False)),
        # Eval-mode math is identical either way; rebuild what was
        # trained for fidelity (older checkpoints predate the flag).
        fused_bn=bool(meta.get("fused_bn", False)),
    )
    if meta.get("lr_schedule", "constant") == "cosine":
        # restore_state structure-matches the FULL TrainState, optimizer
        # included; a scheduled adam stores an extra count leaf, so the
        # template's tx must be schedule-shaped too (the schedule's
        # values are irrelevant to inference).
        import optax

        task = ClassifierTask(
            model=model, tx=optax.adam(optax.constant_schedule(1e-5))
        )
    else:
        task = ClassifierTask(model=model)
    return meta, crop, model, task


def make_scorer(task, variables):
    """The ONE jitted classification scorer: images → (pred_index,
    pred_prob). Shared by ``dsst predict`` and the HTTP server, so
    their outputs agree by construction. Accepts whatever the task's
    ``_images`` accepts (float NHWC, uint8, or NCHW)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def score(images):
        logits = task.model.apply(
            variables, task._images({task.image_key: images}), train=False
        )
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        return jnp.argmax(probs, axis=-1), jnp.max(probs, axis=-1)

    return score

"""Job-DAG pipeline runner (the RUNME deployment equivalent).

The reference deploys a Databricks Workflows job from a literal JSON spec
— a task DAG with ``task_key``/``depends_on``, per-job ``timeout_seconds``
and ``max_concurrent_runs: 1`` (``group_apply/RUNME.py:35-106``) — and
treats that job running green as its integration test (SURVEY.md §4.1).
Here the same shape is a plain JSON file whose tasks are `dsst`
subcommand argv lists, executed in dependency order as subprocesses (one
fresh process per task, like one cluster per notebook task), each under
its own timeout (the reference's child-notebook timeout,
``00-setup.py:59``).

Spec format::

    {
      "name": "demand-forecasting",
      "timeout_seconds": 600,            # default per-task ceiling
      "tasks": [
        {"task_key": "gen",
         "argv": ["datagen", "demand", "--out", "{workdir}/demand"]},
        {"task_key": "forecast",
         "argv": ["forecast", "--data", "{workdir}/demand",
                  "--out", "{workdir}/forecast"],
         "depends_on": ["gen"],
         "timeout_seconds": 1200}
      ]
    }

``{workdir}`` in any argv element is substituted from ``--workdir``.
Tasks run sequentially in topological order (``max_concurrent_runs: 1``
semantics); a failed or timed-out task skips its dependents and fails
the run.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path


def register_pipeline(sub: argparse._SubParsersAction) -> None:
    pl = sub.add_parser("pipeline", help="run a task-DAG of dsst subcommands")
    pl.add_argument("--spec", required=True, help="pipeline JSON file")
    pl.add_argument("--workdir", default=".", help="substituted for {workdir}")
    pl.add_argument(
        "--dry-run", action="store_true", help="print the execution plan only"
    )
    pl.add_argument(
        "--task-platform", default=None, metavar="PLATFORM",
        help="force every task's jax platform (prepends the top-level "
        "--platform flag to each task invocation) — e.g. cpu for CI or "
        "when the accelerator is unavailable",
    )
    pl.set_defaults(fn=run_pipeline)


def _topo_order(tasks: list[dict]) -> list[dict]:
    by_key = {t["task_key"]: t for t in tasks}
    if len(by_key) != len(tasks):
        raise ValueError("duplicate task_key in pipeline spec")
    for t in tasks:
        for dep in t.get("depends_on", []):
            if dep not in by_key:
                raise ValueError(
                    f"task {t['task_key']!r} depends on unknown task {dep!r}"
                )
    order: list[dict] = []
    done: set[str] = set()
    remaining = list(tasks)  # spec order is the tiebreak (stable)
    while remaining:
        ready = [
            t for t in remaining if all(d in done for d in t.get("depends_on", []))
        ]
        if not ready:
            cycle = ", ".join(t["task_key"] for t in remaining)
            raise ValueError(f"dependency cycle among tasks: {cycle}")
        for t in ready:
            order.append(t)
            done.add(t["task_key"])
        remaining = [t for t in remaining if t["task_key"] not in done]
    return order


def run_pipeline(args: argparse.Namespace) -> int:
    spec = json.loads(Path(args.spec).read_text())
    default_timeout = spec.get("timeout_seconds", 28800)  # RUNME.py:36
    order = _topo_order(spec.get("tasks", []))
    workdir = str(Path(args.workdir).absolute())

    platform_prefix = (
        ["--platform", args.task_platform]
        if getattr(args, "task_platform", None)
        else []
    )

    def render(argv: list[str]) -> list[str]:
        return platform_prefix + [
            a.replace("{workdir}", workdir) for a in argv
        ]

    if args.dry_run:
        for t in order:
            deps = ",".join(t.get("depends_on", [])) or "-"
            print(f"{t['task_key']:<20} after [{deps}]  dsst {' '.join(render(t['argv']))}")
        return 0

    print(f"pipeline {spec.get('name', Path(args.spec).stem)}: {len(order)} tasks")
    failed: set[str] = set()
    skipped: set[str] = set()
    for t in order:
        key = t["task_key"]
        blocked = [
            d for d in t.get("depends_on", []) if d in failed or d in skipped
        ]
        if blocked:
            print(f"[{key}] SKIPPED (failed dependency {', '.join(blocked)})")
            skipped.add(key)
            continue
        argv = render(t["argv"])
        timeout = t.get("timeout_seconds", default_timeout)
        # Spark-style task retry (the reference's implicit failure handling,
        # SURVEY.md §5.3): max_retries extra attempts before giving up.
        attempts = 1 + int(t.get("max_retries", 0))
        print(f"[{key}] dsst {' '.join(argv)}")
        for attempt in range(attempts):
            t0 = time.perf_counter()
            try:
                proc = subprocess.run(
                    [sys.executable, "-m", "dss_ml_at_scale_tpu.config.cli", *argv],
                    timeout=timeout,
                )
                code = proc.returncode
            except subprocess.TimeoutExpired:
                print(f"[{key}] TIMEOUT after {timeout}s "
                      f"(attempt {attempt + 1}/{attempts})")
                code = None
            dt = time.perf_counter() - t0
            if code == 0:
                print(f"[{key}] ok ({dt:.1f}s)")
                break
            if code is not None:
                print(f"[{key}] FAILED (exit {code}, {dt:.1f}s, "
                      f"attempt {attempt + 1}/{attempts})")
        else:
            failed.add(key)
    if failed:
        skipped_note = (
            f" (skipped: {', '.join(sorted(skipped))})" if skipped else ""
        )
        print(f"pipeline failed: {', '.join(sorted(failed))}{skipped_note}")
        return 1
    print("pipeline ok")
    return 0

"""Experiment tracking: run/param/metric/artifact store."""

from .store import RunStore, start_run  # noqa: F401

"""Experiment tracking: run/param/metric/artifact store + run journal."""

from .store import (  # noqa: F401
    JOURNAL_NAME,
    RunStore,
    classify_run,
    list_runs,
    load_run,
    read_journal,
    set_run_cmdline,
    start_run,
    sweep_interrupted,
)

"""Experiment tracking: run/param/metric/artifact store."""

from .store import RunStore, list_runs, load_run, start_run  # noqa: F401

"""Lightweight experiment tracking (the MLflow-wiring replacement).

The reference threads MLflow through every track: experiment pinning, a
host/token env relay so Spark workers can log, ``MLFlowLogger`` for
Lightning, and autologged HPO trials (reference
``deep_learning/2.distributed-data-loading-petastorm.py:56-75,357-365``,
``hyperopt/1. hyperopt.py:130-136``, ``group_apply/_resources/00-setup.py:71``).

Here tracking is a plain directory store — no server, no token relay:

    <root>/<experiment>/<run_id>/
        meta.json       run name/status/times
        params.json     flat key->value
        metrics.jsonl   {"name","value","step","ts"} per line
        artifacts/      files

Multi-host discipline matches the build spec (SURVEY.md §5.5): metrics
are already globally-reduced inside SPMD programs, so **only process 0
writes**; non-coordinator processes get a no-op store. An optional
``to_mlflow`` export bridges to a real MLflow server when the client
library is installed.

**Crash-only discipline** (the gap the original design left open:
``finish()`` never runs on a hard kill, so killed runs sat RUNNING
forever): every ``*.json`` publish is durable-atomic
(``resilience.durability``), and each run keeps an intent log —
``journal.jsonl`` — recording the writer's PID + boot id, the invoking
command line, every committed checkpoint step, and the terminal status.
A fresh process can therefore classify any run on disk
(:func:`classify_run`): FINISHED / FAILED / INTERRUPTED (meta says
RUNNING but the recorded PID is dead or from another boot) / RUNNING
(PID alive, same boot). ``dsst runs doctor``
(:func:`sweep_interrupted`) sweeps a store root, durably marks dead
runs INTERRUPTED, clears stranded tmp files, and reports which runs
have a resumable checkpoint — the entry point a watchdog or arbiter
uses to converge the store after any number of kills.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Mapping

import jax

from ..resilience import durability

JOURNAL_NAME = "journal.jsonl"
TERMINAL_STATUSES = ("FINISHED", "FAILED", "INTERRUPTED")

# Journal heartbeat throttle: log_metrics touches the journal's mtime at
# most this often, so "heartbeat age" stays meaningful without an fsync
# per metric line.
_HEARTBEAT_EVERY_S = 5.0

# The dsst argv of the current invocation, stashed by the CLI so the
# journal's start event records a replayable command line (what
# `dsst runs doctor --resume` re-executes with --resume-auto).
_run_cmdline: list[str] | None = None


def set_run_cmdline(argv: list[str] | None) -> None:
    global _run_cmdline
    _run_cmdline = list(argv) if argv is not None else None


def _now() -> float:
    return time.time()


def boot_id() -> str:
    """Kernel boot identity, so a recycled PID on a rebooted host can
    never masquerade as a live run."""
    try:
        return Path(
            "/proc/sys/kernel/random/boot_id"
        ).read_text().strip()
    except OSError:
        return ""


def pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False
    return True


class RunStore:
    """One run's param/metric/artifact sink. Cheap, append-only, crash-safe."""

    # The manifest-finalizer thread journals "checkpoint" events through
    # this store while the fit thread logs metrics (heartbeat throttle)
    # and the exit/preemption paths race finish() — the journal lock is
    # the one lock all of that shared state sits under.
    _guarded_by_lock = ("_last_heartbeat", "_closed")
    _lock_name = "_journal_lock"

    def __init__(
        self,
        root: str | os.PathLike,
        experiment: str,
        run_id: str | None = None,
        run_name: str | None = None,
        *,
        coordinator_only: bool = True,
        resume: bool = False,
    ):
        self.active = not coordinator_only or jax.process_index() == 0
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.path = Path(root) / experiment / self.run_id
        self._closed = False
        if not self.active:
            return
        if self.path.exists() and not resume and run_id is not None:
            raise FileExistsError(f"run already exists: {self.path}")
        (self.path / "artifacts").mkdir(parents=True, exist_ok=True)
        self._metrics = open(self.path / "metrics.jsonl", "a", encoding="utf-8")
        meta = {"experiment": experiment, "run_id": self.run_id,
                "run_name": run_name or self.run_id, "status": "RUNNING",
                "start_time": _now()}
        self._write_json("meta.json", meta)
        # Intent log: who is writing this run, from which boot, launched
        # how. The journal is what lets a FUTURE process classify this
        # run after a hard kill — meta.json alone can only ever say
        # RUNNING.
        self._journal_lock = threading.Lock()
        self._last_heartbeat = 0.0
        start_event: dict[str, Any] = {
            "event": "start", "pid": os.getpid(), "boot_id": boot_id(),
            "cwd": os.getcwd(),
        }
        if _run_cmdline is not None:
            start_event["cmdline"] = list(_run_cmdline)
        self.journal_event(**start_event)
        # Always-on flight recorder: this run's span begin/end events go
        # to a crash-durable tail in the run directory, so the last
        # events of a SIGKILLed run — including spans still open at the
        # kill — are reconstructible (`dsst trace tail`). Registered in
        # the journal so classify_run/doctor can point at the file.
        from ..telemetry import flightrec

        self._trace_path = flightrec.enable(self.path / "flightrec.jsonl")
        self.journal_event("trace", path=str(self._trace_path))
        # SLO alert transitions journal into the run directory through
        # the same crash-durable appender: if this run dies with an
        # alert firing, the doctor can say so from disk alone.
        from ..telemetry import slo as slo_mod

        self._alerts_path = slo_mod.get_engine().attach_journal(
            self.path / "alerts.jsonl"
        )
        self.journal_event("slo_journal", path=str(self._alerts_path))

    # -- logging ----------------------------------------------------------

    def log_params(self, params: Mapping[str, Any]) -> None:
        if not self.active:
            return
        merged = {}
        f = self.path / "params.json"
        if f.exists():
            merged = json.loads(f.read_text())
        merged.update({k: _jsonable(v) for k, v in params.items()})
        self._write_json("params.json", merged)

    def log_metrics(self, metrics: Mapping[str, Any], step: int | None = None) -> None:
        if not self.active:
            return
        ts = _now()
        lines = "".join(
            json.dumps({"name": name, "value": float(value), "step": step,
                        "ts": ts}) + "\n"
            for name, value in metrics.items()
        )
        with self._journal_lock:
            # finish() flips _closed and closes the handle under this
            # lock; a fit thread logging during shutdown drops the lines
            # instead of writing to a closed file.
            if self._closed:
                return
            self._metrics.write(lines)
            self._metrics.flush()
        self._heartbeat(ts)

    def _heartbeat(self, ts: float) -> None:
        """Throttled journal mtime touch: liveness evidence for the
        doctor without an fsync per metric line."""
        with self._journal_lock:
            if ts - self._last_heartbeat < _HEARTBEAT_EVERY_S:
                return
            self._last_heartbeat = ts
        try:
            os.utime(self.path / JOURNAL_NAME)
        except OSError:
            pass

    # -- the run journal (intent log) -------------------------------------

    def journal_event(self, event: str, **fields: Any) -> None:
        """Durably append one intent-log line (``journal.jsonl``).

        Events the package writes: ``start`` (pid/boot_id/cmdline),
        ``resume`` (restored checkpoint step), ``checkpoint``
        (manifest-committed step + dir), ``trial`` (completed HPO
        trial), ``finish`` (terminal status), ``interrupted`` (doctor
        verdict). Foreign events are fine — readers ignore what they
        don't know.
        """
        if not self.active:
            return
        obj = {"event": event, "time": _now(), **fields}
        with self._journal_lock:
            durability.append_jsonl(
                self.path / JOURNAL_NAME, [obj], kind="journal"
            )

    def journal_checkpoint(self, step: int, checkpoint_dir: str) -> None:
        """Record a manifest-committed checkpoint step — the journal's
        'last committed step' the doctor reports as resumable."""
        self.journal_event(
            "checkpoint", step=int(step),
            checkpoint_dir=str(Path(checkpoint_dir).absolute()),
        )

    def log_artifact(self, src: str | os.PathLike, name: str | None = None) -> None:
        if not self.active:
            return
        src = Path(src)
        shutil.copy2(src, self.path / "artifacts" / (name or src.name))

    def log_text(self, text: str, name: str) -> None:
        if not self.active:
            return
        (self.path / "artifacts" / name).write_text(text)

    def log_telemetry(self, snapshot: Mapping[str, Any] | None = None) -> None:
        """Archive a telemetry snapshot as this run's ``telemetry.json``.

        ``snapshot`` defaults to the process registry's current state
        (:func:`dss_ml_at_scale_tpu.telemetry.snapshot`) so callers at
        run end archive their final counters with one call.
        """
        if not self.active:
            return
        if snapshot is None:
            from .. import telemetry

            snapshot = telemetry.snapshot()
        self._write_json("telemetry.json", snapshot)

    def finish(self, status: str = "FINISHED") -> None:
        """Close the run. Idempotent: a second finish (e.g. the crash
        handler racing a normal close) is a no-op instead of a
        double-close of the metrics handle."""
        if not self.active:
            return
        with self._journal_lock:
            if self._closed:
                return
            self._closed = True
        self.journal_event("finish", status=status)
        meta = json.loads((self.path / "meta.json").read_text())
        meta.update(status=status, end_time=_now())
        self._write_json("meta.json", meta)
        self._metrics.close()
        # Stop recording into a finished run — but only if the recorder
        # still targets THIS run's tail (a newer run may have
        # re-targeted it already; disable(path) is a no-op then).
        from ..telemetry import flightrec

        flightrec.disable(self._trace_path)
        # Same scoping rule for the alert journal: detach only if the
        # engine still targets THIS run's file.
        from ..telemetry import slo as slo_mod

        slo_mod.get_engine().detach_journal(self._alerts_path)

    # -- context manager (finish() may never run on a hard crash; `with`
    # scopes the metrics handle to the block and stamps the outcome) ------

    def __enter__(self) -> "RunStore":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.finish("FAILED" if exc_type is not None else "FINISHED")
        return False

    # -- reading back -----------------------------------------------------

    def metrics(self) -> list[dict]:
        if not self.active:
            return []
        with self._journal_lock:
            if not self._closed:
                # Read-back while the append handle is still open: flush
                # so the reader sees every logged line. Under the lock:
                # finish() may close the handle between an unlocked
                # check and the flush.
                self._metrics.flush()
        with open(self.path / "metrics.jsonl", encoding="utf-8") as f:
            return [json.loads(line) for line in f if line.strip()]

    def params(self) -> dict:
        f = self.path / "params.json"
        return json.loads(f.read_text()) if self.active and f.exists() else {}

    def _write_json(self, name: str, obj) -> None:
        # Durable atomic publish: meta.json flipping to FINISHED (or a
        # params/telemetry rewrite) must survive a power cut and can
        # never be read torn.
        durability.durable_write_json(
            self.path / name, obj, indent=2, kind="run_json"
        )

    # -- optional MLflow bridge ------------------------------------------

    def to_mlflow(self, tracking_uri: str | None = None) -> None:
        """Export this run to an MLflow server, if mlflow is installed."""
        if not self.active:
            return
        import mlflow  # optional dependency, import deferred

        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        meta = json.loads((self.path / "meta.json").read_text())
        mlflow.set_experiment(meta["experiment"])
        with mlflow.start_run(run_name=meta["run_name"]):
            mlflow.log_params(self.params())
            for m in self.metrics():
                mlflow.log_metric(m["name"], m["value"], step=m["step"] or 0)


def read_journal(run_dir: str | os.PathLike) -> list[dict]:
    """Parse a run's ``journal.jsonl``, tolerating a torn last line
    (a kill mid-append is exactly the condition the journal exists
    for)."""
    path = Path(run_dir) / JOURNAL_NAME
    events: list[dict] = []
    if not path.exists():
        return events
    try:
        text = path.read_text(encoding="utf-8")
    except OSError:
        return events
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn append: skip, never crash the classifier
        if isinstance(obj, dict) and "event" in obj:
            events.append(obj)
    return events


def classify_run(run_dir: str | os.PathLike) -> dict:
    """Journal-based status of one run directory, judged from disk.

    Returns a dict with (at least): ``status`` (the stored meta
    status), ``effective_status`` (FINISHED / FAILED / INTERRUPTED /
    RUNNING / UNKNOWN), ``live`` (pid alive, same boot), ``pid``,
    ``last_step`` + ``checkpoint_dir`` (newest journaled checkpoint
    commit), ``heartbeat_age_s``, and ``cmdline`` (the recorded dsst
    invocation, for doctor --resume).
    """
    run_dir = Path(run_dir)
    out: dict[str, Any] = {
        "run_dir": str(run_dir),
        "run_id": run_dir.name,
        "experiment": run_dir.parent.name,
        "status": None,
        "effective_status": "UNKNOWN",
        "live": False,
        "pid": None,
        "last_step": None,
        "checkpoint_dir": None,
        "cmdline": None,
        "cwd": None,
        "trace_file": None,
        "alerts_file": None,
        "firing_alerts": [],
        "heartbeat_age_s": None,
    }
    try:
        meta = json.loads((run_dir / "meta.json").read_text())
    except (OSError, json.JSONDecodeError):
        return out
    out["status"] = meta.get("status")
    out["start_time"] = meta.get("start_time")
    events = read_journal(run_dir)
    for e in events:
        if e["event"] == "start":
            out["pid"] = e.get("pid")
            out["boot_id"] = e.get("boot_id", "")
            if e.get("cmdline"):
                out["cmdline"] = e["cmdline"]
            if e.get("cwd"):
                out["cwd"] = e["cwd"]
        elif e["event"] == "config":
            if e.get("checkpoint_dir"):
                out["checkpoint_dir"] = e["checkpoint_dir"]
        elif e["event"] == "trace":
            # The flight-recorder tail this run's writer recorded into —
            # where a dead run's last (and in-flight) spans live.
            out["trace_file"] = e.get("path")
        elif e["event"] == "slo_journal":
            out["alerts_file"] = e.get("path")
        elif e["event"] in ("checkpoint", "manifest_repair"):
            out["last_step"] = e.get("step")
            out["checkpoint_dir"] = e.get("checkpoint_dir")
    journal = run_dir / JOURNAL_NAME
    try:
        out["heartbeat_age_s"] = round(_now() - journal.stat().st_mtime, 1)
    except OSError:
        pass
    if out["alerts_file"]:
        # Alerts whose LAST journaled transition left them firing: for
        # a dead run this is "what was burning when it died"; for a
        # live one, what is burning now.
        from ..telemetry import slo as slo_mod

        out["firing_alerts"] = slo_mod.firing_at_death(out["alerts_file"])
    if out["status"] in TERMINAL_STATUSES:
        out["effective_status"] = out["status"]
        return out
    if out["status"] != "RUNNING":
        return out
    if out["pid"] is None:
        # A pre-journal (or torn-at-birth) RUNNING run: nothing can
        # vouch for a live writer, so it is interrupted by default.
        out["effective_status"] = "INTERRUPTED"
        return out
    same_boot = (not out.get("boot_id")) or out["boot_id"] == boot_id()
    out["live"] = same_boot and pid_alive(int(out["pid"]))
    out["effective_status"] = "RUNNING" if out["live"] else "INTERRUPTED"
    return out


def sweep_interrupted(root, experiment: str | None = None, *,
                      mark: bool = True) -> list[dict]:
    """The ``dsst runs doctor`` core: classify every run under ``root``.

    Dead-PID RUNNING runs are (with ``mark=True``) durably flipped to
    INTERRUPTED in ``meta.json``, journaled (``interrupted`` event),
    counted on ``runs_interrupted_total``, and swept of stranded
    ``*.tmp`` files. Each returned entry additionally carries
    ``resumable_step``: the newest manifest-intact (or unverified)
    checkpoint step under the run's journaled checkpoint dir, or None.
    """
    from .. import telemetry
    from ..resilience import checkpoint as integrity

    interrupted = telemetry.counter(
        "runs_interrupted_total",
        "dead-PID RUNNING runs marked INTERRUPTED by the doctor sweep",
    )
    root = Path(root)
    report: list[dict] = []
    experiments = (
        [root / experiment] if experiment
        else sorted(p for p in root.iterdir() if p.is_dir())
        if root.is_dir() else []
    )
    for exp_dir in experiments:
        if not exp_dir.is_dir():
            continue
        for run_dir in sorted(p for p in exp_dir.iterdir() if p.is_dir()):
            cls = classify_run(run_dir)
            if cls["status"] is None:
                continue  # foreign/unreadable directory: not a run
            newly_marked = (
                mark
                and cls["status"] == "RUNNING"
                and cls["effective_status"] == "INTERRUPTED"
            )
            if newly_marked:
                try:
                    meta = json.loads((run_dir / "meta.json").read_text())
                    meta.update(
                        status="INTERRUPTED",
                        end_time=(run_dir / JOURNAL_NAME).stat().st_mtime
                        if (run_dir / JOURNAL_NAME).exists() else _now(),
                        interrupted_by="runs doctor",
                    )
                    durability.durable_write_json(
                        run_dir / "meta.json", meta, indent=2,
                        kind="run_json",
                    )
                    durability.append_jsonl(
                        run_dir / JOURNAL_NAME,
                        [{"event": "interrupted", "time": _now(),
                          "by": "runs doctor",
                          "dead_pid": cls["pid"]}],
                        kind="journal",
                    )
                except OSError as e:
                    # The mark did NOT land: report and count nothing —
                    # a "marked" claim the next sweep repeats would
                    # double-count forever and lie to the operator.
                    cls["mark_error"] = str(e)
                else:
                    interrupted.inc()
                    cls["marked"] = True
                    swept = durability.sweep_stranded_tmp(run_dir)
                    cls["swept_tmp"] = [str(p) for p in swept]
            cls["resumable_step"] = None
            if (
                cls["effective_status"] == "INTERRUPTED"
                and cls["checkpoint_dir"]
                and Path(cls["checkpoint_dir"]).is_dir()
            ):
                for step in sorted(
                    integrity.list_steps(cls["checkpoint_dir"]), reverse=True
                ):
                    status, _ = integrity.verify_step(
                        Path(cls["checkpoint_dir"]) / str(step)
                    )
                    if status in ("intact", "unverified"):
                        cls["resumable_step"] = step
                        break
            report.append(cls)
    return report


def list_runs(root, experiment: str | None = None) -> list[dict]:
    """Run summaries under a store root, newest first.

    The read side of the store (the `mlflow ui` browsing equivalent for
    a plain-FS root): each entry is the run's ``meta.json`` plus a
    ``wall_seconds`` convenience — metadata only, so listing stays O(1)
    per run regardless of metric volume (``load_run`` reads the
    metrics). Unreadable/foreign directories are skipped, not fatal.
    """
    root = Path(root)
    out: list[dict] = []
    experiments = (
        [root / experiment] if experiment else
        sorted(p for p in root.iterdir() if p.is_dir()) if root.is_dir()
        else []
    )
    for exp_dir in experiments:
        if not exp_dir.is_dir():
            continue
        for run_dir in sorted(p for p in exp_dir.iterdir() if p.is_dir()):
            meta_file = run_dir / "meta.json"
            try:
                meta = json.loads(meta_file.read_text())
            except (OSError, json.JSONDecodeError):
                continue
            if meta.get("end_time") and meta.get("start_time"):
                meta["wall_seconds"] = round(
                    meta["end_time"] - meta["start_time"], 1
                )
            if meta.get("status") == "RUNNING":
                # Journal-truth rendering: a RUNNING run whose recorded
                # PID is dead shows as INTERRUPTED in listings even
                # before a doctor sweep rewrites its meta (the listing
                # itself never writes).
                cls = classify_run(run_dir)
                meta["live"] = cls["live"]
                if cls["effective_status"] == "INTERRUPTED":
                    meta["status"] = "INTERRUPTED"
            out.append(meta)
    out.sort(key=lambda m: m.get("start_time", 0.0), reverse=True)
    return out


def load_run(root, experiment: str, run_id: str) -> dict:
    """Full record of one run: meta, params, the last value of every
    metric (with its step), and artifact names."""
    path = Path(root) / experiment / run_id
    meta = json.loads((path / "meta.json").read_text())
    params_file = path / "params.json"
    params = (
        json.loads(params_file.read_text()) if params_file.exists() else {}
    )
    last: dict[str, dict] = {}
    n_points = 0
    metrics_file = path / "metrics.jsonl"
    if metrics_file.exists():
        with open(metrics_file, encoding="utf-8") as f:
            for line in f:
                if not line.strip():
                    continue
                m = json.loads(line)
                last[m["name"]] = {"value": m["value"], "step": m["step"]}
                n_points += 1
    artifacts_dir = path / "artifacts"
    artifacts = (
        sorted(p.name for p in artifacts_dir.iterdir())
        if artifacts_dir.is_dir() else []
    )
    return {
        "meta": meta,
        "params": params,
        "last_metrics": last,
        "metric_points": n_points,
        "artifacts": artifacts,
    }


@contextlib.contextmanager
def start_run(root, experiment, **kwargs):
    """``with start_run(...) as run:`` — mirrors ``mlflow.start_run()``."""
    run = RunStore(root, experiment, **kwargs)
    try:
        yield run
        run.finish("FINISHED")
    except BaseException:
        run.finish("FAILED")
        raise


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return str(v)

"""Lightweight experiment tracking (the MLflow-wiring replacement).

The reference threads MLflow through every track: experiment pinning, a
host/token env relay so Spark workers can log, ``MLFlowLogger`` for
Lightning, and autologged HPO trials (reference
``deep_learning/2.distributed-data-loading-petastorm.py:56-75,357-365``,
``hyperopt/1. hyperopt.py:130-136``, ``group_apply/_resources/00-setup.py:71``).

Here tracking is a plain directory store — no server, no token relay:

    <root>/<experiment>/<run_id>/
        meta.json       run name/status/times
        params.json     flat key->value
        metrics.jsonl   {"name","value","step","ts"} per line
        artifacts/      files

Multi-host discipline matches the build spec (SURVEY.md §5.5): metrics
are already globally-reduced inside SPMD programs, so **only process 0
writes**; non-coordinator processes get a no-op store. An optional
``to_mlflow`` export bridges to a real MLflow server when the client
library is installed.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import time
import uuid
from pathlib import Path
from typing import Any, Mapping

import jax


def _now() -> float:
    return time.time()


class RunStore:
    """One run's param/metric/artifact sink. Cheap, append-only, crash-safe."""

    def __init__(
        self,
        root: str | os.PathLike,
        experiment: str,
        run_id: str | None = None,
        run_name: str | None = None,
        *,
        coordinator_only: bool = True,
        resume: bool = False,
    ):
        self.active = not coordinator_only or jax.process_index() == 0
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self.path = Path(root) / experiment / self.run_id
        self._closed = False
        if not self.active:
            return
        if self.path.exists() and not resume and run_id is not None:
            raise FileExistsError(f"run already exists: {self.path}")
        (self.path / "artifacts").mkdir(parents=True, exist_ok=True)
        self._metrics = open(self.path / "metrics.jsonl", "a", encoding="utf-8")
        meta = {"experiment": experiment, "run_id": self.run_id,
                "run_name": run_name or self.run_id, "status": "RUNNING",
                "start_time": _now()}
        self._write_json("meta.json", meta)

    # -- logging ----------------------------------------------------------

    def log_params(self, params: Mapping[str, Any]) -> None:
        if not self.active:
            return
        merged = {}
        f = self.path / "params.json"
        if f.exists():
            merged = json.loads(f.read_text())
        merged.update({k: _jsonable(v) for k, v in params.items()})
        self._write_json("params.json", merged)

    def log_metrics(self, metrics: Mapping[str, Any], step: int | None = None) -> None:
        if not self.active:
            return
        ts = _now()
        for name, value in metrics.items():
            self._metrics.write(
                json.dumps({"name": name, "value": float(value), "step": step, "ts": ts})
                + "\n"
            )
        self._metrics.flush()

    def log_artifact(self, src: str | os.PathLike, name: str | None = None) -> None:
        if not self.active:
            return
        src = Path(src)
        shutil.copy2(src, self.path / "artifacts" / (name or src.name))

    def log_text(self, text: str, name: str) -> None:
        if not self.active:
            return
        (self.path / "artifacts" / name).write_text(text)

    def log_telemetry(self, snapshot: Mapping[str, Any] | None = None) -> None:
        """Archive a telemetry snapshot as this run's ``telemetry.json``.

        ``snapshot`` defaults to the process registry's current state
        (:func:`dss_ml_at_scale_tpu.telemetry.snapshot`) so callers at
        run end archive their final counters with one call.
        """
        if not self.active:
            return
        if snapshot is None:
            from .. import telemetry

            snapshot = telemetry.snapshot()
        self._write_json("telemetry.json", snapshot)

    def finish(self, status: str = "FINISHED") -> None:
        """Close the run. Idempotent: a second finish (e.g. the crash
        handler racing a normal close) is a no-op instead of a
        double-close of the metrics handle."""
        if not self.active or self._closed:
            return
        self._closed = True
        meta = json.loads((self.path / "meta.json").read_text())
        meta.update(status=status, end_time=_now())
        self._write_json("meta.json", meta)
        self._metrics.close()

    # -- context manager (finish() may never run on a hard crash; `with`
    # scopes the metrics handle to the block and stamps the outcome) ------

    def __enter__(self) -> "RunStore":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.finish("FAILED" if exc_type is not None else "FINISHED")
        return False

    # -- reading back -----------------------------------------------------

    def metrics(self) -> list[dict]:
        if not self.active:
            return []
        if not self._closed:
            # Read-back while the append handle is still open: flush so
            # the reader sees every logged line.
            self._metrics.flush()
        with open(self.path / "metrics.jsonl", encoding="utf-8") as f:
            return [json.loads(line) for line in f if line.strip()]

    def params(self) -> dict:
        f = self.path / "params.json"
        return json.loads(f.read_text()) if self.active and f.exists() else {}

    def _write_json(self, name: str, obj) -> None:
        tmp = self.path / (name + ".tmp")
        tmp.write_text(json.dumps(obj, indent=2))
        tmp.replace(self.path / name)

    # -- optional MLflow bridge ------------------------------------------

    def to_mlflow(self, tracking_uri: str | None = None) -> None:
        """Export this run to an MLflow server, if mlflow is installed."""
        if not self.active:
            return
        import mlflow  # optional dependency, import deferred

        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        meta = json.loads((self.path / "meta.json").read_text())
        mlflow.set_experiment(meta["experiment"])
        with mlflow.start_run(run_name=meta["run_name"]):
            mlflow.log_params(self.params())
            for m in self.metrics():
                mlflow.log_metric(m["name"], m["value"], step=m["step"] or 0)


def list_runs(root, experiment: str | None = None) -> list[dict]:
    """Run summaries under a store root, newest first.

    The read side of the store (the `mlflow ui` browsing equivalent for
    a plain-FS root): each entry is the run's ``meta.json`` plus a
    ``wall_seconds`` convenience — metadata only, so listing stays O(1)
    per run regardless of metric volume (``load_run`` reads the
    metrics). Unreadable/foreign directories are skipped, not fatal.
    """
    root = Path(root)
    out: list[dict] = []
    experiments = (
        [root / experiment] if experiment else
        sorted(p for p in root.iterdir() if p.is_dir()) if root.is_dir()
        else []
    )
    for exp_dir in experiments:
        if not exp_dir.is_dir():
            continue
        for run_dir in sorted(p for p in exp_dir.iterdir() if p.is_dir()):
            meta_file = run_dir / "meta.json"
            try:
                meta = json.loads(meta_file.read_text())
            except (OSError, json.JSONDecodeError):
                continue
            if meta.get("end_time") and meta.get("start_time"):
                meta["wall_seconds"] = round(
                    meta["end_time"] - meta["start_time"], 1
                )
            out.append(meta)
    out.sort(key=lambda m: m.get("start_time", 0.0), reverse=True)
    return out


def load_run(root, experiment: str, run_id: str) -> dict:
    """Full record of one run: meta, params, the last value of every
    metric (with its step), and artifact names."""
    path = Path(root) / experiment / run_id
    meta = json.loads((path / "meta.json").read_text())
    params_file = path / "params.json"
    params = (
        json.loads(params_file.read_text()) if params_file.exists() else {}
    )
    last: dict[str, dict] = {}
    n_points = 0
    metrics_file = path / "metrics.jsonl"
    if metrics_file.exists():
        with open(metrics_file, encoding="utf-8") as f:
            for line in f:
                if not line.strip():
                    continue
                m = json.loads(line)
                last[m["name"]] = {"value": m["value"], "step": m["step"]}
                n_points += 1
    artifacts_dir = path / "artifacts"
    artifacts = (
        sorted(p.name for p in artifacts_dir.iterdir())
        if artifacts_dir.is_dir() else []
    )
    return {
        "meta": meta,
        "params": params,
        "last_metrics": last,
        "metric_points": n_points,
        "artifacts": artifacts,
    }


@contextlib.contextmanager
def start_run(root, experiment, **kwargs):
    """``with start_run(...) as run:`` — mirrors ``mlflow.start_run()``."""
    run = RunStore(root, experiment, **kwargs)
    try:
        yield run
        run.finish("FINISHED")
    except BaseException:
        run.finish("FAILED")
        raise


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return str(v)

"""Pallas TPU flash attention: blockwise online-softmax attention.

The reference has no attention anywhere (SURVEY.md §5.7) — this op exists
because the framework treats long-context as first-class: it is the
single-device fast path of the attention stack (cross-shard sequence
parallelism lives in :mod:`dss_ml_at_scale_tpu.parallel.ring`, which
shares this module's blockwise-softmax math) and the building block of
the transformer model family.

Design (pallas_guide.md patterns):

- grid ``(batch*heads, q_blocks, k_blocks)``; the k dimension is the
  innermost sequential axis, so VMEM scratch (acc, running max m, running
  denominator l) persists across k steps — the classic TPU flash forward.
- Q·Kᵀ and P·V hit the MXU via ``jnp.dot(..., preferred_element_type=f32)``;
  inputs may be bf16, statistics and accumulation are f32.
- Causal masking via ``broadcasted_iota`` global indices; fully-masked
  k-blocks are skipped with ``pl.when`` (no wasted MXU work past the
  diagonal).
- Backward is a ``custom_vjp`` that recomputes attention in q-chunks under
  ``jax.checkpoint``: peak memory is O(block_q × S) in both directions,
  never O(S²), while the recompute stays compiler-fused XLA.

Off-TPU (CPU tests, the simulated 8-device mesh) the kernel runs in
Pallas interpret mode automatically.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30  # finite "minus infinity": avoids inf-inf NaNs in masking


def _is_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False
) -> jax.Array:
    """Plain XLA attention, the numerical ground truth for the kernel.

    Shapes ``[..., seq, head_dim]`` with softmax over the second-to-last
    axis of the score matrix; computed in f32 regardless of input dtype.
    With ``causal=True`` and ``sq != sk`` the mask is bottom-right aligned
    (query row r attends to keys ``<= r + sk - sq``) — the decode-with-
    cache convention.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum(
        "...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = q.shape[-2], k.shape[-2]
        qi = jnp.arange(sq)[:, None] + (sk - sq)
        ki = jnp.arange(sk)[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "...qk,...kd->...qd", p, v.astype(jnp.float32)
    ).astype(q.dtype)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, causal, block_q,
    block_k, scale, causal_offset
):
    i = pl.program_id(1)  # q-block index
    j = pl.program_id(2)  # k-block index (innermost, sequential)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Under causality, k-blocks wholly above the (offset) diagonal
    # contribute nothing: q rows [i·bq, (i+1)·bq) never see k columns
    # >= (i+1)·bq + offset (bottom-right alignment when sq != sk).
    live = (not causal) or (j * block_k < (i + 1) * block_q + causal_offset)

    @pl.when(live)
    def _step():
        # Keep native dtype into the MXU (bf16×bf16 with f32 accumulate).
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]  # (block_k, d)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            qi = causal_offset + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            ki = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qi >= ki, s, _NEG_INF)

        m_prev = m_ref[:, :1]  # (block_q, 1), lanes replicated
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        # l is never zero: causal rows always see at least the diagonal.
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool,
    block_q: int, block_k: int, interpret: bool
) -> jax.Array:
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    sk = k.shape[1]
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {sk}) must be multiples of blocks "
            f"({block_q}, {block_k}); pad upstream"
        )
    grid = (bh, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _flash_kernel, causal=causal, block_q=block_q, block_k=block_k,
        scale=1.0 / math.sqrt(d), causal_offset=sk - sq,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _chunked_reference(q, k, v, *, causal, chunk):
    """Attention recompute in q-chunks of ``chunk`` rows.

    Each chunk is wrapped in ``jax.checkpoint`` so its O(chunk × sk) score
    matrix is rematerialized during the backward instead of stored —
    differentiating through this keeps peak memory O(chunk × sk), never
    O(sq × sk). Used only inside the custom VJP.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)

    @jax.checkpoint
    def one_chunk(q_chunk, start):
        s = jnp.einsum(
            "bqd,bkd->bqk", q_chunk, k, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qi = start + (sk - sq) + jnp.arange(chunk)[:, None]
            ki = jnp.arange(sk)[None, :]
            s = jnp.where(qi >= ki, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)

    n = sq // chunk
    q_chunks = q.reshape(bh, n, chunk, d).transpose(1, 0, 2, 3)
    starts = jnp.arange(n) * chunk
    out = jax.lax.map(lambda args: one_chunk(*args), (q_chunks, starts))
    return out.transpose(1, 0, 2, 3).reshape(bh, sq, d)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    chunk = min(block_q, q.shape[1])
    _, vjp = jax.vjp(
        lambda q, k, v: _chunked_reference(q, k, v, causal=causal, chunk=chunk),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Blockwise flash attention over ``[batch, heads, seq, head_dim]``.

    Differentiable (custom VJP); bf16 in/out with f32 softmax statistics.
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU.
    Default blocks (256, 512) measured fastest on TPU v5e at seq 2048,
    head_dim 128 — ~1.3× the fused XLA attention on the same shapes.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [batch, heads, seq, head_dim], got {q.shape}")
    if causal and q.shape[2] > k.shape[2]:
        # Bottom-right alignment gives the first sq - sk query rows zero
        # visible keys: their softmax denominator is 0 and the kernel
        # emits non-finite rows. No attention semantics want this shape.
        raise ValueError(
            f"causal flash attention needs sq <= sk, got sq={q.shape[2]} "
            f"sk={k.shape[2]} (rows before the first key would attend to "
            "nothing)"
        )
    if interpret is None:
        interpret = not _is_tpu()
    b, h, sq, d = q.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, k.shape[2])
    out = _flash(
        q.reshape(b * h, sq, d),
        k.reshape(b * h, k.shape[2], d),
        v.reshape(b * h, v.shape[2], d),
        causal, block_q, block_k, interpret,
    )
    return out.reshape(b, h, sq, d)

"""SARIMAX (ARIMA + exogenous regressors) as a vmappable JAX program.

Capability target (SURVEY.md §2.2 X10): statsmodels
``SARIMAX(train, exog=..., order=(p,d,q), seasonal_order=(0,0,0,0))
.fit(method='nm')`` then ``.predict(start, end, exog=...)`` — the exact
surface the reference's per-SKU tuner exercises
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:441-494``), with
p ∈ [0,4], d ∈ [0,2], q ∈ [0,4] searched by Hyperopt (``:462-464``).

TPU-first design: the reference runs one Python/statsmodels fit per Spark
task per SKU. Here orders ``(p, d, q)`` are **traced** values masked
against static maxima (``SarimaxConfig``), so a single compiled program
``vmap``s the whole fit across thousands of groups — and across HPO
candidates — at once. That is the max-order padded parameterization
SURVEY.md §7 ("hard parts" #1) calls for.

Model: y_t = x_t'beta + u_t, with Delta^d u_t ~ ARMA(p, q). The ARMA part
runs through a Harvey-representation Kalman filter (state dim
``max(max_p, max_q + 1)``); initialization solves the stationary
Lyapunov equation when valid and falls back to approximate-diffuse
(statsmodels' ``initialization='approximate_diffuse'``) otherwise, which
covers non-stationary iterates since stationarity is not enforced
(reference passes ``enforce_stationarity=False``, ``:447-448``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .kalman import kalman_filter
from .neldermead import nelder_mead


@dataclasses.dataclass(frozen=True)
class SarimaxConfig:
    """Static shape bounds; traced per-fit orders are masked against these."""

    max_p: int = 4
    max_d: int = 2
    max_q: int = 4
    k_exog: int = 0
    kappa: float = 1e4  # approximate-diffuse prior variance scale
    max_iter: int = 200  # Nelder-Mead iterations (reference: method='nm')
    bfgs_iter: int = 100  # gradient polish after NM (0 disables)

    @property
    def state_dim(self) -> int:
        return max(self.max_p, self.max_q + 1)

    @property
    def n_params(self) -> int:
        # [beta (k_exog), phi (max_p), theta (max_q), log_sigma2]
        return self.k_exog + self.max_p + self.max_q + 1

    def unpack(self, params):
        k, p, q = self.k_exog, self.max_p, self.max_q
        return (
            params[:k],
            params[k : k + p],
            params[k + p : k + p + q],
            params[k + p + q],
        )


class SarimaxResult(NamedTuple):
    params: jax.Array  # (n_params,) packed [beta, phi, theta, log_sigma2]
    loglike: jax.Array
    n_iter: jax.Array
    converged: jax.Array


def _difference(x: jax.Array, d: jax.Array, max_d: int) -> jax.Array:
    """Delta^d x with traced d <= max_d; first d outputs are invalid."""
    z = jnp.zeros_like(x[:1])
    branches = [lambda x=x: x]
    if max_d >= 1:
        branches.append(lambda x=x: jnp.concatenate([z, x[1:] - x[:-1]]))
    if max_d >= 2:
        branches.append(
            lambda x=x: jnp.concatenate([z, z, x[2:] - 2 * x[1:-1] + x[:-2]])
        )
    return lax.switch(jnp.clip(d, 0, max_d), branches)


def _ssm_matrices(cfg: SarimaxConfig, phi_eff, theta_eff, sigma2):
    """Harvey representation: T companion on phi, R = [1, theta...]."""
    r = cfg.state_dim
    T = jnp.zeros((r, r), phi_eff.dtype)
    T = T.at[:, 0].set(jnp.pad(phi_eff, (0, r - cfg.max_p)))
    T = T.at[jnp.arange(r - 1), jnp.arange(1, r)].set(1.0)
    R = jnp.concatenate([jnp.ones(1, theta_eff.dtype), jnp.pad(theta_eff, (0, r - 1 - cfg.max_q))])
    R = R.reshape(r, 1)
    Q = sigma2.reshape(1, 1)
    Z = jnp.zeros(r, phi_eff.dtype).at[0].set(1.0)
    return T, R, Q, Z


def _init_cov(cfg: SarimaxConfig, T, RQR, sigma2, r_eff):
    """Stationary Lyapunov solve, approximate-diffuse fallback.

    The diffuse identity covers only the ``r_eff = max(p, q+1)`` ACTIVE
    state dims: the companion superdiagonal feeds padded dims into the
    observed one, so diffuse mass on them would inflate early innovation
    variances relative to the unpadded (statsmodels) state space. With
    zero diffuse variance and zero dynamics on padded dims, the padded
    filter reproduces the unpadded one exactly.
    """
    r = cfg.state_dim
    eye = jnp.eye(r * r, dtype=T.dtype)
    P_vec = jnp.linalg.solve(eye - jnp.kron(T, T), RQR.reshape(-1))
    P = P_vec.reshape(r, r)
    P = 0.5 * (P + P.T)
    kappa = cfg.kappa * jnp.maximum(sigma2, 1.0)
    # Padded state dims legitimately have zero stationary variance, so the
    # validity check allows diag == 0; only reject non-finite / negative /
    # exploding solves (non-stationary phi iterates under Nelder-Mead).
    ok = (
        jnp.all(jnp.isfinite(P))
        & jnp.all(jnp.diag(P) >= -1e-6)
        & (jnp.max(jnp.abs(P)) < kappa)
    )
    active = (jnp.arange(r) < r_eff).astype(T.dtype)
    return jnp.where(ok, P, kappa * jnp.diag(active))


def _filter(cfg: SarimaxConfig, params, y, exog, order, n_valid):
    """Shared setup: regression residual → difference → Kalman filter."""
    p, d, q = order
    beta, phi, theta, log_sigma2 = cfg.unpack(params)
    phi_eff = phi * (jnp.arange(cfg.max_p) < p)
    theta_eff = theta * (jnp.arange(cfg.max_q) < q)
    sigma2 = jnp.exp(log_sigma2)

    resid = y - (exog @ beta if cfg.k_exog else jnp.zeros_like(y))
    w = _difference(resid, d, cfg.max_d)
    t_idx = jnp.arange(y.shape[0])
    mask = (t_idx >= d) & (t_idx < n_valid)

    T, R, Q, Z = _ssm_matrices(cfg, phi_eff, theta_eff, sigma2)
    r_eff = jnp.maximum(jnp.maximum(p, q + 1), 1)
    P0 = _init_cov(cfg, T, R @ Q @ R.T, sigma2, r_eff)
    a0 = jnp.zeros(cfg.state_dim, y.dtype)
    filt = kalman_filter(w, T, R, Q, Z, jnp.asarray(0.0, y.dtype), a0, P0, mask=mask)
    return filt, resid, mask


def sarimax_loglike(cfg: SarimaxConfig, params, y, exog, order, n_valid) -> jax.Array:
    """Exact (prediction-error decomposition) log-likelihood."""
    filt, _, _ = _filter(cfg, params, y, exog, order, n_valid)
    return filt.loglike


def _lagmat(x, k: int):
    """(n, k) matrix of x lagged 1..k, zero before the start."""
    n = x.shape[0]
    idx = jnp.arange(n)[:, None] - (jnp.arange(k)[None, :] + 1)
    return jnp.where(idx >= 0, x[jnp.clip(idx, 0)], 0.0)


def _masked_ridge(X, t, row_mask, lam):
    """Ridge OLS of t on X over masked rows (fixed shapes, vmappable)."""
    Xm = X * row_mask[:, None]
    k = X.shape[1]
    return jnp.linalg.solve(
        Xm.T @ Xm + lam * jnp.eye(k, dtype=X.dtype), Xm.T @ (t * row_mask)
    )


def _start_params(cfg: SarimaxConfig, y, exog, order, n_valid):
    """Start values: OLS beta, then Hannan-Rissanen phi/theta.

    statsmodels seeds its 'nm' fit the same way (long-AR regression for
    innovations, then ARMA-by-regression); starting the padded simplex at
    zeros instead loses tens of nats of likelihood at the orders the HPO
    grid visits (p, q up to 4 on near-integrated demand series).
    """
    p, d, q = order[0], order[1], order[2]
    t_idx = jnp.arange(y.shape[0])
    obs = (t_idx < n_valid).astype(y.dtype)
    if cfg.k_exog:
        # Masked ridge OLS of y on exog for beta start values.
        Xw = exog * obs[:, None]
        beta0 = jnp.linalg.solve(
            Xw.T @ exog + 1e-3 * jnp.eye(cfg.k_exog, dtype=y.dtype), Xw.T @ y
        )
        resid = y - exog @ beta0
    else:
        beta0 = jnp.zeros(0, y.dtype)
        resid = y
    w = _difference(resid, d, cfg.max_d)
    wmask = (t_idx >= d) & (t_idx < n_valid)
    wm = jnp.where(wmask, w, 0.0)

    # Stage 1: long AR(L) for innovation estimates e_t.
    L = cfg.max_p + cfg.max_q
    X1 = _lagmat(wm, L)
    m1 = (wmask & (t_idx >= d + L)).astype(y.dtype)
    a_long = _masked_ridge(X1, wm, m1, 1e-2)
    e = jnp.where(wmask, wm - X1 @ a_long, 0.0)

    # Stage 2: w_t ~ [w lags (<p), e lags (<q)]; inactive columns masked.
    X2 = jnp.concatenate([_lagmat(wm, cfg.max_p), _lagmat(e, cfg.max_q)], axis=1)
    col_mask = jnp.concatenate(
        [
            (jnp.arange(cfg.max_p) < p).astype(y.dtype),
            (jnp.arange(cfg.max_q) < q).astype(y.dtype),
        ]
    )
    sol = _masked_ridge(X2 * col_mask[None, :], wm, m1, 1e-2) * col_mask
    phi0 = jnp.clip(sol[: cfg.max_p], -2.0, 2.0)
    theta0 = jnp.clip(sol[cfg.max_p :], -2.0, 2.0)

    # Innovation-variance start from the stage-2 residuals.
    res2 = jnp.where(wmask, wm - (X2 * col_mask[None, :]) @ sol, 0.0)
    denom = jnp.maximum(m1.sum(), 1)
    var = jnp.maximum(jnp.sum(res2 * res2 * m1) / denom, 1e-8)
    hr = jnp.concatenate([beta0, phi0, theta0, jnp.log(var)[None]])

    # Alternative start: pure long-AR coefficients as phi (theta = 0) —
    # the strong seed when the series is (near-)integrated and the best
    # AR fit sits at a unit root, where the HR stage-2 regression is
    # ill-conditioned.
    phi_ar = jnp.clip(a_long[: cfg.max_p], -2.0, 2.0) * (
        jnp.arange(cfg.max_p) < p
    ).astype(y.dtype)
    ar = jnp.concatenate(
        [beta0, phi_ar, jnp.zeros(cfg.max_q, y.dtype), jnp.log(var)[None]]
    )
    return hr, ar


def _concentrated_nll(cfg: SarimaxConfig, free, y, exog, order, n_valid):
    """Scale-concentrated negative loglike over [beta, phi, theta].

    The statsmodels ``concentrate_scale`` trick: with ``Q = sigma2`` the
    innovation variances scale linearly in sigma2, so the filter runs at
    sigma2 = 1 and the ML scale has the closed form
    ``sigma2* = mean(v_t^2 / F~_t)``. The search loses its
    worst-conditioned dimension (log variance), which is what lets a
    padded 11-dim simplex reach statsmodels-grade optima.

    Returns ``(nll, log_sigma2*)``.
    """
    d = order[1]
    params1 = jnp.concatenate([free, jnp.zeros(1, y.dtype)])  # sigma2 = 1
    filt, resid, mask = _filter(cfg, params1, y, exog, order, n_valid)
    w = _difference(resid, d, cfg.max_d)
    v = jnp.where(mask, w - filt.pred_mean, 0.0)
    F = jnp.maximum(filt.pred_var, 1e-12)
    n_obs = jnp.maximum(mask.sum(), 1).astype(y.dtype)
    sigma2 = jnp.maximum(jnp.sum(jnp.where(mask, v * v / F, 0.0)) / n_obs, 1e-12)
    nll = 0.5 * (
        n_obs * (_LOG2PI_ + 1.0 + jnp.log(sigma2))
        + jnp.sum(jnp.where(mask, jnp.log(F), 0.0))
    )
    return nll, jnp.log(sigma2)


_LOG2PI_ = 1.8378770664093453


@partial(jax.jit, static_argnames=("cfg",))
def sarimax_fit(
    cfg: SarimaxConfig,
    y: jax.Array,
    exog: jax.Array,
    order: jax.Array,
    n_valid: jax.Array | int | None = None,
) -> SarimaxResult:
    """ML fit via Nelder-Mead (the reference's ``method='nm'``).

    ``order`` is a length-3 int array ``(p, d, q)`` — traced, so the same
    compiled fit serves every order in the HPO grid. ``vmap`` over
    ``(y, exog, order, n_valid)`` for batched per-group fits. The scale
    is concentrated out of the search (see :func:`_concentrated_nll`);
    the reported ``loglike`` is the exact unconcentrated likelihood at
    the returned packed params.
    """
    y = jnp.asarray(y)
    n_valid = jnp.asarray(y.shape[0] if n_valid is None else n_valid)
    order = jnp.asarray(order)
    hr_full, ar_full = _start_params(cfg, y, exog, order, n_valid)
    n_eff = jnp.maximum(n_valid - order[1], 1).astype(y.dtype)

    # Coefficients masked out by (p, q) don't touch the likelihood; pin them
    # with a quadratic penalty so the simplex doesn't wander flat directions.
    pin = jnp.concatenate(
        [
            jnp.zeros(cfg.k_exog, y.dtype),
            (jnp.arange(cfg.max_p) >= order[0]).astype(y.dtype),
            (jnp.arange(cfg.max_q) >= order[2]).astype(y.dtype),
        ]
    )

    def objective(free):
        nll, _ = _concentrated_nll(cfg, free, y, exog, order, n_valid)
        return jnp.nan_to_num(nll, nan=jnp.inf) / n_eff + 10.0 * jnp.sum(
            (free * pin) ** 2
        )

    # Three starting points — Hannan-Rissanen (sharp when its regressions
    # are well-conditioned; can be explosive on over-differenced series),
    # pure long-AR (the right seed near unit roots), and conservative
    # zeros. Each runs a 2-round NM chain (the restart re-inflates the
    # simplex around the incumbent, recovering progress a 9+-dim padded
    # simplex loses to premature shrinkage) and then a BFGS polish —
    # exact gradients through the Kalman scan are the advantage this
    # implementation has over statsmodels' gradient-free 'nm'.
    # The chains are independent, so they run as ONE vmapped stacked
    # candidate axis: XLA batches the Kalman scans across the starts
    # (and, under an outer group/order vmap, across every fit in the
    # launch) instead of serializing three while-loops per fit.
    from jax.scipy.optimize import minimize as _bfgs_minimize

    hr = hr_full[:-1]  # drop log_sigma2: concentrated out
    start_stack = jnp.stack([hr, ar_full[:-1], hr.at[cfg.k_exog :].set(0.0)])

    def _chain(start):
        r1 = nelder_mead(objective, start, max_iter=cfg.max_iter,
                         xatol=1e-5, fatol=1e-7)
        r2 = nelder_mead(objective, r1.x, max_iter=cfg.max_iter,
                         xatol=1e-5, fatol=1e-7)
        cands = [r1.x, r2.x]
        if cfg.bfgs_iter > 0:
            b = _bfgs_minimize(
                objective, r2.x, method="BFGS",
                options={"maxiter": cfg.bfgs_iter},
            )
            cands.append(b.x)
        return (jnp.stack(cands), r1.n_iter + r2.n_iter,
                r1.converged | r2.converged)

    chain_cands, chain_iters, chain_convs = jax.vmap(_chain)(start_stack)
    n_iter_total = chain_iters.sum().astype(jnp.int32)
    any_conv = chain_convs.any()

    # Rank every candidate under ONE evaluation of the objective — f32
    # likelihoods near unit roots are sensitive enough that values from
    # differently-compiled programs must not be compared against each
    # other.
    cand_stack = chain_cands.reshape(-1, start_stack.shape[-1])
    fs = jnp.nan_to_num(jax.vmap(objective)(cand_stack), nan=jnp.inf)
    best_free = cand_stack[jnp.argmin(fs)]
    _, log_sigma2 = _concentrated_nll(cfg, best_free, y, exog, order, n_valid)
    best_x = jnp.concatenate([best_free, log_sigma2[None]])
    loglike = sarimax_loglike(cfg, best_x, y, exog, order, n_valid)
    return SarimaxResult(best_x, loglike, n_iter_total, any_conv)


def grid_orders(cfg: SarimaxConfig) -> "np.ndarray":
    """The full discrete HPO grid as a ``(K, 3)`` int32 host array.

    Every ``(p, d, q)`` with ``p <= max_p``, ``d <= max_d``,
    ``q <= max_q`` in p-major order — 5x3x5 = 75 orders at the
    reference's search bounds (``02...py:462-464``). This is the exact
    space the reference's Hyperopt samples; enumerating it makes the
    argmin exact instead of sampled.
    """
    import numpy as np

    grids = np.meshgrid(
        np.arange(cfg.max_p + 1),
        np.arange(cfg.max_d + 1),
        np.arange(cfg.max_q + 1),
        indexing="ij",
    )
    return np.stack(grids, axis=-1).reshape(-1, 3).astype(np.int32)


class SarimaxGridResult(NamedTuple):
    """One group's grid-fused fit: the argmin over the order axis has
    already been taken ON DEVICE, so only the winner (not K losses per
    group) crosses to the host."""

    order: jax.Array  # (3,) winning (p, d, q)
    params: jax.Array  # (n_params,) packed params at the winning order
    loss: jax.Array  # selection score at the winner (mse, or -loglike)
    loglike: jax.Array  # exact loglike of the winning fit
    pred: jax.Array  # (N,) full-range predictions at the winning order
    n_iter: jax.Array  # NM iterations summed over the whole grid
    converged: jax.Array  # the winning fit's convergence flag


@partial(jax.jit, static_argnames=("cfg", "select"))
def sarimax_fit_grid(
    cfg: SarimaxConfig,
    y: jax.Array,
    exog: jax.Array,
    orders: jax.Array,
    n_train: jax.Array | int,
    n_valid: jax.Array | int | None = None,
    select: str = "mse",
) -> SarimaxGridResult:
    """Fit-tune-score ONE series over a whole ``(K, 3)`` order grid.

    Replaces the per-round HPO loop (host-side TPE proposing one order
    per group per launch) with grid fusion: every candidate order is fit
    in one program via ``vmap`` over the order axis, scored, and reduced
    to the per-series argmin on device. ``vmap`` this function over a
    group axis and the whole (G x K) fit plane becomes a single XLA
    launch (see ``parallel.group_apply.make_grid_fit``).

    ``select`` picks the tuning criterion: ``"mse"`` — holdout MSE on
    ``[n_train, n_valid)`` of predictions from a fit on ``[0, n_train)``
    (the reference's Hyperopt objective, ``02...py:455-459``) — or
    ``"loglike"`` — maximize the in-sample log-likelihood (exact-argmax
    counterpart of the TPE path's best-observed loglike, and the parity
    axis the golden fixture pins). Predictions at the winning order ride
    along so no separate refit launch is needed: the eval fit IS the
    final fit (same inputs, deterministic).
    """
    if select not in ("mse", "loglike"):
        raise ValueError(
            f"select must be 'mse' or 'loglike', got {select!r}"
        )
    y = jnp.asarray(y)
    orders = jnp.asarray(orders)
    n_train = jnp.asarray(n_train)
    n_valid = jnp.asarray(y.shape[0] if n_valid is None else n_valid)

    def one(order):
        fit = sarimax_fit(cfg, y, exog, order, n_train)
        pred = sarimax_predict(cfg, fit.params, y, exog, order, n_train)
        t = jnp.arange(y.shape[0])
        m = (t >= n_train) & (t < n_valid)
        err = jnp.where(m, y - pred, 0.0)
        mse = jnp.sum(err * err) / jnp.maximum(m.sum(), 1)
        return fit, pred, mse

    fits, preds, mses = jax.vmap(one)(orders)
    if select == "mse":
        score = jnp.nan_to_num(mses, nan=jnp.inf)
    else:
        score = jnp.nan_to_num(-fits.loglike, nan=jnp.inf)
    best = jnp.argmin(score)
    return SarimaxGridResult(
        order=orders[best],
        params=fits.params[best],
        loss=score[best],
        loglike=fits.loglike[best],
        pred=preds[best],
        n_iter=fits.n_iter.sum().astype(jnp.int32),
        converged=fits.converged[best],
    )


@partial(jax.jit, static_argnames=("cfg",))
def sarimax_predict(
    cfg: SarimaxConfig,
    params: jax.Array,
    y: jax.Array,
    exog: jax.Array,
    order: jax.Array,
    n_valid: jax.Array | int,
) -> jax.Array:
    """Full-range prediction, the reference's ``predict(start, end, exog)``.

    Arrays span the full range (train + horizon): ``y`` is observed up to
    ``n_valid`` (ignored after), ``exog`` holds known future regressors.
    Returns length-N predictions: one-step-ahead in-sample for
    ``t < n_valid`` (first ``d`` points echo the observation, as there is
    nothing to difference against), dynamic multi-step forecasts after —
    matching statsmodels' behavior when predicting past the sample end.
    """
    y = jnp.asarray(y)
    n_valid = jnp.asarray(n_valid)
    order = jnp.asarray(order)
    p, d, q = order
    beta = cfg.unpack(params)[0]
    xb = exog @ beta if cfg.k_exog else jnp.zeros_like(y)

    filt, resid, _ = _filter(cfg, params, y, exog, order, n_valid)
    w_hat = filt.pred_mean  # one-step in-sample; multi-step beyond n_valid
    t_idx = jnp.arange(y.shape[0])

    def undiff_step(carry, inp):
        rm1, rm2 = carry
        w_hat_t, r_obs_t, t = inp
        lag_term = jnp.where(
            d == 1, rm1, jnp.where(d == 2, 2 * rm1 - rm2, jnp.zeros_like(rm1))
        )
        pred = jnp.where(t < d, r_obs_t, w_hat_t + lag_term)
        r_t = jnp.where(t < n_valid, r_obs_t, pred)
        return (r_t, rm1), pred

    zero = jnp.zeros((), y.dtype)
    _, r_pred = lax.scan(undiff_step, (zero, zero), (w_hat, resid, t_idx))
    return xb + r_pred

"""SARIMAX (ARIMA + exogenous regressors) as a vmappable JAX program.

Capability target (SURVEY.md §2.2 X10): statsmodels
``SARIMAX(train, exog=..., order=(p,d,q), seasonal_order=(0,0,0,0))
.fit(method='nm')`` then ``.predict(start, end, exog=...)`` — the exact
surface the reference's per-SKU tuner exercises
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:441-494``), with
p ∈ [0,4], d ∈ [0,2], q ∈ [0,4] searched by Hyperopt (``:462-464``).

TPU-first design: the reference runs one Python/statsmodels fit per Spark
task per SKU. Here orders ``(p, d, q)`` are **traced** values masked
against static maxima (``SarimaxConfig``), so a single compiled program
``vmap``s the whole fit across thousands of groups — and across HPO
candidates — at once. That is the max-order padded parameterization
SURVEY.md §7 ("hard parts" #1) calls for.

Model: y_t = x_t'beta + u_t, with Delta^d u_t ~ ARMA(p, q). The ARMA part
runs through a Harvey-representation Kalman filter (state dim
``max(max_p, max_q + 1)``); initialization solves the stationary
Lyapunov equation when valid and falls back to approximate-diffuse
(statsmodels' ``initialization='approximate_diffuse'``) otherwise, which
covers non-stationary iterates since stationarity is not enforced
(reference passes ``enforce_stationarity=False``, ``:447-448``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .kalman import kalman_filter
from .neldermead import nelder_mead


@dataclasses.dataclass(frozen=True)
class SarimaxConfig:
    """Static shape bounds; traced per-fit orders are masked against these."""

    max_p: int = 4
    max_d: int = 2
    max_q: int = 4
    k_exog: int = 0
    kappa: float = 1e4  # approximate-diffuse prior variance scale
    max_iter: int = 200  # Nelder-Mead iterations (reference: method='nm')

    @property
    def state_dim(self) -> int:
        return max(self.max_p, self.max_q + 1)

    @property
    def n_params(self) -> int:
        # [beta (k_exog), phi (max_p), theta (max_q), log_sigma2]
        return self.k_exog + self.max_p + self.max_q + 1

    def unpack(self, params):
        k, p, q = self.k_exog, self.max_p, self.max_q
        return (
            params[:k],
            params[k : k + p],
            params[k + p : k + p + q],
            params[k + p + q],
        )


class SarimaxResult(NamedTuple):
    params: jax.Array  # (n_params,) packed [beta, phi, theta, log_sigma2]
    loglike: jax.Array
    n_iter: jax.Array
    converged: jax.Array


def _difference(x: jax.Array, d: jax.Array, max_d: int) -> jax.Array:
    """Delta^d x with traced d <= max_d; first d outputs are invalid."""
    z = jnp.zeros_like(x[:1])
    branches = [lambda x=x: x]
    if max_d >= 1:
        branches.append(lambda x=x: jnp.concatenate([z, x[1:] - x[:-1]]))
    if max_d >= 2:
        branches.append(
            lambda x=x: jnp.concatenate([z, z, x[2:] - 2 * x[1:-1] + x[:-2]])
        )
    return lax.switch(jnp.clip(d, 0, max_d), branches)


def _ssm_matrices(cfg: SarimaxConfig, phi_eff, theta_eff, sigma2):
    """Harvey representation: T companion on phi, R = [1, theta...]."""
    r = cfg.state_dim
    T = jnp.zeros((r, r), phi_eff.dtype)
    T = T.at[:, 0].set(jnp.pad(phi_eff, (0, r - cfg.max_p)))
    T = T.at[jnp.arange(r - 1), jnp.arange(1, r)].set(1.0)
    R = jnp.concatenate([jnp.ones(1, theta_eff.dtype), jnp.pad(theta_eff, (0, r - 1 - cfg.max_q))])
    R = R.reshape(r, 1)
    Q = sigma2.reshape(1, 1)
    Z = jnp.zeros(r, phi_eff.dtype).at[0].set(1.0)
    return T, R, Q, Z


def _init_cov(cfg: SarimaxConfig, T, RQR, sigma2):
    """Stationary Lyapunov solve, approximate-diffuse fallback."""
    r = cfg.state_dim
    eye = jnp.eye(r * r, dtype=T.dtype)
    P_vec = jnp.linalg.solve(eye - jnp.kron(T, T), RQR.reshape(-1))
    P = P_vec.reshape(r, r)
    P = 0.5 * (P + P.T)
    kappa = cfg.kappa * jnp.maximum(sigma2, 1.0)
    # Padded state dims legitimately have zero stationary variance, so the
    # validity check allows diag == 0; only reject non-finite / negative /
    # exploding solves (non-stationary phi iterates under Nelder-Mead).
    ok = (
        jnp.all(jnp.isfinite(P))
        & jnp.all(jnp.diag(P) >= -1e-6)
        & (jnp.max(jnp.abs(P)) < kappa)
    )
    return jnp.where(ok, P, kappa * jnp.eye(r, dtype=T.dtype))


def _filter(cfg: SarimaxConfig, params, y, exog, order, n_valid):
    """Shared setup: regression residual → difference → Kalman filter."""
    p, d, q = order
    beta, phi, theta, log_sigma2 = cfg.unpack(params)
    phi_eff = phi * (jnp.arange(cfg.max_p) < p)
    theta_eff = theta * (jnp.arange(cfg.max_q) < q)
    sigma2 = jnp.exp(log_sigma2)

    resid = y - (exog @ beta if cfg.k_exog else jnp.zeros_like(y))
    w = _difference(resid, d, cfg.max_d)
    t_idx = jnp.arange(y.shape[0])
    mask = (t_idx >= d) & (t_idx < n_valid)

    T, R, Q, Z = _ssm_matrices(cfg, phi_eff, theta_eff, sigma2)
    P0 = _init_cov(cfg, T, R @ Q @ R.T, sigma2)
    a0 = jnp.zeros(cfg.state_dim, y.dtype)
    filt = kalman_filter(w, T, R, Q, Z, jnp.asarray(0.0, y.dtype), a0, P0, mask=mask)
    return filt, resid, mask


def sarimax_loglike(cfg: SarimaxConfig, params, y, exog, order, n_valid) -> jax.Array:
    """Exact (prediction-error decomposition) log-likelihood."""
    filt, _, _ = _filter(cfg, params, y, exog, order, n_valid)
    return filt.loglike


def _start_params(cfg: SarimaxConfig, y, exog, order, n_valid):
    d = order[1]
    t_idx = jnp.arange(y.shape[0])
    obs = (t_idx < n_valid).astype(y.dtype)
    if cfg.k_exog:
        # Masked ridge OLS of y on exog for beta start values.
        Xw = exog * obs[:, None]
        beta0 = jnp.linalg.solve(
            Xw.T @ exog + 1e-3 * jnp.eye(cfg.k_exog, dtype=y.dtype), Xw.T @ y
        )
        resid = y - exog @ beta0
    else:
        beta0 = jnp.zeros(0, y.dtype)
        resid = y
    w = _difference(resid, d, cfg.max_d)
    wmask = (t_idx >= d) & (t_idx < n_valid)
    denom = jnp.maximum(wmask.sum(), 1)
    wm = jnp.where(wmask, w, 0.0)
    var = jnp.maximum(jnp.sum(wm * wm) / denom - (jnp.sum(wm) / denom) ** 2, 1e-8)
    return jnp.concatenate(
        [
            beta0,
            jnp.zeros(cfg.max_p + cfg.max_q, y.dtype),
            jnp.log(var)[None],
        ]
    )


@partial(jax.jit, static_argnames=("cfg",))
def sarimax_fit(
    cfg: SarimaxConfig,
    y: jax.Array,
    exog: jax.Array,
    order: jax.Array,
    n_valid: jax.Array | int | None = None,
) -> SarimaxResult:
    """ML fit via Nelder-Mead (the reference's ``method='nm'``).

    ``order`` is a length-3 int array ``(p, d, q)`` — traced, so the same
    compiled fit serves every order in the HPO grid. ``vmap`` over
    ``(y, exog, order, n_valid)`` for batched per-group fits.
    """
    y = jnp.asarray(y)
    n_valid = jnp.asarray(y.shape[0] if n_valid is None else n_valid)
    order = jnp.asarray(order)
    x0 = _start_params(cfg, y, exog, order, n_valid)
    n_eff = jnp.maximum(n_valid - order[1], 1).astype(y.dtype)

    # Coefficients masked out by (p, q) don't touch the likelihood; pin them
    # with a quadratic penalty so the simplex doesn't wander flat directions.
    pin = jnp.concatenate(
        [
            jnp.zeros(cfg.k_exog, y.dtype),
            (jnp.arange(cfg.max_p) >= order[0]).astype(y.dtype),
            (jnp.arange(cfg.max_q) >= order[2]).astype(y.dtype),
            jnp.zeros(1, y.dtype),
        ]
    )

    def objective(params):
        nll = -sarimax_loglike(cfg, params, y, exog, order, n_valid) / n_eff
        return nll + 10.0 * jnp.sum((params * pin) ** 2)

    # Two NM rounds: a restart re-inflates the simplex around the incumbent,
    # which recovers the progress a 9+-dim padded simplex loses to premature
    # shrinkage (statsmodels' unpadded 'nm' fit has only p+q+1 dims).
    res = nelder_mead(objective, x0, max_iter=cfg.max_iter, xatol=1e-5, fatol=1e-7)
    res2 = nelder_mead(objective, res.x, max_iter=cfg.max_iter, xatol=1e-5, fatol=1e-7)
    take2 = res2.fun <= res.fun
    best_x = jnp.where(take2, res2.x, res.x)
    best_fun = jnp.where(take2, res2.fun, res.fun)
    nll_best = best_fun - 10.0 * jnp.sum((best_x * pin) ** 2)
    best_conv = jnp.where(take2, res2.converged, res.converged)
    return SarimaxResult(best_x, -nll_best * n_eff, res.n_iter + res2.n_iter, best_conv)


@partial(jax.jit, static_argnames=("cfg",))
def sarimax_predict(
    cfg: SarimaxConfig,
    params: jax.Array,
    y: jax.Array,
    exog: jax.Array,
    order: jax.Array,
    n_valid: jax.Array | int,
) -> jax.Array:
    """Full-range prediction, the reference's ``predict(start, end, exog)``.

    Arrays span the full range (train + horizon): ``y`` is observed up to
    ``n_valid`` (ignored after), ``exog`` holds known future regressors.
    Returns length-N predictions: one-step-ahead in-sample for
    ``t < n_valid`` (first ``d`` points echo the observation, as there is
    nothing to difference against), dynamic multi-step forecasts after —
    matching statsmodels' behavior when predicting past the sample end.
    """
    y = jnp.asarray(y)
    n_valid = jnp.asarray(n_valid)
    order = jnp.asarray(order)
    p, d, q = order
    beta = cfg.unpack(params)[0]
    xb = exog @ beta if cfg.k_exog else jnp.zeros_like(y)

    filt, resid, _ = _filter(cfg, params, y, exog, order, n_valid)
    w_hat = filt.pred_mean  # one-step in-sample; multi-step beyond n_valid
    t_idx = jnp.arange(y.shape[0])

    def undiff_step(carry, inp):
        rm1, rm2 = carry
        w_hat_t, r_obs_t, t = inp
        lag_term = jnp.where(
            d == 1, rm1, jnp.where(d == 2, 2 * rm1 - rm2, jnp.zeros_like(rm1))
        )
        pred = jnp.where(t < d, r_obs_t, w_hat_t + lag_term)
        r_t = jnp.where(t < n_valid, r_obs_t, pred)
        return (r_t, rm1), pred

    zero = jnp.zeros((), y.dtype)
    _, r_pred = lax.scan(undiff_step, (zero, zero), (w_hat, resid, t_idx))
    return xb + r_pred

"""ARMA sample generation as a ``lax.scan`` IIR filter.

Replaces ``statsmodels.tsa.arma_generate_sample`` as used by the demand
generator (``group_apply/_resources/01-data-generator.py:246-254``): the
reference draws one ARMA series per SKU in a pandas UDF; here a single
``vmap`` over per-SKU keys/params draws every series at once on device.

Conventions match statsmodels/scipy: ``ar`` and ``ma`` are full lag
polynomials including the leading 1, with AR signs as in
``ar = [1, -phi_1, ..., -phi_p]``. The filter itself is scipy's
``lfilter`` (transposed direct-form II) as a scan, so outputs match
``scipy.signal.lfilter(ma, ar, eps)`` exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def lfilter(b: jax.Array, a: jax.Array, x: jax.Array) -> jax.Array:
    """IIR filter ``y = lfilter(b, a, x)``, matching scipy semantics.

    ``b``/``a`` are the numerator/denominator polynomials; ``a[0]`` must be
    nonzero (it normalizes both). Implemented as transposed direct-form II:

        y[t] = b[0] x[t] + z[0]
        z[i] = b[i+1] x[t] + z[i+1] - a[i+1] y[t]
    """
    b = jnp.atleast_1d(jnp.asarray(b))
    a = jnp.atleast_1d(jnp.asarray(a))
    # nfilt >= 2 keeps the scan state non-empty even for the ARMA(0,0) /
    # pure-gain case (b and a both scalar), where the filter is y = (b0/a0) x.
    nfilt = max(b.shape[0], a.shape[0], 2)
    b = jnp.pad(b, (0, nfilt - b.shape[0])) / a[0]
    a = jnp.pad(a, (0, nfilt - a.shape[0])) / a[0]

    def step(z, x_t):
        y_t = b[0] * x_t + z[0]
        z_new = b[1:] * x_t + jnp.concatenate([z[1:], jnp.zeros(1, z.dtype)]) - a[1:] * y_t
        return z_new, y_t

    z0 = jnp.zeros(nfilt - 1, x.dtype)
    _, y = lax.scan(step, z0, x)
    return y


def arma_generate_sample(
    key: jax.Array,
    ar: jax.Array,
    ma: jax.Array,
    nsample: int,
    scale: float | jax.Array = 1.0,
    burnin: int = 0,
) -> jax.Array:
    """Draw an ARMA sample; mirrors ``sm.tsa.arma_generate_sample``.

    The reference calls this with ``burnin=3000`` per SKU
    (``01-data-generator.py:246``). ``vmap`` over ``key`` (and optionally
    per-series ``ar``/``ma`` rows padded to equal length) to draw a whole
    SKU panel in one call.
    """
    eps = scale * jax.random.normal(key, (nsample + burnin,))
    y = lfilter(ma, ar, eps)
    return y[burnin:]

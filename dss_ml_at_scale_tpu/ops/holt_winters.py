"""Holt-Winters exponential smoothing via ``lax.scan``.

Covers the four variants the reference's EDA fits
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:143-188``):
additive trend × {additive, multiplicative} seasonal, each optionally
damped, with optional Box-Cox pre-transform, least-squares (SSE)
parameter estimation — statsmodels ``ExponentialSmoothing(...,
use_boxcox=True).fit(method='ls')`` capability, re-built as a pure JAX
function that ``vmap``s across series.

Deviations from statsmodels (documented, not accidental):
- initial level/trend/seasonals use the standard two-season heuristic
  rather than joining the optimization (``initialization_method=
  "estimated"``); smoothing params are still SSE-optimized.
- Box-Cox lambda is estimated by golden-section MLE on the concentrated
  likelihood (scipy ``boxcox`` does the same via Brent); inputs are
  clamped to a small positive floor first (statsmodels raises on
  non-positive data — a traced value can't, so the clamp is the
  documented behavior for zero-demand periods).

Variant flags (``seasonal``/``damped``/``use_boxcox``) are Python-static
at fit time, like the statsmodels constructor, and are recorded in the
result (as array codes) so :func:`holt_winters_forecast` can never be
called with a mismatched variant.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .neldermead import nelder_mead

_SEASONAL_CODES = {None: 0, "add": 1, "mul": 2}


class HoltWintersResult(NamedTuple):
    alpha: jax.Array
    beta: jax.Array
    gamma: jax.Array
    phi: jax.Array  # damping (1.0 when undamped)
    boxcox_lambda: jax.Array  # 1.0 when no transform
    use_boxcox: jax.Array  # bool: whether fit ran on the Box-Cox scale
    seasonal_code: jax.Array  # 0 = none, 1 = additive, 2 = multiplicative
    level: jax.Array  # final level state
    trend: jax.Array  # final trend state
    season: jax.Array  # (m,) final seasonal buffer; [h % m] applies to step h+1
    fittedvalues: jax.Array  # (n,) one-step-ahead fitted values, original scale
    sse: jax.Array  # SSE on the (transformed) scale the fit ran on


def _boxcox(y, lam):
    return jnp.where(jnp.abs(lam) < 1e-8, jnp.log(y), (y**lam - 1.0) / lam)


def _inv_boxcox(z, lam):
    return jnp.where(
        jnp.abs(lam) < 1e-8, jnp.exp(z), jnp.maximum(lam * z + 1.0, 1e-12) ** (1.0 / lam)
    )


def boxcox_mle_lambda(y: jax.Array, lo: float = -1.0, hi: float = 2.0) -> jax.Array:
    """Golden-section maximizer of the concentrated Box-Cox likelihood.

    ``y`` must be positive (callers clamp).
    """
    n = y.shape[0]
    logsum = jnp.log(y).sum()

    def neg_llf(lam):
        z = _boxcox(y, lam)
        return 0.5 * n * jnp.log(jnp.maximum(z.var(), 1e-300)) - (lam - 1.0) * logsum

    gr = 0.6180339887498949

    def body(_, ab):
        a, b = ab
        c = b - gr * (b - a)
        d = a + gr * (b - a)
        shrink_right = neg_llf(c) < neg_llf(d)
        return jnp.where(shrink_right, a, c), jnp.where(shrink_right, d, b)

    a, b = lax.fori_loop(0, 60, body, (jnp.asarray(lo, y.dtype), jnp.asarray(hi, y.dtype)))
    return 0.5 * (a + b)


def _heuristic_init(z, m, seasonal):
    """Level/trend/seasonals from the first two complete seasons."""
    s1 = lax.dynamic_slice(z, (0,), (m,))
    s2 = lax.dynamic_slice(z, (m,), (m,))
    l0 = s1.mean()
    b0 = (s2.mean() - s1.mean()) / m
    if seasonal == "mul":
        s0 = s1 / jnp.maximum(l0, 1e-12)
    else:
        s0 = s1 - l0
    return l0, b0, s0


def _smooth(z, params, init, m, seasonal, damped):
    """Run the recursions; returns (sse, fitted, level, trend, season)."""
    alpha, beta, gamma, phi = params
    l0, b0, s0 = init

    def step(carry, z_t):
        l, b, s = carry
        s_old = s[0]
        lb = l + phi * b
        if seasonal == "mul":
            fitted = lb * s_old
            l_new = alpha * (z_t / jnp.where(s_old == 0, 1e-12, s_old)) + (1 - alpha) * lb
            s_new = gamma * (z_t / jnp.maximum(lb, 1e-12)) + (1 - gamma) * s_old
        elif seasonal == "add":
            fitted = lb + s_old
            l_new = alpha * (z_t - s_old) + (1 - alpha) * lb
            s_new = gamma * (z_t - lb) + (1 - gamma) * s_old
        else:
            fitted = lb
            l_new = alpha * z_t + (1 - alpha) * lb
            s_new = s_old
        b_new = beta * (l_new - l) + (1 - beta) * phi * b
        s_buf = jnp.concatenate([s[1:], s_new[None]])
        return (l_new, b_new, s_buf), fitted

    (l, b, s), fitted = lax.scan(step, (l0, b0, s0), z)
    sse = jnp.sum((z - fitted) ** 2)
    return sse, fitted, l, b, s


@partial(jax.jit, static_argnames=("seasonal_periods", "seasonal", "damped", "use_boxcox", "max_iter"))
def holt_winters_fit(
    y: jax.Array,
    seasonal_periods: int,
    seasonal: str | None = "add",
    damped: bool = False,
    use_boxcox: bool = False,
    max_iter: int = 200,
) -> HoltWintersResult:
    """Fit additive-trend Holt-Winters to ``y`` by SSE minimization."""
    y = jnp.asarray(y)
    m = seasonal_periods
    if y.shape[0] < 2 * m:
        raise ValueError(
            f"need >= 2 full seasons ({2 * m} points) to initialize, got {y.shape[0]}"
        )
    if use_boxcox or seasonal == "mul":
        y = jnp.maximum(y, 1e-6)  # Box-Cox / mul-seasonal need positive data
    lam = boxcox_mle_lambda(y) if use_boxcox else jnp.asarray(1.0, y.dtype)
    z = _boxcox(y, lam) if use_boxcox else y
    init = _heuristic_init(z, m, seasonal)

    def unpack(theta):
        alpha = jax.nn.sigmoid(theta[0])
        beta = jax.nn.sigmoid(theta[1]) * alpha  # 0 < beta < alpha
        gamma = jax.nn.sigmoid(theta[2]) * (1 - alpha)  # 0 < gamma < 1 - alpha
        phi = 0.8 + 0.198 * jax.nn.sigmoid(theta[3]) if damped else jnp.asarray(1.0, theta.dtype)
        return alpha, beta, gamma, phi

    def objective(theta):
        sse, *_ = _smooth(z, unpack(theta), init, m, seasonal, damped)
        return sse

    theta0 = jnp.array([0.0, -1.0, -1.0, 0.0], z.dtype)
    res = nelder_mead(objective, theta0, max_iter=max_iter, xatol=1e-5, fatol=1e-6)
    alpha, beta, gamma, phi = unpack(res.x)
    sse, fitted, l, b, s = _smooth(z, (alpha, beta, gamma, phi), init, m, seasonal, damped)
    fitted_orig = _inv_boxcox(fitted, lam) if use_boxcox else fitted
    return HoltWintersResult(
        alpha,
        beta,
        gamma,
        phi,
        lam,
        jnp.asarray(use_boxcox),
        jnp.asarray(_SEASONAL_CODES[seasonal], jnp.int32),
        l,
        b,
        s,
        fitted_orig,
        sse,
    )


def holt_winters_forecast(result: HoltWintersResult, horizon: int) -> jax.Array:
    """Forecast ``horizon`` steps ahead (original scale).

    The variant (seasonal mode, Box-Cox) is read from the result, so the
    forecast always matches the scale and structure the fit used.
    """
    h = jnp.arange(1, horizon + 1)
    phi = result.phi
    # Damped trend accumulates sum_{j=1..h} phi^j; phi=1 degenerates to h.
    bsum = jnp.where(
        jnp.abs(phi - 1.0) < 1e-8,
        h.astype(result.level.dtype),
        phi * (1 - phi**h) / (1 - phi + 1e-12),
    )
    m = result.season.shape[0]
    s = result.season[(h - 1) % m]
    base = result.level + bsum * result.trend
    z = jnp.where(
        result.seasonal_code == 2,
        base * s,
        jnp.where(result.seasonal_code == 1, base + s, base),
    )
    return jnp.where(result.use_boxcox, _inv_boxcox(z, result.boxcox_lambda), z)

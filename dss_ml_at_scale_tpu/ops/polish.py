"""Host-side float64 SARIMAX polish for razor-thin optima.

The TPU fit (:func:`~dss_ml_at_scale_tpu.ops.sarimax.sarimax_fit`) is
float32 by design — that's what vmaps over thousands of SKUs on the MXU.
Its one documented concession is the misspecified-order corner (d=0
requested on an integrated series, reference HPO grid
``group_apply/02_Fine_Grained_Demand_Forecasting.py:461-469``): the ML
optimum there sits on an exact unit root with near-cancelling MA
structure, a basin too thin for f32 to resolve (measured ~19 nats short
on the golden fixture; statsmodels, always f64, reaches it).

This module closes that corner the way the reference's stack implicitly
does — in double precision on the host: a plain-NumPy f64 Kalman
likelihood and a scipy Nelder-Mead polish *started from the f32 fit*.
Measured on the golden fixture's (4,0,4) corner the polish recovers the
oracle optimum to ~1 nat in ~30 s of host time.

Use it where single-fit quality matters (final refits, reported
likelihoods, model comparison by information criteria) — NOT inside the
batched panel path, whose whole point is one compiled program for all
groups. One fit at a time, host CPU only.
"""

from __future__ import annotations

import numpy as np

from .sarimax import SarimaxConfig


def _f64_loglike(
    params: np.ndarray,
    y: np.ndarray,
    exog: np.ndarray,
    order: tuple[int, int, int],
    n_valid: int,
    kappa: float = 1e4,
) -> float:
    """Exact Kalman log-likelihood, float64, unpadded Harvey state space.

    Same model semantics as the f32 kernel (``ops/sarimax.py``): regress
    out exog, difference d times, ARMA(p, q) innovations, stationary
    Lyapunov initialization with approximate-diffuse fallback.
    """
    from scipy import linalg

    p, d, q = order
    k = exog.shape[1] if exog.ndim == 2 else 0
    beta = params[:k]
    phi = params[k : k + p]
    theta = params[k + p : k + p + q]
    sigma2 = float(np.exp(np.clip(params[-1], -30.0, 30.0)))

    u = y - (exog @ beta if k else 0.0)
    w = np.diff(u, n=d) if d else u.copy()
    w = np.concatenate([np.zeros(d), w])  # keep indexing aligned with t

    r = max(p, q + 1, 1)
    T = np.zeros((r, r))
    T[:p, 0] = phi
    T[: r - 1, 1:] += np.eye(r - 1)
    R = np.zeros((r, 1))
    R[0, 0] = 1.0
    R[1 : 1 + q, 0] = theta
    Z = np.zeros(r)
    Z[0] = 1.0
    RQR = sigma2 * (R @ R.T)

    diffuse = kappa * max(sigma2, 1.0)
    try:
        P = linalg.solve_discrete_lyapunov(T, RQR)
        P = 0.5 * (P + P.T)
        if not (
            np.all(np.isfinite(P))
            and np.all(np.diag(P) >= -1e-6)
            and np.max(np.abs(P)) < diffuse
        ):
            P = diffuse * np.eye(r)
    except Exception:
        P = diffuse * np.eye(r)

    a = np.zeros(r)
    ll = 0.0
    log2pi = float(np.log(2.0 * np.pi))
    for t in range(d, int(n_valid)):
        a = T @ a
        P = T @ P @ T.T + RQR
        v = w[t] - Z @ a
        F = max(float(Z @ P @ Z), 1e-300)
        if (
            not np.isfinite(v)
            or F > 1e280
            or abs(v) > 1e150  # v*v itself must not overflow...
            # ...and neither may the ratio v²/F (tiny-F case).
            or (abs(v) > 1.0 and 2.0 * np.log(abs(v)) - np.log(F) > 700.0)
        ):
            # A diverged candidate (explosive AR draw): reject it
            # outright instead of letting v*v/F overflow into inf/nan
            # arithmetic (nan would also confuse Nelder-Mead's ordering,
            # where -inf sorts cleanly worst). The log-space check bounds
            # v²/F below the float64 overflow threshold.
            return -np.inf
        ll += -0.5 * (log2pi + np.log(F) + v * v / F)
        K = P @ Z / F
        a = a + K * v
        P = P - np.outer(K, Z @ P)
        P = 0.5 * (P + P.T)
    return ll


def sarimax_polish(
    cfg: SarimaxConfig,
    params,
    y,
    exog,
    order,
    n_valid: int | None = None,
    *,
    max_iter: int = 4000,
) -> tuple[np.ndarray, float]:
    """Polish an f32 fit's packed params in float64 on the host.

    ``params`` is the packed vector :func:`sarimax_fit` returns
    (``[beta, phi(max_p), theta(max_q), log_sigma2]``); the polish
    optimizes only the active ``(p, d, q)`` coefficients and returns the
    re-packed vector plus the achieved f64 log-likelihood. Two chained
    scipy Nelder-Mead runs (restarted simplex) mirror the f32 fit's own
    chain, just in double precision.
    """
    from scipy import optimize

    y = np.asarray(y, float)
    exog = np.asarray(exog, float)
    params = np.asarray(params, float)
    p, d, q = (int(v) for v in np.asarray(order))
    k = cfg.k_exog
    n_valid = int(len(y) if n_valid is None else n_valid)

    # Unpad: pull the active coefficients out of the packed layout.
    x0 = np.concatenate(
        [
            params[:k],
            params[k : k + p],
            params[k + cfg.max_p : k + cfg.max_p + q],
            params[-1:],
        ]
    )

    def nll(x):
        ll = _f64_loglike(x, y, exog, (p, d, q), n_valid)
        return -ll if np.isfinite(ll) else 1e12

    opts = {"maxiter": max_iter, "xatol": 1e-6, "fatol": 1e-8}
    res = optimize.minimize(nll, x0, method="Nelder-Mead", options=opts)
    res = optimize.minimize(nll, res.x, method="Nelder-Mead", options=opts)
    # Keep the polish only if it actually improved (it starts at the f32
    # incumbent, so this is monotone by construction barring pathologies).
    if res.fun > nll(x0):
        res.x, res.fun = x0, nll(x0)

    out = params.copy()
    out[:k] = res.x[:k]
    out[k : k + cfg.max_p] = 0.0
    out[k : k + p] = res.x[k : k + p]
    out[k + cfg.max_p : k + cfg.max_p + cfg.max_q] = 0.0
    out[k + cfg.max_p : k + cfg.max_p + q] = res.x[k + p : k + p + q]
    out[-1] = res.x[-1]
    return out, -float(res.fun)

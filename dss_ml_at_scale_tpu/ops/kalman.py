"""Univariate linear-Gaussian Kalman filter as a ``lax.scan``.

The numerical core under SARIMAX: the reference fits demand series by
state-space maximum likelihood (`statsmodels` Kalman ML,
``group_apply/02_Fine_Grained_Demand_Forecasting.py:441-450``). Here the
filter is one scan over time — sequential by nature, but cheap (state
dim ≤ ~8) and ``vmap``-able across thousands of series, which is where
the TPU parallelism comes from.

Model (time-invariant, scalar observation):

    y_t = Z a_t + eps_t,        eps_t ~ N(0, H)
    a_{t+1} = T a_t + R eta_t,  eta_t ~ N(0, Q)

A per-timestep ``mask`` marks valid observations; masked steps skip the
measurement update and contribute zero log-likelihood, which is how
padded variable-length groups ride a single fixed-shape vmapped filter.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

_LOG2PI = 1.8378770664093453


class KalmanFiltered(NamedTuple):
    loglike: jax.Array  # scalar: sum of masked per-step log-likelihoods
    pred_mean: jax.Array  # (n,) one-step-ahead prediction Z a_{t|t-1}
    pred_var: jax.Array  # (n,) one-step-ahead prediction variance F_t
    a_last: jax.Array  # (m,) filtered state after the last step
    P_last: jax.Array  # (m, m) filtered state covariance after the last step


def kalman_filter(
    y: jax.Array,
    T: jax.Array,
    R: jax.Array,
    Q: jax.Array,
    Z: jax.Array,
    H: jax.Array,
    a0: jax.Array,
    P0: jax.Array,
    mask: jax.Array | None = None,
) -> KalmanFiltered:
    """Run the filter over ``y`` (shape ``(n,)``), return likelihood + preds.

    ``T``: (m, m) transition; ``R``: (m, r) selection; ``Q``: (r, r) state
    noise cov; ``Z``: (m,) observation row; ``H``: scalar obs noise.
    """
    y = jnp.asarray(y)
    n = y.shape[0]
    m = T.shape[0]
    mask = jnp.ones(n, bool) if mask is None else jnp.asarray(mask, bool)
    RQR = R @ Q @ R.T

    def step(carry, inp):
        a, P = carry
        y_t, valid = inp
        # Predict.
        a_pred = T @ a
        P_pred = T @ P @ T.T + RQR
        # Innovation.
        v = y_t - Z @ a_pred
        F = Z @ P_pred @ Z + H
        F_safe = jnp.maximum(F, 1e-12)
        ll = -0.5 * (_LOG2PI + jnp.log(F_safe) + v * v / F_safe)
        # Update (skipped where masked).
        K = P_pred @ Z / F_safe
        a_upd = a_pred + K * v
        P_upd = P_pred - jnp.outer(K, Z @ P_pred)
        a_new = jnp.where(valid, a_upd, a_pred)
        P_new = jnp.where(valid, P_upd, P_pred)
        # Keep covariance symmetric against roundoff drift.
        P_new = 0.5 * (P_new + P_new.T)
        return (a_new, P_new), (jnp.where(valid, ll, 0.0), Z @ a_pred, F)

    (a_last, P_last), (lls, pred_mean, pred_var) = lax.scan(
        step, (a0.reshape(m), P0), (y, mask)
    )
    return KalmanFiltered(lls.sum(), pred_mean, pred_var, a_last, P_last)


def kalman_forecast(
    a: jax.Array,
    P: jax.Array,
    steps: int,
    T: jax.Array,
    R: jax.Array,
    Q: jax.Array,
    Z: jax.Array,
    H: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Iterate the prediction step ``steps`` times from filtered ``(a, P)``.

    Returns ``(means, variances)`` of y_{n+1..n+steps}, each ``(steps,)``.
    """
    RQR = R @ Q @ R.T

    def step(carry, _):
        a, P = carry
        a = T @ a
        P = T @ P @ T.T + RQR
        return (a, P), (Z @ a, Z @ P @ Z + H)

    _, (means, variances) = lax.scan(step, (a, P), None, length=steps)
    return means, variances

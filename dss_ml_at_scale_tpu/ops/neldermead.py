"""Vmappable Nelder-Mead simplex minimizer in pure JAX.

The reference fits SARIMAX with ``method='nm'``
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:450``) — scipy's
Nelder-Mead, one Python loop per SKU. This version runs the whole
algorithm inside ``lax.while_loop`` so a single ``vmap`` fits thousands
of series in one XLA program (SURVEY.md §7 "hard parts" #1).

Branchless variant: each iteration evaluates reflection, expansion, both
contractions and the shrink simplex, then selects with ``jnp.where`` —
a few extra objective evaluations per iteration buys uniform control
flow, which is what vmap/TPU want. Constants follow Nelder & Mead
(alpha=1, gamma=2, rho=0.5, sigma=0.5), the same defaults scipy uses.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class NelderMeadResult(NamedTuple):
    x: jax.Array  # (n,) best point
    fun: jax.Array  # scalar: objective at x
    n_iter: jax.Array  # iterations actually run
    converged: jax.Array  # bool: tolerances met before max_iter


def _init_simplex(x0: jax.Array) -> jax.Array:
    # scipy's initialization: perturb each coordinate by 5% (0.00025 if zero).
    n = x0.shape[0]
    pert = jnp.where(x0 == 0.0, 0.00025, 0.05 * x0)
    return jnp.concatenate([x0[None, :], x0[None, :] + jnp.diag(pert)], axis=0)


def nelder_mead(
    fn: Callable[[jax.Array], jax.Array],
    x0: jax.Array,
    max_iter: int = 200,
    xatol: float = 1e-4,
    fatol: float = 1e-4,
) -> NelderMeadResult:
    """Minimize ``fn`` (R^n -> R, JAX-traceable) starting at ``x0``."""
    # A floating x0 keeps its dtype: forcing f64 under x64 split the
    # simplex from f32 state inside the objective (the Kalman scan
    # carry), which the x64 lens of `dsst audit` flagged — callers that
    # want an f64 search pass an f64 start. Non-float starts take the
    # configuration's default float.
    x0 = jnp.asarray(x0)
    if not jnp.issubdtype(x0.dtype, jnp.floating):
        x0 = x0.astype(
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        )
    n = x0.shape[0]
    simplex = _init_simplex(x0)
    # Non-finite objective values must not poison the simplex ordering.
    fvals = jnp.nan_to_num(jax.vmap(fn)(simplex), nan=jnp.inf)

    def body(carry):
        simplex, fvals, it = carry
        order = jnp.argsort(fvals)
        simplex = simplex[order]
        fvals = fvals[order]
        f_best, f_second, f_worst = fvals[0], fvals[-2], fvals[-1]
        centroid = simplex[:-1].mean(axis=0)
        worst = simplex[-1]

        xr = centroid + (centroid - worst)  # reflection
        xe = centroid + 2.0 * (centroid - worst)  # expansion
        xoc = centroid + 0.5 * (centroid - worst)  # outside contraction
        xic = centroid - 0.5 * (centroid - worst)  # inside contraction
        fr, fe, foc, fic = [
            jnp.nan_to_num(fn(x), nan=jnp.inf) for x in (xr, xe, xoc, xic)
        ]

        # Decide the replacement for the worst vertex.
        take_exp = (fr < f_best) & (fe < fr)
        take_ref = (fr < f_second) & ~take_exp & ~(fr < f_best)
        take_ref = take_ref | ((fr < f_best) & ~(fe < fr))
        take_oc = (fr >= f_second) & (fr < f_worst) & (foc <= fr)
        take_ic = (fr >= f_second) & ~(fr < f_worst) & (fic < f_worst)
        shrink = ~(take_exp | take_ref | take_oc | take_ic)

        new_vertex = jnp.where(
            take_exp[..., None],
            xe,
            jnp.where(
                take_ref[..., None],
                xr,
                jnp.where(take_oc[..., None], xoc, xic),
            ),
        )
        new_f = jnp.where(
            take_exp, fe, jnp.where(take_ref, fr, jnp.where(take_oc, foc, fic))
        )

        replaced_simplex = simplex.at[-1].set(new_vertex)
        replaced_fvals = fvals.at[-1].set(new_f)

        shrunk_simplex = simplex[0][None, :] + 0.5 * (simplex - simplex[0])
        shrunk_fvals = jnp.nan_to_num(jax.vmap(fn)(shrunk_simplex), nan=jnp.inf)
        shrunk_fvals = shrunk_fvals.at[0].set(fvals[0])  # best vertex unchanged

        simplex = jnp.where(shrink, shrunk_simplex, replaced_simplex)
        fvals = jnp.where(shrink, shrunk_fvals, replaced_fvals)
        return simplex, fvals, it + 1

    def cond(carry):
        simplex, fvals, it = carry
        x_spread = jnp.max(jnp.abs(simplex[1:] - simplex[0]))
        f_spread = jnp.max(jnp.abs(fvals[1:] - fvals[0]))
        done = (x_spread <= xatol) & (f_spread <= fatol)
        return (it < max_iter) & ~done

    simplex, fvals, it = lax.while_loop(cond, body, (simplex, fvals, jnp.array(0)))
    best = jnp.argmin(fvals)
    x_spread = jnp.max(jnp.abs(simplex[1:] - simplex[0]))
    f_spread = jnp.max(jnp.abs(fvals[1:] - fvals[0]))
    converged = (x_spread <= xatol) & (f_spread <= fatol)
    return NelderMeadResult(simplex[best], fvals[best], it, converged)

"""Pallas fused BN-apply + 1x1-conv (matmul) with a byte-minimal VJP.

The second HBM byte-cutting lever on top of :mod:`.fused_norm` (which
removed autodiff's *saved-residual* bloat around BatchNorm).  What is
left after that fusion is the normalize/relu **apply** pass itself: at
every BN site the network writes the normalized activation ``a`` to HBM
and the next convolution reads it back — two full activation-sized HBM
trips that exist only because the ops are separate HLOs.

Two of the three convolutions in a ResNet bottleneck block are 1x1 —
i.e. plain matmuls over the flattened ``[batch*H*W, C]`` layout.  For
those sites this module fuses the BN apply INTO the consuming matmul as
a tile **prologue**: the kernel streams the raw conv output ``y`` from
HBM and computes ``a = relu((y - mean) * inv * gamma + beta)`` in
registers immediately before feeding the MXU.  The post-BN activation
never exists in HBM, in either the forward or the backward pass:

    forward:    out = relu(y_hat * gamma + beta) @ W      (one kernel)
    backward:   da  = g @ W^T, masked in-epilogue, with the
                per-channel sums the BN backward needs accumulated
                across the grid in the same pass
                dW  = a^T @ g with a recomputed in-prologue

Division of labour with XLA (why this is not "rewrite convs in Pallas"):

- The batch statistics (mean/var of ``y``) stay a plain HLO reduction,
  computed by the caller (:class:`.fused_norm.BatchNorm` in
  ``stats_only`` mode).  Under a batch-sharded mesh GSPMD turns that
  reduction global, so sync-BN is preserved exactly as in the HLO
  fused path.  Only the elementwise apply — trivially shardable —
  moves into the kernel.
- The 3x3 convolutions stay XLA's (spatial convs are where XLA's conv
  emitter earns its keep); this kernel handles the matmul-shaped sites
  where a prologue costs nothing.

Gradient semantics mirror :mod:`.fused_norm`: the op takes the batch
``mean``/``var`` as explicit inputs but its VJP **internalizes** the
statistics' dependence on ``y`` (the classic ``(n*g - sum_g -
x_hat*sum_gx)/n`` correction), returning zero cotangents for them — the
same total gradient as differentiating through the stats, with flax's
stop-gradient running-average semantics.

SPMD: on one device (the headline benchmark path) the kernel-internal
per-channel sums are exact as-is.  Under a batch-sharded mesh, call
the op inside ``shard_map`` with ``axis_name=`` — the backward then
``psum``s the sums feeding ``dy`` so every shard uses the global
statistics backward, while dgamma/dbeta/dW stay shard-local (the
shard_map transpose of replicated inputs reduces them).  The model
integrates this as ``ResNet(fused_bn="pallas", pallas_mesh=mesh)``
(models/resnet.py), validated end to end by the driver's multichip
dryrun and tests/test_fused_matmul.py on the simulated 8-device mesh.
The HLO fused path (``fused_bn=True``) remains the default for
multi-chip training; compiled-TPU multi-chip pallas awaits real
multi-chip hardware to validate.

Capability parity: the composition equals the reference's
``Conv2d(1x1, bias=False) ∘ ReLU ∘ BatchNorm2d`` sequence inside
torchvision's Bottleneck (reference
``deep_learning/2.distributed-data-loading-petastorm.py:135-165``
fine-tunes exactly that ResNet-50), re-fused for the TPU memory
hierarchy instead of executed as three kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["bn_relu_matmul"]

# M-dimension tile: small enough that every site's VMEM working set
# (y tile + weight panel + f32 accumulator) fits comfortably in 16 MB,
# large enough to amortize the per-step prologue.
_TM = 512
# Lane width: K and N are padded to multiples of this (TPU lane count;
# zero-padded params/weights make the padding semantically inert).
_LANE = 128


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    size = x.shape[axis]
    rem = size % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad)


def _interpret_default() -> bool:
    # Interpret mode on CPU hosts (tests, dryruns); compiled on TPU.
    return jax.default_backend() == "cpu"


def _n_tile(n: int) -> int:
    """Largest N-tile <= 512 dividing n (n is a multiple of _LANE)."""
    for cand in (512, 256, 128):
        if n % cand == 0:
            return cand
    return _LANE


# ---------------------------------------------------------------------------
# Kernels.  Channel vectors arrive as [1, K] f32 rows.  ``with_res``
# switches the optional pre-relu residual operand (the bottleneck
# shortcut); refs are unpacked positionally to keep each operand a
# separate HBM array (no stacking copies).
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, with_res):
    if with_res:
        y_ref, res_ref, s_ref, t_ref, w_ref, out_ref = refs
    else:
        y_ref, s_ref, t_ref, w_ref, out_ref = refs
    z = y_ref[...].astype(jnp.float32) * s_ref[...] + t_ref[...]
    if with_res:
        z = z + res_ref[...].astype(jnp.float32)
    a = jnp.maximum(z, 0.0)
    out_ref[...] = jnp.dot(
        a.astype(y_ref.dtype), w_ref[...],
        preferred_element_type=jnp.float32,
    ).astype(out_ref.dtype)


def _bwd_da_kernel(*refs, with_res):
    """Grid over M: gt = (g @ w^T) * relu_mask, plus the per-channel
    sums the BN backward needs, accumulated across the whole grid."""
    if with_res:
        (g_ref, w_ref, y_ref, res_ref, s_ref, t_ref, m_ref, u_ref,
         gt_ref, sum_g_ref, sum_gx_ref) = refs
    else:
        (g_ref, w_ref, y_ref, s_ref, t_ref, m_ref, u_ref,
         gt_ref, sum_g_ref, sum_gx_ref) = refs
    da = jax.lax.dot_general(
        g_ref[...], w_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y32 = y_ref[...].astype(jnp.float32)
    z = y32 * s_ref[...] + t_ref[...]
    if with_res:
        z = z + res_ref[...].astype(jnp.float32)
    gt = jnp.where(z > 0.0, da, 0.0)
    gt_ref[...] = gt.astype(gt_ref.dtype)
    x_hat = (y32 - m_ref[...]) * u_ref[...]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        sum_g_ref[...] = jnp.zeros_like(sum_g_ref)
        sum_gx_ref[...] = jnp.zeros_like(sum_gx_ref)

    sum_g_ref[...] += jnp.sum(gt, axis=0, keepdims=True)
    sum_gx_ref[...] += jnp.sum(gt * x_hat, axis=0, keepdims=True)


def _bwd_dw_kernel(*refs, with_res):
    """Grid over M: dw[K, N] += a^T @ g with a recomputed in-prologue."""
    if with_res:
        y_ref, res_ref, s_ref, t_ref, g_ref, dw_ref = refs
    else:
        y_ref, s_ref, t_ref, g_ref, dw_ref = refs
    z = y_ref[...].astype(jnp.float32) * s_ref[...] + t_ref[...]
    if with_res:
        z = z + res_ref[...].astype(jnp.float32)
    a = jnp.maximum(z, 0.0)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += jax.lax.dot_general(
        a.astype(y_ref.dtype), g_ref[...],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


# ---------------------------------------------------------------------------
# custom-VJP op over flattened, padded [M, K] inputs (private)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _make_op(with_res: bool, interpret: bool, eps: float,
             axis_name: str | None = None, batch_stats: bool = True):
    """Op for one configuration; shapes already padded: y [M, K],
    gamma/beta/mean/var [1, K] f32, w [K, N]; M % _TM == 0,
    K % _LANE == 0, N % _LANE == 0.  The op takes an extra trailing
    ``n_count`` operand (f32 scalar, TRACED): the UNPADDED row count —
    the N of the batch statistics' mean, which the backward's stats
    correction divides by (padded rows carry zero cotangents, so the
    sums are unaffected, but the divisor must be the real one). Traced
    rather than baked into this cache key so variable-shape callers
    can't leak one custom_vjp op per distinct M — the key space here is
    a handful of static configurations, a naturally bounded cache.

    With ``axis_name`` (shard_map over the flattened-M axis): the
    channel sums feeding ``dy``'s statistics correction are ``psum``-ed
    (global), while dgamma/dbeta/dw are returned shard-local —
    shard_map's transpose of replicated inputs reduces those itself.
    ``n_count`` must then be the global row count."""

    def _vectors(gamma, beta, mean, var):
        inv = jax.lax.rsqrt(var + eps)
        s = gamma * inv
        t = beta - mean * s
        return s, t, inv

    def _row_spec(k):
        return pl.BlockSpec((1, k), lambda *idx: (0, 0))

    def _call_fwd(y, s, t, w, res):
        m, k = y.shape
        n = w.shape[1]
        tn = _n_tile(n)
        ys = [y] + ([res] if with_res else [])
        y_specs = [
            pl.BlockSpec((_TM, k), lambda i, j: (i, 0)) for _ in ys
        ]
        return pl.pallas_call(
            functools.partial(_fwd_kernel, with_res=with_res),
            grid=(m // _TM, n // tn),
            in_specs=y_specs + [
                _row_spec(k),
                _row_spec(k),
                pl.BlockSpec((k, tn), lambda i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((_TM, tn), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((m, n), y.dtype),
            interpret=interpret,
        )(*ys, s, t, w)

    def f(y, gamma, beta, mean, var, w, n_count, *maybe_res):
        s, t, _ = _vectors(gamma, beta, mean, var)
        res = maybe_res[0] if with_res else None
        return _call_fwd(y, s, t, w, res)

    def f_fwd(y, gamma, beta, mean, var, w, n_count, *maybe_res):
        s, t, inv = _vectors(gamma, beta, mean, var)
        res = maybe_res[0] if with_res else None
        out = _call_fwd(y, s, t, w, res)
        # Saved: y (the raw conv output — the only activation-sized
        # tensor, and the one the surrounding graph keeps alive
        # anyway), the per-channel vectors, w, and the scalar row
        # count.  The normalized activation is never materialized.
        saved = (y, s, t, mean, inv, w, n_count) + (
            (res,) if with_res else ()
        )
        return out, saved

    def f_bwd(saved, g):
        y, s, t, mean, inv, w, n_count = saved[:7]
        res = saved[7] if with_res else None
        m, k = y.shape
        n = w.shape[1]
        ys = [y] + ([res] if with_res else [])

        y_specs1 = [pl.BlockSpec((_TM, k), lambda i: (i, 0)) for _ in ys]
        gt, sum_g, sum_gx = pl.pallas_call(
            functools.partial(_bwd_da_kernel, with_res=with_res),
            grid=(m // _TM,),
            in_specs=[
                pl.BlockSpec((_TM, n), lambda i: (i, 0)),
                pl.BlockSpec((k, n), lambda i: (0, 0)),
            ] + y_specs1 + [
                _row_spec(k), _row_spec(k), _row_spec(k), _row_spec(k),
            ],
            out_specs=[
                pl.BlockSpec((_TM, k), lambda i: (i, 0)),
                _row_spec(k),
                _row_spec(k),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((m, k), y.dtype),
                jax.ShapeDtypeStruct((1, k), jnp.float32),
                jax.ShapeDtypeStruct((1, k), jnp.float32),
            ],
            interpret=interpret,
        )(g, w, *ys, s, t, mean, inv)

        y_specs2 = [pl.BlockSpec((_TM, k), lambda i: (i, 0)) for _ in ys]
        dw = pl.pallas_call(
            functools.partial(_bwd_dw_kernel, with_res=with_res),
            grid=(m // _TM,),
            in_specs=y_specs2 + [
                _row_spec(k),
                _row_spec(k),
                pl.BlockSpec((_TM, n), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((k, n), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((k, n), jnp.float32),
            interpret=interpret,
        )(*ys, s, t, g)

        # dy's statistics correction needs the GLOBAL sums (mean/var
        # were global); dgamma/dbeta/dw stay LOCAL — shard_map's
        # transpose of replicated (P()) inputs psums per-shard
        # cotangents itself, so reducing them here would double-count.
        if axis_name is not None:
            g_sum = jax.lax.psum(sum_g, axis_name)
            gx_sum = jax.lax.psum(sum_gx, axis_name)
        else:
            g_sum, gx_sum = sum_g, sum_gx
        dw = dw.astype(w.dtype)

        # Elementwise finish in HLO (XLA fuses it into one pass over
        # gt/y): the BN backward with the stats path internalized —
        #   dy = s * (gt - (sum_g + x_hat * sum_gx) / n_count)
        # dbeta/dgamma are the accumulated sums; dres is gt itself (the
        # masked cotangent), no extra traffic.  (Padded rows produce
        # nonzero dy here, but the caller's pad-VJP slices them off.)
        # With constant (running-average) stats the correction does not
        # exist — mean/var are not functions of y — so dy is s*gt.
        gt32 = gt.astype(jnp.float32)
        if batch_stats:
            x_hat = (y.astype(jnp.float32) - mean) * inv
            # n_count is a traced f32 scalar operand (not part of the
            # op-cache key), so variable-M callers reuse one op.
            dy32 = s * (gt32 - (g_sum + x_hat * gx_sum) / n_count)
        else:
            dy32 = s * gt32
        dy = dy32.astype(y.dtype)
        dgamma = sum_gx
        dbeta = sum_g
        grads = (dy, dgamma, dbeta, jnp.zeros_like(mean),
                 jnp.zeros_like(mean), dw, jnp.zeros_like(n_count))
        if with_res:
            grads = grads + (gt,)
        return grads

    op = jax.custom_vjp(f)
    op.defvjp(f_fwd, f_bwd)
    return op


# ---------------------------------------------------------------------------
# Public entry: NHWC conv-output in, matmul out
# ---------------------------------------------------------------------------

def bn_relu_matmul(
    y: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    mean: jax.Array,
    var: jax.Array,
    kernel: jax.Array,
    *,
    eps: float = 1e-5,
    residual: jax.Array | None = None,
    interpret: bool | None = None,
    axis_name: str | None = None,
    global_count: int | None = None,
    batch_stats: bool = True,
) -> jax.Array:
    """``relu(BN(y)) @ W`` (1x1 conv) without materializing the
    normalized activation.

    Args:
      y: raw conv output ``[..., K]`` (NHWC or already flattened).
      gamma/beta: BN scale/offset ``[K]`` (f32).
      mean/var: batch (or running) statistics ``[K]`` (f32).  With
        ``batch_stats=True`` (training) they must be the actual
        statistics of ``y`` and their dependence on ``y`` is
        internalized by the VJP; with ``batch_stats=False`` (eval /
        frozen BN) they are treated as constants and the backward
        skips the statistics correction — matching autodiff through
        the unfused eval composition.
      kernel: 1x1 conv kernel, shape ``[1, 1, K, N]`` or ``[K, N]``.
      residual: optional tensor added pre-relu (the bottleneck shortcut
        fused exactly as in :func:`.fused_norm.bn_act`).
      axis_name: set when calling from inside ``shard_map`` with the
        leading (batch) axis sharded: the backward ``psum``s the
        channel sums feeding ``dy`` so every shard uses the global
        statistics backward; dgamma/dbeta/dW stay shard-local because
        shard_map's transpose of replicated inputs reduces them.
        ``mean``/``var`` must be the global statistics and
        ``global_count`` the global row count.

    Returns the conv output with shape ``[..., N]``.
    """
    if kernel.ndim == 4:
        if kernel.shape[:2] != (1, 1):
            raise ValueError(f"not a 1x1 kernel: {kernel.shape}")
        kernel = kernel[0, 0]
    k, n = kernel.shape
    if y.shape[-1] != k:
        raise ValueError(f"y channels {y.shape[-1]} != kernel K {k}")
    if interpret is None:
        interpret = _interpret_default()

    lead = y.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    y2 = y.reshape(m, k)
    res2 = None
    if residual is not None:
        if residual.shape != y.shape:
            raise ValueError(
                f"residual shape {residual.shape} != y shape {y.shape}"
            )
        res2 = residual.reshape(m, k)

    # Zero-padding is semantically inert everywhere: padded M rows get
    # zero cotangents (g is zero there), padded K channels have
    # gamma=beta=mean=var=0 so a=relu(0)=0 contributes nothing, padded
    # N columns multiply zero kernel columns and are sliced off.
    y2 = _pad_to(_pad_to(y2, 0, _TM), 1, _LANE)
    if res2 is not None:
        res2 = _pad_to(_pad_to(res2, 0, _TM), 1, _LANE)
    w2 = _pad_to(_pad_to(kernel, 0, _LANE), 1, _LANE)

    def row(v):
        return _pad_to(v.astype(jnp.float32).reshape(1, k), 1, _LANE)

    op = _make_op(res2 is not None, bool(interpret), float(eps),
                  axis_name, bool(batch_stats))
    n_count = jnp.asarray(
        global_count if global_count is not None else m, jnp.float32
    )
    args = (y2, row(gamma), row(beta), row(mean), row(var), w2, n_count)
    if res2 is not None:
        args = args + (res2,)
    out = op(*args)
    return out[:m, :n].reshape(*lead, n)

"""Fused train-mode BatchNorm + activation (+ residual add) with a
hand-written minimal-residual VJP.

Why this exists (the round-3 measurement): ResNet-50 training on v5e is
HBM-bound — XLA cost analysis counts ~327 MB of HBM traffic per image at
batch 212 while the MXU idles at ~29% of bf16 peak (BASELINE.md). The
FLOPs cannot be cut; the bytes can. The biggest avoidable byte source is
autodiff's residual bloat around BatchNorm: reverse-mode AD of the
``normalize → scale/shift → (add) → relu`` chain saves intermediate
activation-sized tensors (x̂, the pre-activation, relu masks) from the
forward pass for the backward pass, each a full HBM round trip at
activation size.

The fix is NOT a Pallas kernel. The forward math here is plain XLA HLO —
two fused passes (one multi-output reduction for mean/E[x²], one
elementwise normalize+act) is already optimal, and keeping it HLO means
GSPMD partitions it: under a batch-sharded mesh the ``jnp.mean`` over
the batch axis becomes a global (cross-chip) reduction, i.e. sync-BN
falls out for free exactly as in :mod:`..models.resnet` — a property a
``pallas_call`` (an opaque custom call to SPMD) would break. What is
hand-written is the VJP: it saves ONLY ``(x, mean, inv_std, scale)``
where ``x`` is the convolution output that must stay alive anyway for
the conv's own weight gradient — so BatchNorm's backward adds **zero**
saved activation-sized tensors — and recomputes x̂ and the relu mask
in-register inside the backward's two passes:

    pass 1 (reads x, g):          Σg, Σg·x̂  → dβ, dγ
    pass 2 (reads x, g, writes):  dx = γ·inv/n · (n·g − Σg − x̂·Σg·x̂)

Fusing the residual add of a ResNet block into the same op removes the
separate ``relu(residual + y)`` elementwise pass and its saved mask as
well; ``dresidual`` is the masked cotangent already in registers.

Capability parity: train-mode semantics match ``flax.linen.BatchNorm``
(biased variance for both normalization and the running update, f32
statistics accumulation regardless of compute dtype), which is what the
reference's torchvision ResNet-50 wrapper uses per layer (reference
``deep_learning/2.distributed-data-loading-petastorm.py:135-165``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = ["BatchNorm", "bn_act"]


@functools.lru_cache(maxsize=None)
def _make_bn_act(eps: float, relu: bool, with_residual: bool):
    """Build (and cache) the custom-VJP fused op for one configuration.

    Configurations are closed over rather than passed as arguments so the
    custom_vjp signature holds arrays only (``residual`` present iff
    ``with_residual``) and tracing never sees a ``None`` pytree.
    """

    def fwd_math(x, scale, bias, residual):
        x32 = x.astype(jnp.float32)
        # Multi-output fusion: mean and E[x²] in ONE read pass over x.
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x32, axes)
        mean2 = jnp.mean(jnp.square(x32), axes)
        var = mean2 - jnp.square(mean)
        inv = jax.lax.rsqrt(var + eps)
        pre = (x32 - mean) * (inv * scale) + bias
        if with_residual:
            pre = pre + residual.astype(jnp.float32)
        out = jnp.maximum(pre, 0.0) if relu else pre
        return out.astype(x.dtype), mean, var, inv

    def f(x, scale, bias, *maybe_res):
        out, mean, var, _ = fwd_math(x, scale, bias,
                                     maybe_res[0] if with_residual else None)
        return out, mean, var

    f = jax.custom_vjp(f)

    def f_fwd(x, scale, bias, *maybe_res):
        residual = maybe_res[0] if with_residual else None
        out, mean, var, inv = fwd_math(x, scale, bias, residual)
        # Residuals: x is the conv output (alive anyway for the conv's
        # dW); mean/inv/scale/bias are per-channel vectors; the block
        # residual is the block input (alive anyway for its own
        # backward). No new activation-sized tensors are saved.
        saved = (x, mean, inv, scale, bias) + (
            (residual,) if with_residual else ()
        )
        return (out, mean, var), saved

    def f_bwd(saved, cotangents):
        x, mean, inv, scale, bias = saved[:5]
        residual = saved[5] if with_residual else None
        g_out, g_mean, g_var = cotangents
        del g_mean, g_var  # stats feed running-average updates only
        # (stop-gradient semantics, as in flax BatchNorm)

        axes = tuple(range(x.ndim - 1))
        n = 1.0
        for d in axes:
            n *= x.shape[d]

        x32 = x.astype(jnp.float32)
        g32 = g_out.astype(jnp.float32)
        x_hat = (x32 - mean) * inv
        if relu:
            # Recompute the relu mask in-register instead of saving it:
            # the forward pre-activation is a function of saved values.
            pre = x_hat * scale + bias
            if with_residual:
                pre = pre + residual.astype(jnp.float32)
            g32 = jnp.where(pre > 0, g32, 0.0)

        sum_g = jnp.sum(g32, axes)
        sum_gx = jnp.sum(g32 * x_hat, axes)
        dscale = sum_gx
        dbias = sum_g
        dx = (scale * inv) * (g32 - (sum_g + x_hat * sum_gx) / n)
        grads = (dx.astype(x.dtype), dscale, dbias)
        if with_residual:
            grads = grads + (g32.astype(residual.dtype),)
        return grads

    f.defvjp(f_fwd, f_bwd)
    return f


def bn_act(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    *,
    eps: float = 1e-5,
    relu: bool = False,
    residual: jax.Array | None = None,
):
    """Fused train-mode BN(+relu)(+residual add) over the last axis.

    Returns ``(out, mean, var)`` with ``out`` in ``x.dtype`` and biased
    ``var`` in float32 (flax semantics — the same var normalizes and
    feeds the running average). Gradients do not flow through the
    returned statistics (matching flax, where the running-average update
    is outside the differentiated graph).
    """
    fn = _make_bn_act(float(eps), bool(relu), residual is not None)
    if residual is not None:
        return fn(x, scale, bias, residual)
    return fn(x, scale, bias)


class BatchNorm(nn.Module):
    """Drop-in ``flax.linen.BatchNorm`` replacement with fused act/residual.

    Deliberately named ``BatchNorm`` so ``nn.compact`` auto-naming
    produces the same ``BatchNorm_k`` parameter paths as the unfused
    model — checkpoints and the torchvision pretrained-weights converter
    (:mod:`..models.pretrained`, which keys on those names) work
    unchanged, and fused/unfused configurations are checkpoint-portable
    in both directions.

    Differences from flax's module: ``act`` ("relu" or None) and an
    optional ``residual`` call argument are applied INSIDE the fused op;
    only channels-last (reduce over all but the last axis) is supported,
    which is the only layout the TPU-native models use.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None  # kept for call-site compatibility; out follows x.dtype
    act: str | None = None
    scale_init: Callable = nn.initializers.ones_init()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x, residual=None, *, stats_only: bool = False):
        features = x.shape[-1]
        scale = self.param("scale", self.scale_init, (features,), jnp.float32)
        bias = self.param("bias", self.bias_init, (features,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda s: jnp.zeros(s, jnp.float32), (features,),
        )
        ra_var = self.variable(
            "batch_stats", "var",
            lambda s: jnp.ones(s, jnp.float32), (features,),
        )
        relu = self.act == "relu"

        if stats_only:
            # The Pallas prologue-fusion path (ops/fused_matmul.py):
            # compute the statistics HERE, in plain HLO — a batch-
            # sharded mesh still gets the global (sync-BN) reduction —
            # update the running averages exactly as the applying path
            # does, and hand (scale, bias, mean, var) to the consuming
            # kernel, which applies normalize+relu in-register.
            if self.use_running_average:
                return scale, bias, ra_mean.value, ra_var.value
            x32 = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x32, axes)
            var = jnp.mean(jnp.square(x32), axes) - jnp.square(mean)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = (
                    m * ra_mean.value
                    + (1.0 - m) * jax.lax.stop_gradient(mean)
                )
                ra_var.value = (
                    m * ra_var.value
                    + (1.0 - m) * jax.lax.stop_gradient(var)
                )
            return scale, bias, mean, var

        if self.use_running_average:
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon)
            pre = (x.astype(jnp.float32) - ra_mean.value) * (inv * scale) + bias
            if residual is not None:
                pre = pre + residual.astype(jnp.float32)
            out = jnp.maximum(pre, 0.0) if relu else pre
            return out.astype(x.dtype)

        out, mean, var = bn_act(
            x, scale, bias, eps=self.epsilon, relu=relu, residual=residual
        )
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return out

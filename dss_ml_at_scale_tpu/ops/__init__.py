"""JAX numerical kernels: time-series fits + the hot deep-learning ops.

TPU-native replacement for the statsmodels surface the reference
exercises (SURVEY.md §2.2 X10): SARIMAX state-space ML fit, Holt-Winters
exponential smoothing, ARMA sample generation, plus the vmappable
Nelder-Mead optimizer that statsmodels' ``fit(method='nm')`` maps to.
The deep-learning hot path adds the Pallas flash-attention kernel and
the fused BN+act custom VJP (``fused_norm``) that cuts ResNet HBM bytes.

Everything here is pure JAX (``lax.scan`` / ``lax.while_loop``), built to
``vmap`` across thousands of SKU groups at once — one sharded batched fit
replaces the reference's one-Spark-task-per-group Python processes
(``group_apply/02_Fine_Grained_Demand_Forecasting.py:516-528``).
"""

from .arma import arma_generate_sample, lfilter
from .flash_attention import attention_reference, flash_attention
from .fused_norm import bn_act
from .holt_winters import HoltWintersResult, holt_winters_fit, holt_winters_forecast
from .kalman import kalman_filter, kalman_forecast
from .neldermead import NelderMeadResult, nelder_mead
from .polish import sarimax_polish
from .sarimax import (
    SarimaxConfig,
    SarimaxGridResult,
    SarimaxResult,
    grid_orders,
    sarimax_fit,
    sarimax_fit_grid,
    sarimax_loglike,
    sarimax_predict,
)

__all__ = [
    "arma_generate_sample",
    "lfilter",
    "attention_reference",
    "flash_attention",
    "bn_act",
    "HoltWintersResult",
    "holt_winters_fit",
    "holt_winters_forecast",
    "kalman_filter",
    "kalman_forecast",
    "NelderMeadResult",
    "nelder_mead",
    "SarimaxConfig",
    "SarimaxGridResult",
    "SarimaxResult",
    "grid_orders",
    "sarimax_fit",
    "sarimax_fit_grid",
    "sarimax_loglike",
    "sarimax_polish",
    "sarimax_predict",
]

"""Image-dataset ingestion tooling (SURVEY.md §2 R1)."""

from .imagenet import (
    copy_parallel,
    extract_object,
    ingest_image_dataset,
    object_id_from_path,
    scan_binary_files,
    xml_annotation_to_json,
)

__all__ = [
    "copy_parallel",
    "extract_object",
    "ingest_image_dataset",
    "object_id_from_path",
    "scan_binary_files",
    "xml_annotation_to_json",
]

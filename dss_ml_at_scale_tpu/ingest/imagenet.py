"""ImageNet-style ingestion: image tree → Delta table of binary rows.

Rebuilds ``deep_learning/1.data-preparation.py`` without Spark/DBFS:
threaded parallel copy (``copy_parallel``, ``:48-74``), recursive
binary-file scan (the ``binaryFile`` reader, ``:118-124``), XML
annotation → JSON and label extraction (``:140-169``; stdlib
``xml.etree`` replaces xmltodict, producing the same
``{"annotation": {"object": ...}}`` shape the extractors consume),
stable monotonic ``id`` assignment (the ``zipWithIndex`` trick,
``:181-186``), and an uncompressed-parquet Delta write (``:191,200``).
``OPTIMIZE ZORDER BY id`` has no equivalent because the TPU loader
shards by file/row-group, not by id clustering (SURVEY.md §2.2 X14).
"""

from __future__ import annotations

import json
import os
import shutil
import xml.etree.ElementTree as ET
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Iterator, Sequence

import pyarrow as pa

from ..data.delta import DeltaTable, write_delta


def copy_parallel(
    src: str | os.PathLike,
    dest: str | os.PathLike,
    file_pattern: str = "*",
    n_workers: int = 100,
) -> int:
    """Threaded recursive copy; returns the number of files copied.

    Preserves the relative directory layout under ``dest`` (an ImageNet
    tree has one directory per wnid with repeated filenames across dirs,
    so flattening would silently drop copies).
    """
    src = Path(src)
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    files = [p for p in sorted(src.rglob(file_pattern)) if p.is_file()]

    def _copy(p: Path) -> None:
        target = dest / p.relative_to(src)
        target.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(p, target)

    with ThreadPoolExecutor(max_workers=n_workers) as pool:
        list(pool.map(_copy, files))
    return len(files)


def scan_binary_files(
    root: str | os.PathLike, file_pattern: str = "*.JPEG"
) -> Iterator[dict]:
    """Recursive binary-file scan, one dict per file (path/length/mtime/content).

    Generator, so arbitrarily large trees stream through bounded memory —
    the Spark ``binaryFile`` source contract without a JVM.
    """
    for p in sorted(Path(root).rglob(file_pattern)):
        stat = p.stat()
        yield {
            "path": str(p),
            "modificationTime": int(stat.st_mtime * 1000),
            "length": stat.st_size,
            "content": p.read_bytes(),
        }


def _etree_to_dict(node: ET.Element):
    """xmltodict-shaped dict: repeated children become lists."""
    children = list(node)
    if not children:
        return node.text
    out: dict = {}
    for child in children:
        val = _etree_to_dict(child)
        if child.tag in out:
            if not isinstance(out[child.tag], list):
                out[child.tag] = [out[child.tag]]
            out[child.tag].append(val)
        else:
            out[child.tag] = val
    return out


def xml_annotation_to_json(
    img_path: str, data_dir: str = "Data", annotations_dir: str = "Annotations"
) -> str:
    """JSON annotation for an image path (reference ``:146-157``): the
    sibling ``Annotations`` tree holds one ``.xml`` per ``.JPEG``; a
    missing file yields ``"{}"``."""
    xml_path = Path(
        img_path.replace(f"/{data_dir}/", f"/{annotations_dir}/").replace(
            ".JPEG", ".xml"
        )
    )
    if not xml_path.exists():
        return "{}"
    root = ET.parse(xml_path).getroot()
    return json.dumps({root.tag: _etree_to_dict(root)})


def extract_object(annotation_json: str) -> str | None:
    """First object label from an annotation (reference ``:159-169``)."""
    objects = json.loads(annotation_json).get("annotation", {}).get("object")
    if objects is None:
        return None
    if isinstance(objects, dict):
        return objects.get("name")
    return objects[0].get("name")


def object_id_from_path(path: str) -> str:
    """Train-split label from the filename: ``n02007558_10693.JPEG`` →
    ``n02007558`` (reference ``:183`` split logic)."""
    return Path(path).name.split("_")[0]


def ingest_image_dataset(
    data_root: str | os.PathLike,
    table_path: str | os.PathLike,
    *,
    file_pattern: str = "*.JPEG",
    label_from: str = "path",  # "path" (train) | "annotation" (val)
    annotations_dir: str = "Annotations",
    data_dir: str = "Data",
    rows_per_fragment: int = 1024,
    mode: str = "overwrite",
    on_missing_label: str = "error",
) -> DeltaTable:
    """Scan → annotate → label → write Delta with stable ``id`` column.

    Streams in fragments of ``rows_per_fragment`` so content bytes never
    all sit in memory; ids are contiguous across fragments (zipWithIndex
    semantics). ``label_from`` mirrors the reference's two splits: train
    labels parsed from filenames, val labels from XML annotations.

    A row whose label cannot be determined (``label_from="annotation"``
    with a missing or object-less XML) raises by default — a silent
    sentinel would corrupt training loss downstream. Pass
    ``on_missing_label="keep"`` to ingest it anyway with
    ``label_index=-1`` (callers must then filter before training).
    """
    if label_from not in ("path", "annotation"):
        raise ValueError(f"label_from must be 'path' or 'annotation', got {label_from!r}")
    if on_missing_label not in ("error", "keep"):
        raise ValueError(
            f"on_missing_label must be 'error' or 'keep', got {on_missing_label!r}"
        )

    # Appending continues the id sequence from the existing table so ids
    # stay unique and monotonic (zipWithIndex semantics across ingests).
    id_start = 0
    if mode == "append" and Path(table_path, "_delta_log").exists():
        import pyarrow.parquet as pq

        # Tables ingested before label_index existed lack the column in
        # their fragments; mixing schemas would break every whole-table
        # read mid-epoch instead of failing here with a way out.
        first_uri = next(iter(DeltaTable(table_path).file_uris()), None)
        if first_uri is not None and "label_index" not in set(
            pq.ParquetFile(first_uri).schema_arrow.names
        ):
            raise ValueError(
                f"{table_path} was ingested by an older version without "
                "the label_index column; re-ingest it (mode='overwrite') "
                "before appending"
            )

        for uri in DeltaTable(table_path).file_uris():
            # Footer statistics only — no data pages read.
            meta = pq.ParquetFile(uri).metadata
            col = meta.schema.to_arrow_schema().get_field_index("id")
            for rg in range(meta.num_row_groups):
                stats = meta.row_group(rg).column(col).statistics
                if stats is not None and stats.has_min_max:
                    id_start = max(id_start, stats.max + 1)
                else:  # no stats written: fall back to reading the column
                    ids = pq.read_table(uri, columns=["id"])["id"]
                    if len(ids):
                        id_start = max(id_start, ids.to_numpy().max() + 1)
                    break

    # object_id → label_index assigned on first encounter. The scan is
    # sorted (scan_binary_files rglob-sorts), so for an ImageNet-style
    # tree this is sorted-wnid order and deterministic across re-ingests
    # of the same tree; the vocabulary is persisted as labels.json next
    # to the table so train/predict (which consume the int label_index
    # column directly) can map predictions back to names.
    vocab: dict[str, int] = {}
    if mode == "append":
        labels_path = Path(table_path) / "labels.json"
        if labels_path.exists():
            vocab = json.loads(labels_path.read_text())

    def rows() -> Iterator[dict]:
        for i, rec in enumerate(scan_binary_files(data_root, file_pattern), start=id_start):
            ann = xml_annotation_to_json(rec["path"], data_dir, annotations_dir)
            rec["annotation"] = ann
            rec["object_id"] = (
                object_id_from_path(rec["path"])
                if label_from == "path"
                else extract_object(ann)
            )
            if rec["object_id"] is None:
                if on_missing_label == "error":
                    raise ValueError(
                        f"no label for {rec['path']} (label_from="
                        f"{label_from!r}); fix the annotation or pass "
                        "on_missing_label='keep' to ingest it with "
                        "label_index=-1"
                    )
                rec["label_index"] = -1
            else:
                rec["label_index"] = vocab.setdefault(
                    rec["object_id"], len(vocab)
                )
            rec["id"] = i
            yield rec

    schema = pa.schema(
        [
            ("path", pa.string()),
            ("modificationTime", pa.int64()),
            ("length", pa.int64()),
            ("content", pa.binary()),
            ("annotation", pa.string()),
            ("object_id", pa.string()),
            ("label_index", pa.int64()),
            ("id", pa.int64()),
        ]
    )

    written = False
    batch: list[dict] = []

    from .. import telemetry

    rows_total = telemetry.counter(
        "ingest_rows_total", "rows written by ingest_image_dataset"
    )
    bytes_total = telemetry.counter(
        "ingest_bytes_total", "content bytes written by ingest_image_dataset"
    )

    def flush(batch: Sequence[dict], first: bool) -> None:
        tbl = pa.Table.from_pylist(list(batch), schema=schema)
        write_delta(tbl, table_path, mode=mode if first else "append")
        rows_total.inc(len(batch))
        bytes_total.inc(sum(r["length"] for r in batch))

    with telemetry.span("ingest", root=str(data_root)):
        for rec in rows():
            batch.append(rec)
            if len(batch) >= rows_per_fragment:
                flush(batch, not written)
                written = True
                batch = []
        if batch or not written:
            flush(batch, not written)
    (Path(table_path) / "labels.json").write_text(json.dumps(vocab))
    return DeltaTable(table_path)

"""Shared utilities: profiling, step timing, diagnostics."""

from .profiling import (  # noqa: F401
    StepTimer,
    annotate,
    trace,
)

__all__ = ["StepTimer", "annotate", "trace"]

"""Profiling and step-timing hooks (the subsystem the reference lacks).

The reference's only observability artifacts are a wall-clock epoch print
(``deep_learning/2.distributed-data-loading-petastorm.py:184``) and debug
batch prints gated on a logging level (``:176-179,203-206``); SURVEY.md
§5.1 calls for real ``jax.profiler`` trace hooks plus per-step timing.
This module provides both:

- :func:`trace` — context manager around
  ``jax.profiler.start_trace``/``stop_trace`` producing a TensorBoard /
  XProf-loadable trace directory (XLA HLO timelines, host/device
  activity).
- :func:`annotate` — named ``TraceAnnotation`` so framework phases
  (decode, device_put, train_step) show up as labeled spans.
- :class:`StepTimer` — cheap host-side per-step wall-time recorder with
  summary statistics. It deliberately does NOT block on device results:
  steady-state dispatch intervals equal device step time once the
  dispatch queue fills, and blocking every step would serialize the very
  pipeline being measured. Call :meth:`StepTimer.summary` after a
  ``block_until_ready`` for honest totals.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Callable, Iterator

import jax


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace for the enclosed block.

    The resulting ``logdir`` loads in TensorBoard's profile plugin /
    XProf and shows the XLA op timeline on device plus host-side Python
    activity — the diagnostic the reference's epoch print stood in for.
    """
    # ProfileOptions is newer than some installed jaxlibs; fall back to a
    # plain trace (default host tracer level) when it's absent.
    options_cls = getattr(jax.profiler, "ProfileOptions", None)
    if options_cls is not None:
        options = options_cls()
        options.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=options)
    else:
        jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace span: ``with annotate("decode"): ...``."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Rolling per-step wall-time recorder.

    ``tick()`` marks a step boundary; intervals between consecutive ticks
    are recorded. With ``skip_first_interval`` (default) the first
    recorded interval after construction is discarded at record time —
    that interval spans the jit compile of the first step. Discarding at
    the recorder, not in :meth:`summary`, keeps the stats honest after
    ring-buffer eviction and across per-epoch :meth:`reset` calls (epochs
    ≥ 2 have no compile step, so ``reset`` does not re-arm the skip
    unless asked).
    """

    def __init__(self, capacity: int = 4096, skip_first_interval: bool = True,
                 observer: Callable[[float], None] | None = None):
        self.capacity = capacity
        # deque(maxlen=...) evicts in O(1); list.pop(0) was O(n) per step
        # once at capacity — a growing per-step tax on long runs.
        self._times: collections.deque[float] = collections.deque(
            maxlen=capacity
        )
        self._last: float | None = None
        self._skip_next = skip_first_interval
        # Called once per RECORDED interval (compile-skipped intervals are
        # not observed) — the telemetry histogram hook, kept out of the
        # eviction-bounded ring so exported stats cover the whole run.
        self._observer = observer

    def reset(self, *, skip_next_interval: bool = False) -> None:
        self._times.clear()
        self._last = None
        self._skip_next = skip_next_interval

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            if self._skip_next:
                self._skip_next = False
            else:
                dt = now - self._last
                self._times.append(dt)
                if self._observer is not None:
                    self._observer(dt)
        self._last = now

    @property
    def intervals(self) -> list[float]:
        return list(self._times)

    def summary(self) -> dict[str, float]:
        """Mean / p50 / p90 / max step seconds and steps/sec."""
        xs = self._times
        if not xs:
            return {}
        xs_sorted = sorted(xs)
        n = len(xs_sorted)
        mean = sum(xs_sorted) / n
        return {
            "step_time_mean_s": mean,
            "step_time_p50_s": xs_sorted[n // 2],
            "step_time_p90_s": xs_sorted[min(n - 1, (9 * n) // 10)],
            "step_time_max_s": xs_sorted[-1],
            "steps_per_sec": 1.0 / mean if mean > 0 else float("inf"),
        }

"""Shared benchmark scaffolding for bench.py / bench_scaling.py.

One place for the model/task construction, synthetic batches, and the
timing methodology — in particular the sync discipline: through remote
device tunnels ``block_until_ready`` has proven unreliable, so timing
windows end by fetching a scalar that data-depends on the last step.
"""

from __future__ import annotations

import time


def build_resnet_task(num_classes: int, on_accel: bool,
                      learning_rate: float = 1e-5, fused_bn: bool = True):
    """Benchmark ResNet-50: full-size bf16 on accelerators, a small f32
    stand-in on CPU (where the number is a harness check, not a result).

    ``fused_bn`` (default on) selects the minimal-residual fused
    BN+relu(+residual) path (ops/fused_norm.py) — the HBM byte cut that
    BASELINE.md identifies as the throughput lever on v5e."""
    import jax.numpy as jnp
    import optax

    from ..models import ResNet50
    from ..parallel import ClassifierTask

    model = (
        ResNet50(num_classes=num_classes, fused_bn=fused_bn)
        if on_accel
        else ResNet50(
            num_classes=num_classes, num_filters=8, dtype=jnp.float32,
            fused_bn=fused_bn,
        )
    )
    return ClassifierTask(model=model, tx=optax.adam(learning_rate))


def dp_sharded_step(task, n_devices: int, batch_per_device: int, image: int,
                    num_classes: int, donate: bool = True):
    """(jitted step, placed state, placed batch) for a pure-DP mesh.

    The one DP sharding scaffold shared by the throughput harness
    (bench_scaling.py) and the collective-bytes model (scaling_model.py),
    so the program they measure is the same program."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..runtime import make_mesh

    mesh = make_mesh({"data": n_devices}, devices=jax.devices()[:n_devices])
    batch = synthetic_image_batch(
        batch_per_device * n_devices, image, num_classes=num_classes
    )
    state = task.init_state(jax.random.key(0), batch)
    replicated = NamedSharding(mesh, P())
    state = jax.device_put(state, replicated)
    batch = {
        "image": jax.device_put(
            batch["image"], NamedSharding(mesh, P("data", None, None, None))
        ),
        "label": jax.device_put(batch["label"], NamedSharding(mesh, P("data"))),
    }
    step = jax.jit(
        task.train_step,
        donate_argnums=(0,) if donate else (),
        out_shardings=(replicated, replicated),
    )
    return step, state, batch


def synthetic_image_batch(batch: int, image: int, num_classes: int,
                          seed: int = 0) -> dict:
    import numpy as np

    rng = np.random.default_rng(seed)
    return {
        "image": rng.normal(size=(batch, image, image, 3)).astype(np.float32),
        "label": rng.integers(0, num_classes, batch).astype(np.int32),
    }


def synthetic_image_batch_device(batch: int, image: int, num_classes: int,
                                 seed: int = 0) -> dict:
    """Device-resident synthetic batch, generated ON the device.

    The host-numpy path (``synthetic_image_batch`` + ``device_put``)
    ships ~127 MB through the accelerator tunnel at batch 212; a
    degraded tunnel has been observed to stall exactly there (round-4
    live run: train_step compiled in ~3 min, then 12 min with no
    progress).  Generating the batch with on-device PRNG removes bulk
    host->device traffic from the compute-path benchmark entirely —
    which is also the honest shape of the metric: it measures the chip,
    not the tunnel.
    """
    import jax
    import jax.numpy as jnp

    # Eager (un-jitted) on purpose: a fresh jit closure per call would
    # guarantee a cache-miss compile per sweep point; eager PRNG ops
    # compile nothing extra and still run on the default device.
    ki, kl = jax.random.split(jax.random.key(seed))
    out = {
        "image": jax.random.normal(ki, (batch, image, image, 3),
                                   jnp.float32),
        "label": jax.random.randint(kl, (batch,), 0, num_classes,
                                    jnp.int32),
    }
    jax.block_until_ready(out)
    return out


def timed_train_steps(step_fn, state, batch, steps: int,
                      loss_key: str = "train_loss", warmup: int = 2):
    """(state, seconds) for ``steps`` chained calls after ``warmup``.

    Ends the window with a scalar fetch that depends on the final step —
    the only sync that holds through remote device tunnels.
    """
    for _ in range(warmup):
        state, metrics = step_fn(state, batch)
    if warmup:
        float(metrics[loss_key])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    float(metrics[loss_key])
    return state, time.perf_counter() - t0

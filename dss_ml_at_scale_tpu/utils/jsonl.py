"""One locked append-and-flush JSONL writer, shared by every tee file.

The span log's tee, the serving access log — any "one JSON object per
line, flushed as it happens, closed once at exit" stream — share the
same mechanics: parent dir created, append handle, per-line
serialize+write+flush under a lock, idempotent close hooked to
``atexit`` (the interpreter never runs ``__del__`` reliably for
module-lifetime objects, and an unclosed append handle can lose its
last buffered lines). Keeping one implementation means a policy fix
(flush discipline, atexit bookkeeping) reaches every stream.

This is operational evidence, NOT durable state: a crash loses at most
the in-flight line. Crash-durable appends (the run journal, the flight
recorder) go through ``resilience.durability.append_jsonl`` instead.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from pathlib import Path


class JsonlWriter:
    """Append one JSON object per line to ``path``, flushed per line."""

    # Lint contract (dsst lint, lock-discipline rule): writers run on
    # arbitrary threads (span log: every instrumented thread family;
    # access log: every HTTP handler thread).
    _guarded_by_lock = ("_file",)

    def __init__(self, path: str | os.PathLike):
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._file = open(path, "a", encoding="utf-8")
        atexit.register(self.close)

    def write(self, row: dict) -> None:
        # Serialize outside the lock — only the file touch is guarded,
        # so a slow disk never blocks the serialization of other rows.
        line = json.dumps(row) + "\n"
        with self._lock:
            if self._file is not None:
                self._file.write(line)
                self._file.flush()

    def close(self) -> None:
        """Idempotent; also unhooks the atexit registration so a closed
        writer doesn't stay pinned for the process lifetime."""
        with self._lock:
            if self._file is None:
                return
            self._file.close()
            self._file = None
        atexit.unregister(self.close)

"""The serving scheduler: admission → decode pool → batcher → scorer.

Converts serving from per-request synchronous scoring (every HTTP
thread racing to run the one compiled executable, a 1-image request
padding a whole micro-batch alone) to scheduler-mediated: requests are
admitted under a bound, their images decoded by a worker pool, and a
single batcher thread coalesces images *across requests* into the fixed
compiled micro-batch shape before scoring once.

What the client sees at each gate:

====================  ======================================  =====
gate                  condition                               HTTP
====================  ======================================  =====
admission             pending images would exceed the depth   429 + Retry-After
deadline              not scored before ``deadline_ms``       503 (work dropped, never scored late)
lifecycle             draining or stopped                     503
decode                broken JPEG / bad base64 payload        400 (raised type preserved)
scorer                XLA runtime fault                       500
====================  ======================================  =====

Telemetry (all on the process registry, so ``GET /metrics`` sees them):
``serving_queue_depth`` gauge, ``serving_time_in_queue_seconds`` and
``serving_batch_fill`` histograms, ``serving_admission_rejected_total``
/ ``serving_deadline_expired_total`` / ``serving_batches_total``
counters.

The predictor contract is duck-typed: a full
:class:`~dss_ml_at_scale_tpu.workloads.serving.Predictor` exposes
``decode(jpegs) -> array`` and ``score(images) -> rows`` (the split
pipeline); anything exposing only ``predict(payloads) -> rows`` (test
stubs, foreign models) still works — decode becomes a passthrough and
batches score through ``predict``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import queue
import threading
import time

from .. import telemetry
from ..telemetry import tracecontext
from .admission import (
    AdmissionController,
    DeadlineExceeded,
    NotAccepting,
    Request,
    WorkItem,
)
from .batcher import Batcher, DecodePool
from .lifecycle import Lifecycle

# Linear-ish fill buckets: micro-batches are small integers; the
# default log-seconds buckets would waste every edge below 1.
FILL_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0,
                48.0, 64.0, 128.0, 256.0)


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Knobs `dsst serve` exposes; defaults favor low added latency.

    ``queue_depth`` is counted in *images* (the unit of scorer work),
    not requests — one 64-image request costs what 64 singles cost.
    ``deadline_ms`` 0 disables deadlines (embedding/test default; the
    CLI defaults it on). ``batch_window_ms`` is the tradeoff dial: the
    most latency an under-filled batch waits for company.
    """

    queue_depth: int = 64
    batch_window_ms: float = 5.0
    deadline_ms: float = 0.0
    drain_timeout_s: float = 10.0
    decode_workers: int = 2


class ServingScheduler:
    """Cross-request dynamic batching between HTTP and the scorer."""

    def __init__(self, predictor, config: SchedulerConfig | None = None, *,
                 lifecycle: Lifecycle | None = None):
        self.predictor = predictor
        self.config = config or SchedulerConfig()
        self.lifecycle = lifecycle or Lifecycle()
        self.micro_batch = int(getattr(predictor, "micro_batch", 8))

        self._queue_gauge = telemetry.gauge(
            "serving_queue_depth",
            "images admitted and not yet scored (or dropped)",
        )
        self._time_in_queue = telemetry.histogram(
            "serving_time_in_queue_seconds",
            "admission to batch-assembly wait per image",
        )
        self._batch_fill = telemetry.histogram(
            "serving_batch_fill",
            "images per scored batch (micro_batch is a full ride)",
            buckets=FILL_BUCKETS,
        )
        self._rejected = telemetry.counter(
            "serving_admission_rejected_total",
            "requests refused 429 at the admission gate",
        )
        self._expired = telemetry.counter(
            "serving_deadline_expired_total",
            "requests 503'd past their deadline instead of scored late",
        )
        self._batches = telemetry.counter(
            "serving_batches_total", "scored micro-batches"
        )
        # The admission controller's internal model, exported: the
        # service-rate EWMA and the queue-wait estimate used to be
        # private state only a 429's Retry-After ever revealed; the
        # self-tuning controller (ROADMAP item 5) and `dsst top` need
        # them as live gauges.
        self._svc_rate_gauge = telemetry.gauge(
            "admission_service_rate_ewma",
            "admission controller's EWMA of scorer seconds per image",
        )
        self._queue_wait_gauge = telemetry.gauge(
            "admission_est_queue_wait_ms",
            "estimated queue wait for a newly admitted image "
            "(pending x service-rate EWMA)",
        )

        self._admission = AdmissionController(
            self.config.queue_depth, on_depth=self._queue_gauge.set
        )
        if self.config.deadline_ms > 0:
            # Arm the latency objective with the real budget: the SLO
            # plane judges requests against the deadline clients see.
            from ..telemetry import slo as slo_mod

            slo_mod.get_engine().set_latency_budget(
                self.config.deadline_ms / 1000.0
            )
        self._decode_q: queue.Queue = queue.Queue()
        self._batch_q: queue.Queue = queue.Queue()
        self._stop = threading.Event()

        if hasattr(predictor, "decode") and hasattr(predictor, "score"):
            import numpy as np

            # Decode jobs are per REQUEST, so a multi-image request
            # keeps the transform spec's vectorized decode (one call
            # over N images, not N calls of 1); batching stays per
            # IMAGE downstream.
            self._decode_many = predictor.decode
            self._score_items = lambda items: predictor.score(
                np.stack([it.image for it in items])
            )
        else:
            # predict()-only predictors: payloads pass through decode
            # untouched and score as one coalesced predict() call.
            self._decode_many = lambda payloads: payloads
            self._score_items = lambda items: predictor.predict(
                [it.image for it in items]
            )

        self._pool = DecodePool(
            decode=self._decode_many,
            in_q=self._decode_q,
            out_q=self._batch_q,
            on_skip=self._skip_item,
            on_error=self._fail_job,
            stop=self._stop,
            workers=self.config.decode_workers,
            trace=self._decode_trace,
        )
        self._batcher = Batcher(
            in_q=self._batch_q,
            micro_batch=self.micro_batch,
            window_s=self.config.batch_window_ms / 1000.0,
            run_batch=self._run_batch,
            on_skip=self._skip_item,
            stop=self._stop,
        )
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServingScheduler":
        if not self._started:
            self._started = True
            self._pool.start()
            self._batcher.start()
        return self

    @property
    def pending(self) -> int:
        return self._admission.pending

    def drain(self, timeout_s: float | None = None) -> None:
        """Finish admitted work (bounded), then stop the worker threads.

        Callers flip the lifecycle to DRAINING first so admission stops
        feeding the queues and the wait below converges.
        """
        if timeout_s is None:
            timeout_s = self.config.drain_timeout_s
        end = time.monotonic() + timeout_s
        while self._admission.pending > 0 and time.monotonic() < end:
            time.sleep(0.02)
        self.stop()

    def stop(self) -> None:
        """Hard stop: workers exit, anything still queued fails cleanly."""
        self._stop.set()
        self._pool.join()
        self._batcher.join()
        for q in (self._decode_q, self._batch_q):
            while True:
                try:
                    entry = q.get_nowait()
                except queue.Empty:
                    break
                # decode queue holds per-request jobs (lists); batch
                # queue holds single items.
                items = entry if isinstance(entry, list) else [entry]
                for item in items:
                    item.request.fail(NotAccepting("serving stopped"))
                    self._retire(item)
        self.lifecycle.mark_stopped()

    # -- the client-facing call -------------------------------------------

    def submit(self, payloads: list, info: dict | None = None) -> list:
        """Score ``payloads`` through the shared batch pipeline.

        Blocks the calling (HTTP handler) thread until its request
        settles; raises the scheduler refusal or the pipeline's own
        error, exactly as the synchronous path would have.

        ``info`` (optional dict) is populated with per-request
        accounting on the way out — ``queue_ms`` (admission to
        settlement) and ``batch_fill`` (size of the micro-batch the
        request scored in) — the structured-access-log side channel.
        """
        if not payloads:
            raise ValueError("empty batch")
        if len(payloads) > self.config.queue_depth:
            # Admission is all-or-nothing, so a request wider than the
            # whole queue could NEVER be admitted — a 429 here would
            # send a well-behaved client into a forever-retry loop.
            # ValueError is the client's permanent 400.
            raise ValueError(
                f"request of {len(payloads)} images exceeds the "
                f"admission queue depth {self.config.queue_depth}; "
                "send smaller batches"
            )
        if not self.lifecycle.accepting:
            raise NotAccepting(
                f"not accepting requests (state={self.lifecycle.state})"
            )
        try:
            self._admission.admit(len(payloads))
        except Exception:
            self._rejected.inc()
            raise
        cfg = self.config
        deadline = (
            time.monotonic() + cfg.deadline_ms / 1000.0
            if cfg.deadline_ms > 0 else None
        )
        req = Request(len(payloads), deadline)
        # The submitting thread's trace rides the request: the decode
        # pool and batcher adopt it around their spans, so one
        # request_id follows admission → decode → score across threads.
        req.trace = tracecontext.Handoff.capture()
        # One decode job per request (vectorized decode); the pool
        # fans the decoded items out per image for the batcher.
        self._decode_q.put(
            [WorkItem(req, i, payload) for i, payload in enumerate(payloads)]
        )

        while not req.settled:
            timeout = 0.1  # cap only bounds stop-detection; done wakes now
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    self._expire(req)
                    break
                timeout = min(timeout, left)
            if req.wait(timeout):
                break
            if self._stop.is_set():
                req.fail(NotAccepting("serving stopped"))
                break
        if info is not None:
            info["queue_ms"] = round(
                (time.monotonic() - req.t_admit) * 1000.0, 3
            )
            info["batch_fill"] = req.batch_fill
        # One locked snapshot instead of direct error/results reads: the
        # deadline/stop exits reach here while a worker may still be
        # settling the request (found by `dsst sanitize`, guarded-by).
        error, results = req.outcome()
        if error is not None:
            raise error
        return results

    # -- worker callbacks --------------------------------------------------

    @contextlib.contextmanager
    def _decode_trace(self, job: list):
        """Decode-pool hook: the decode runs under the owning request's
        trace, as a ``serve.decode`` span on the worker thread."""
        handoff = job[0].request.trace or tracecontext.Handoff(None)
        with handoff.activate(), telemetry.span(
            "serve.decode", images=len(job)
        ):
            yield

    def _expire(self, req: Request) -> None:
        if req.fail(DeadlineExceeded(
            f"deadline of {self.config.deadline_ms:g} ms passed before "
            "scoring"
        )):
            self._expired.inc()

    def _retire(self, item: WorkItem) -> None:
        if item.retire():
            self._admission.release(1)

    def _skip_item(self, item: WorkItem) -> None:
        req = item.request
        if not req.settled and req.expired():
            self._expire(req)
        self._retire(item)

    def _fail_job(self, items: list, exc: Exception) -> None:
        items[0].request.fail(exc)
        for item in items:
            self._retire(item)

    # dsst: hotpath — the serving score path: every admitted image crosses here
    def _run_batch(self, items: list) -> None:
        now = time.monotonic()
        for item in items:
            self._time_in_queue.observe(now - item.request.t_admit)
        t0_wall = time.time()
        t0 = time.perf_counter()
        try:
            rows = self._score_items(items)
        except Exception as exc:
            # A scorer fault fails the batch's requests (their handlers
            # answer 500) but never the scheduler: the next batch runs.
            for item in items:
                item.request.fail(exc)
                self._retire(item)
            return
        score_dur = time.perf_counter() - t0
        self._admission.note_service_rate(score_dur / len(items))
        # Sampled exactly where the EWMA is fed: the gauges track the
        # controller's model batch-for-batch, no separate poller.
        self._svc_rate_gauge.set(self._admission.service_rate_ewma)
        self._queue_wait_gauge.set(
            self._admission.est_queue_wait_s * 1000.0
        )
        self._batch_fill.observe(len(items))
        self._batches.inc()
        # One coalesced batch serves many requests; each traced request
        # gets its OWN serve.score span (same wall window, its trace id)
        # on this batcher thread — the third thread hop of the request's
        # flow chain. Recorded BEFORE completion so the handler thread
        # observes batch_fill after settlement.
        by_request: dict[int, tuple] = {}
        for item in items:
            by_request.setdefault(id(item.request), (item.request, []))[
                1
            ].append(item)
        span_log = telemetry.get_span_log()
        for req, req_items in by_request.values():
            req.batch_fill = len(items)
            handoff = req.trace
            if handoff is not None and handoff.ctx is not None:
                # dsst: ignore[span-discipline] one shared scoring window fans out into N per-request records; a with-span per request would nest N overlapping scopes on this thread
                span_log.record(
                    "serve.score", t0_wall, score_dur,
                    trace=handoff.ctx,
                    images=len(req_items), batch_fill=len(items),
                )
        for item, row in zip(items, rows):
            item.request.complete_item(item.index, row)
            self._retire(item)

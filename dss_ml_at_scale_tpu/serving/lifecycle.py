"""Serving lifecycle: readiness state machine and the server handle.

Splits the two questions load balancers ask into two answers:

- **liveness** (``GET /healthz``): is the process up? — 200 from start
  to final close, *including* while draining (a draining server is
  healthy; restarting it would kill the very work the drain protects).
- **readiness** (``GET /readyz``): should new traffic come here? — 200
  only in the READY state; 503 while STARTING (scorer still warming),
  DRAINING, or STOPPED, so an orchestrator pulls the instance from
  rotation *before* requests start bouncing off admission.

:class:`ServerHandle` is the embedding/ops face of graceful shutdown:
``close()`` walks READY → DRAINING (stop admitting, readiness flips)
→ finish queued work (bounded by the drain timeout) → stop the HTTP
loop → close the socket. In-flight responses finish writing — the
server never kills a request mid-body.
"""

from __future__ import annotations

import threading

from .. import telemetry

STARTING = "starting"
READY = "ready"
DRAINING = "draining"
STOPPED = "stopped"


class Lifecycle:
    """Thread-safe STARTING → READY → DRAINING → STOPPED progression."""

    # Lint contract: state transitions race between the serve thread,
    # handler threads, and the SIGTERM/drain path — _state only under
    # _lock.
    _guarded_by_lock = ("_state",)

    def __init__(self):
        self._lock = threading.Lock()
        self._state = STARTING
        # 1 exactly when /readyz answers 200 — scrapeable readiness, so
        # dashboards see the drain the instant it starts.
        self._ready_gauge = telemetry.gauge(
            "serving_ready", "1 when accepting requests (the /readyz state)"
        )
        self._ready_gauge.set(0)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def accepting(self) -> bool:
        return self.state == READY

    def mark_ready(self) -> None:
        with self._lock:
            if self._state != STARTING:
                return  # never un-drain: READY is reachable only once
            self._state = READY
        self._ready_gauge.set(1)

    def start_drain(self) -> None:
        with self._lock:
            if self._state in (DRAINING, STOPPED):
                return
            self._state = DRAINING
        self._ready_gauge.set(0)

    def mark_stopped(self) -> None:
        with self._lock:
            self._state = STOPPED
        self._ready_gauge.set(0)


class ServerHandle:
    """Owns a running server's clean end-of-life.

    ``serve_in_thread`` returns one of these instead of a bare
    ``(server, thread)`` pair: the old shape leaked the server socket
    and killed in-flight requests mid-write, because nothing tied
    "stop the accept loop" to "finish the queued work first".
    ``close()`` is idempotent and safe from any thread.
    """

    # Lint contract (dsst lint, lock-discipline rule; enforced at
    # runtime by dsst sanitize): close() races between the serve
    # thread, Ctrl-C handlers, and embedding teardown — the
    # exactly-once latch only under _lock.
    _guarded_by_lock = ("_closed",)

    def __init__(self, server, thread, *, drain_timeout_s: float | None = None):
        self.server = server
        self.thread = thread
        self._drain_timeout_s = drain_timeout_s
        self._lock = threading.Lock()
        self._closed = False

    @property
    def scheduler(self):
        return self.server.scheduler

    @property
    def lifecycle(self) -> Lifecycle:
        return self.server.lifecycle

    @property
    def port(self) -> int:
        return self.server.server_address[1]

    @property
    def address(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}"

    def close(self, drain_timeout_s: float | None = None) -> None:
        """Graceful: drain admitted work, then stop accepting, then close.

        Order matters: admission closes first (new /predict → 503, so
        the drain converges), queued work finishes (bounded by the
        drain timeout; leftovers are failed, not abandoned), and only
        then does the accept loop stop and the socket close. Every
        admitted request has settled by the time the loop stops, so
        handler threads are just flushing already-computed responses.
        """
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if drain_timeout_s is None:
            drain_timeout_s = self._drain_timeout_s
        self.lifecycle.start_drain()
        self.scheduler.drain(drain_timeout_s)
        self.server.shutdown()
        self.thread.join(timeout=5.0)
        self.server.server_close()

    def __enter__(self) -> "ServerHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

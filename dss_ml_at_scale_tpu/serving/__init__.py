"""Serving scheduler: cross-request batching, admission, lifecycle.

The subsystem between the HTTP layer (:mod:`..workloads.serving`) and
the compiled scorer. Pipeline per admitted image::

    HTTP thread          decode pool          batcher (1 thread)
    -----------          -----------          ------------------
    admit (429 if full)  JPEG -> array        coalesce ACROSS requests
    enqueue + block      off the scorer       to the compiled micro-batch
    ... wait ...         thread               (full OR window elapsed)
    respond <-------------------- results <-- score once, fan out rows

:class:`ServingScheduler` is the facade; :class:`SchedulerConfig` the
knobs (`dsst serve` flags map 1:1); :class:`Lifecycle` +
:class:`ServerHandle` the readiness/drain story; the exceptions the
HTTP status contract (QueueFull → 429, DeadlineExceeded/NotAccepting →
503).
"""

from __future__ import annotations

from .admission import (
    AdmissionController,
    DeadlineExceeded,
    NotAccepting,
    QueueFull,
    Request,
    SchedulerError,
    WorkItem,
)
from .batcher import Batcher, DecodePool
from .lifecycle import DRAINING, READY, STARTING, STOPPED, Lifecycle, ServerHandle
from .scheduler import SchedulerConfig, ServingScheduler

__all__ = [
    "AdmissionController",
    "Batcher",
    "DRAINING",
    "DeadlineExceeded",
    "DecodePool",
    "Lifecycle",
    "NotAccepting",
    "QueueFull",
    "READY",
    "Request",
    "STARTING",
    "STOPPED",
    "SchedulerConfig",
    "SchedulerError",
    "ServerHandle",
    "ServingScheduler",
    "WorkItem",
]

"""Continuous-batching decode engine: token serving over slot arenas.

Generalizes the image-serving scheduler (one compiled shape, batch
ACROSS requests) to an always-running decode loop: requests are
admitted INTO an in-flight batch. One engine thread alternates

    admit waiting requests into free slots
        (bucket-padded prefill, compiled once per bucket;
         aliased scatter into the slot arena; first token = TTFT)
    one ``slot_decode`` step over ALL slots
        (every active request advances one token per step)
    per-slot retirement
        (EOS / max-token / deadline / cancel — the slot frees and the
         batch keeps running; nothing stops, nothing recompiles)

The HTTP layer talks to the engine through :meth:`LMEngine.submit`,
which returns a :class:`Generation` whose event queue streams tokens
to the response writer. Admission, deadline, and drain semantics are
the image tier's, reused verbatim: a full queue raises
:class:`~..admission.QueueFull` (429 + Retry-After), a draining engine
raises :class:`~..admission.NotAccepting` (503), and drain = stop
admitting, finish every in-flight slot.

Two decoder backends satisfy the same five-method protocol
(``prefill``/``step``/``warmup`` + ``slots``/``vocab_size``):
:class:`TransformerDecoder` runs the real audited programs;
:class:`StubLMDecoder` is the bench/CI stand-in whose per-STEP cost is
independent of how many slots are active — exactly the property that
makes continuous batching win, minus the model weights.
"""

from __future__ import annotations

import dataclasses
import math
import queue
import threading
import time

import numpy as np

from ... import telemetry
from ..admission import AdmissionController, DeadlineExceeded, NotAccepting
from . import kvcache


class PromptTooLong(ValueError):
    """Request exceeds the preallocated KV capacity (HTTP 400).

    The guard the tentpole issue demands: an oversized budget must be
    REJECTED before a slot is touched — never allowed to scatter past
    the arena (the same cap ``models.transformer.generate`` now derives
    from its cache shape).
    """


@dataclasses.dataclass(frozen=True)
class LMConfig:
    """Engine knobs — ``dsst serve-lm`` flags map 1:1."""

    slots: int = 8
    max_len: int = 128
    prefill_buckets: tuple = (16, 32, 64)
    queue_depth: int = 32
    deadline_ms: float = 0.0  # admit -> last token; 0 disables
    inter_token_budget_ms: float = 0.0  # arms inter_token_p99 when > 0
    drain_timeout_s: float = 10.0

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        buckets = tuple(sorted(set(int(b) for b in self.prefill_buckets)))
        if not buckets:
            raise ValueError("at least one prefill bucket is required")
        if buckets[0] < 1 or buckets[-1] > self.max_len:
            raise ValueError(
                f"prefill buckets {buckets} must lie in [1, max_len="
                f"{self.max_len}]"
            )
        object.__setattr__(self, "prefill_buckets", buckets)


class Generation:
    """One streamed request: engine-side state + client-side queue.

    The engine thread owns the decode state (``n_past``, ``last_token``,
    ``emitted``); the HTTP thread only reads the event queue and may set
    ``cancelled`` (a latch, safe without the engine lock). Events are
    ``("token", token, index)`` then exactly one terminal
    ``("done", reason)`` or ``("error", exc)`` — :meth:`settle_once` is
    the latch that keeps the terminal exactly-once even when engine
    retirement and drain's leftovers sweep race to settle the same
    generation.
    """

    _guarded_by_lock = ("_settled",)
    _lock_name = "_lock"

    def __init__(self, gen_id, prompt, max_new_tokens, *, temperature,
                 top_k, eos_id, seed, trace_id, deadline):
        self.gen_id = gen_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.trace_id = trace_id
        self.deadline = deadline  # monotonic, or None
        self.queue: queue.Queue = queue.Queue()
        self.cancelled = False
        self.reason: str | None = None
        self._lock = threading.Lock()
        self._settled = False
        self.t_admit = time.monotonic()
        self.t_first: float | None = None
        self.t_last: float | None = None
        # Engine-thread-only decode state.
        self.n_past = 0
        self.last_token = 0
        self.emitted = 0
        self._rng = np.random.default_rng(seed)

    def sample(self, logits_row: np.ndarray) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(logits_row))
        scaled = logits_row.astype(np.float64) / self.temperature
        if self.top_k is not None:
            kth = np.sort(scaled)[-self.top_k]
            scaled = np.where(scaled < kth, -np.inf, scaled)
        scaled -= scaled.max()
        p = np.exp(scaled)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def settle_once(self) -> bool:
        """Claim the right to emit THE terminal event (first caller
        wins). Engine retirement and drain's leftovers sweep can race
        to settle the same generation; exactly one of them may emit the
        terminal and release the admission ticket."""
        with self._lock:
            if self._settled:
                return False
            self._settled = True
            return True

    def is_settled(self) -> bool:
        with self._lock:
            return self._settled

    def next_event(self, timeout: float | None = None):
        """Block for the next stream event (raises ``queue.Empty``)."""
        return self.queue.get(timeout=timeout)

    def cancel(self) -> None:
        """Client went away: retire the slot at the next step."""
        self.cancelled = True


class TransformerDecoder:
    """The real backend: audited slot-decode/prefill/scatter programs.

    One compiled ``slot_decode`` for the life of the server (the arena
    is donated through every call — aliased, never copied), one
    ``prefill_bucket`` executable per configured bucket length, and a
    donated ``write_slot`` scatter per admission. ``warmup()`` compiles
    all of them before the server reports ready.
    """

    def __init__(self, model, variables, *, slots, max_len, buckets):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self.model = model
        self.variables = variables
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.buckets = tuple(buckets)
        self.vocab_size = model.vocab_size
        self._arena = kvcache.make_arena(model, self.slots, self.max_len)
        # ONE prefill scratch cache, recycled: the returned (donated-in)
        # buffers become the next call's input. Stale rows past the real
        # prompt are never attended and are overwritten before the
        # position pointer reaches them, so no re-zeroing is needed.
        self._scratch = kvcache.make_arena(model, 1, self.max_len)
        self._step_fn = jax.jit(
            kvcache.slot_decode, static_argnums=0, donate_argnums=(3,)
        )
        self._prefill_fn = jax.jit(
            kvcache.prefill_bucket, static_argnums=0, donate_argnums=(3,)
        )
        self._write_fn = jax.jit(kvcache.write_slot, donate_argnums=(0,))

    def warmup(self) -> None:
        """Compile every production shape before serving traffic."""
        for bucket in self.buckets:
            self.prefill(np.zeros((1, bucket), np.int32), 1, 0)
        self.step(
            np.zeros(self.slots, np.int32), np.zeros(self.slots, np.int32)
        )

    def prefill(self, tokens: np.ndarray, n_real: int, slot: int):
        """Prefill one bucket-padded prompt and scatter it into ``slot``.

        Returns the logits row of the last REAL prompt position (host
        numpy) — what the first sampled token comes from.
        """
        jnp = self._jnp
        logits, cache = self._prefill_fn(
            self.model, self.variables,
            jnp.asarray(tokens, jnp.int32), self._scratch,
        )
        self._arena = self._write_fn(self._arena, cache, jnp.int32(slot))
        self._scratch = cache
        row = logits[0] if logits.ndim == 2 else logits[0, n_real - 1]
        return np.asarray(row, np.float32)

    def step(self, tokens: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """One ``slot_decode`` over every slot; returns [slots, vocab]."""
        jnp = self._jnp
        logits, self._arena = self._step_fn(
            self.model, self.variables,
            jnp.asarray(tokens, jnp.int32), self._arena,
            jnp.asarray(pos, jnp.int32),
        )
        return np.asarray(logits, np.float32)


class StubLMDecoder:
    """Model-free backend for bench/CI: fixed per-STEP cost.

    The next token is a pure function of (last token, position), so
    streams are deterministic; ``step()`` sleeps ``step_ms`` ONCE no
    matter how many slots are active — the continuous-batching speedup
    the ``lm_serving`` bench gates is therefore structural, not noise.
    Logits are one-hot so greedy sampling recovers the function exactly.
    """

    def __init__(self, *, vocab_size=256, step_ms=2.0, prefill_ms=None,
                 slots=8, max_len=128, buckets=(16,)):
        self.vocab_size = int(vocab_size)
        self.step_ms = float(step_ms)
        self.prefill_ms = float(
            step_ms if prefill_ms is None else prefill_ms
        )
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.buckets = tuple(buckets)

    def _next(self, tok: int, pos: int) -> int:
        return (int(tok) * 1103515245 + int(pos) * 12345 + 7) % self.vocab_size

    def warmup(self) -> None:
        pass

    def prefill(self, tokens: np.ndarray, n_real: int, slot: int):
        time.sleep(self.prefill_ms / 1000.0)
        row = np.zeros(self.vocab_size, np.float32)
        row[self._next(tokens[0, n_real - 1], n_real - 1)] = 1.0
        return row

    def step(self, tokens: np.ndarray, pos: np.ndarray) -> np.ndarray:
        time.sleep(self.step_ms / 1000.0)
        out = np.zeros((self.slots, self.vocab_size), np.float32)
        for i in range(self.slots):
            out[i, self._next(tokens[i], pos[i])] = 1.0
        return out


class LMEngine:
    """The always-running decode loop + admission front door."""

    # Lint/sanitize contract: HTTP threads submit and drain; the engine
    # thread admits, steps, and retires — the shared scheduling state
    # below only moves under _cond.
    _guarded_by_lock = ("_waiting", "_active", "_admitting", "_accepting",
                        "_stopped")
    _lock_name = "_cond"

    def __init__(self, decoder, config: LMConfig | None = None):
        self.cfg = config or LMConfig()
        self.decoder = decoder
        if getattr(decoder, "max_len", self.cfg.max_len) < self.cfg.max_len:
            raise ValueError(
                f"decoder max_len {decoder.max_len} < config max_len "
                f"{self.cfg.max_len}"
            )
        if decoder.slots < self.cfg.slots:
            raise ValueError(
                f"decoder has {decoder.slots} slots, config wants "
                f"{self.cfg.slots}"
            )
        self._alloc = kvcache.SlotAllocator(self.cfg.slots)
        self._cond = threading.Condition()
        self._waiting: list[Generation] = []
        self._active: dict[int, Generation] = {}
        # Generations pulled off _waiting but not yet in _active (their
        # prefill is running): drain must see this in-transit window or
        # it can declare the engine empty mid-admission and truncate a
        # stream it promised to finish — and its leftovers sweep must
        # settle them if the engine thread wedges, so the actual
        # Generations are tracked, not just a count.
        self._admitting: list[Generation] = []
        self._accepting = True
        self._stopped = False
        self._gen_seq = 0
        self._thread: threading.Thread | None = None
        self._slo = telemetry.slo.get_engine()
        self._admission = AdmissionController(
            self.cfg.queue_depth,
            on_depth=lambda n: self._depth_gauge.set(n),
        )
        self._depth_gauge = telemetry.gauge(
            "lm_queue_depth", "LM generations admitted and not yet retired"
        )
        self._tokens_total = telemetry.counter(
            "lm_tokens_total", "tokens streamed by the LM engine"
        )
        self._slots_gauge = telemetry.gauge(
            "lm_slots_active", "KV arena slots currently decoding"
        )
        self._retired = telemetry.counter(
            "lm_retired_total",
            "generations retired, by reason",
            labels=("reason",),
        )
        self._prefill_hist = telemetry.histogram(
            "lm_prefill_seconds", "bucketed prefill latency (per admission)"
        )
        self._step_hist = telemetry.histogram(
            "lm_decode_step_seconds", "slot_decode latency (per step)"
        )
        self._ttft_window = telemetry.window(
            "lm_ttft_window_seconds",
            "live windowed time-to-first-token (admit -> first chunk)",
        )
        self._inter_window = telemetry.window(
            "lm_inter_token_window_seconds",
            "live windowed gap between streamed tokens",
        )

    # -- front door (HTTP threads) ------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, temperature=0.0,
               top_k=None, eos_id=None, seed=0, trace_id=None) -> Generation:
        """Admit one generation (or raise the HTTP-mapped refusal).

        Raises :class:`PromptTooLong` (400) when the request cannot fit
        the preallocated capacity, ``ValueError`` (400) for sampling
        params the engine thread could not survive (non-finite
        temperature, out-of-range top_k — json accepts NaN, so the door
        must not), ``QueueFull`` (429) at the admission bound,
        ``NotAccepting`` (503) while draining.
        """
        prompt = [int(t) for t in prompt]
        n_new = int(max_new_tokens)
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if n_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        vocab = self.decoder.vocab_size
        if any(t < 0 or t >= vocab for t in prompt):
            raise ValueError(f"prompt tokens must lie in [0, {vocab})")
        # Sampling-state validation: everything Generation.sample and
        # default_rng consume is checked HERE, before the admission
        # ticket — a bad value past this point would blow up inside the
        # shared engine thread (or leak a ticket), not in this request.
        temperature = float(temperature)
        if not math.isfinite(temperature):
            raise ValueError(f"temperature must be finite, got {temperature}")
        if top_k is not None:
            top_k = int(top_k)
            if not 1 <= top_k <= vocab:
                raise ValueError(
                    f"top_k must lie in [1, vocab_size={vocab}], "
                    f"got {top_k}"
                )
        seed = int(seed)
        if seed < 0:
            raise ValueError(f"seed must be >= 0, got {seed}")
        buckets = self.cfg.prefill_buckets
        if len(prompt) > buckets[-1]:
            raise PromptTooLong(
                f"prompt length {len(prompt)} exceeds the largest prefill "
                f"bucket {buckets[-1]}"
            )
        if len(prompt) + n_new > self.cfg.max_len:
            raise PromptTooLong(
                f"prompt + max_new_tokens = {len(prompt) + n_new} > "
                f"max_len {self.cfg.max_len} (preallocated KV slot capacity)"
            )
        deadline = None
        if self.cfg.deadline_ms > 0:
            deadline = time.monotonic() + self.cfg.deadline_ms / 1000.0
        with self._cond:
            if not self._accepting:
                raise NotAccepting("LM engine is draining")
            self._admission.admit(1)
            self._gen_seq += 1
            gen = Generation(
                self._gen_seq, prompt, n_new, temperature=temperature,
                top_k=top_k, eos_id=eos_id, seed=seed, trace_id=trace_id,
                deadline=deadline,
            )
            self._waiting.append(gen)
            self._cond.notify_all()
        return gen

    @property
    def pending(self) -> int:
        """Generations admitted and not yet retired (for drain prints)."""
        return self._admission.pending

    def start(self) -> "LMEngine":
        """Arm SLO targets, warm the decoder, start the decode thread."""
        if self.cfg.deadline_ms > 0:
            # TTFT must beat the full-request deadline; arming turns the
            # informational quantile objective into a judged one.
            self._slo.set_target("ttft_p99", self.cfg.deadline_ms / 1000.0)
        if self.cfg.inter_token_budget_ms > 0:
            self._slo.set_target(
                "inter_token_p99", self.cfg.inter_token_budget_ms / 1000.0
            )
        self.decoder.warmup()
        self._thread = threading.Thread(
            target=self._loop, name="lm-decode", daemon=True
        )
        self._thread.start()
        return self

    def drain(self, timeout_s: float | None = None) -> bool:
        """Stop admitting, finish in-flight slots, stop the loop.

        Returns True when everything retired within the budget; on
        timeout the loop is stopped anyway and survivors are settled
        with a ``("done", "drain")`` event so no client hangs forever.
        """
        budget = self.cfg.drain_timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + max(0.0, budget)
        with self._cond:
            self._accepting = False
            self._cond.notify_all()
            while (
                (self._waiting or self._active or self._admitting)
                and not self._stopped
            ):
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(min(left, 0.1))
            clean = (
                not self._waiting and not self._active
                and not self._admitting
            )
            self._stopped = True
            self._cond.notify_all()
        thread = self._thread
        alive = False
        if thread is not None:
            thread.join(5.0)
            alive = thread.is_alive()
        # Settle anything the budget abandoned — including generations
        # caught in the in-transit admission window (neither waiting
        # nor active while their prefill runs). The join may have timed
        # out with the thread wedged inside a slow decoder call; the
        # settle-once latch makes this sweep safe to race against a
        # thread that later comes back and retires the same slots.
        with self._cond:
            leftovers = (
                list(self._waiting) + list(self._active.values())
                + list(self._admitting)
            )
            self._waiting.clear()
            self._active.clear()
            self._admitting.clear()
        for gen in leftovers:
            self._settle(gen, "drain")
        return clean and not alive

    # -- engine thread ------------------------------------------------

    def _loop(self) -> None:
        try:
            self._run()
        except Exception as exc:
            # Nothing may escape the engine thread: an unguarded raise
            # here used to kill the loop silently — every in-flight
            # stream stalled and every later request hung until its
            # event timeout. Fail CLOSED instead: refuse new work (503)
            # and settle every owned generation with an error event.
            self._halt(exc)

    def _halt(self, exc: Exception) -> None:
        with self._cond:
            self._accepting = False
            self._stopped = True
            leftovers = (
                list(self._waiting) + list(self._active.values())
                + list(self._admitting)
            )
            self._waiting.clear()
            self._active.clear()
            self._admitting.clear()
            self._cond.notify_all()
        for gen in leftovers:
            self._settle(gen, "error", error=exc)

    def _run(self) -> None:
        while True:
            admitted, expired, cancelled = [], [], []
            with self._cond:
                while (
                    not self._stopped
                    and not self._waiting
                    and not self._active
                ):
                    self._cond.wait(0.05)
                if self._stopped:
                    return
                now = time.monotonic()
                still_waiting = []
                for gen in self._waiting:
                    if gen.cancelled:
                        cancelled.append(gen)
                        continue
                    if gen.deadline is not None and now > gen.deadline:
                        expired.append(gen)
                        continue
                    slot = self._alloc.alloc()
                    if slot is None:
                        still_waiting.append(gen)
                    else:
                        admitted.append((gen, slot))
                self._waiting[:] = still_waiting
                self._admitting.extend(gen for gen, _ in admitted)
            for gen in cancelled:
                self._settle(gen, "cancelled")
            for gen in expired:
                self._settle(
                    gen, "deadline",
                    error=DeadlineExceeded(
                        "deadline passed before a slot freed"
                    ),
                )
            for gen, slot in admitted:
                try:
                    self._admit_into_slot(gen, slot)
                except Exception as exc:
                    # A poisoned generation (sampling state the door's
                    # validation could not foresee) retires ITSELF, not
                    # the shared loop: free its slot, settle it with an
                    # error event, keep serving everyone else.
                    with self._cond:
                        self._active.pop(slot, None)
                        self._slots_gauge.set(len(self._active))
                    if not gen.is_settled():
                        self._alloc.free(slot)
                        self._settle(gen, "error", error=exc)
            if admitted:
                with self._cond:
                    for gen, _ in admitted:
                        if gen in self._admitting:
                            self._admitting.remove(gen)
                    self._cond.notify_all()
            self._step_once()

    def _admit_into_slot(self, gen: Generation, slot: int) -> None:
        """Bucketed prefill + scatter + first token (TTFT)."""
        prompt = gen.prompt
        bucket = next(
            b for b in self.cfg.prefill_buckets if b >= len(prompt)
        )
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(prompt)] = prompt
        t0 = time.perf_counter()
        with telemetry.span("lm.prefill", bucket=bucket,
                            prompt_tokens=len(prompt)):
            row = self.decoder.prefill(padded, len(prompt), slot)
        self._prefill_hist.observe(time.perf_counter() - t0)
        gen.n_past = len(prompt)
        token = gen.sample(row)
        now = time.monotonic()
        ttft = now - gen.t_admit
        gen.t_first = gen.t_last = now
        self._emit(gen, token)
        self._ttft_window.observe(ttft, gen.trace_id)
        self._slo.note_ttft(ttft, trace_id=gen.trace_id)
        if self._should_retire(gen, token):
            self._retire_slot(slot, gen)
            return
        with self._cond:
            self._active[slot] = gen
            self._slots_gauge.set(len(self._active))

    def _step_once(self) -> None:
        with self._cond:
            active = dict(self._active)
        if not active:
            return
        # Sized to the DECODER's arena, not cfg.slots: both backends
        # iterate/vmap over decoder.slots, and the constructor allows a
        # decoder with more slots than the config admits.
        tokens = np.zeros(self.decoder.slots, np.int32)
        pos = np.zeros(self.decoder.slots, np.int32)
        for slot, gen in active.items():
            tokens[slot] = gen.last_token
            pos[slot] = gen.n_past
        t0 = time.perf_counter()
        with telemetry.span("lm.step", active=len(active)):
            logits = self.decoder.step(tokens, pos)
        self._step_hist.observe(time.perf_counter() - t0)
        now = time.monotonic()
        for slot in sorted(active):
            gen = active[slot]
            gen.n_past += 1
            if gen.cancelled:
                self._retire_slot(slot, gen, reason="cancelled")
                continue
            if gen.deadline is not None and now > gen.deadline:
                self._retire_slot(slot, gen, reason="deadline")
                continue
            try:
                token = gen.sample(logits[slot])
            except Exception as exc:
                # Per-generation blast radius: a sample() failure
                # retires this slot with an error event; the step loop
                # and every other stream keep running.
                self._retire_slot(slot, gen, reason="error", error=exc)
                continue
            gap = now - (gen.t_last if gen.t_last is not None else now)
            gen.t_last = now
            self._emit(gen, token)
            self._inter_window.observe(gap, gen.trace_id)
            self._slo.note_inter_token(gap, trace_id=gen.trace_id)
            if self._should_retire(gen, token):
                self._retire_slot(slot, gen)

    def _emit(self, gen: Generation, token: int) -> None:
        gen.last_token = token
        if gen.is_settled():
            # Drain's sweep already emitted the terminal event while
            # this thread was wedged: no tokens after a terminal.
            return
        gen.queue.put(("token", token, gen.emitted))
        gen.emitted += 1
        self._tokens_total.inc()

    def _should_retire(self, gen: Generation, token: int) -> bool:
        if gen.eos_id is not None and token == gen.eos_id:
            gen.reason = "eos"
            return True
        if gen.emitted >= gen.max_new_tokens:
            gen.reason = "max_tokens"
            return True
        return False

    def _retire_slot(self, slot: int, gen: Generation,
                     reason: str | None = None,
                     error: Exception | None = None) -> None:
        with self._cond:
            self._active.pop(slot, None)
            self._slots_gauge.set(len(self._active))
            self._cond.notify_all()
        self._alloc.free(slot)
        wall = time.monotonic() - gen.t_admit
        # Seconds-per-generation normalized by slot count: the cost one
        # admission adds to the shared step loop, feeding Retry-After.
        self._admission.note_service_rate(wall / max(1, self.cfg.slots))
        self._settle(gen, reason or gen.reason or "done", error=error)

    def _settle(self, gen: Generation, reason: str,
                error: Exception | None = None) -> None:
        """Terminal event + admission release, exactly once.

        Engine retirement, the drain sweep, and the halt path can race
        to settle the same generation; the per-generation latch makes
        every settlement after the first a no-op, so a client sees ONE
        terminal and the pending count can never go negative.
        """
        if not gen.settle_once():
            return
        if gen.reason is None:
            gen.reason = reason
        if error is not None:
            gen.queue.put(("error", error))
        else:
            gen.queue.put(("done", gen.reason))
        self._retired.labels(reason=gen.reason).inc()
        self._admission.release(1)

"""Preallocated slot-based KV cache for continuous-batching decode.

The image-serving tier batches fixed-shape requests through ONE
compiled program; token serving cannot, because every request is at a
different decode position. The classic answer (and the one the audit
donation rule can certify) is a slot arena: a fixed
``[slots, heads, max_len, head_dim]`` k/v slab per layer, allocated
once at boot, DONATED through every decode step so XLA aliases it
in-place — zero per-token cache copies, no per-request allocation, no
shape churn, one compiled program for the life of the server.

Three compiled programs live here, all registered as audited
entrypoints (donation + collective ceilings + program hashes pinned
like the other production programs):

``slot_decode``
    One token for EVERY slot at once — ``jax.vmap`` of the single-
    sequence cached decode over the slot axis with a per-slot ``pos``
    vector. Inactive slots decode garbage at position 0; the mask
    (``arange(max_len) <= pos``) never lets any slot read another
    slot's rows, and a freshly allocated slot is overwritten wholesale
    by ``write_slot`` before its first real step, so the garbage is
    provably harmless (the bitwise-parity test in
    ``tests/test_lm_serving.py`` holds the proof).

``prefill_bucket``
    The whole prompt through one causal pass into a single-sequence
    cache, compiled once per configured bucket length. The cache
    argument is donated too: the engine keeps ONE prefill scratch
    cache and recycles the returned buffers.

``write_slot``
    Scatters a prefilled single-sequence cache into one arena slot via
    ``dynamic_update_slice`` — donated, so admission costs one aliased
    scatter, not an arena copy.

Slot bookkeeping (:class:`SlotAllocator`) is deliberately host-side
and boring: a lock, a sorted free list, an in-use set.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from ...models.transformer import TransformerLM

Arena = tuple  # tuple per layer of {"k": [slots,h,max_len,d], "v": ...}


def make_arena(model: TransformerLM, slots: int, max_len: int) -> Arena:
    """Allocate the slot arena: one k/v slab per layer.

    ``max_len`` may be smaller than ``model.max_seq`` — the attention
    mask and the cache writes both derive their length from the cache's
    own shape, so a short arena is a working (cheaper) cache.
    """
    if max_len > model.max_seq:
        raise ValueError(
            f"arena max_len {max_len} > model max_seq {model.max_seq}"
        )
    head_dim = model.dim // model.num_heads
    shape = (slots, model.num_heads, max_len, head_dim)
    return tuple(
        {
            "k": jnp.zeros(shape, dtype=model.dtype),
            "v": jnp.zeros(shape, dtype=model.dtype),
        }
        for _ in range(model.num_layers)
    )


def slot_decode(model, variables, tokens, arena, pos):
    """One decode step for every slot: the audited production program.

    ``tokens`` ``[slots] int32`` (each slot's last sampled token),
    ``pos`` ``[slots] int32`` (the cache position that token occupies).
    Returns ``(logits [slots, vocab], new_arena)`` with the arena
    aliased in-place when jitted with ``donate_argnums=(3,)``.
    """

    def one(tok, slot_cache, p):
        cache1 = jax.tree_util.tree_map(lambda a: a[None], slot_cache)
        logits, new_cache = model.apply(
            variables, tok[None, None], cache=cache1, pos=p
        )
        return logits[0], jax.tree_util.tree_map(lambda a: a[0], new_cache)

    return jax.vmap(one, in_axes=(0, 0, 0))(tokens, arena, pos)


def prefill_bucket(model, variables, tokens, cache):
    """Prefill one bucket-padded prompt into a single-sequence cache.

    ``tokens`` is ``[1, bucket]`` int32; compiled once per bucket
    length. Returns ``(logits, cache)`` where logits is
    ``[1, bucket, vocab]`` (or ``[1, vocab]`` for the degenerate
    1-token bucket). Positions past the real prompt hold padding k/v —
    never attended (causal mask) and overwritten by later decode steps
    before the position pointer passes them.
    """
    return model.apply(variables, tokens, cache=cache, pos=0)


def write_slot(arena, rows, slot):
    """Scatter a prefilled single-sequence cache into arena ``slot``.

    ``rows`` leaves are ``[1, heads, len, head_dim]``; ``slot`` is an
    int32 scalar. Donating ``arena`` makes this an in-place aliased
    update in the lowered program.
    """
    return jax.tree_util.tree_map(
        lambda a, r: jax.lax.dynamic_update_slice(
            a, r.astype(a.dtype), (slot, 0, 0, 0)
        ),
        arena,
        rows,
        is_leaf=lambda x: isinstance(x, jax.Array),
    )


class SlotAllocator:
    """Host-side free-list over arena slots (lowest index first).

    Lowest-first keeps allocation deterministic, which the bitwise
    parity test leans on: the same admission order always lands in the
    same slots.
    """

    _guarded_by_lock = ("_free", "_in_use")

    def __init__(self, slots: int):
        self._lock = threading.Lock()
        self._free = list(range(slots))
        self._in_use: set[int] = set()
        self.slots = slots

    def alloc(self) -> int | None:
        """Claim the lowest free slot, or None when the arena is full."""
        with self._lock:
            if not self._free:
                return None
            slot = min(self._free)
            self._free.remove(slot)
            self._in_use.add(slot)
            return slot

    def free(self, slot: int) -> None:
        with self._lock:
            if slot not in self._in_use:
                raise ValueError(f"slot {slot} is not allocated")
            self._in_use.remove(slot)
            self._free.append(slot)

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_used(self) -> int:
        with self._lock:
            return len(self._in_use)

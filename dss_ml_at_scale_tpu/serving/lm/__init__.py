"""Token-level LM serving: slot KV arenas + continuous batching.

The subsystem between ``POST /generate`` (chunked token streaming in
:mod:`..workloads.serving`) and the audited decode programs
(:mod:`.kvcache`). See :mod:`.engine` for the decode-loop design and
the README "LM serving" section for the operator view.
"""

from __future__ import annotations

from .engine import (
    Generation,
    LMConfig,
    LMEngine,
    PromptTooLong,
    StubLMDecoder,
    TransformerDecoder,
)
from .kvcache import (
    SlotAllocator,
    make_arena,
    prefill_bucket,
    slot_decode,
    write_slot,
)

__all__ = [
    "Generation",
    "LMConfig",
    "LMEngine",
    "PromptTooLong",
    "SlotAllocator",
    "StubLMDecoder",
    "TransformerDecoder",
    "make_arena",
    "prefill_bucket",
    "slot_decode",
    "write_slot",
]

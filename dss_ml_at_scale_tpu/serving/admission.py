"""Admission control for the serving scheduler.

The front door of the cross-request batching pipeline: a bounded count
of admitted-but-unfinished images. Admission is *counted*, not queued —
the actual work items flow through the decode/batch queues — so the
bound covers everything the process has promised to score, wherever it
currently sits (waiting for decode, decoded and waiting for a batch
slot, or mid-score on the device).

Design points:

- **Reject at the door, not mid-pipeline**: a request either fits under
  ``depth`` whole or is refused with :class:`QueueFull` before any of
  its images enter a queue — no partial admissions to unwind.
- **Retry-After from measured service rate**: the controller keeps an
  EWMA of seconds-per-image observed by the batcher, so the 429 a
  client sees carries an honest estimate of when capacity frees up
  instead of a magic constant.
- **Deadlines settle requests, never threads**: an expired
  :class:`Request` is *settled* (client unblocked with
  :class:`DeadlineExceeded`) while its items are still in the queues;
  workers recognize settled requests and retire the items lazily. No
  scan-and-remove over queue internals, no lock ordering between the
  queues and the request.
"""

from __future__ import annotations

import math
import threading
import time


class SchedulerError(Exception):
    """Base of every scheduler-surfaced refusal (never a server fault)."""


class QueueFull(SchedulerError):
    """Admission refused: the pending-image bound is hit (HTTP 429).

    ``retry_after`` is whole seconds (ceil, >= 1) — the unit the HTTP
    ``Retry-After`` header speaks.
    """

    def __init__(self, depth: int, pending: int, retry_after: float = 1.0):
        self.depth = depth
        self.pending = pending
        self.retry_after = max(1, int(math.ceil(retry_after)))
        super().__init__(
            f"admission queue full ({pending}/{depth} images pending)"
        )


class DeadlineExceeded(SchedulerError):
    """The request's deadline passed before scoring finished (HTTP 503).

    The work is *dropped*, not scored late: items of an expired request
    are skipped by the decode pool and batcher, so a backed-up server
    sheds load instead of burning scorer time on answers nobody is
    waiting for.
    """


class NotAccepting(SchedulerError):
    """The scheduler is draining or stopped (HTTP 503)."""


class Request:
    """One client request: ``n`` images in, ``n`` result rows out.

    Settles exactly once — either every item completes (``results`` is
    full) or :meth:`fail` records the first error (deadline, decode
    failure, scorer fault). Completions after settlement are no-ops, so
    a batch that finishes scoring just as the deadline fires cannot
    corrupt the already-delivered 503.
    """

    __slots__ = ("n", "deadline", "t_admit", "results", "error",
                 "trace", "batch_fill", "_remaining", "_done", "_lock")

    # Lint contract (dsst lint, lock-discipline rule): settlement state
    # is written by whichever worker thread ends the request — always
    # under _lock. (Readers outside this class consume it only after
    # the _done event, which publishes the writes.)
    _guarded_by_lock = ("results", "error", "_remaining")

    def __init__(self, n: int, deadline: float | None = None):
        self.n = n
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.t_admit = time.monotonic()
        self.results: list = [None] * n
        self.error: BaseException | None = None
        # Causal identity, attached by the scheduler: the submitting
        # thread's trace handoff (workers adopt it around decode/score
        # spans). This module stays telemetry-free — it only carries
        # the object.
        self.trace = None
        # Fill of the micro-batch this request last scored in (written
        # by the batcher thread before completion, read by the handler
        # after settlement — the _done event publishes the write).
        self.batch_fill: int | None = None
        self._remaining = n
        self._done = threading.Event()
        self._lock = threading.Lock()

    @property
    def settled(self) -> bool:
        return self._done.is_set()

    def expired(self, now: float | None = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline

    def complete_item(self, index: int, row) -> None:
        with self._lock:
            if self._done.is_set():
                return  # settled (expired/failed) — result discarded
            self.results[index] = row
            self._remaining -= 1
            if self._remaining == 0:
                self._done.set()

    def fail(self, exc: BaseException) -> bool:
        """Settle with ``exc``; True only for the call that settled it."""
        with self._lock:
            if self._done.is_set():
                return False
            self.error = exc
            self._done.set()
            return True

    def outcome(self) -> tuple[BaseException | None, list]:
        """Settlement snapshot ``(error, results)``, read under the
        lock. Callers used to read ``error``/``results`` directly after
        :meth:`wait`, leaning on the ``_done`` event to publish the
        writes — correct for waiters, but the timeout/stop paths read
        them while a worker thread can still be settling, and the
        runtime sanitizer (``dsst sanitize``, guarded-by rule) flags
        exactly that. One locked snapshot serves every exit path."""
        with self._lock:
            return self.error, list(self.results)

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)


class WorkItem:
    """One image of one request, as it flows decode-queue → batch-queue.

    ``retire()`` is the single accounting point: whichever worker ends
    the item's life (scored, skipped, failed, or flushed at stop) calls
    it, and only the first caller releases the admission slot.
    """

    __slots__ = ("request", "index", "payload", "image", "_retired")

    def __init__(self, request: Request, index: int, payload):
        self.request = request
        self.index = index
        self.payload = payload  # raw bytes in
        self.image = None       # decoded array out of the decode pool
        self._retired = False

    def retire(self) -> bool:
        """True only for the first caller (under the request's lock)."""
        with self.request._lock:
            if self._retired:
                return False
            self._retired = True
            return True


class AdmissionController:
    """The bounded gate: at most ``depth`` images pending at once."""

    # Lint contract: HTTP handler threads admit, worker threads release,
    # the batcher feeds the service-rate EWMA — all under _lock.
    _guarded_by_lock = ("_pending", "_seconds_per_image")

    def __init__(self, depth: int, on_depth=None):
        if depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self._pending = 0
        self._lock = threading.Lock()
        self._on_depth = on_depth or (lambda n: None)
        # Seed pessimistically (50 ms/image ≈ a cold CPU scorer); real
        # measurements from the batcher replace it within one batch.
        self._seconds_per_image = 0.05

    @property
    def pending(self) -> int:
        with self._lock:
            return self._pending

    @property
    def service_rate_ewma(self) -> float:
        """The measured seconds-per-image EWMA (what Retry-After is
        computed from) — exported so the live monitoring plane can see
        the controller's internal model instead of inferring it."""
        with self._lock:
            return self._seconds_per_image

    @property
    def est_queue_wait_s(self) -> float:
        """Estimated wait for a newly admitted image: everything
        already pending, at the measured service rate."""
        with self._lock:
            return self._pending * self._seconds_per_image

    def note_service_rate(self, seconds_per_image: float) -> None:
        """EWMA of measured scoring cost, feeding Retry-After."""
        with self._lock:
            self._seconds_per_image = (
                0.7 * self._seconds_per_image + 0.3 * max(seconds_per_image, 0.0)
            )

    def admit(self, n: int) -> None:
        """Reserve ``n`` slots or raise :class:`QueueFull` (all or nothing)."""
        with self._lock:
            if self._pending + n > self.depth:
                raise QueueFull(
                    self.depth, self._pending,
                    retry_after=self._pending * self._seconds_per_image,
                )
            self._pending += n
            depth_now = self._pending
        self._on_depth(depth_now)

    def release(self, n: int = 1) -> None:
        with self._lock:
            self._pending -= n
            depth_now = self._pending
        self._on_depth(depth_now)

"""Decode pool and cross-request batcher threads.

The two worker stages between admission and the compiled scorer:

- :class:`DecodePool` — N threads turning raw JPEG bytes into decoded
  arrays *off* the scoring thread, so host-side libjpeg work overlaps
  device scoring instead of serializing in front of it (the serving
  analogue of the training reader's decode workers).
- :class:`Batcher` — ONE thread that coalesces decoded images *across
  requests* into the fixed compiled micro-batch shape: take the first
  waiting image, then keep gathering until the batch is full or the
  batch window elapses, whichever comes first. Sixteen concurrent
  single-image requests ride one padded executable call instead of
  sixteen; a lone request waits at most the window.

Both stages are policy-free plumbing: what "decode", "score", "skip"
and "expired" mean is injected by the scheduler, so this module never
imports a predictor, telemetry, or HTTP anything — and the unit tests
can drive it with plain lists.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time


def _NULL_TRACE(job):
    return contextlib.nullcontext()


# dsst: ignore[lock-discipline] no lock-guarded state: policy-free plumbing — work crosses threads only via the injected queues and stop Event; per-item state lives on WorkItem/Request (which declare their contracts)
class DecodePool:
    """N daemon threads: decode-queue → (decode) → batch-queue.

    The decode queue carries *jobs* — each a list of one request's
    :class:`~.admission.WorkItem`\\ s — so a multi-image request keeps
    its vectorized decode (ONE ``decode`` call over N payloads, not N
    calls of 1); the decoded items then fan out per image into the
    batch queue, where cross-request coalescing is per-image again.

    Jobs whose request already settled (deadline hit while waiting,
    sibling image failed) are skipped via ``on_skip`` (per item)
    without paying the decode. A decode raise fails the whole request
    via ``on_error`` — one broken image makes the request's response an
    error, matching the synchronous path's semantics.
    """

    def __init__(self, *, decode, in_q: queue.Queue, out_q: queue.Queue,
                 on_skip, on_error, stop: threading.Event,
                 workers: int = 2, poll_s: float = 0.05, trace=None):
        if workers < 1:
            raise ValueError(f"decode workers must be >= 1, got {workers}")
        self._decode = decode
        self._in_q = in_q
        self._out_q = out_q
        self._on_skip = on_skip
        self._on_error = on_error
        self._stop = stop
        self._poll_s = poll_s
        # Optional tracing hook: a callable(job) returning a context
        # manager the decode runs inside (the scheduler injects the
        # request's trace handoff + span there — this module stays
        # policy- and telemetry-free).
        self._trace = trace if trace is not None else _NULL_TRACE
        self._threads = [
            threading.Thread(
                target=self._run, name=f"dsst-serve-decode-{i}", daemon=True
            )
            for i in range(workers)
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def join(self, timeout: float = 2.0) -> None:
        for t in self._threads:
            t.join(timeout)

    # dsst: hotpath — decode must overlap device scoring, never sync with it
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._in_q.get(timeout=self._poll_s)
            except queue.Empty:
                continue
            req = job[0].request
            if req.settled or req.expired():
                for item in job:
                    self._on_skip(item)
                continue
            try:
                with self._trace(job):
                    images = self._decode([item.payload for item in job])
            except Exception as exc:
                self._on_error(job, exc)
                continue
            for item, image in zip(job, images):
                item.image = image
                self._out_q.put(item)


# dsst: ignore[lock-discipline] no lock-guarded state: single batcher thread owns all its locals; items arrive via the queue and leave via run_batch
class Batcher:
    """ONE thread: batch-queue → (coalesce) → ``run_batch``.

    The fill policy is wait-up-to-window *after the first image*, so an
    idle server adds zero latency floor beyond the window, and a busy
    server's batches fill instantly from the queue without waiting at
    all. Expired/settled items discovered at assembly time are dropped
    via ``on_skip`` — the compiled scorer never runs for a client that
    already got its 503.
    """

    def __init__(self, *, in_q: queue.Queue, micro_batch: int,
                 window_s: float, run_batch, on_skip,
                 stop: threading.Event, poll_s: float = 0.05):
        if micro_batch < 1:
            raise ValueError(f"micro_batch must be >= 1, got {micro_batch}")
        self._in_q = in_q
        self._micro_batch = micro_batch
        self._window_s = max(window_s, 0.0)
        self._run_batch = run_batch
        self._on_skip = on_skip
        self._stop = stop
        self._poll_s = poll_s
        self._thread = threading.Thread(
            target=self._run, name="dsst-serve-batcher", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: float = 2.0) -> None:
        self._thread.join(timeout)

    # dsst: hotpath — batch assembly sits between admission and the scorer
    def _gather(self, first) -> list:
        """``first`` plus whatever arrives before full-or-window."""
        batch = [first]
        window_end = time.monotonic() + self._window_s
        while len(batch) < self._micro_batch:
            left = window_end - time.monotonic()
            if left <= 0:
                break
            try:
                batch.append(self._in_q.get(timeout=left))
            except queue.Empty:
                break
        return batch

    # dsst: hotpath — ONE batcher thread feeds the compiled scorer
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._in_q.get(timeout=self._poll_s)
            except queue.Empty:
                continue
            batch = self._gather(first)
            live = []
            for item in batch:
                if item.request.settled or item.request.expired():
                    self._on_skip(item)
                else:
                    live.append(item)
            if live:
                self._run_batch(live)

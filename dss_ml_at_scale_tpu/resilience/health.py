"""Training-health supervision: non-finite/spike detection + policy ladder.

The failure mode that wastes TPU-scale budgets is *silent*: one NaN
gradient (bad sample, overflow, flaky interconnect bit) poisons the
optimizer state and the run keeps "succeeding" on garbage, or a loss
spike knocks the model off its trajectory and a human rewinds it by hand
at 3am (PaLM's rewind-and-skip; the OPT logbook's restarts). This module
makes that recovery automatic, deterministic, and cheap:

- **On-device signals** (:func:`guard_train_step`): the task's train
  step is wrapped so every step also computes fused ``isfinite``
  reductions over the loss and global grad-norm plus an EWMA
  mean/variance z-score of the loss — all inside the one jitted
  program, carried in a tiny replicated :class:`HealthState`. The
  verdict rides the metrics dict; no extra device→host sync beyond the
  metrics fetch the supervised loop already performs.
- **On-device discard**: the wrapper commits the new state only when
  the step is healthy (``lax.cond`` select), so a bad update never
  touches params or optimizer state and the step counter does not
  advance — by the time the host *sees* the verdict, the damage has
  already been contained in the dataflow.
- **Host policy ladder** (:class:`HealthSupervisor`): the first
  response is always discard-and-skip (the batch's provenance is
  quarantined); under ``policy="rollback"`` a streak of
  ``max_consecutive_skips`` bad steps escalates to restoring the newest
  manifest-intact checkpoint; after ``max_rollbacks`` restores the run
  aborts with a diagnostic bundle (``policy="abort"`` aborts on the
  first bad step).

Fault sites ``grads.nonfinite`` and ``loss.spike`` (value faults,
:func:`~.faults.fault_fires`) drive a traced ``inject`` scalar through
the wrapper, so every path is provable on CPU in tier-1: the injected
NaN flows through the *real* detection reductions and the *real*
discard select.

Counters: ``nonfinite_steps_total``, ``loss_spikes_total``,
``health_rollbacks_total``, ``quarantined_batches_total``; rollbacks
also record a ``health_rollback`` span.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import math
import time
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from .. import telemetry
from . import durability
from .faults import active_plan, fault_fires

log = logging.getLogger(__name__)

# Verdict codes emitted by the guarded step (metrics["health_verdict"]).
VERDICT_OK = 0
VERDICT_NONFINITE = 1
VERDICT_SPIKE = 2

# Injection codes fed to the guarded step's `inject` argument.
INJECT_NONE = 0
INJECT_NONFINITE = 1
INJECT_SPIKE = 2

_VERDICT_NAMES = {VERDICT_NONFINITE: "nonfinite", VERDICT_SPIKE: "spike"}


class TrainingHealthError(RuntimeError):
    """Training aborted by the health policy ladder.

    ``bundle_path`` points at the diagnostic bundle when one was written
    (a checkpoint dir was configured), else None.
    """

    def __init__(self, message: str, bundle_path: str | None = None):
        super().__init__(message)
        self.bundle_path = bundle_path


@dataclasses.dataclass
class HealthConfig:
    """Knobs for the supervised training loop.

    ``policy``: ``skip`` discards bad updates and keeps going;
    ``rollback`` escalates to restore-newest-intact-checkpoint, aborting
    after ``max_rollbacks``; ``abort`` stops on the first bad step.
    ``max_consecutive_skips`` is the number of consecutive bad steps
    TOLERATED as plain skips — the (N+1)-th consecutive bad step
    escalates (rollback under ``rollback``; abort under ``skip``, so a
    fully-poisoned stream cannot spin forever).
    """

    policy: str = "skip"
    # Spike detector: |loss - ewma_mean| > spike_zscore * ewma_std, armed
    # only after warmup_steps healthy observations so init-time loss
    # motion never false-positives. ewma_alpha is the decay of both the
    # mean and the variance; min_spike_std floors the std so a perfectly
    # flat loss (synthetic tasks) cannot divide by ~0.
    spike_zscore: float = 6.0
    ewma_alpha: float = 0.1
    warmup_steps: int = 20
    min_spike_std: float = 1e-3
    # Policy ladder.
    max_consecutive_skips: int = 3
    max_rollbacks: int = 2
    # Metric keys the wrapper reads from the task's train_step output.
    loss_key: str = "train_loss"
    grad_norm_key: str = "grad_norm"
    # Where quarantined batch provenance is persisted (a
    # resilience.rollback.QuarantineList), or None to only count/skip.
    quarantine: Any = None
    # Magnitude of the injected loss spike (site loss.spike) — large
    # enough to clear any sane z-score band.
    inject_spike_delta: float = 1e4

    def __post_init__(self):
        if self.policy not in ("skip", "rollback", "abort"):
            raise ValueError(
                f"health policy must be skip|rollback|abort, "
                f"got {self.policy!r}"
            )


class HealthState(struct.PyTreeNode):
    """EWMA loss statistics carried on device through the guarded step."""

    mean: jnp.ndarray
    var: jnp.ndarray
    count: jnp.ndarray

    @classmethod
    def create(cls) -> "HealthState":
        return cls(
            mean=jnp.zeros((), jnp.float32),
            var=jnp.zeros((), jnp.float32),
            count=jnp.zeros((), jnp.int32),
        )


def guard_train_step(train_step, cfg: HealthConfig):
    """Wrap a task's ``train_step`` with on-device health supervision.

    Returns ``guarded((state, health_state), batch, inject)`` →
    ``((state', health_state'), metrics)`` where ``inject`` is a traced
    int scalar (:data:`INJECT_NONE`/``_NONFINITE``/``_SPIKE``) the host
    loop derives from the fault plan. The commit-or-discard select and
    the EWMA update happen inside the jitted program, so a bad step
    leaves params, optimizer state, and the step counter untouched
    without any host round-trip, and garbage never updates the detector.
    """

    def guarded(carry, batch, inject):
        state, h = carry
        new_state, metrics = train_step(state, batch)
        loss = jnp.asarray(metrics[cfg.loss_key], jnp.float32)
        # Value-fault injection: poison the signals AFTER the real
        # update was computed, exactly as a NaN gradient would present.
        loss = jnp.where(inject == INJECT_NONFINITE, jnp.nan, loss)
        loss = jnp.where(
            inject == INJECT_SPIKE, loss + cfg.inject_spike_delta, loss
        )
        finite = jnp.isfinite(loss)
        gn = metrics.get(cfg.grad_norm_key)
        if gn is not None:
            gn = jnp.asarray(gn, jnp.float32)
            gn = jnp.where(inject == INJECT_NONFINITE, jnp.nan, gn)
            finite = finite & jnp.isfinite(gn)
            metrics = {**metrics, cfg.grad_norm_key: gn}

        std = jnp.sqrt(jnp.maximum(h.var, cfg.min_spike_std**2))
        z = jnp.abs(loss - h.mean) / std
        armed = h.count >= cfg.warmup_steps
        spike = armed & (z > cfg.spike_zscore) & finite
        ok = finite & ~spike
        verdict = jnp.where(
            ~finite,
            VERDICT_NONFINITE,
            jnp.where(spike, VERDICT_SPIKE, VERDICT_OK),
        ).astype(jnp.int32)

        delta = jnp.where(finite, loss - h.mean, 0.0)
        new_h = HealthState(
            mean=h.mean + cfg.ewma_alpha * delta,
            var=(1.0 - cfg.ewma_alpha) * (h.var + cfg.ewma_alpha * delta**2),
            count=h.count + 1,
        )
        committed = jax.lax.cond(
            ok,
            lambda: (new_state, new_h),
            # Discard: the whole update AND the detector update — a
            # spike must not widen the band it just tripped.
            lambda: (state, h),
        )
        metrics = {
            **metrics,
            cfg.loss_key: loss,
            "health_verdict": verdict,
            "loss_zscore": z,
        }
        return committed, metrics

    return guarded


class HealthSupervisor:
    """Host half: verdict bookkeeping, quarantine, the policy ladder."""

    def __init__(self, cfg: HealthConfig):
        self.cfg = cfg
        self.bad_streak = 0
        self.rollbacks = 0
        self.skipped_steps = 0
        self.recent: collections.deque = collections.deque(maxlen=64)
        # Registered eagerly so `dsst telemetry` / /metrics render the
        # families (as zeros) even before the first incident.
        self._nonfinite = telemetry.counter(
            "nonfinite_steps_total",
            "train steps discarded for a non-finite loss/grad-norm",
        )
        self._spikes = telemetry.counter(
            "loss_spikes_total",
            "train steps discarded by the EWMA loss-spike detector",
        )
        self._rollback_counter = telemetry.counter(
            "health_rollbacks_total",
            "checkpoint rollbacks performed by the health supervisor",
        )
        self._quarantined = telemetry.counter(
            "quarantined_batches_total",
            "poison batches whose provenance was quarantined",
        )

    # -- per-step ---------------------------------------------------------

    def next_injection(self) -> int:
        """Injection code for the next step, per the active fault plan."""
        if fault_fires("grads.nonfinite"):
            return INJECT_NONFINITE
        if fault_fires("loss.spike"):
            return INJECT_SPIKE
        return INJECT_NONE

    def observe(self, step: int, metrics, provenance=None) -> str:
        """Digest one step's verdict → ``commit|skip|rollback|abort``.

        ``step`` is the host step mirror (the step the update would have
        committed as); ``provenance`` the batch's RowRange list, if the
        reader supplied one.
        """
        verdict = int(metrics["health_verdict"])
        if verdict == VERDICT_OK:
            self.bad_streak = 0
            return "commit"

        loss = float(metrics[self.cfg.loss_key])
        z = float(metrics.get("loss_zscore", 0.0))
        kind = _VERDICT_NAMES[verdict]
        self.recent.append(
            {"step": step, "verdict": kind, "loss": loss, "zscore": z}
        )
        (self._nonfinite if verdict == VERDICT_NONFINITE
         else self._spikes).inc()
        self.skipped_steps += 1
        self.bad_streak += 1
        log.warning(
            "health: %s at step %d (loss=%g z=%g); update discarded "
            "(streak %d)", kind, step, loss, z, self.bad_streak,
        )
        if provenance and self.cfg.quarantine is not None:
            # Counted only when the provenance actually lands on the
            # blocklist: the counter's contract is "these rows are
            # excluded from replay/resume", not merely "discarded once".
            self.cfg.quarantine.add(
                provenance,
                reason=f"{kind} at step {step} (loss={loss!r})",
                step=step,
            )
            self._quarantined.inc()
        if self.cfg.policy == "abort":
            return "abort"
        if self.bad_streak > self.cfg.max_consecutive_skips:
            if (
                self.cfg.policy == "rollback"
                and self.rollbacks < self.cfg.max_rollbacks
            ):
                return "rollback"
            return "abort"
        return "skip"

    def record_rollback(self, from_step: int, to_step: int,
                        t0_wall: float, duration: float) -> None:
        self.rollbacks += 1
        self.bad_streak = 0
        self._rollback_counter.inc()
        # dsst: ignore[span-discipline] the rollback already happened when this is called — the timing was measured by the Trainer, so a with-span here would lie about when the work ran
        telemetry.get_span_log().record(
            "health_rollback", t0_wall, duration,
            from_step=from_step, to_step=to_step,
        )
        log.warning(
            "health: rolled back from step %d to checkpoint step %d "
            "(rollback %d/%d)", from_step, to_step, self.rollbacks,
            self.cfg.max_rollbacks,
        )

    # -- abort ------------------------------------------------------------

    def abort(self, step: int, reason: str,
              bundle_dir: str | None) -> TrainingHealthError:
        """Build the abort error, writing the diagnostic bundle if a
        directory is available. The caller raises the return value."""
        bundle_path = None
        bundle = {
            "reason": reason,
            "step": step,
            "policy": self.cfg.policy,
            "rollbacks": self.rollbacks,
            "skipped_steps": self.skipped_steps,
            "bad_streak": self.bad_streak,
            "spike_zscore": self.cfg.spike_zscore,
            "recent_incidents": list(self.recent),
            "quarantine_file": (
                str(self.cfg.quarantine.path)
                if self.cfg.quarantine is not None else None
            ),
            "quarantined_entries": (
                len(self.cfg.quarantine)
                if self.cfg.quarantine is not None else 0
            ),
            "fault_plan_stats": (
                active_plan().stats() if active_plan() is not None else None
            ),
            "time": time.time(),
        }
        if bundle_dir is not None:
            try:
                path = Path(bundle_dir) / f"health_abort_step{step}.json"
                path.parent.mkdir(parents=True, exist_ok=True)
                # _json_safe: the incidents being reported are BY
                # DEFINITION non-finite floats, which json.dumps would
                # emit as bare `NaN` tokens — invalid JSON for the strict
                # parsers (jq, JSON.parse) an operator points at a 3am
                # abort. Durable publish: the bundle is the run's last
                # word — it must survive the process (and the host)
                # dying right after.
                durability.durable_write_json(
                    path, _json_safe(bundle), indent=1, kind="bundle"
                )
                bundle_path = str(path)
            except OSError:
                log.exception("could not write health diagnostic bundle")
        log.error("health: aborting training at step %d: %s", step, reason)
        return TrainingHealthError(
            f"training aborted by health supervisor at step {step}: "
            f"{reason}"
            + (f" (diagnostic bundle: {bundle_path})" if bundle_path else ""),
            bundle_path=bundle_path,
        )


def _json_safe(obj):
    """Replace non-finite floats with their string spelling ('nan',
    'inf', '-inf') so the document stays strictly-valid JSON."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


